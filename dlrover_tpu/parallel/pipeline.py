"""Pipeline parallelism: staged execution over the 'pp' mesh axis.

Parity with ATorch's PP stack (reference
``pipeline_parallel/scheduler.py:15`` GPipe/1F1B schedulers,
``distributed_pippy_compiler.py``, P2P ``communication/pipe_communicator.py``)
— TPU-first, two schedules:

- **GPipe** (:func:`pipeline_apply`): fill-drain expressed as one
  ``lax.scan`` with ``ppermute`` neighbour hops; differentiable (backward
  falls out of autodiff through the scan).
- **1F1B** (:func:`pipeline_value_and_grad`): the Megatron-style
  one-forward-one-backward schedule, built as an explicit static schedule
  table (:func:`build_1f1b_schedule`) executed tick-by-tick; the backward of
  each stage recomputes from the saved stage *input* (``jax.vjp``), so live
  activation memory is O(n_stages) microbatch inputs per stage instead of
  GPipe's O(n_microbatches).

Both run inside a **partial-manual** ``shard_map`` (``axis_names={'pp'}``):
only the pipeline axis is manual; parameters may additionally be sharded on
'tp'/'fsdp'/'dp', which GSPMD handles automatically inside each stage — this
is how pp composes with the other parallel axes in one mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(per_stage_params: list) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage axis.

    Stage trees must share a structure (e.g. each stage = the same pattern of
    transformer blocks); heterogeneity must live *inside* a stage.
    """
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params
    )


def stage_param_specs(stage_specs: Any) -> Any:
    """Prepend the 'pp' axis to every per-stage PartitionSpec."""
    return jax.tree_util.tree_map(
        lambda spec: P("pp", *spec),
        stage_specs,
        is_leaf=lambda s: isinstance(s, P),
    )


def _pcast_pp(tree, pp_axis):
    """Mark a carry tree as varying over pp so scan carries typecheck."""
    return jax.tree_util.tree_map(
        lambda x: jax.lax.pcast(x, (pp_axis,), to="varying"), tree
    )


def _safe_ppermute(tree, axis, perm):
    return jax.tree_util.tree_map(
        lambda x: jax.lax.ppermute(x, axis, perm), tree
    )


def _carry_dtype(dt):
    """Pipeline scan-carry dtype: 16-bit carries inside a partial-manual
    shard_map scan crash the XLA CPU compiler ("Invalid binary instruction
    opcode copy"); widen to f32 on CPU, keep native on TPU."""
    if jax.default_backend() == "cpu" and dt in (jnp.bfloat16, jnp.float16):
        return jnp.dtype(jnp.float32)
    return jnp.dtype(dt)


# ---------------------------------------------------------------------------
# GPipe (differentiable fill-drain scan)
# ---------------------------------------------------------------------------


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,  # [n_micro * micro_bs, ...] global batch
    mesh: Mesh,
    *,
    n_microbatches: int,
    pp_axis: str = "pp",
) -> jax.Array:
    """Run ``x`` through ``n_stages`` pipeline stages (GPipe fill-drain).

    ``stage_fn(stage_params, micro_activations) -> micro_activations`` is the
    per-stage computation (e.g. a group of transformer blocks).  The input
    batch is split into ``n_microbatches``; activations circulate so stage
    ``s`` processes microbatch ``m`` at tick ``s + m`` (total ticks =
    n_stages + n_micro - 1).  Differentiable; compose with ``jax.checkpoint``
    on ``stage_fn`` for the 1F1B-like memory profile.
    """
    n_stages = mesh.shape[pp_axis]
    if n_stages == 1:
        return stage_fn(
            jax.tree_util.tree_map(lambda p: p[0], stacked_params), x
        )
    assert x.shape[0] % n_microbatches == 0
    micro_bs = x.shape[0] // n_microbatches

    def body(params_local, x_local):
        params_me = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage_idx = jax.lax.axis_index(pp_axis)
        micros = x_local.reshape(
            (n_microbatches, micro_bs) + x_local.shape[1:]
        )

        n_ticks = n_stages + n_microbatches - 1
        cdt = _carry_dtype(x_local.dtype)
        buf = jnp.zeros((micro_bs,) + x_local.shape[1:], cdt)
        outputs = jnp.zeros(micros.shape, cdt)

        def tick(carry, t):
            buf, outputs = carry
            # Stage 0 injects microbatch t (when in range).
            inject = jnp.where(t < n_microbatches, t, 0)
            buf = jnp.where(stage_idx == 0,
                            micros[inject].astype(cdt), buf)
            out = stage_fn(params_me, buf.astype(x_local.dtype))
            # Last stage emits microbatch (t - n_stages + 1).
            emit = t - (n_stages - 1)
            emit_clip = jnp.clip(emit, 0, n_microbatches - 1)
            outputs = jnp.where(
                (stage_idx == n_stages - 1) & (emit >= 0),
                outputs.at[emit_clip].set(out.astype(cdt)),
                outputs,
            )
            # Shift activations to the next stage.
            perm = [(s, (s + 1) % n_stages) for s in range(n_stages)]
            buf = _safe_ppermute(out.astype(cdt), pp_axis, perm)
            return (buf, outputs), None

        (buf, outputs), _ = jax.lax.scan(
            tick, _pcast_pp((buf, outputs), pp_axis), jnp.arange(n_ticks)
        )
        # Rotate so stage 0 holds the last stage's outputs, then psum-select
        # to make the result provably replicated over pp.
        outputs = _safe_ppermute(
            outputs, pp_axis,
            [(s, (s + 1) % n_stages) for s in range(n_stages)],
        )
        sel = (stage_idx == 0).astype(outputs.dtype)
        outputs = jax.lax.psum(outputs * sel, pp_axis)
        return outputs.reshape(x_local.shape).astype(x_local.dtype)

    param_specs = jax.tree_util.tree_map(
        lambda _: P(pp_axis), stacked_params
    )
    # Barrier: a gather (e.g. embedding lookup) feeding directly into the
    # partial-manual shard_map trips an XLA CPU SPMD partitioner crash
    # ("Invalid binary instruction opcode copy"); the barrier pins the
    # producer outside the manual region.
    x = jax.lax.optimization_barrier(x)
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        axis_names={pp_axis},
    )(stacked_params, x)


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------


class Schedule(NamedTuple):
    """Static 1F1B schedule: per-(tick, stage) microbatch indices, -1 = idle.
    Shapes [n_ticks, n_stages]."""

    fwd: np.ndarray
    bwd: np.ndarray


def build_1f1b_schedule(n_stages: int, n_micro: int) -> Schedule:
    """Megatron-style non-interleaved 1F1B (reference
    ``pipeline_parallel/scheduler.py:15`` PipeSchedulerType.OneFOneB).

    Per-stage action order: ``min(S-1-s, M)`` warmup forwards, then
    alternating f/b until forwards are exhausted, then cooldown backwards.
    Actions are placed at the earliest tick satisfying (a) one action per
    stage per tick and (b) cross-stage dependencies (activations/grads arrive
    at the end of the producing tick).
    """
    S, M = n_stages, n_micro
    actions = []  # per stage: list of ('f'|'b', micro)
    for s in range(S):
        warmup = min(S - 1 - s, M)
        acts = [("f", m) for m in range(warmup)]
        nf, nb = warmup, 0
        while nf < M or nb < M:
            if nf < M:
                acts.append(("f", nf))
                nf += 1
            if nb < M and (nb < nf):
                acts.append(("b", nb))
                nb += 1
        actions.append(acts)

    done_f = {}  # (m, s) -> tick
    done_b = {}
    ptr = [0] * S
    fwd_rows, bwd_rows = [], []
    t = 0
    while any(ptr[s] < len(actions[s]) for s in range(S)):
        frow = [-1] * S
        brow = [-1] * S
        for s in range(S):
            # The executor runs one fwd AND one bwd unit per tick (both are
            # computed SPMD-uniformly anyway), so co-schedule up to one of
            # each kind per tick, in action-list order.
            for _ in range(2):
                if ptr[s] >= len(actions[s]):
                    break
                kind, m = actions[s][ptr[s]]
                if kind == "f":
                    if frow[s] >= 0:
                        break  # fwd slot already used this tick
                    ready = s == 0 or done_f.get((m, s - 1), t) < t
                    if not ready:
                        break
                    frow[s] = m
                    done_f[(m, s)] = t
                    ptr[s] += 1
                else:
                    if brow[s] >= 0:
                        break
                    if s == S - 1:
                        ready = done_f.get((m, s), t) < t
                    else:
                        ready = done_b.get((m, s + 1), t) < t
                    if not ready:
                        break
                    brow[s] = m
                    done_b[(m, s)] = t
                    ptr[s] += 1
        fwd_rows.append(frow)
        bwd_rows.append(brow)
        t += 1
        if t > 4 * (S + M) + 8:  # safety: schedule must terminate
            raise RuntimeError("1F1B schedule failed to converge")
    return Schedule(
        np.asarray(fwd_rows, np.int32), np.asarray(bwd_rows, np.int32)
    )


# ---------------------------------------------------------------------------
# 1F1B executor
# ---------------------------------------------------------------------------


def pipeline_value_and_grad(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    pre_fn: Callable[[Any, jax.Array], jax.Array],
    post_fn: Callable[[Any, jax.Array, jax.Array], jax.Array],
    stacked_params: Any,
    pre_params: Any,
    post_params: Any,
    inputs: jax.Array,   # [n_micro * micro_bs, ...] (e.g. token ids)
    targets: jax.Array,  # [n_micro * micro_bs, ...]
    mesh: Mesh,
    *,
    n_microbatches: int,
    pp_axis: str = "pp",
) -> Tuple[jax.Array, Tuple[Any, Any, Any]]:
    """1F1B pipelined loss + grads for a (pre -> stages -> post) model.

    - ``pre_fn(pre_params, micro_inputs) -> x``    (stage-0 head, e.g. embed)
    - ``stage_fn(stage_params, x) -> x``           (homogeneous stage body)
    - ``post_fn(post_params, x, micro_targets) -> scalar`` (last-stage loss,
      mean over the microbatch)

    Returns ``(loss, (d_stacked, d_pre, d_post))`` where loss and grads match
    ``value_and_grad`` of the unpipelined mean-over-microbatches loss.
    Backward recomputes each stage from its saved input (FlashAttention-style
    recompute), so per-stage live memory is O(S) microbatch activations.
    """
    n_stages = mesh.shape[pp_axis]
    assert inputs.shape[0] % n_microbatches == 0
    micro_bs = inputs.shape[0] // n_microbatches
    M, S = n_microbatches, n_stages
    sched = build_1f1b_schedule(S, M)
    fwd_tab = jnp.asarray(sched.fwd)
    bwd_tab = jnp.asarray(sched.bwd)
    n_ticks = sched.fwd.shape[0]

    # Activation shape probe (host-side, no device compute).
    x_shape = jax.eval_shape(
        pre_fn, pre_params,
        jax.ShapeDtypeStruct((micro_bs,) + inputs.shape[1:], inputs.dtype),
    )

    def body(stacked_local, pre_p, post_p, inputs_, targets_):
        blocks_me = jax.tree_util.tree_map(lambda p: p[0], stacked_local)
        s_idx = jax.lax.axis_index(pp_axis)
        is_first = s_idx == 0
        is_last = s_idx == S - 1
        micros_in = inputs_.reshape((M, micro_bs) + inputs_.shape[1:])
        micros_tgt = targets_.reshape((M, micro_bs) + targets_.shape[1:])

        ring_dt = _carry_dtype(x_shape.dtype)

        def zeros_ring():
            return jnp.zeros((S,) + x_shape.shape, ring_dt)

        def scaled_post(post_p_, y, tgt):
            # 1/M so per-micro grads sum to the grad of the mean loss.
            return post_fn(post_p_, y, tgt) / M

        zero_tree = functools.partial(
            jax.tree_util.tree_map, lambda p: jnp.zeros(p.shape, jnp.float32)
        )

        # Everything differentiable is cast VARYING over pp first: inside a
        # manual-axes region, jax.vjp cotangents w.r.t. pp-invariant inputs
        # carry an implicit psum over 'pp' (while custom_vjp ops skip it) —
        # per-stage masking is only sound when every cotangent is the plain
        # per-stage value, so grads flow from varying params and get one
        # explicit psum at the end.
        pre_v = _pcast_pp(pre_p, pp_axis)
        post_v = _pcast_pp(post_p, pp_axis)

        carry0 = dict(
            in_ring=zeros_ring(),    # activations awaiting fwd
            g_ring=zeros_ring(),     # grads awaiting bwd
            seed_ring=zeros_ring(),  # last-stage loss grads
            x_saved=zeros_ring(),    # saved stage inputs (recompute bwd)
            loss=jnp.zeros((), jnp.float32),
            d_blocks=zero_tree(blocks_me),
            d_pre=zero_tree(pre_p),
            d_post=zero_tree(post_p),
        )

        def masked_add(acc, delta, valid):
            return jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(valid, d.astype(a.dtype), 0.0),
                acc, delta,
            )

        def tick(carry, t):
            mf = fwd_tab[t, s_idx]
            f_valid = mf >= 0
            mfc = jnp.clip(mf, 0, M - 1)
            slot_f = mfc % S

            # ---- forward unit ----
            x_entry = pre_fn(pre_v, micros_in[mfc]).astype(ring_dt)
            x_in = jnp.where(is_first, x_entry, carry["in_ring"][slot_f])
            x_saved = carry["x_saved"].at[slot_f].set(
                jnp.where(f_valid, x_in, carry["x_saved"][slot_f])
            )
            y = stage_fn(blocks_me, x_in.astype(x_shape.dtype))
            lv = f_valid & is_last
            # Last stage: micro loss + seed grad + post grads, in-slot.
            (loss_m, (gy, d_post_m)) = jax.value_and_grad(
                lambda y_, pp_: scaled_post(pp_, y_, micros_tgt[mfc]),
                argnums=(0, 1),
            )(y, post_v)
            loss = carry["loss"] + jnp.where(lv, loss_m, 0.0)
            d_post = masked_add(carry["d_post"], d_post_m, lv)
            seed_ring = carry["seed_ring"].at[slot_f].set(
                jnp.where(lv, gy.astype(ring_dt),
                          carry["seed_ring"][slot_f])
            )

            # ---- backward unit ----
            mb = bwd_tab[t, s_idx]
            b_valid = mb >= 0
            mbc = jnp.clip(mb, 0, M - 1)
            slot_b = mbc % S
            g_in = jnp.where(
                is_last, seed_ring[slot_b], carry["g_ring"][slot_b]
            ).astype(x_shape.dtype)
            _, stage_vjp = jax.vjp(
                stage_fn, blocks_me,
                carry["x_saved"][slot_b].astype(x_shape.dtype),
            )
            d_blocks_m, dx = stage_vjp(g_in)
            d_blocks = masked_add(carry["d_blocks"], d_blocks_m, b_valid)
            # Stage 0: fold dx into the pre (embed) params.
            _, pre_vjp = jax.vjp(
                lambda pp_: pre_fn(pp_, micros_in[mbc]), pre_v
            )
            (d_pre_m,) = pre_vjp(dx.astype(x_shape.dtype))
            d_pre = masked_add(carry["d_pre"], d_pre_m,
                               b_valid & is_first)

            # ---- neighbour exchange (end of tick) ----
            # Micro index rides along, +1-encoded so ppermute's zero-fill on
            # unpaired receivers decodes as invalid.
            send_f_ok = f_valid & (s_idx < S - 1)
            f_payload = (
                y.astype(ring_dt),
                jnp.where(send_f_ok, mf + 1, 0),
            )
            perm_f = [(s, s + 1) for s in range(S - 1)]
            y_in, mfe_in = _safe_ppermute(f_payload, pp_axis, perm_f)
            in_slot = jnp.clip(mfe_in - 1, 0, M - 1) % S
            in_ring = carry["in_ring"].at[in_slot].set(
                jnp.where(mfe_in > 0, y_in, carry["in_ring"][in_slot])
            )

            send_b_ok = b_valid & (s_idx > 0)
            b_payload = (
                dx.astype(ring_dt),
                jnp.where(send_b_ok, mb + 1, 0),
            )
            perm_b = [(s, s - 1) for s in range(1, S)]
            dx_in, mbe_in = _safe_ppermute(b_payload, pp_axis, perm_b)
            g_slot = jnp.clip(mbe_in - 1, 0, M - 1) % S
            g_ring = carry["g_ring"].at[g_slot].set(
                jnp.where(mbe_in > 0, dx_in, carry["g_ring"][g_slot])
            )

            return dict(
                in_ring=in_ring, g_ring=g_ring, seed_ring=seed_ring,
                x_saved=x_saved, loss=loss, d_blocks=d_blocks,
                d_pre=d_pre, d_post=d_post,
            ), None

        carry, _ = jax.lax.scan(
            tick, _pcast_pp(carry0, pp_axis), jnp.arange(n_ticks)
        )

        loss = jax.lax.psum(carry["loss"], pp_axis)  # only last stage != 0
        d_pre = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, pp_axis), carry["d_pre"]
        )
        d_post = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, pp_axis), carry["d_post"]
        )
        d_blocks = jax.tree_util.tree_map(
            lambda g: g[None], carry["d_blocks"]
        )
        return loss, d_blocks, d_pre, d_post

    stacked_specs = jax.tree_util.tree_map(
        lambda _: P(pp_axis), stacked_params
    )
    loss, d_blocks, d_pre, d_post = jax.shard_map(
        body, mesh=mesh,
        in_specs=(stacked_specs, P(), P(), P(), P()),
        out_specs=(P(), stacked_specs, P(), P()),
        axis_names={pp_axis},
    )(stacked_params, pre_params, post_params, inputs, targets)
    return loss, (d_blocks, d_pre, d_post)
