"""Mixture-of-Experts with expert parallelism.

Parity with ATorch's MoE stack (reference ``modules/moe/``:
``Grouped_GEMM_MoE grouped_gemm_moe.py:345``, ``MOELayer moe_layer.py:161``,
``_AllToAll :87``, token dispatchers, switch gating) — TPU-first: experts are
sharded on the ``ep`` mesh axis; token routing uses a capacity-bucketed
dense dispatch (one-hot combine) that XLA lowers to all-to-alls on the
expert axis, and the expert computation is one **grouped einsum** that maps
straight onto the MXU (the grouped-GEMM analogue, no custom CUDA needed).

Top-k gating with auxiliary load-balancing loss (Switch/GShard style).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    d_model: int = 512
    d_ff: int = 2048
    dtype: object = jnp.bfloat16
    router_z_loss: float = 1e-3
    aux_loss: float = 1e-2


def init_moe_params(rng: jax.Array, cfg: MoEConfig) -> Dict:
    k_router, k_wi, k_wo = jax.random.split(rng, 3)
    std = 0.02
    return {
        "router": jax.random.normal(
            k_router, (cfg.d_model, cfg.num_experts), jnp.float32) * std,
        # Stacked expert weights: [E, d_model, d_ff] / [E, d_ff, d_model].
        "wi": jax.random.normal(
            k_wi, (cfg.num_experts, cfg.d_model, cfg.d_ff), jnp.float32) * std,
        "wo": jax.random.normal(
            k_wo, (cfg.num_experts, cfg.d_ff, cfg.d_model), jnp.float32) * std,
    }


def moe_param_specs(cfg: MoEConfig) -> Dict:
    """Experts sharded on 'ep'; per-expert matrices TP-shardable on 'tp'
    (reference: MoE-EP x TP composition, ``ds_3d_parallel``)."""
    return {
        "router": P(None, None),
        "wi": P("ep", None, "tp"),
        "wo": P("ep", "tp", None),
    }


def moe_layer(
    params: Dict,
    x: jax.Array,  # [B, S, d_model]
    cfg: MoEConfig,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Dense-dispatch MoE: returns (output [B,S,d_model], aux metrics).

    Capacity dispatch keeps shapes static (XLA requirement); overflow tokens
    are dropped (standard Switch behaviour) and counted in metrics.
    """
    B, S, D = x.shape
    E, K = cfg.num_experts, cfg.top_k
    N = B * S
    tokens = x.reshape(N, D)

    logits = (tokens.astype(jnp.float32) @ params["router"])  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, K)  # [N, K]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    capacity = int(max(1, round(cfg.capacity_factor * N * K / E)))

    # Position of each (token, k) within its expert's capacity buffer.
    # The -1 comes AFTER the sum over E: inside it, every non-selected
    # expert column adds a spurious -1 (pos = rank - (E-1)) and rank-0
    # assignments land on pos -1, where one_hot() is all-zero — each
    # expert's first token silently vanished from the dispatch.
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # [N, K, E]
    flat_onehot = onehot.reshape(N * K, E)
    pos_in_expert = jnp.cumsum(flat_onehot, axis=0) * flat_onehot
    pos = pos_in_expert.reshape(N, K, E).sum(-1) - 1  # [N, K]
    expert_of = gate_idx  # [N, K]
    keep = pos < capacity

    # Dispatch: [E, C, D] buffers via scatter (one-hot matmul form for MXU).
    dispatch = (
        jax.nn.one_hot(expert_of, E, dtype=tokens.dtype)[..., None]
        * jax.nn.one_hot(pos, capacity, dtype=tokens.dtype)[..., None, :]
    )  # [N, K, E, C]
    dispatch = dispatch * keep[..., None, None].astype(tokens.dtype)
    expert_in = jnp.einsum("nd,nkec->ecd", tokens.astype(cfg.dtype),
                           dispatch.astype(cfg.dtype))  # [E, C, D]

    # Grouped-GEMM expert FFN: one einsum over the expert dim -> MXU-batched.
    h = jnp.einsum("ecd,edf->ecf", expert_in,
                   params["wi"].astype(cfg.dtype))
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ecf,efd->ecd", h,
                            params["wo"].astype(cfg.dtype))  # [E, C, D]

    combine = (dispatch * gate_vals[..., None, None].astype(tokens.dtype))
    out = jnp.einsum("ecd,nkec->nd", expert_out,
                     combine.astype(cfg.dtype))  # [N, D]

    # Aux losses (GShard load balance + router z-loss).
    me = jnp.mean(probs, axis=0)  # [E]
    ce = jnp.mean(
        jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32), axis=0
    )
    aux = cfg.aux_loss * E * jnp.sum(me * ce)
    z = cfg.router_z_loss * jnp.mean(
        jnp.square(jax.nn.logsumexp(logits, axis=-1))
    )
    dropped = 1.0 - jnp.mean(keep.astype(jnp.float32))
    return out.reshape(B, S, D).astype(x.dtype), {
        "moe_aux_loss": aux,
        "moe_z_loss": z,
        "moe_dropped_frac": dropped,
    }
