"""Logical-axis sharding rules: model code names axes, rules map to mesh.

The TPU-native equivalent of ATorch's per-strategy module wrapping (reference
``tensor_parallel/manual_tp.py TPInfo`` shard specs + Megatron-style layers
``modules/distributed_modules/layers.py``): models annotate parameters with
*logical* axis names; a rule table maps logical -> mesh axes; changing the
strategy means changing the rules, never the model.

Standard logical axes (t5x/maxtext convention):
  'batch', 'seq', 'embed', 'heads', 'kv', 'mlp', 'vocab', 'layers', 'expert'
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# rule value: mesh axis name, tuple of axes, or None (replicate)
Rules = Dict[str, Union[str, Tuple[str, ...], None]]

# Megatron layout on one mesh: qkv/fc column-parallel on tp, proj
# row-parallel; fsdp shards embed; batch over dp+fsdp (ZeRO-style: data
# parallel over both, params gathered on fsdp).
DEFAULT_RULES: Rules = {
    "batch": ("dp", "fsdp"),
    "seq": None,
    "embed": "fsdp",
    "heads": "tp",
    "kv": None,
    "mlp": "tp",
    "vocab": "tp",
    "layers": None,
    "expert": "ep",
    "expert_mlp": "tp",
}


def logical_to_spec(
    logical_axes: Sequence[Optional[str]], rules: Optional[Rules] = None
) -> P:
    """('embed','mlp') -> PartitionSpec('fsdp','tp') under the rule table."""
    rules = {**DEFAULT_RULES, **(rules or {})}
    out = []
    used = set()
    for ax in logical_axes:
        if ax is None:
            out.append(None)
            continue
        phys = rules.get(ax)
        if phys is None:
            out.append(None)
            continue
        # A mesh axis may appear only once in a PartitionSpec.
        if isinstance(phys, tuple):
            phys = tuple(p for p in phys if p not in used)
            used.update(phys)
            out.append(phys if phys else None)
        else:
            if phys in used:
                out.append(None)
            else:
                used.add(phys)
                out.append(phys)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def tree_logical_to_specs(logical_tree: Any, rules: Optional[Rules] = None):
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree_util.tree_map(
        lambda axes: logical_to_spec(axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(a, (str, type(None))) for a in x),
    )


def shard_tree(tree: Any, specs: Any, mesh: Mesh):
    """device_put a pytree with per-leaf NamedShardings."""
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree,
        specs,
        is_leaf=lambda x: not isinstance(x, (dict, list, tuple)),
    )


def named_sharding_tree(specs: Any, mesh: Mesh):
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def constraint(x, logical_axes: Sequence[Optional[str]],
               rules: Optional[Rules] = None):
    """``with_sharding_constraint`` by logical axes — used inside model code
    to pin activation layouts (the reference pins them by wrapping modules)."""
    return jax.lax.with_sharding_constraint(
        x, logical_to_spec(logical_axes, rules)
    )
