"""The offline worker loop: chunks through a decode server, reclaim in one round.

``OfflineRunner`` is the batch-tier sibling of the serving
``ReplicaRunner``: it drives the SAME ``DecodeServer.serve_incremental``
surface (tick = the decode loop's admission point), but feeds it from
the journaled :class:`~dlrover_tpu.offline.queue.OfflineWorkQueue`
instead of gateway grants.  One chunk is in flight at a time — the
chunk IS the preemption grain.

The instant-reclaim contract lives here: :meth:`request_reclaim` (the
fleet's ``OfflineRole.begin_drain`` calls it) is honoured at the very
next tick — a chunk whose decode already finished is committed (one
local fsync, not a wasted replay), every still-in-flight request is
aborted (the paged arena frees its blocks at that same admission
point), the active chunk is requeued intact, and the loop drains.  The hard bound — at most ONE decode
round between the request and the chip being free — is what the tier-1
loopback test and the bench's reclaim-latency row assert.

Chaos sites wired at the admission point, mirroring the replica
runner:

- ``offline.chunk_kill`` (flag): THIS worker dies mid-chunk, scoped to
  the chunk machinery — partial results are discarded, the chunk
  requeued; the journal's dedupe makes the replay exactly-once.
- ``serving.replica_kill`` (crash): the whole worker process dies
  (``os._exit(78)``), exactly as a serving replica would — the
  journal-before-ack ordering is what the relaunched worker's replay
  leans on.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from dlrover_tpu import chaos
from dlrover_tpu.common.log import logger
from dlrover_tpu.obs import journal
from dlrover_tpu.offline.queue import OfflineChunk, OfflineWorkQueue


class OfflineRunner:
    """One offline worker: leases chunks, decodes them, commits results.

    ``server`` is anything with the ``DecodeServer`` incremental
    surface (``submit`` / ``abort`` / ``serve_incremental``);
    ``queue`` the shared :class:`OfflineWorkQueue`.  ``round_floor_s``
    models the device-bound round time on CPU benches (same knob as
    the replica runner)."""

    def __init__(
        self,
        server,
        queue: OfflineWorkQueue,
        worker_id: str,
        max_chunks: int = 0,          # 0 = run until drained/stopped
        stop_when_drained: bool = True,
        round_floor_s: float = 0.0,
        clock=time.monotonic,
    ):
        self.server = server
        self.queue = queue
        self.worker_id = worker_id
        self.max_chunks = int(max_chunks)
        self.stop_when_drained = stop_when_drained
        self.round_floor_s = round_floor_s
        self._clock = clock
        self._chunk: Optional[OfflineChunk] = None
        self._results: Dict[str, List[int]] = {}
        self._reclaim_requested = False
        self._request_tick: Optional[int] = None
        self._ticks = 0
        self.running = False
        self.chunks_done = 0
        self.chunk_kills = 0
        self.tokens_out = 0
        #: Decode rounds between request_reclaim() and the loop
        #: draining — the instant-reclaim bound (must be <= 1).
        self.reclaim_rounds: Optional[int] = None

    # -- the instant-reclaim contract ---------------------------------------

    @property
    def busy(self) -> bool:
        return self._chunk is not None

    def request_reclaim(self) -> None:
        """An SLO-bearing role wants this chip.  Thread-safe flag; the
        next tick aborts in-flight work, requeues the chunk, and
        drains the loop — at most one decode round away."""
        if not self._reclaim_requested:
            self._reclaim_requested = True
            self._request_tick = self._ticks

    # -- chunk bookkeeping ---------------------------------------------------

    def _abandon_chunk(self) -> None:
        """Discard the active chunk's in-flight work and requeue it
        intact: aborts free paged-KV blocks at this same admission
        point, partial tokens are dropped (exactly-once is owed to the
        JOURNALED results, not the partials), and the journal's dedupe
        absorbs any completion that raced ahead."""
        chunk, self._chunk = self._chunk, None
        self._results = {}
        if chunk is None:
            return
        for rid in chunk.request_ids:
            try:
                self.server.abort(rid)
            except Exception:  # noqa: BLE001 - a dead rid is already free
                logger.debug(
                    "offline[%s]: abort of %s failed (already gone)",
                    self.worker_id, rid, exc_info=True,
                )
        self.queue.requeue(chunk.chunk_id)

    def _on_finish(self, rid, tokens) -> None:
        if self._chunk is None or rid not in self._chunk.request_ids:
            return  # a stale completion from an abandoned chunk
        self._results[rid] = [int(t) for t in tokens]

    def _commit_if_complete(self) -> None:
        chunk = self._chunk
        if chunk is None or len(self._results) < len(chunk.prompts):
            return
        # Journal-before-ack: complete() fsyncs the results record
        # before we account the chunk done anywhere else.
        fresh = self.queue.complete(chunk.chunk_id, self._results)
        if fresh:
            self.chunks_done += 1
            self.tokens_out += sum(
                len(t) for t in self._results.values()
            )
        journal("offline.chunk", worker=self.worker_id,
                chunk=chunk.chunk_id, fresh=fresh,
                prompts=len(chunk.prompts))
        self._chunk = None
        self._results = {}

    def _lease_next(self) -> bool:
        chunk = self.queue.lease()
        if chunk is None:
            return False
        self._chunk = chunk
        self._results = {}
        for rid, prompt in zip(chunk.request_ids, chunk.prompts):
            self.server.submit(rid, list(prompt), chunk.max_new_tokens)
        return True

    # -- the loop ------------------------------------------------------------

    def _tick(self) -> bool:
        self._ticks += 1
        # Whole-worker death, exactly as a serving replica dies: the
        # relaunched worker's queue replay is what must hold.
        chaos.inject("serving.replica_kill", replica=self.worker_id,
                     step=self._ticks)
        if self._reclaim_requested:
            # Instant reclaim: commit, abort, requeue, drain — all at
            # THIS admission point, so the chip frees within one
            # round.  A chunk whose decode already finished last round
            # is COMMITTED first (one local fsync, inside the round
            # bound) rather than discarded and re-decoded elsewhere.
            self.reclaim_rounds = self._ticks - (
                self._request_tick
                if self._request_tick is not None else self._ticks
            )
            self._commit_if_complete()
            self._abandon_chunk()
            return False
        self._commit_if_complete()
        if self._chunk is not None and chaos.inject(
            "offline.chunk_kill", method=self.worker_id,
            chunk=self._chunk.chunk_id, step=self._ticks,
        ):
            # Scoped worker death: this chunk's work evaporates as if
            # the process died, and the queue replays it exactly-once.
            self.chunk_kills += 1
            self._abandon_chunk()
        if self._chunk is None and not self._lease_next():
            if self.stop_when_drained:
                return False
        if self.max_chunks and self.chunks_done >= self.max_chunks:
            return False
        if self.round_floor_s > 0:
            time.sleep(self.round_floor_s)
        return True

    def run(self) -> Dict[str, Any]:
        """Run until the queue drains, ``max_chunks`` is hit, or a
        reclaim evicts this worker.  Returns the worker's counters."""
        self.running = True
        try:
            self.server.serve_incremental(
                tick=self._tick, on_finish=self._on_finish,
            )
            # The loop may exit with a fully-decoded chunk not yet
            # committed (drain finished the in-flight work after the
            # last tick): commit it — unless we were reclaimed, where
            # the chunk was already requeued and partials dropped.
            if not self._reclaim_requested:
                self._commit_if_complete()
            if self._chunk is not None:
                self._abandon_chunk()
        finally:
            self.running = False
        logger.info(
            "offline[%s]: done=%d kills=%d tokens=%d reclaim_rounds=%s",
            self.worker_id, self.chunks_done, self.chunk_kills,
            self.tokens_out, self.reclaim_rounds,
        )
        return {
            "worker": self.worker_id,
            "chunks_done": self.chunks_done,
            "chunk_kills": self.chunk_kills,
            "tokens_out": self.tokens_out,
            "reclaim_rounds": self.reclaim_rounds,
            "ticks": self._ticks,
        }
