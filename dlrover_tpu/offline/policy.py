"""The virtual-capacity policy: how many chips batch work may soak.

A PURE decision object (graftcheck DET701–705, registered in
``tools/graftcheck/policy_registry.py``): every answer is a function
of the arguments — no ambient clock, randomness, threads, or I/O —
so the wind tunnel (``sim/offline.py``) drives the production object
over a 10k-node day and the double-run law holds byte-for-byte.

The priority-class contract, in arithmetic:

- **zero bid** (:meth:`OfflinePolicy.borrow_bid`): the offline tier
  never registers demand with the borrow arbiter, no matter how deep
  its backlog — its capacity is *virtual*, carved only from chips no
  SLO-bearing role wanted this round;
- **soak** (:meth:`OfflinePolicy.target_workers`): the worker target
  is the min of idle chips (past an operator reserve), the backlog,
  and the cap — sized in *weighted* chips when the fleet mixes
  hardware generations (ISSUE 20c: a v6e chip soaks more work than a
  v4 chip, and the policy must not pretend otherwise);
- **evacuate** (:meth:`OfflinePolicy.target_workers` with
  ``online_pressure=True``, and :meth:`evacuate`): any online
  pressure — a reclaim in flight, a blackout freeze, a queue spike —
  zeroes the target immediately.  The drain bound itself (one decode
  round) is the runner's contract; the policy's job is never to be
  the reason a chip was held.
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass
class OfflinePolicy:
    """Pure sizing policy for the preemptible offline worker pool."""

    #: Hard cap on offline workers (0 = uncapped beyond idle supply).
    max_workers: int = 64
    #: Chips one offline worker occupies (TPU slices are the grain).
    chips_per_worker: int = 1
    #: Idle chips NEVER soaked — operator headroom so an online spike
    #: can grow without even the one-round offline drain in its path.
    reserve_chips: int = 0
    #: Chunks of backlog one worker is worth spawning for: with a
    #: backlog below ``workers * chunks_per_worker`` the pool shrinks
    #: toward the tail of the queue instead of idling chips.
    chunks_per_worker: int = 1

    def borrow_bid(self) -> int:
        """The offline tier's demand as seen by the chip-borrow
        arbiter: ALWAYS zero.  Virtual capacity never bids — a backlog
        of batch work is not pressure, and must never pull a chip from
        an SLO-bearing role."""
        return 0

    def target_workers(self, idle_chips: int, backlog_chunks: int,
                       online_pressure: bool = False,
                       speed_weight: float = 1.0) -> int:
        """Worker target for one pass.

        ``idle_chips`` is the cell's unclaimed chip count AFTER every
        online role took what it wanted; ``backlog_chunks`` the work
        queue's pending depth; ``speed_weight`` the pool's mean
        per-chip speed weight (faster chips drain more backlog, so
        fewer workers cover the same queue).  ``online_pressure``
        True means an SLO-bearing role wants chips (reclaim in
        flight, blackout freeze, queue spike): the answer is 0,
        unconditionally."""
        if online_pressure:
            return 0
        idle = max(0, int(idle_chips) - max(0, int(self.reserve_chips)))
        supply = idle // max(1, int(self.chips_per_worker))
        weight = speed_weight if speed_weight > 0 else 1.0
        # Float ceiling, not integer ceil-div: truncating the weighted
        # divisor (2.7 -> 2, 1.9 -> 1) overstates worker demand and
        # erases fractional weights entirely.
        per_worker = max(1.0, self.chunks_per_worker * weight)
        demand = math.ceil(int(backlog_chunks) / per_worker)
        target = min(supply, demand)
        if self.max_workers > 0:
            target = min(target, int(self.max_workers))
        return max(0, target)

    def evacuate(self, current_workers: int) -> int:
        """Workers to preempt NOW (all of them) when the cell must be
        vacated — a blackout, a whole-cell reclaim.  Split out so call
        sites read as policy, not arithmetic."""
        return max(0, int(current_workers))
