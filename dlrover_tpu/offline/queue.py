"""The offline work plane: a journaled, chunked batch-job queue.

Batch jobs are submitted as prompt lists and split into bounded
*chunks* (the preemption / replay / checkpoint unit: small enough that
abandoning one mid-flight wastes at most a few requests' decode work,
large enough that journal fsyncs amortize).  Durability rides the PR-5
``CompletionJournal`` idiom — append-only fsync'd JSONL, req-id-keyed
dedupe, torn-tail truncation on reopen — with two record kinds:

- ``job`` records pin a submitted job's identity (job id + prompts
  hash + chunking), so resubmitting the same job id is a no-op
  (req-id-keyed dedupe: retried submits after a crash must not fork a
  second copy of the work);
- ``chunk`` records commit one chunk's RESULTS.  The record is fsync'd
  BEFORE the chunk is acknowledged done (journal-before-ack, the
  replica runner's exactly-once contract), so a worker killed between
  the append and the ack replays to a dedupe hit, never a re-execute;
- ``job_done`` records are compaction tombstones: a fully-complete
  job's ``job`` record and ALL of its ``chunk`` records are retired
  together, replaced by one tombstone pinning the job's identity and
  chunk count.  Reopen skips re-indexing tombstoned jobs (nothing
  goes pending again), a late replayed completion still dedupes, and
  a retried submit is still a no-op — only the result PAYLOADS age
  out past the retention cap, never the completion state.

Leases are deliberately NOT journaled: a lease is scratch state (who
is working on what right now), and any chunk leased but never
completed is pending again after a restart — the crash-consistency
rule that makes ``offline.chunk_kill`` (and a whole-worker
``serving.replica_kill``) lose zero work.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from dlrover_tpu.common.log import logger


def _prompts_hash(prompts: Sequence[Sequence[int]]) -> str:
    h = hashlib.sha1()
    for p in prompts:
        h.update(b"|")
        h.update(",".join(str(int(t)) for t in p).encode())
    return h.hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class OfflineChunk:
    """One bounded unit of batch work: the lease/preempt/replay grain."""

    chunk_id: str                      # "<job_id>/<index>"
    job_id: str
    index: int
    prompts: Tuple[Tuple[int, ...], ...]
    max_new_tokens: int

    @property
    def request_ids(self) -> Tuple[str, ...]:
        """Per-prompt request ids — what the runner submits to the
        decode server, and what keys each prompt's tokens inside the
        chunk's journal record."""
        return tuple(
            f"{self.chunk_id}#{i}" for i in range(len(self.prompts))
        )


class OfflineWorkQueue:
    """Journaled chunk queue with exactly-once completion.

    The in-memory state machine per chunk is ``pending -> leased ->
    done``; only ``done`` (and job identity) is durable.  FIFO lease
    order; :meth:`requeue` returns a preempted lease to the FRONT so
    the interrupted chunk replays next (work stays roughly in
    submission order even under churn), and :meth:`preempt_youngest`
    picks the NEWEST lease as the victim — the chunk with the least
    sunk decode cost, mirroring the paged arena's preempt-youngest
    admission law.
    """

    def __init__(self, path: str, chunk_size: int = 8,
                 max_records: int = 10000):
        self.path = path
        self.chunk_size = max(1, int(chunk_size))
        self.max_records = max_records
        self._mu = threading.Lock()
        self._f = None
        #: job_id -> job record (identity + chunking).
        self._jobs: Dict[str, Dict[str, Any]] = {}
        #: job_id -> job_done tombstone (identity + chunk count) for
        #: fully-complete jobs whose records compaction retired.
        self._done_jobs: Dict[str, Dict[str, Any]] = {}
        #: chunk_id -> done record (results live here; dedupe key).
        self._done: Dict[str, Dict[str, Any]] = {}
        #: Submitted chunk bodies, by id (prompts are re-derivable from
        #: the job record; kept in memory for lease speed).
        self._chunks: Dict[str, OfflineChunk] = {}
        #: FIFO of pending chunk ids; leased ids live in _leased in
        #: lease order (newest last — the preempt victim).
        self._pending: List[str] = []
        self._leased: List[str] = []
        self.requeues = 0
        self._load()

    # -- durability (the CompletionJournal idiom) --------------------------

    def _load(self) -> None:
        with self._mu:
            self._load_under_mu()

    def _load_under_mu(self) -> None:
        # Caller holds self._mu (the only call site is _load above);
        # split out so the reopen/replay path reads as one unit.
        try:
            with open(self.path, "r+") as f:
                content = f.read()
                cut = content.rfind("\n") + 1
                if cut < len(content):
                    # Torn tail from a SIGKILL mid-append: truncate it
                    # away before the first new append.
                    f.truncate(cut)
                for line in content[:cut].split("\n"):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn line persisted by an old writer
                    if rec.get("kind") == "job":
                        # graftcheck: disable=CC101 -- caller _load
                        # holds self._mu; the only call site.
                        self._jobs[str(rec["rid"])] = rec
                    elif rec.get("kind") == "job_done":
                        self._done_jobs[str(rec["rid"])] = rec
                    elif rec.get("kind") == "chunk":
                        # graftcheck: disable=CC101 -- caller _load
                        # holds self._mu; the only call site.
                        self._done[str(rec["rid"])] = rec
        except OSError:
            pass  # no journal yet
        # Rebuild the pending set: every submitted chunk not journaled
        # done is pending again (leases are scratch — a lease that died
        # with its worker must replay).  Tombstoned jobs are COMPLETE:
        # re-indexing one would re-lease and re-execute acknowledged
        # work, the exactly-once violation compaction must not create.
        for job_id in sorted(self._jobs):
            if job_id in self._done_jobs:
                continue
            rec = self._jobs[job_id]
            prompts = tuple(
                tuple(int(t) for t in p) for p in rec["prompts"]
            )
            self._index_job(
                job_id, prompts, int(rec["mnt"]), int(rec["chunk"])
            )

    def _append(self, rec: Dict[str, Any]) -> None:
        if self._f is None:
            self._f = open(self.path, "a")
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def _maybe_compact(self) -> None:
        # Caller holds self._mu (the only call site is complete()).
        if len(self._done) < self.max_records + max(
            64, self.max_records // 4
        ):
            return
        # Retire fully-complete jobs WHOLE, oldest job id first: the
        # job record and all of its done records drop together,
        # replaced by one job_done tombstone — a reopen must never see
        # a job record without the done records that prove its chunks
        # finished (that re-indexes completed work as pending and
        # re-executes it).  A job with ANY incomplete chunk keeps
        # everything: its done records are the dedupe that keeps a
        # late replay exactly-once.  Rewrite atomically.
        excess = len(self._done) - self.max_records
        for job_id in sorted(self._jobs):
            if excess <= 0:
                break
            done, total = self._job_progress_under_mu(job_id)
            if done < total:
                continue
            rec = self._jobs.pop(job_id)
            self._done_jobs[job_id] = {
                "kind": "job_done", "rid": job_id,
                "ph": rec["ph"], "n": total,
            }
            for idx in range(total):
                cid = f"{job_id}/{idx}"
                if self._done.pop(cid, None) is not None:
                    excess -= 1
                self._chunks.pop(cid, None)
        if self._f is not None:
            self._f.close()
            self._f = None
        tmp = self.path + ".compact"
        with open(tmp, "w") as f:
            for rec in self._done_jobs.values():
                f.write(json.dumps(rec) + "\n")
            for rec in self._jobs.values():
                f.write(json.dumps(rec) + "\n")
            for rec in self._done.values():
                f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    # -- submission ---------------------------------------------------------

    def _index_job(self, job_id: str, prompts, mnt: int,
                   chunk_size: int) -> int:
        n_chunks = 0
        for lo in range(0, len(prompts), chunk_size):
            idx = lo // chunk_size
            cid = f"{job_id}/{idx}"
            n_chunks += 1
            if cid in self._chunks:
                continue
            self._chunks[cid] = OfflineChunk(
                chunk_id=cid, job_id=job_id, index=idx,
                prompts=tuple(prompts[lo:lo + chunk_size]),
                max_new_tokens=mnt,
            )
            if cid not in self._done:
                self._pending.append(cid)
        return n_chunks

    def submit(self, job_id: str, prompts: Sequence[Sequence[int]],
               max_new_tokens: int) -> int:
        """Enqueue a batch job; returns its chunk count.  Idempotent by
        ``job_id`` (req-id-keyed dedupe): resubmitting a known id with
        the same prompts is a no-op; with DIFFERENT prompts it raises —
        silently serving old work under a reused id is the corruption
        this journal exists to prevent."""
        canon = tuple(tuple(int(t) for t in p) for p in prompts)
        if not canon:
            raise ValueError("offline job with no prompts")
        ph = _prompts_hash(canon)
        with self._mu:
            gone = self._done_jobs.get(job_id)
            if gone is not None:
                # The job completed and compaction retired it: a
                # retried submit is still a no-op, never a re-run.
                if gone["ph"] != ph:
                    raise ValueError(
                        f"offline job id {job_id!r} resubmitted with "
                        "different prompts"
                    )
                return int(gone["n"])
            known = self._jobs.get(job_id)
            if known is not None:
                if known["ph"] != ph:
                    raise ValueError(
                        f"offline job id {job_id!r} resubmitted with "
                        "different prompts"
                    )
                return self._index_job(
                    job_id, canon, int(known["mnt"]),
                    int(known["chunk"]),
                )
            rec = {
                "kind": "job", "rid": job_id, "ph": ph,
                "prompts": [list(p) for p in canon],
                "mnt": int(max_new_tokens), "chunk": self.chunk_size,
            }
            # Journal BEFORE indexing: a submit acknowledged to the
            # caller must survive the very next SIGKILL.
            self._append(rec)
            self._jobs[job_id] = rec
            return self._index_job(
                job_id, canon, int(max_new_tokens), self.chunk_size
            )

    # -- the lease cycle ----------------------------------------------------

    def lease(self) -> Optional[OfflineChunk]:
        """Pop the next pending chunk (FIFO); ``None`` when drained."""
        with self._mu:
            while self._pending:
                cid = self._pending.pop(0)
                if cid in self._done:
                    continue  # completed by a racing worker's replay
                self._leased.append(cid)
                return self._chunks[cid]
            return None

    def requeue(self, chunk_id: str) -> bool:
        """Return a leased chunk to the FRONT of the queue (preemption,
        worker death): it replays next, zero work lost.  Completing a
        requeued chunk later still dedupes exactly-once."""
        with self._mu:
            if chunk_id not in self._leased:
                return False
            self._leased.remove(chunk_id)
            if chunk_id not in self._done:
                self._pending.insert(0, chunk_id)
                self.requeues += 1
            return True

    def preempt_youngest(self) -> Optional[str]:
        """Pick the NEWEST lease as the preemption victim and requeue
        it — the least sunk decode cost, the paged arena's admission
        law.  Returns the victim chunk id (``None`` when idle)."""
        with self._mu:
            if not self._leased:
                return None
            victim = self._leased[-1]
        self.requeue(victim)
        return victim

    def complete(self, chunk_id: str,
                 results: Dict[str, Sequence[int]]) -> bool:
        """Commit one chunk's results — journal-before-ack.  Returns
        ``False`` (and writes nothing) when the chunk is already done:
        the dedupe that makes a replayed chunk exactly-once."""
        with self._mu:
            if (chunk_id in self._done
                    or chunk_id.rsplit("/", 1)[0] in self._done_jobs):
                # Already journaled done — or so long done that the
                # whole job was compacted to a tombstone.  Either way
                # the replayed completion dedupes, never re-executes.
                if chunk_id in self._leased:
                    self._leased.remove(chunk_id)
                return False
            chunk = self._chunks.get(chunk_id)
            if chunk is None:
                raise KeyError(f"unknown offline chunk {chunk_id!r}")
            missing = [
                rid for rid in chunk.request_ids if rid not in results
            ]
            if missing:
                raise ValueError(
                    f"chunk {chunk_id} completion missing {missing}"
                )
            rec = {
                "kind": "chunk", "rid": chunk_id,
                "ph": _prompts_hash(chunk.prompts),
                "tokens": {
                    rid: [int(t) for t in results[rid]]
                    for rid in chunk.request_ids
                },
            }
            self._append(rec)  # fsync'd BEFORE any ack
            self._done[chunk_id] = rec
            if chunk_id in self._leased:
                self._leased.remove(chunk_id)
            if chunk_id in self._pending:
                self._pending.remove(chunk_id)
            self._maybe_compact()
            return True

    # -- views --------------------------------------------------------------

    def result(self, chunk_id: str) -> Optional[Dict[str, List[int]]]:
        """One done chunk's tokens, or ``None``.  Result PAYLOADS are
        retained up to ``max_records`` completions: once compaction
        retires a fully-complete job, its chunks stay done (dedupe,
        progress, resubmit-no-op all hold) but this returns ``None`` —
        consumers drain results before a job ages past the cap."""
        with self._mu:
            rec = self._done.get(chunk_id)
            if rec is None:
                return None
            return {
                rid: [int(t) for t in toks]
                for rid, toks in rec["tokens"].items()
            }

    def _job_progress_under_mu(self, job_id: str) -> Tuple[int, int]:
        gone = self._done_jobs.get(job_id)
        if gone is not None:
            n = int(gone["n"])
            return n, n
        total = done = 0
        for cid, chunk in self._chunks.items():
            if chunk.job_id != job_id:
                continue
            total += 1
            if cid in self._done:
                done += 1
        return done, total

    def job_progress(self, job_id: str) -> Tuple[int, int]:
        """(chunks done, chunks total) for one job."""
        with self._mu:
            return self._job_progress_under_mu(job_id)

    def stats(self) -> Dict[str, int]:
        with self._mu:
            return {
                "jobs": len(self._jobs),
                "retired_jobs": len(self._done_jobs),
                "pending": len(self._pending),
                "leased": len(self._leased),
                "done": len(self._done),
                "requeues": self.requeues,
            }

    def backlog(self) -> int:
        """Pending chunks — the offline tier's (non-bidding) demand
        signal: what :class:`~dlrover_tpu.offline.policy.OfflinePolicy`
        sizes the worker pool against."""
        with self._mu:
            return len(self._pending)

    def drained(self) -> bool:
        with self._mu:
            return not self._pending and not self._leased
