"""The offline tier (ROADMAP item 8): a preemptible priority class.

The sixth role family and the first NON-SLO workload class: batch
inference (eval sweeps, synthetic-data generation, embedding backfill)
that soaks whatever chips the online roles are not using and vanishes
— drain-first, bounded by ONE decode round — the instant an
SLO-bearing role wants them back.  VirtualFlow (2009.09523) argues the
workload's view of resources should be decoupled from the hardware;
this package is that decoupling as a *priority class*: the offline
tier's capacity is VIRTUAL (it bids zero in the borrow arbiter, owns
nothing, and is charged for nothing), so every chip it holds is by
construction a chip nobody with an SLO wanted.

Three pieces:

- :class:`~dlrover_tpu.offline.queue.OfflineWorkQueue` — the work
  plane: a journaled (fsync'd JSONL, the PR-5 ``CompletionJournal``
  idiom) queue of batch jobs split into bounded *chunks*, req-id-keyed
  dedupe, so a preempted or chaos-killed worker replays exactly-once
  with zero lost work.
- :class:`~dlrover_tpu.offline.runner.OfflineRunner` — rides the
  existing ``DecodeServer`` incremental surface to execute chunks on
  otherwise-idle replicas; honours the instant-reclaim contract at its
  tick (the decode loop's admission point).
- :class:`~dlrover_tpu.offline.policy.OfflinePolicy` — the pure
  virtual-capacity policy (graftcheck DET701–705): target worker count
  from idle weighted chips and backlog, zero borrow bid, evacuate on
  online pressure.
"""

from dlrover_tpu.offline.policy import OfflinePolicy
from dlrover_tpu.offline.queue import OfflineChunk, OfflineWorkQueue
from dlrover_tpu.offline.runner import OfflineRunner

__all__ = [
    "OfflineChunk",
    "OfflinePolicy",
    "OfflineRunner",
    "OfflineWorkQueue",
]
