"""Virtual roles: the RoleAdapter contract over simulated node blocks.

:class:`SimRole` is a real :class:`~dlrover_tpu.fleet.role.RoleAdapter`
subclass — the arbiters and movers under test call the same
``observe``/``spawn``/drain-trio surface they call in production, and
the generic borrow/lend/reclaim machinery of the base class runs
unmodified.  Members are named blocks (``"c3/serving-7"``); a member
"process" is ``block_nodes`` fleet nodes, so a 10,000-node fleet is a
few hundred adapter members, not ten thousand Python objects.

Drains are modeled as a countdown: ``begin_drain`` marks the youngest
member, and each ``pump_drain`` pass burns one of ``drain_passes``
before the member actually leaves — which is exactly the shape the
``CrossCellMover`` ladder budgets against (``drain_budget_passes``).
Everything is plain lists; there is deliberately no wall time, no
randomness, and no thread anywhere in this file.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from dlrover_tpu.fleet.role import RoleAdapter, RoleSpec, RoleStatus


class SimRole(RoleAdapter):
    """A count-backed role whose members exist only in the sim."""

    def __init__(self, spec: RoleSpec, prefix: str,
                 block_nodes: int = 1, drain_passes: int = 2):
        super().__init__(spec)
        self.prefix = prefix
        self.block_nodes = int(block_nodes)
        self.drain_passes = int(drain_passes)
        self.members: List[str] = [
            f"{prefix}-{i}" for i in range(spec.desired)
        ]
        self._next_id = spec.desired
        #: member -> remaining pump passes before it leaves.
        self._draining: Dict[str, int] = {}
        self.signals: Dict[str, object] = {}
        self.spawned = 0
        self.drained = 0

    # -- RoleAdapter primitives -------------------------------------------

    def observe(self) -> RoleStatus:
        return RoleStatus(
            members=tuple(self.members),
            draining=tuple(self._draining),
            signals=dict(self.signals),
        )

    def spawn(self, n: int) -> int:
        for _ in range(max(0, int(n))):
            self.members.append(f"{self.prefix}-{self._next_id}")
            self._next_id += 1
        self.spawned += max(0, int(n))
        return max(0, int(n))

    def begin_drain(self) -> Optional[str]:
        if self._draining:
            return None  # one drain in flight per role
        for m in reversed(self.members):
            self.members.remove(m)
            self._draining[m] = self.drain_passes
            return m
        return None

    def drain_pending(self) -> bool:
        return bool(self._draining)

    def pump_drain(self) -> None:
        done = []
        for m in self._draining:
            self._draining[m] -= 1
            if self._draining[m] <= 0:
                done.append(m)
        for m in done:
            del self._draining[m]
            self.drained += 1

    # -- sim surface -------------------------------------------------------

    @property
    def count(self) -> int:
        return len(self.members)

    @property
    def node_count(self) -> int:
        return len(self.members) * self.block_nodes

    def fail(self, n: int) -> int:
        """``n`` members die abruptly (churn wave): no drain, they are
        simply gone next observe.  Returns how many actually died."""
        n = min(max(0, int(n)), len(self.members))
        for _ in range(n):
            self.members.pop()
        return n

    def snapshot(self) -> Tuple[int, int, int]:
        """(members, draining, desired) — the event log's view."""
        return (len(self.members), len(self._draining),
                self.spec.desired)
