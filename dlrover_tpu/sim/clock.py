"""The wind tunnel's clock: virtual seconds, advanced only by events.

Every policy object in the registry takes an injected ``clock``
callable (the DET701 seam).  In production that is ``time.monotonic``;
in the simulator it is a :class:`VirtualClock` the event scheduler
advances — no code under test can tell the difference, and a 24-hour
diurnal trace runs in however long its *events* take to process, not
24 hours.

Monotonicity is enforced here rather than trusted: an event handler
that tried to move time backwards would silently corrupt every
latency/ cooldown computation downstream, so ``advance_to`` clamps.
"""

from __future__ import annotations


class VirtualClock:
    """A monotonic virtual clock, callable like ``time.monotonic``."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance_to(self, t: float) -> float:
        """Move time forward to ``t`` (never backward); returns now."""
        if t > self.t:
            self.t = float(t)
        return self.t

    def advance(self, dt: float) -> float:
        """Move time forward by ``dt`` (negative deltas are ignored)."""
        return self.advance_to(self.t + dt)
