"""Seeded, ambient-free randomness for the wind tunnel.

The simulator may not touch ``random`` or ``np.random`` — graftcheck's
effect analysis bans both for anything reachable from a registered
policy object, because module-global RNG state makes a replay depend
on call *order across components*, not on the trace.  Instead every
draw here is a pure function of ``(seed, site, n)``, hashed through
SHA-1 exactly like ``common.hashring``'s ring positions and the chaos
plan's crc32 decisions: same coordinates, same draw, forever, on any
platform.

``site`` is a free-form string naming the decision point
(``"arr:120:cell3"``); ``n`` disambiguates multiple draws at one
site.  Nothing is stateful, so concurrent sim components can never
steal each other's draws.
"""

from __future__ import annotations

import hashlib
import math
from typing import List, Sequence, Tuple

#: 53 bits of hash -> a float in [0, 1) with full double precision.
_DENOM = float(1 << 53)


def u01(seed: int, site: str, n: int = 0) -> float:
    """Uniform draw in [0, 1), a pure function of its coordinates."""
    h = hashlib.sha1(f"{seed}:{site}:{n}".encode()).digest()
    return (int.from_bytes(h[:8], "big") >> 11) / _DENOM


def exp_gap(seed: int, site: str, n: int, mean: float) -> float:
    """Exponential inter-arrival gap with the given mean."""
    u = u01(seed, site, n)
    # 1-u is in (0, 1]; log of it is finite.
    return -float(mean) * math.log(1.0 - u)


def poisson(seed: int, site: str, lam: float) -> int:
    """Poisson count with mean ``lam``.

    Knuth's product method below ``lam < 30`` (exact, one sub-draw per
    event); above that a clamped normal approximation — at fleet
    scale the per-cell arrival counts this feeds are hundreds to
    thousands, where the approximation error is far below the model
    error the fidelity section states.
    """
    lam = float(lam)
    if lam <= 0.0:
        return 0
    if lam < 30.0:
        limit = math.exp(-lam)
        k = 0
        prod = u01(seed, site, 0)
        while prod > limit:
            k += 1
            prod *= u01(seed, site, k)
        return k
    z = normal01(seed, site)
    return max(0, int(round(lam + math.sqrt(lam) * z)))


def normal01(seed: int, site: str) -> float:
    """Standard normal via Box-Muller from two coordinate draws."""
    u1 = u01(seed, site, 1000001)
    u2 = u01(seed, site, 1000002)
    r = math.sqrt(-2.0 * math.log(1.0 - u1))
    return r * math.cos(2.0 * math.pi * u2)


def zipf_shares(n: int, a: float) -> List[float]:
    """Zipf(``a``) probability over ranks 0..n-1 (rank 0 hottest)."""
    if n <= 0:
        return []
    w = [1.0 / float(k) ** float(a) for k in range(1, n + 1)]
    total = sum(w)
    return [x / total for x in w]


def cdf_of(shares: Sequence[float]) -> Tuple[float, ...]:
    """Cumulative form of a share vector, for :func:`pick`."""
    acc = 0.0
    out = []
    for s in shares:
        acc += s
        out.append(acc)
    return tuple(out)


def pick(u: float, cdf: Sequence[float]) -> int:
    """Index of the first cdf entry >= u (inverse-CDF sampling)."""
    lo, hi = 0, len(cdf) - 1
    while lo < hi:
        mid = (lo + hi) // 2
        if cdf[mid] < u:
            lo = mid + 1
        else:
            hi = mid
    return lo
