"""The control-plane rig: the cell bench's shard physics in virtual time.

:class:`CellPlaneSim` replays ``--cell_bench``'s open-loop row: a
uniform arrival stream at ``offered_rps``, a shared FIFO the client
workers pull in order, and each op routed to its key's owning cell by
the REAL ``cells.cell.cell_for_node`` consistent hash — so the
hot/cold split over the 256-key space is byte-for-byte the production
ring's, not a modeled approximation.

Each cell's journaled mutation path is a serialized resource (the
PR-13 append lock): an op holds its worker from pull to completion
and holds the owning cell for ``floor_ms + overhead_ms`` — the
modeled durable-log floor plus one calibrated constant for the
request path around it (gRPC hop, handler, commit bookkeeping).  The
calibration point is the committed 1-cell floored row of
``CELL_BENCH_CPU.json``; every other row is a prediction.  The convoy
effect the real bench measures — workers FIFO-blocked behind the hot
cell starve the cold cells — emerges from the same structure here, it
is not programmed in.

No randomness anywhere: arrivals are uniform (the bench's arrival
loop is deterministic), routing is the consistent hash, service is
constant — a double run is byte-identical by construction, and the
determinism test pins it anyway.
"""

from __future__ import annotations

import heapq
from typing import Any, Dict, List

from dlrover_tpu.cells.cell import cell_for_node


class CellPlaneSim:
    """One cell-bench row in virtual time."""

    def __init__(self, n_cells: int, floor_ms: float,
                 offered_rps: float, clients: int,
                 duration_s: float, warmup_s: float,
                 overhead_ms: float, n_keys: int = 256):
        self.n_cells = int(n_cells)
        self.floor_ms = float(floor_ms)
        self.offered_rps = float(offered_rps)
        self.clients = int(clients)
        self.duration_s = float(duration_s)
        self.warmup_s = float(warmup_s)
        self.overhead_ms = float(overhead_ms)
        self.n_keys = int(n_keys)

    def run(self) -> Dict[str, Any]:
        cids = [f"cell{i}" for i in range(self.n_cells)]
        owner = {k: cell_for_node(k, cids)
                 for k in range(self.n_keys)}
        svc = (self.floor_ms + self.overhead_ms) / 1e3
        period = 1.0 / max(1.0, self.offered_rps)
        horizon = self.warmup_s + self.duration_s
        # Worker pool as a min-heap of (free_at, worker_id): the next
        # op goes to the earliest-free worker — the shared-FIFO pull.
        workers: List = [(0.0, w) for w in range(self.clients)]
        heapq.heapify(workers)
        cell_free = {c: 0.0 for c in cids}
        per_cell = {c: 0 for c in cids}
        completed = 0
        measured = 0
        i = 0
        at = 0.0
        while at < horizon:
            free_at, w = heapq.heappop(workers)
            cid = owner[i % self.n_keys]
            start = max(at, free_at, cell_free[cid])
            done = start + svc
            cell_free[cid] = done
            heapq.heappush(workers, (done, w))
            completed += 1
            per_cell[cid] += 1
            if self.warmup_s <= done < horizon:
                measured += 1
            i += 1
            at += period
        return {
            "cells": self.n_cells,
            "floor_ms": self.floor_ms,
            "offered_rps": round(self.offered_rps, 1),
            "ops_per_s": round(measured / self.duration_s, 1),
            "completed": completed,
            "errors": 0,
            "clients": self.clients,
            "duration_s": round(self.duration_s, 2),
            "per_cell": per_cell,
        }


def run_cell_rows(cell_counts, floor_ms: float, rate_mult: float,
                  clients: int, duration_s: float, warmup_s: float,
                  overhead_ms: float) -> List[Dict[str, Any]]:
    """The bench's row grid: for each cell count, a floored row and a
    floor_ms=0 honesty row, offered at ``rate_mult`` x the 1-cell
    floor ceiling (the bench's exact load rule)."""
    ceiling = 1000.0 / max(floor_ms, 1e-9)
    offered = ceiling * rate_mult
    rows = []
    for n in cell_counts:
        for f in (floor_ms, 0.0):
            rows.append(CellPlaneSim(
                n, f, offered, clients, duration_s, warmup_s,
                overhead_ms=overhead_ms,
            ).run())
    return rows
