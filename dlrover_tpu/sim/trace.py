"""Synthetic fleet traces: diurnal load, hot-cell skew, chaos storms.

:class:`TraceGenerator` is a registered policy object and a pure
function of its :class:`TraceConfig` — the VirtualFlow premise
(capacity planning as a function of predicted load) is only testable
if the load itself replays bit-for-bit.  Every number it emits is
derived through :mod:`dlrover_tpu.sim.rand`'s coordinate hashing, so
``TraceGenerator(cfg).arrivals(step)`` is the same tuple on every
call, every run, every machine with the same ``cfg.seed``.

The trace grammar (README "Wind tunnel" documents it for PR authors):

* **load**: a sinusoidal diurnal rate around ``base_rps`` with
  amplitude ``diurnal_amp`` (trough at t=0, peak at half period),
  split over cells by a Zipf(``zipf_a``) share vector — cell 0 is the
  hot region; per-(step, cell) request counts are Poisson draws.
* **storms**: first-class trace events, not harness hacks.  A
  :class:`StormSpec` names a kind (``blackout`` — the named cells
  answer nothing for the window; ``net_gray`` — cross-cell transfers
  succeed but arrive ``delay_steps`` late and duplicate with
  probability ``severity``; ``churn`` — a wave that detaches
  ``severity`` of each named cell's nodes, which rejoin after the
  window), a window ``[at_s, at_s + duration_s)`` and the target
  cells.  Correlated failure is the default posture: one storm, many
  cells, same instant.
* **churn noise**: below storm scale, background node churn per
  (step, cell) is itself a seeded Poisson draw at ``churn_rate_s``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Tuple

from .rand import cdf_of, poisson, u01, zipf_shares


@dataclasses.dataclass(frozen=True)
class StormSpec:
    """One chaos storm as a trace event (see module doc for kinds)."""

    kind: str                  # "blackout" | "net_gray" | "churn"
    at_s: float
    duration_s: float
    cells: Tuple[int, ...] = ()
    #: net_gray: duplicate probability; churn: fraction detached.
    severity: float = 0.0
    #: net_gray: extra transfer latency, in whole steps.
    delay_steps: int = 1

    def active(self, t: float) -> bool:
        return self.at_s <= t < self.at_s + self.duration_s


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Everything the trace is a function of.  Frozen: the config IS
    the trace identity (plus nothing)."""

    seed: int = 0
    n_cells: int = 24
    nodes: int = 10000
    duration_s: float = 86400.0
    step_s: float = 30.0
    base_rps: float = 1000.0
    diurnal_amp: float = 0.6
    diurnal_period_s: float = 86400.0
    zipf_a: float = 0.6
    churn_rate_s: float = 0.001   # background leaves/s per cell
    storms: Tuple[StormSpec, ...] = ()

    @property
    def n_steps(self) -> int:
        return int(self.duration_s / self.step_s)


class TraceGenerator:
    """Pure trace oracle: same config -> same trace, query by query."""

    def __init__(self, config: TraceConfig):
        self.cfg = config
        self._shares = zipf_shares(config.n_cells, config.zipf_a)
        self._cdf = cdf_of(self._shares)

    # -- load --------------------------------------------------------------

    def rate_at(self, t: float) -> float:
        """Fleet-wide arrival rate (rps) at virtual time ``t``: the
        diurnal sinusoid, trough at t=0."""
        cfg = self.cfg
        phase = 2.0 * math.pi * (t / cfg.diurnal_period_s)
        return max(
            0.0,
            cfg.base_rps * (1.0 + cfg.diurnal_amp * -math.cos(phase)),
        )

    def share(self, cell: int) -> float:
        return self._shares[cell]

    def arrivals(self, step: int) -> Tuple[int, ...]:
        """Request count per cell for ``step`` (Poisson per cell)."""
        cfg = self.cfg
        t = step * cfg.step_s
        lam_total = self.rate_at(t) * cfg.step_s
        return tuple(
            poisson(cfg.seed, f"arr:{step}:{c}",
                    lam_total * self._shares[c])
            for c in range(cfg.n_cells)
        )

    def home_of(self, step: int, n: int) -> int:
        """Home cell of the ``n``-th request of ``step`` — the
        per-request view of the same Zipf split, for micro rigs."""
        return self._pick_cell(u01(self.cfg.seed, f"home:{step}", n))

    def _pick_cell(self, u: float) -> int:
        lo, hi = 0, len(self._cdf) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if self._cdf[mid] < u:
                lo = mid + 1
            else:
                hi = mid
        return lo

    # -- chaos -------------------------------------------------------------

    def storms_at(self, t: float) -> Tuple[StormSpec, ...]:
        """Storms whose window covers virtual time ``t``, in trace
        order (the declaration order in the config)."""
        return tuple(s for s in self.cfg.storms if s.active(t))

    def dead_cells(self, t: float) -> Tuple[int, ...]:
        """Cells blacked out at ``t`` (sorted, deduplicated)."""
        dead: List[int] = []
        for s in self.cfg.storms:
            if s.kind == "blackout" and s.active(t):
                dead.extend(s.cells)
        return tuple(sorted({c: None for c in dead}))

    def gray_at(self, t: float) -> Tuple[StormSpec, ...]:
        return tuple(s for s in self.cfg.storms
                     if s.kind == "net_gray" and s.active(t))

    def gray_duplicates(self, step: int, cell: int, n: int,
                        severity: float) -> bool:
        """Does the ``n``-th gray transfer out of ``cell`` at ``step``
        get duplicated?  A seeded coin, same shape as the chaos plan's
        crc32 decision."""
        return u01(self.cfg.seed, f"gray:{step}:{cell}", n) < severity

    def churn_leaves(self, step: int, cell: int) -> int:
        """Background node departures for (step, cell)."""
        cfg = self.cfg
        return poisson(cfg.seed, f"churn:{step}:{cell}",
                       cfg.churn_rate_s * cfg.step_s)

    # -- identity ----------------------------------------------------------

    def describe(self) -> Dict[str, object]:
        """A json-stable summary for event logs and artifacts."""
        cfg = self.cfg
        return {
            "seed": cfg.seed,
            "n_cells": cfg.n_cells,
            "nodes": cfg.nodes,
            "duration_s": cfg.duration_s,
            "step_s": cfg.step_s,
            "base_rps": cfg.base_rps,
            "diurnal_amp": cfg.diurnal_amp,
            "zipf_a": cfg.zipf_a,
            "hot_share": round(self._shares[0], 4) if self._shares
            else 0.0,
            "storms": [dataclasses.asdict(s) for s in cfg.storms],
        }
