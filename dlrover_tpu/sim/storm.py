"""The 10,000-node wind tunnel: a fleet-scale chaos-storm rig.

:class:`FleetStormSim` drives the REAL control-plane policy objects —
per-cell :class:`~dlrover_tpu.fleet.policy.ChipBorrowArbiter` over
real :class:`~dlrover_tpu.sim.fleet.SimRole` adapters, the federation
triple :func:`~dlrover_tpu.cells.federation.merge_cell_snapshots` /
:func:`~dlrover_tpu.cells.federation.place_roles` /
:func:`~dlrover_tpu.cells.federation.plan_moves` actuated by a real
:class:`~dlrover_tpu.fleet.policy.CrossCellMover`, the
:class:`~dlrover_tpu.serving.spillover.SpilloverPolicy` forward/stay
decision, :func:`~dlrover_tpu.serving.autoscale.decide` and the
:class:`~dlrover_tpu.common.hashring.HashRing` re-home path — over a
synthetic 10,000-node fleet and a day-long diurnal trace, in seconds
of wall clock.  Only the *plant* is simulated (request counts age
through per-cell backlog buckets instead of per-request objects); the
*decisions* are the production code paths, unmodified.

Two modes make the paper's argument measurable:

* ``static`` — partitioned cells: a request's home cell is its fate.
  Blackouts lose the dead cells' arrivals, hot cells drown alone
  (chip borrows still run — the delta below isolates the DATA plane).
* ``global`` — the full PR-17 posture: dead cells' arrivals re-home
  through the consistent-hash ring over the surviving cell set,
  saturated cells spill overflow to policy-chosen siblings, and the
  federation's move orders rebalance blocks between cells.

Chaos storms come from the trace (:class:`StormSpec`), not from the
harness: correlated blackouts (the N hottest cells at the diurnal
peak), gray networks (spill transfers DELAYED and DUPLICATED, never
dropped — the receiver dedupes), and churn waves.  Every run appends
one JSON line per step to an event log and returns its sha256 — the
double-run law for a 10k-node day is one string comparison.

Accounting is conservative by construction and checked:
``offered == served + timeout + blackout_lost + stranded + backlog +
in_transit`` at the end of every run (duplicates are counted apart —
they are copies, not offered load).
"""

from __future__ import annotations

import hashlib
import json
import zlib
from typing import Any, Dict, List, Optional, Tuple

from dlrover_tpu.cells.cell import cell_for_node
from dlrover_tpu.cells.federation import (
    merge_cell_snapshots,
    place_roles,
    plan_moves,
)
from dlrover_tpu.fleet.policy import (
    BorrowPolicy,
    ChipBorrowArbiter,
    CrossCellMover,
    MovePolicy,
)
from dlrover_tpu.fleet.role import RoleSpec
from dlrover_tpu.serving.autoscale import ScalePolicy, ScaleState, decide
from dlrover_tpu.serving.spillover import SpilloverConfig, SpilloverPolicy

from .clock import VirtualClock
from .fleet import SimRole
from .trace import TraceConfig, TraceGenerator

#: Coarse shard keys for the re-home path: dead-cell load re-homes by
#: the REAL ring over these keys, so the survivor split is exactly
#: what production consistent hashing would produce.
N_SHARD_KEYS = 256


class _Cell:
    """One cell's simulated plant: roles, backlog, counters."""

    def __init__(self, cid: str, blocks: int, block_nodes: int):
        self.cid = cid
        self.blocks = blocks
        srv = blocks // 2
        self.serving = SimRole(
            RoleSpec(name=f"{cid}/serving", desired=srv, min_count=2,
                     max_count=blocks),
            prefix=f"{cid}/srv", block_nodes=block_nodes,
        )
        self.training = SimRole(
            RoleSpec(name=f"{cid}/training", desired=blocks - srv,
                     min_count=2, max_count=blocks),
            prefix=f"{cid}/trn", block_nodes=block_nodes,
        )
        #: FIFO backlog as [enqueue_step, count] buckets, oldest first.
        self.backlog: List[List[int]] = []
        self.dead = False

    def backlog_n(self) -> int:
        return sum(n for _, n in self.backlog)

    def enqueue(self, step: int, n: int) -> None:
        if n <= 0:
            return
        if self.backlog and self.backlog[-1][0] == step:
            self.backlog[-1][1] += n
        else:
            self.backlog.append([step, n])

    def enqueue_aged(self, buckets: List[List[int]]) -> None:
        """Merge transferred buckets, preserving request age (SLO
        clocks keep running across the wire)."""
        for enq, n in buckets:
            if n <= 0:
                continue
            placed = False
            for b in self.backlog:
                if b[0] == enq:
                    b[1] += n
                    placed = True
                    break
            if not placed:
                self.backlog.append([enq, n])
        self.backlog.sort(key=lambda b: b[0])


class FleetStormSim:
    """One mode's day in the wind tunnel.  ``run()`` returns the
    result row; see the module doc for the physics."""

    def __init__(
        self,
        trace_cfg: TraceConfig,
        mode: str = "global",
        per_block_rps: float = 6.0,
        block_nodes: int = 8,
        slo_steps: int = 2,
        timeout_steps: int = 10,
        fed_every: int = 10,
        mover_passes: int = 2,
        spill_rounds: int = 3,
        spill_cap: int = 2000,
    ):
        if mode not in ("static", "global"):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        self.trace = TraceGenerator(trace_cfg)
        self.cfg = trace_cfg
        self.per_block_rps = float(per_block_rps)
        self.slo_steps = int(slo_steps)
        self.timeout_steps = int(timeout_steps)
        self.fed_every = int(fed_every)
        self.mover_passes = int(mover_passes)
        self.spill_rounds = int(spill_rounds)
        self.spill_cap = int(spill_cap)
        self.clock = VirtualClock()

        # -- fleet construction: cfg.nodes spread over cfg.n_cells in
        # block_nodes-node blocks, remainder blocks to the first cells.
        n = trace_cfg.n_cells
        base = trace_cfg.nodes // (n * block_nodes)
        extra = (trace_cfg.nodes - base * n * block_nodes) // block_nodes
        self.cell_ids = [f"c{i:02d}" for i in range(n)]
        self.cells: Dict[str, _Cell] = {}
        for i, cid in enumerate(self.cell_ids):
            blocks = base + (1 if i < extra else 0)
            self.cells[cid] = _Cell(cid, blocks, block_nodes)
        self.node_count = sum(
            c.serving.node_count + c.training.node_count
            for c in self.cells.values()
        )

        # -- the real policy objects under test.
        self.spill_policy = SpilloverPolicy(
            SpilloverConfig(max_hops=1, spill_at=1.0,
                            sibling_headroom=0.85,
                            failure_cooldown_s=5.0 * trace_cfg.step_s),
            clock=self.clock,
        )
        self.arbiters: Dict[str, ChipBorrowArbiter] = {}
        for cid in self.cell_ids:
            cell = self.cells[cid]
            self.arbiters[cid] = ChipBorrowArbiter(
                lender=cell.training,
                borrower=cell.serving,
                policy=BorrowPolicy(
                    queue_high_per_member=60.0, spike_patience=2,
                    queue_low_per_member=5.0, decay_patience=8,
                    max_borrow=4, cooldown_passes=4,
                ),
                signal_fn=(lambda c=cell: {
                    "queue_depth": c.backlog_n(),
                    "members_alive": c.serving.count,
                }),
                scope=cid,
                hold_fn=(lambda c=cell: c.dead),
            )
        self._orders: List[tuple] = []
        self.mover = CrossCellMover(
            orders_fn=self._live_orders,
            cells={
                cid: {"serving": self.cells[cid].serving,
                      "training": self.cells[cid].training}
                for cid in self.cell_ids
            },
            policy=MovePolicy(drain_budget_passes=20, cooldown_passes=2),
        )
        self.scale_states: Dict[str, ScaleState] = {
            cid: ScaleState() for cid in self.cell_ids
        }
        self.scale_policy = ScalePolicy(
            min_replicas=2, max_replicas=10_000,
            queue_high_per_replica=30.0, up_patience=2,
        )

        #: In-flight spill/re-home transfers:
        #: [deliver_step, dst_cid, buckets, dup_n].
        self.transfers: List[List[Any]] = []
        self._ring_cache: Dict[Tuple[str, ...], Dict[int, str]] = {}
        self._home_keys = {
            cid: [k for k in range(N_SHARD_KEYS)
                  if cell_for_node(k, self.cell_ids) == cid]
            for cid in self.cell_ids
        }

        # -- counters (fleet totals; conservation checked at the end).
        self.offered = 0
        self.served = 0
        self.served_in_slo = 0
        self.timeout = 0
        self.blackout_lost = 0
        self.stranded = 0
        self.spilled = 0
        self.spill_ingress = 0
        self.rehomed = 0
        self.dup_dropped = 0
        self.storm_offered = 0
        self.storm_in_slo = 0
        self.storm_lost = 0
        self._storm_tail = 0
        self._digest = hashlib.sha256()
        self._log_lines = 0

    # -- helpers -------------------------------------------------------------

    def _alive(self) -> List[str]:
        return [cid for cid in self.cell_ids
                if not self.cells[cid].dead]

    def _owner_map(self, alive: List[str]) -> Dict[int, str]:
        key = tuple(alive)
        got = self._ring_cache.get(key)
        if got is None:
            got = {k: cell_for_node(k, alive)
                   for k in range(N_SHARD_KEYS)}
            self._ring_cache[key] = got
        return got

    def _live_orders(self) -> List[tuple]:
        """Mover's order feed: the latest federation plan, minus any
        order touching a currently dead cell."""
        return [o for o in self._orders
                if not self.cells[o[1]].dead
                and not self.cells[o[2]].dead]

    def _capacity(self, cell: _Cell) -> int:
        """Requests one step of this cell's serving pool absorbs."""
        if cell.dead:
            return 0
        return int(cell.serving.count * self.per_block_rps
                   * self.cfg.step_s)

    def _rehome(self, step: int, src: str, n: int,
                alive: List[str]) -> Dict[str, int]:
        """Split ``n`` dead-homed requests over the survivors the way
        the REAL ring does: the dead cell's shard keys re-hash over
        the alive set, load follows the keys."""
        out: Dict[str, int] = {}
        keys = self._home_keys[src]
        if not keys or not alive:
            return out
        owners = self._owner_map(alive)
        per, rem = divmod(n, len(keys))
        for j, k in enumerate(keys):
            share = per + (1 if j < rem else 0)
            if share <= 0:
                continue
            dst = owners[k]
            out[dst] = out.get(dst, 0) + share
        return out

    # -- one step ------------------------------------------------------------

    def _storm_flags(self, t: float):
        dead_idx = self.trace.dead_cells(t)
        grays = self.trace.gray_at(t)
        churn_storms = [s for s in self.trace.storms_at(t)
                        if s.kind == "churn"]
        return dead_idx, grays, churn_storms

    def _apply_blackouts(self, dead_idx: Tuple[int, ...]) -> int:
        """Flip cell liveness to match the trace; returns requests
        stranded by cells that died this step."""
        stranded = 0
        dead_now = {self.cell_ids[i] for i in dead_idx}
        for cid in self.cell_ids:
            cell = self.cells[cid]
            if cid in dead_now and not cell.dead:
                cell.dead = True
                lost = cell.backlog_n()
                stranded += lost
                cell.backlog = []
            elif cid not in dead_now and cell.dead:
                cell.dead = False
        return stranded

    def _apply_churn(self, step: int, churn_storms) -> int:
        """Storm waves + background churn; returns members failed."""
        failed = 0
        t = step * self.cfg.step_s
        for s in churn_storms:
            # The wave hits once, at the storm's first step.
            if int(s.at_s / self.cfg.step_s) != step:
                continue
            for i in s.cells:
                cell = self.cells[self.cell_ids[i]]
                if cell.dead:
                    continue
                failed += cell.serving.fail(
                    int(cell.serving.count * s.severity)
                )
        for i, cid in enumerate(self.cell_ids):
            cell = self.cells[cid]
            if cell.dead:
                continue
            leaves = self.trace.churn_leaves(step, i)
            if leaves:
                role = cell.serving if (step + i) % 2 == 0 \
                    else cell.training
                failed += role.fail(leaves)
        return failed

    def _deliver_transfers(self, step: int, alive: List[str]) -> int:
        """Land transfers due this step; gray duplicates are deduped
        at the receiver.  Returns requests delivered."""
        due = [tr for tr in self.transfers if tr[0] <= step]
        if not due:
            return 0
        self.transfers = [tr for tr in self.transfers if tr[0] > step]
        landed = 0
        for _, dst, buckets, dup_n in due:
            self.dup_dropped += dup_n
            n = sum(b[1] for b in buckets)
            cell = self.cells[dst]
            if cell.dead:
                # The target died while the transfer was in flight:
                # re-home again over the current survivor set.
                if not alive:
                    self.blackout_lost += n
                    continue
                for nxt, share in sorted(
                        self._rehome(step, dst, n, alive).items()):
                    self.transfers.append(
                        [step + 1, nxt, [[buckets[0][0], share]], 0]
                    )
                continue
            cell.enqueue_aged(buckets)
            self.spill_ingress += n
            landed += n
        return landed

    def _serve(self, step: int, cell: _Cell) -> Tuple[int, int, int]:
        """Drain one step of capacity FIFO; returns (served, in_slo,
        timed_out)."""
        # Age out requests past the deadline first (they would be
        # rejected by the gateway's deadline sweep, not served late).
        timed_out = 0
        keep: List[List[int]] = []
        for enq, n in cell.backlog:
            if step - enq > self.timeout_steps:
                timed_out += n
            else:
                keep.append([enq, n])
        cell.backlog = keep
        cap = self._capacity(cell)
        served = in_slo = 0
        while cap > 0 and cell.backlog:
            enq, n = cell.backlog[0]
            take = min(n, cap)
            served += take
            if step - enq <= self.slo_steps:
                in_slo += take
            cap -= take
            if take == n:
                cell.backlog.pop(0)
            else:
                cell.backlog[0][1] = n - take
        return served, in_slo, timed_out

    def _spill(self, step: int, alive: List[str],
               grays) -> Tuple[int, int]:
        """Policy-gated overflow forwarding for every saturated cell;
        returns (spilled, duplicated)."""
        spilled = dup_total = 0
        views = {}
        for cid in self.cell_ids:
            cell = self.cells[cid]
            cap = max(1, self._capacity(cell)) if not cell.dead else 1
            views[cid] = {
                "alive": not cell.dead,
                "pressure": round(cell.backlog_n() / cap, 4),
            }
        for cid in alive:
            cell = self.cells[cid]
            cap = max(1, self._capacity(cell))
            overflow = cell.backlog_n() - cap
            rounds = 0
            while overflow > 0 and rounds < self.spill_rounds:
                rounds += 1
                local = {"pressure": views[cid]["pressure"],
                         "draining": False}
                sibs = {c: views[c] for c in self.cell_ids if c != cid}
                d = self.spill_policy.decide(local, sibs, hops=0)
                if not d.forward:
                    break
                chunk = min(overflow, self.spill_cap)
                buckets = self._take_newest(cell, chunk)
                moved = sum(b[1] for b in buckets)
                if moved <= 0:
                    break
                delay = 1
                dup_n = 0
                for s in grays:
                    touched = {self.cell_ids[i] for i in s.cells}
                    if cid in touched or d.target in touched:
                        delay += s.delay_steps
                        dup_n += sum(
                            1 for j in range(moved)
                            if self.trace.gray_duplicates(
                                step, self.cell_ids.index(cid), j,
                                s.severity)
                        )
                self.transfers.append(
                    [step + delay, d.target, buckets, dup_n]
                )
                spilled += moved
                dup_total += dup_n
                overflow -= moved
                tcap = max(1, self._capacity(self.cells[d.target]))
                views[d.target]["pressure"] = round(
                    views[d.target]["pressure"] + moved / tcap, 4
                )
        return spilled, dup_total

    @staticmethod
    def _take_newest(cell: _Cell, n: int) -> List[List[int]]:
        """Pull up to ``n`` requests from the NEWEST buckets — the
        router spills fresh admissions, never the queue head the local
        pool is about to serve."""
        taken: List[List[int]] = []
        while n > 0 and cell.backlog:
            enq, have = cell.backlog[-1]
            take = min(have, n)
            taken.append([enq, take])
            n -= take
            if take == have:
                cell.backlog.pop()
            else:
                cell.backlog[-1][1] = have - take
        taken.reverse()
        return taken

    def _federate(self, step: int) -> None:
        """The real federation pass: merge -> place -> diff."""
        alive = self._alive()
        snaps = []
        for cid in alive:
            cell = self.cells[cid]
            snaps.append({
                "cell_id": cid,
                "nodes": cell.serving.node_count
                + cell.training.node_count,
                "tasks_doing": self._capacity(cell),
                "tasks_pending": cell.backlog_n(),
                "placement_epoch": step // self.fed_every,
                "pools": {
                    "serving": {
                        "alive": cell.serving.count,
                        "slots": cell.serving.count,
                        "assigned": min(cell.serving.count,
                                        cell.backlog_n()),
                        "queue_depth": cell.backlog_n(),
                    },
                },
            })
        merged = merge_cell_snapshots(snaps)
        caps = {cid: {"capacity": self.cells[cid].blocks}
                for cid in alive}
        demands = {
            "serving": sum(self.cells[c].serving.spec.desired
                           for c in alive),
            "training": sum(self.cells[c].training.spec.desired
                            for c in alive),
        }
        # Training stays pinned where it runs (collectives in place);
        # serving is the mobile role the mover rebalances.  The
        # planner's one opinion: cells under sustained queue pressure
        # get pinned ABOVE the uniform spread — the diff against the
        # current placement becomes the mover's move orders (capacity
        # follows load, the VirtualFlow argument).
        uniform = demands["serving"] // max(1, len(alive))
        pressured = sorted(
            (
                (ent["tasks_pending"]
                 / max(1, ent["tasks_doing"]), cid)
                for cid, ent in merged["cells"].items()
            ),
            reverse=True,
        )
        pinned_serving = {
            cid: min(self.cells[cid].blocks, uniform + 2)
            for p, cid in pressured[:2] if p > 0.5
        }
        pinned = {"training": {
            c: self.cells[c].training.spec.desired for c in alive
        }}
        if pinned_serving:
            pinned["serving"] = pinned_serving
        target = place_roles(caps, demands, pinned=pinned)
        current = {
            "serving": {c: self.cells[c].serving.count for c in alive},
            "training": {c: self.cells[c].training.count
                         for c in alive},
        }
        self._orders = plan_moves(current, target)
        self._merged_alive = merged.get("cells_alive", len(alive))

    def _step(self, step: int) -> Dict[str, Any]:
        t = step * self.cfg.step_s
        self.clock.advance_to(t)
        dead_idx, grays, churn_storms = self._storm_flags(t)
        stranded = self._apply_blackouts(dead_idx)
        self.stranded += stranded
        churned = self._apply_churn(step, churn_storms)
        alive = self._alive()

        delivered = self._deliver_transfers(step, alive)

        # -- arrivals.
        arr = self.trace.arrivals(step)
        offered = sum(arr)
        self.offered += offered
        lost = 0
        rehomed = 0
        for i, cid in enumerate(self.cell_ids):
            n = arr[i]
            if n <= 0:
                continue
            cell = self.cells[cid]
            if not cell.dead:
                cell.enqueue(step, n)
                continue
            if self.mode == "static" or not alive:
                lost += n
                continue
            for dst, share in sorted(
                    self._rehome(step, cid, n, alive).items()):
                self.cells[dst].enqueue(step, share)
            rehomed += n
        self.blackout_lost += lost
        self.rehomed += rehomed

        # -- the autoscale opinion (logged; capacity moves via the
        # borrow arbiter and the mover, which conserve nodes).
        targets = {}
        for cid in alive:
            cell = self.cells[cid]
            cap = max(1, self._capacity(cell))
            targets[cid] = decide(
                {
                    "replicas_alive": cell.serving.count,
                    "queue_depth": cell.backlog_n(),
                    "occupancy": min(1.0, round(arr[
                        self.cell_ids.index(cid)] / cap, 4)),
                },
                self.scale_policy,
                self.scale_states[cid],
            )

        # -- serve one step of capacity everywhere.
        served = in_slo = timed_out = 0
        for cid in alive:
            s, g, to = self._serve(step, self.cells[cid])
            served += s
            in_slo += g
            timed_out += to
        self.served += served
        self.served_in_slo += in_slo
        self.timeout += timed_out

        # -- the data plane (global mode only): overflow spills.
        spilled = dup_n = 0
        if self.mode == "global":
            spilled, dup_n = self._spill(step, alive, grays)
            self.spilled += spilled

        # -- the control plane: borrows, supervision, federation.
        for cid in alive:
            self.arbiters[cid].step()
            self.cells[cid].serving.reconcile()
            self.cells[cid].training.reconcile()
        if self.mode == "global" and step % self.fed_every == 0:
            self._federate(step)
            for _ in range(self.mover_passes):
                self.mover.step()
        elif self.mode == "global":
            for _ in range(self.mover_passes):
                self.mover.step()

        # -- storm-window accounting (blackout window + a 1h tail).
        in_storm = bool(dead_idx)
        if in_storm:
            self._storm_tail = int(3600.0 / self.cfg.step_s)
        elif self._storm_tail > 0:
            self._storm_tail -= 1
        if in_storm or self._storm_tail > 0:
            self.storm_offered += offered
            self.storm_in_slo += in_slo
            self.storm_lost += lost + stranded

        backlogs = tuple(self.cells[c].backlog_n()
                         for c in self.cell_ids)
        line = {
            "t": step,
            "off": offered,
            "rh": rehomed,
            "sv": served,
            "slo": in_slo,
            "to": timed_out,
            "lost": lost,
            "str": stranded,
            "sp": spilled,
            "dl": delivered,
            "dup": dup_n,
            "bl": sum(backlogs),
            "bh": zlib.crc32(repr(backlogs).encode()),
            "dead": list(dead_idx),
            "ch": churned,
            "bor": sum(a.borrowed for a in self.arbiters.values()),
            "mv": self.mover.moved,
            "lad": self.mover.laddered,
            "tgt": zlib.crc32(repr(sorted(targets.items())).encode()),
        }
        self._digest.update(
            (json.dumps(line, sort_keys=True) + "\n").encode()
        )
        self._log_lines += 1
        return line

    # -- the run -------------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        for step in range(self.cfg.n_steps):
            self._step(step)
        backlog_final = sum(c.backlog_n() for c in self.cells.values())
        in_transit = sum(sum(b[1] for b in tr[2])
                         for tr in self.transfers)
        accounted = (self.served + self.timeout + self.blackout_lost
                     + self.stranded + backlog_final + in_transit)
        storm_off = max(1, self.storm_offered)
        return {
            "mode": self.mode,
            "trace": self.trace.describe(),
            "nodes": self.node_count,
            "steps": self.cfg.n_steps,
            "offered": self.offered,
            "served": self.served,
            "served_in_slo": self.served_in_slo,
            "slo_goodput": round(
                self.served_in_slo / max(1, self.offered), 4),
            "timeout": self.timeout,
            "blackout_lost": self.blackout_lost,
            "stranded": self.stranded,
            "spilled": self.spilled,
            "spill_ingress": self.spill_ingress,
            "rehomed": self.rehomed,
            "dup_dropped": self.dup_dropped,
            "borrow_events": sum(len(a.events)
                                 for a in self.arbiters.values()),
            "moved_blocks": self.mover.moved,
            "laddered": self.mover.laddered,
            "storm_offered": self.storm_offered,
            "storm_in_slo": self.storm_in_slo,
            "storm_goodput": round(self.storm_in_slo / storm_off, 4),
            "storm_lost": self.storm_lost,
            "backlog_final": backlog_final,
            "in_transit_final": in_transit,
            "conservation_ok": accounted == self.offered,
            "event_log_lines": self._log_lines,
            "event_log_sha256": self._digest.hexdigest(),
        }
