"""The wind tunnel (ROADMAP item 7): deterministic fleet simulation.

A discrete-event harness that drives the repo's REAL registered
policy objects — gateway admission, spillover, autoscale, chip
borrows, federation placement and cross-cell moves — over synthetic
fleets and traces in virtual time.  Three rigs, one law:

* :class:`~dlrover_tpu.sim.serve.GlobalServeSim` — the micro rig: an
  event-by-event replay of ``bench.py --global_bench`` (real
  ``GatewayCore`` + ``CellSpillRouter`` per cell), fidelity-checked
  against the committed ``GLOBAL_BENCH_CPU.json`` rows.
* :class:`~dlrover_tpu.sim.cellsim.CellPlaneSim` — the control-plane
  rig: the cell bench's shard physics over the real consistent hash,
  fidelity-checked against ``CELL_BENCH_CPU.json``.
* :class:`~dlrover_tpu.sim.storm.FleetStormSim` — the macro rig:
  10,000 nodes, 24 cells, a day-long diurnal trace and chaos storms
  (correlated blackouts, gray networks, churn waves) no real bench
  could stage.
* :class:`~dlrover_tpu.sim.offline.OfflineTierSim` — the priority-
  class rig (ISSUE 20): the preemptible offline tier soaking the
  diurnal trough, instant reclaim at the peak, total evacuation
  under blackout storms — baseline vs offline over the same trace.

The law: same seed + same trace ⇒ byte-identical event log (the
double-run digest), because the only clock is the injected
:class:`~dlrover_tpu.sim.clock.VirtualClock` and the only randomness
is :mod:`~dlrover_tpu.sim.rand`'s coordinate hashing.
"""

from dlrover_tpu.sim.cellsim import CellPlaneSim, run_cell_rows
from dlrover_tpu.sim.clock import VirtualClock
from dlrover_tpu.sim.events import SimScheduler
from dlrover_tpu.sim.fleet import SimRole
from dlrover_tpu.sim.offline import OfflineTierSim, PreemptibleSimRole
from dlrover_tpu.sim.serve import GlobalServeSim, run_global_rows
from dlrover_tpu.sim.storm import FleetStormSim
from dlrover_tpu.sim.trace import StormSpec, TraceConfig, TraceGenerator

__all__ = [
    "CellPlaneSim",
    "FleetStormSim",
    "GlobalServeSim",
    "OfflineTierSim",
    "PreemptibleSimRole",
    "SimRole",
    "SimScheduler",
    "StormSpec",
    "TraceConfig",
    "TraceGenerator",
    "VirtualClock",
    "run_cell_rows",
    "run_global_rows",
]
