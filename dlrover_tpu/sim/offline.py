"""The offline-tier wind tunnel: priority classes at 10k-node scale.

:class:`OfflineTierSim` drives the REAL priority-class objects — the
:class:`~dlrover_tpu.offline.policy.OfflinePolicy` sizing decision and
a per-cell :class:`~dlrover_tpu.fleet.policy.ChipBorrowArbiter` whose
LENDER is the preemptible tier — over the same diurnal storm trace the
PR-18 rig replays.  Only the plant is simulated (chunks and requests
are counts, not objects); the decisions are production code paths.

Two modes make ISSUE 20's argument measurable:

* ``baseline`` — no offline tier.  The online pool runs at its
  mean-demand size and borrows PEAK capacity from a plain idle-chip
  pool through the arbiter; trough chips simply idle.
* ``offline`` — the same online plant, but the idle pool is replaced
  by the preemptible tier: chips the online roles are not using run
  batch chunks.  The lender now has ``preemptible = True``, so (a)
  every reclaim requeues the victim's chunks (exactly-once is the
  journal's job in production; conservation is the sim's law) and
  (b) the arbiter charges NO cooldown on reclaims — online re-borrows
  at the next spike pass instead of waiting one out.

The three verdicts the bench derives from a baseline/offline pair:
online SLO goodput not regressed (the online plant only ever GAINS
capacity from the tier's cooldown exemption), fleet utilization
strictly higher (trough chips now work), and the measured reclaim
latency — steps the arbiter spends in LENDING before the chip is
granted to online work — bounded by ONE round.

Everything here is integer arithmetic over the seeded trace: no
clock, no randomness, no threads, no float in the event log.  Same
config + seed ⇒ byte-identical event log (sha256-pinned, the
double-run law).
"""

from __future__ import annotations

import hashlib
import json
import zlib
from typing import Any, Dict, List, Tuple

from dlrover_tpu.fleet.policy import (
    BorrowPolicy,
    ChipBorrowArbiter,
    LENDING,
)
from dlrover_tpu.fleet.role import RoleSpec
from dlrover_tpu.offline.policy import OfflinePolicy
from dlrover_tpu.scheduler.platform import chip_speed_weight

from .fleet import SimRole
from .trace import TraceConfig, TraceGenerator

#: Hardware generations cycled over cells (cell i gets GENERATIONS[i %
#: len]) so every run exercises mixed-fleet speed weights (ISSUE 20c).
GENERATIONS = ("v4", "v5e", "v5p", "v6e")


class PreemptibleSimRole(SimRole):
    """The offline tier's count-backed role: same SimRole machinery,
    ``preemptible = True`` — which is the ONLY thing the arbiter's
    cooldown exemption keys on."""

    preemptible = True


class _Cell:
    """One cell's plant: an online pool, a lender pool (idle chips in
    ``baseline`` mode, the preemptible tier in ``offline`` mode), an
    online request backlog, and the offline chunk ledger."""

    def __init__(self, cid: str, blocks: int, block_nodes: int,
                 online_base: int, offline_mode: bool):
        self.cid = cid
        self.blocks = blocks
        self.online = SimRole(
            RoleSpec(name=f"{cid}/online", desired=online_base,
                     min_count=1, max_count=blocks),
            prefix=f"{cid}/on", block_nodes=block_nodes,
            drain_passes=1,
        )
        lender_cls = PreemptibleSimRole if offline_mode else SimRole
        self.lender = lender_cls(
            RoleSpec(name=f"{cid}/offline" if offline_mode
                     else f"{cid}/idle",
                     desired=0, min_count=0, max_count=blocks),
            prefix=f"{cid}/off" if offline_mode else f"{cid}/idle",
            block_nodes=block_nodes, drain_passes=1,
        )
        #: Online FIFO backlog as [enqueue_step, count] buckets.
        self.backlog: List[List[int]] = []
        self.dead = False
        #: Chunks leased and not yet completed (counts, not objects).
        self.in_flight = 0
        #: Worker count at lease time — a later drop is a preemption
        #: and the difference's chunks requeue before completion.
        self.lease_workers = 0
        #: Integer tenths of chunk-throughput carry (weight 2.7 = 27).
        self.rem_tenths = 0
        #: Steps the arbiter has been in LENDING (reclaim in flight).
        self.lending_for = 0

    def backlog_n(self) -> int:
        return sum(n for _, n in self.backlog)

    def enqueue(self, step: int, n: int) -> None:
        if n <= 0:
            return
        if self.backlog and self.backlog[-1][0] == step:
            self.backlog[-1][1] += n
        else:
            self.backlog.append([step, n])


class OfflineTierSim:
    """One mode's day in the offline wind tunnel; ``run()`` returns
    the result row (see the module doc for the physics)."""

    def __init__(
        self,
        trace_cfg: TraceConfig,
        mode: str = "offline",
        per_block_rps: float = 6.0,
        block_nodes: int = 8,
        slo_steps: int = 2,
        timeout_steps: int = 10,
        submit_factor: float = 0.8,
        reserve_chips: int = 0,
    ):
        if mode not in ("baseline", "offline"):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        self.offline_mode = mode == "offline"
        self.trace = TraceGenerator(trace_cfg)
        self.cfg = trace_cfg
        self.per_block_rps = float(per_block_rps)
        self.slo_steps = int(slo_steps)
        self.timeout_steps = int(timeout_steps)

        n = trace_cfg.n_cells
        base = trace_cfg.nodes // (n * block_nodes)
        extra = (trace_cfg.nodes - base * n * block_nodes) // block_nodes
        self.cell_ids = [f"c{i:02d}" for i in range(n)]
        #: Per-cell speed weight in integer TENTHS (v6e = 27): the one
        #: representation both throughput laws and the event log share.
        self.w_tenths: Dict[str, int] = {}
        self.cells: Dict[str, _Cell] = {}
        mean_step_load = trace_cfg.base_rps * trace_cfg.step_s
        for i, cid in enumerate(self.cell_ids):
            blocks = base + (1 if i < extra else 0)
            w = chip_speed_weight(GENERATIONS[i % len(GENERATIONS)])
            self.w_tenths[cid] = int(round(w * 10))
            # Online base size: the cell's MEAN demand in weighted
            # blocks — peaks are the arbiter's job, troughs the
            # tier's.  Same formula in both modes (trace-pure).
            cap_per_block = (self.per_block_rps * trace_cfg.step_s
                             * self.w_tenths[cid]) / 10.0
            want = int(mean_step_load * self.trace.share(i)
                       / max(1.0, cap_per_block)) + 1
            online_base = max(1, min(blocks - 1, want))
            self.cells[cid] = _Cell(
                cid, blocks, block_nodes, online_base,
                self.offline_mode,
            )
        self.total_blocks = sum(c.blocks for c in self.cells.values())
        self.node_count = self.total_blocks * block_nodes

        # The real policy objects under test.
        self.policy = OfflinePolicy(
            max_workers=0, chips_per_worker=1,
            reserve_chips=int(reserve_chips), chunks_per_worker=1,
        )
        self.arbiters: Dict[str, ChipBorrowArbiter] = {}
        for cid in self.cell_ids:
            cell = self.cells[cid]
            self.arbiters[cid] = ChipBorrowArbiter(
                lender=cell.lender,
                borrower=cell.online,
                policy=BorrowPolicy(
                    queue_high_per_member=30.0, spike_patience=2,
                    queue_low_per_member=2.0, decay_patience=6,
                    max_borrow=cell.blocks, cooldown_passes=4,
                ),
                signal_fn=(lambda c=cell: {
                    "queue_depth": c.backlog_n(),
                    "members_alive": c.online.count,
                }),
                scope=cid,
                hold_fn=(lambda c=cell: c.dead),
            )

        #: Chunks submitted to the (global) offline queue per step.
        self.submit_per_step = (
            int(self.total_blocks * float(submit_factor))
            if self.offline_mode else 0
        )
        self.chunk_backlog = 0

        # Fleet counters.
        self.offered = 0
        self.served = 0
        self.served_in_slo = 0
        self.timeout = 0
        self.blackout_lost = 0
        self.chunks_submitted = 0
        self.chunks_done = 0
        self.chunks_done_trough = 0
        self.chunk_requeues = 0
        self.reclaims = 0
        self.max_reclaim_rounds = 0
        self.evacuations_ok = True
        self.overcommit_steps = 0
        self.util_milli_sum = 0
        self._digest = hashlib.sha256()
        self._log_lines = 0

    # -- plant helpers ------------------------------------------------------

    def _capacity(self, cell: _Cell) -> int:
        """Requests one step of the cell's online pool absorbs
        (weighted: a v6e block drains 2.7x a v4 block)."""
        if cell.dead:
            return 0
        return int(cell.online.count * self.per_block_rps
                   * self.cfg.step_s * self.w_tenths[cell.cid]) // 10

    def _serve(self, step: int, cell: _Cell) -> Tuple[int, int, int]:
        timed_out = 0
        keep: List[List[int]] = []
        for enq, n in cell.backlog:
            if step - enq > self.timeout_steps:
                timed_out += n
            else:
                keep.append([enq, n])
        cell.backlog = keep
        cap = self._capacity(cell)
        served = in_slo = 0
        while cap > 0 and cell.backlog:
            enq, n = cell.backlog[0]
            take = min(n, cap)
            served += take
            if step - enq <= self.slo_steps:
                in_slo += take
            cap -= take
            if take == n:
                cell.backlog.pop(0)
            else:
                cell.backlog[0][1] = n - take
        return served, in_slo, timed_out

    def _requeue(self, cell: _Cell, n: int) -> None:
        n = min(max(0, n), cell.in_flight)
        if n <= 0:
            return
        cell.in_flight -= n
        self.chunk_backlog += n
        self.chunk_requeues += n

    def _offline_chunks(self, step: int, cell: _Cell,
                        trough: bool) -> int:
        """One cell's chunk cycle: requeue preempted leases, complete
        the survivors, lease against this step's worker throughput.
        Returns chunks completed."""
        workers = cell.lender.count
        # Preemption since lease time (arbiter lend, policy shrink,
        # churn): each departed worker's chunk requeues BEFORE any
        # completion is counted — zero lost work, possibly re-done.
        if workers < cell.lease_workers:
            self._requeue(cell, cell.lease_workers - workers)
        done = cell.in_flight
        cell.in_flight = 0
        self.chunks_done += done
        if trough:
            self.chunks_done_trough += done
        # Lease: weighted worker-steps of throughput, integer tenths.
        cell.rem_tenths += workers * self.w_tenths[cell.cid]
        cap = cell.rem_tenths // 10
        cell.rem_tenths -= cap * 10
        take = min(self.chunk_backlog, cap)
        self.chunk_backlog -= take
        cell.in_flight = take
        cell.lease_workers = workers
        return done

    # -- one step ------------------------------------------------------------

    def _step(self, step: int) -> Dict[str, Any]:
        t = step * self.cfg.step_s
        dead_idx = self.trace.dead_cells(t)
        dead_now = {self.cell_ids[i] for i in dead_idx}
        stranded = 0
        for cid in self.cell_ids:
            cell = self.cells[cid]
            if cid in dead_now and not cell.dead:
                cell.dead = True
                stranded += cell.backlog_n()
                cell.backlog = []
                # Blackout evacuation: every in-flight chunk requeues,
                # every offline worker is gone (the cell answers
                # nothing); the journal makes the replay exactly-once
                # in production — conservation is the law here.
                self._requeue(cell, cell.in_flight)
                cell.lender.fail(cell.lender.count)
                cell.lease_workers = 0
            elif cid not in dead_now and cell.dead:
                cell.dead = False
        self.blackout_lost += stranded

        # Background churn hits the online pool (supervision respawns
        # under the relaunch budget, exactly as the storm rig models).
        churned = 0
        for i, cid in enumerate(self.cell_ids):
            cell = self.cells[cid]
            if cell.dead:
                continue
            leaves = self.trace.churn_leaves(step, i)
            if leaves:
                churned += cell.online.fail(leaves)

        # Arrivals (dead cells' arrivals are lost: this rig is the
        # PRIORITY plane; re-homing is the PR-17 global rig's story).
        arr = self.trace.arrivals(step)
        offered = sum(arr)
        self.offered += offered
        lost = 0
        for i, cid in enumerate(self.cell_ids):
            cell = self.cells[cid]
            if cell.dead:
                lost += arr[i]
            else:
                cell.enqueue(step, arr[i])
        self.blackout_lost += lost

        # Offline submissions ride the global queue.
        if self.offline_mode:
            self.chunk_backlog += self.submit_per_step
            self.chunks_submitted += self.submit_per_step

        # Serve one step of online capacity everywhere.
        served = in_slo = timed_out = 0
        for cid in self.cell_ids:
            cell = self.cells[cid]
            if cell.dead:
                continue
            s, g, to = self._serve(step, cell)
            served += s
            in_slo += g
            timed_out += to
        self.served += served
        self.served_in_slo += in_slo
        self.timeout += timed_out

        # The control plane: the REAL arbiter decides peak borrows and
        # trough hand-backs; reconcile pumps drains and supervision.
        for cid in self.cell_ids:
            cell = self.cells[cid]
            if cell.dead:
                continue
            arb = self.arbiters[cid]
            arb.step()
            if arb.phase == LENDING:
                cell.lending_for += 1
                self.max_reclaim_rounds = max(
                    self.max_reclaim_rounds, cell.lending_for)
            else:
                if cell.lending_for > 0:
                    self.reclaims += 1
                cell.lending_for = 0
            cell.online.reconcile()
            cell.lender.reconcile()

        # The tier's own sizing: the REAL OfflinePolicy over idle
        # chips and backlog (baseline mode sizes the plain idle pool
        # with the same arithmetic so both modes' arbiters have chips
        # to lend at the peak).
        rate = self.trace.rate_at(t)
        trough = rate < self.cfg.base_rps
        done_step = 0
        for cid in self.cell_ids:
            cell = self.cells[cid]
            if cell.dead:
                continue
            if not cell.lender.drain_pending():
                idle = cell.blocks - cell.online.count \
                    - cell.lender.count
                # Baseline's idle pool is sized by the same policy
                # under a synthetic always-deep backlog: both modes'
                # arbiters see the same lendable supply at the peak.
                backlog = self.chunk_backlog if self.offline_mode \
                    else cell.blocks * 10
                target = self.policy.target_workers(
                    idle_chips=idle + cell.lender.count,
                    backlog_chunks=backlog,
                    online_pressure=(
                        self.arbiters[cid].phase == LENDING),
                    speed_weight=self.w_tenths[cid] / 10.0,
                )
                target = min(target, cell.lender.count + max(0, idle))
                delta = target - cell.lender.count
                if delta > 0:
                    cell.lender.spec.desired = target
                    cell.lender.spawn(delta)
                elif delta < 0:
                    cell.lender.spec.desired = target
                    cell.lender.fail(-delta)
            # Hard law: priority classes never overcommit a cell.
            over = (cell.online.count + cell.lender.count
                    - cell.blocks)
            if over > 0:
                self.overcommit_steps += 1
                cell.lender.spec.desired = max(
                    0, cell.lender.spec.desired - over)
                cell.lender.fail(over)
            if self.offline_mode:
                done_step += self._offline_chunks(step, cell, trough)
        for cell in self.cells.values():
            # The blackout law: a dead cell holds NO chunk and no
            # offline worker — evacuation is total, every step.
            if cell.dead and (cell.in_flight or cell.lender.count):
                self.evacuations_ok = False

        online_n = sum(c.online.count for c in self.cells.values()
                       if not c.dead)
        offline_n = sum(c.lender.count for c in self.cells.values()
                        if not c.dead) if self.offline_mode else 0
        self.util_milli_sum += (
            (online_n + offline_n) * 1000 // max(1, self.total_blocks)
        )

        backlogs = tuple(self.cells[c].backlog_n()
                         for c in self.cell_ids)
        line = {
            "t": step,
            "off": offered,
            "sv": served,
            "slo": in_slo,
            "to": timed_out,
            "lost": lost,
            "str": stranded,
            "ch": churned,
            "dead": list(dead_idx),
            "on": online_n,
            "ofw": offline_n,
            "bor": sum(a.borrowed for a in self.arbiters.values()),
            "cb": self.chunk_backlog,
            "cif": sum(c.in_flight for c in self.cells.values()),
            "cd": done_step,
            "rq": self.chunk_requeues,
            "bl": sum(backlogs),
            "bh": zlib.crc32(repr(backlogs).encode()),
        }
        self._digest.update(
            (json.dumps(line, sort_keys=True) + "\n").encode()
        )
        self._log_lines += 1
        return line

    # -- the run -------------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        for step in range(self.cfg.n_steps):
            self._step(step)
        in_flight = sum(c.in_flight for c in self.cells.values())
        chunk_accounted = (self.chunks_done + self.chunk_backlog
                           + in_flight)
        return {
            "mode": self.mode,
            "trace": self.trace.describe(),
            "nodes": self.node_count,
            "blocks": self.total_blocks,
            "steps": self.cfg.n_steps,
            "offered": self.offered,
            "served": self.served,
            "served_in_slo": self.served_in_slo,
            "slo_goodput": round(
                self.served_in_slo / max(1, self.offered), 4),
            "timeout": self.timeout,
            "blackout_lost": self.blackout_lost,
            "utilization": round(
                self.util_milli_sum / max(1, self.cfg.n_steps) / 1000,
                4),
            "borrow_events": sum(len(a.events)
                                 for a in self.arbiters.values()),
            "reclaims": self.reclaims,
            "max_reclaim_rounds": self.max_reclaim_rounds,
            "chunks_submitted": self.chunks_submitted,
            "chunks_done": self.chunks_done,
            "chunks_done_trough": self.chunks_done_trough,
            "chunk_requeues": self.chunk_requeues,
            "chunk_backlog_final": self.chunk_backlog,
            "chunk_in_flight_final": in_flight,
            "chunk_conservation_ok": (
                chunk_accounted == self.chunks_submitted),
            "evacuations_ok": self.evacuations_ok,
            "overcommit_steps": self.overcommit_steps,
            "event_log_lines": self._log_lines,
            "event_log_sha256": self._digest.hexdigest(),
        }
