"""The discrete-event heart of the wind tunnel.

:class:`SimScheduler` is a registered policy object (graftcheck's
DET70x families verify its whole method surface is ambient-effect
free): a seeded event queue over the injected :class:`VirtualClock`.
Determinism comes from two properties the double-run tests pin:

* ties break on an insertion sequence number, never on payload
  identity or hash order — two events at the same virtual instant
  always pop in the order they were pushed;
* popping an event *advances the injected clock* to the event's time,
  so every policy call made from a handler observes exactly the
  event's timestamp — there is no other source of time.

Handlers schedule follow-up events at or after "now"; a push into the
past is clamped to now (the simulated analogue of a late timer, which
fires immediately rather than rewriting history).
"""

from __future__ import annotations

import heapq
from typing import Any, List, Optional, Tuple

#: (time, seq, kind, payload) — seq is globally unique per scheduler,
#: so heap comparison never reaches the payload.
Event = Tuple[float, int, str, Any]


class SimScheduler:
    """A deterministic event queue bound to one virtual clock."""

    def __init__(self, clock):
        self.clock = clock
        self._heap: List[Event] = []
        self._seq = 0
        self.popped = 0

    def push(self, at: float, kind: str, payload: Any = None) -> int:
        """Schedule ``kind`` at virtual time ``at`` (clamped to now);
        returns the event's sequence number (its FIFO tie-break)."""
        now = self.clock()
        if at < now:
            at = now
        self._seq += 1
        heapq.heappush(self._heap, (float(at), self._seq, kind, payload))
        return self._seq

    def pop(self) -> Optional[Event]:
        """Next event in (time, insertion) order; advances the clock
        to its timestamp.  None when the queue is empty."""
        if not self._heap:
            return None
        ev = heapq.heappop(self._heap)
        self.clock.advance_to(ev[0])
        self.popped += 1
        return ev

    def peek_time(self) -> Optional[float]:
        return self._heap[0][0] if self._heap else None

    def __len__(self) -> int:
        return len(self._heap)

    def empty(self) -> bool:
        return not self._heap
