"""The request-exact micro rig: the global-serve bench in virtual time.

:class:`GlobalServeSim` re-runs the ``--global_bench`` scenario —
real :class:`~dlrover_tpu.serving.gateway.GatewayCore` admission, real
:class:`~dlrover_tpu.serving.spillover.CellSpillRouter` +
:class:`SpilloverPolicy` forwarding, real
``merge_global_snapshots`` accounting — with every thread, socket and
sleep of the bench replaced by scheduler events over one
:class:`VirtualClock`.  The arrival trace is an *input* (the caller
replays the bench's own seeded ``zipf_cell_trace``, or synthesizes one
from :mod:`sim.rand`), so the fidelity comparison against the
committed ``GLOBAL_BENCH_CPU.json`` is apples to apples: identical
arrivals, identical policy code, only the transport physics modeled.

The physics model, calibrated once (see ``SIM_BENCH.json``):

* each cell's gateway is a serialized server with a per-message floor
  (``gw_service_us``, the bench's ``_PacedPipeline`` budget) — submits
  and completion reports occupy it, polls are treated as free;
* each replica is the bench's ``_StubDecodeServer`` loop: poll with
  full ``slots``, serve the granted batch serially at ``service_ms``
  plus ``overhead_ms`` (the calibration constant standing in for
  completion-RPC turnaround and host scheduling), poll again;
* blackout kills the hot cell exactly like the bench: its gateway
  answers nothing (casts on the wire drop), its replicas stop
  un-drained (in-core work stays in ``_by_id`` and is counted
  stranded), and in spillover mode the driver re-homes later arrivals
  and lands the dead cell's chips at the survivor ``move_delay_s``
  later.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from dlrover_tpu.common.messages import ServeSubmit
from dlrover_tpu.serving.gateway import GatewayConfig, GatewayCore
from dlrover_tpu.serving.spillover import (
    CellSpillRouter,
    SpilloverPolicy,
    merge_global_snapshots,
)
from dlrover_tpu.serving import merge_snapshots

from .clock import VirtualClock
from .events import SimScheduler


class _SimCellTransport:
    """The inter-cell hop: a direct call into the sibling cell's
    admission dispatch at the same virtual instant (the bench charges
    the hop to the origin's budget; its cost here is the origin's
    pipeline slot already consumed by the submit)."""

    def __init__(self, sim: "GlobalServeSim", cell_id: str):
        self._sim = sim
        self._cell = cell_id

    def call(self, msg, **_kw):
        if self._cell in self._sim.dead_cells:
            raise ConnectionError("cell blacked out")
        return self._sim.dispatch_submit(self._cell, msg)


class GlobalServeSim:
    """One bench row in virtual time.  ``opts`` uses the global
    bench's exact knob names; ``times``/``homes`` are the replayed
    arrival trace (seconds, home-cell indices)."""

    def __init__(self, opts: Dict[str, Any], mode: str, blackout: bool,
                 times: Sequence[float], homes: Sequence[int],
                 overhead_ms: float = 0.0):
        self.opts = dict(opts)
        self.mode = mode
        self.blackout = blackout
        self.times = list(times)
        self.homes = list(homes)
        self.overhead_s = float(overhead_ms) / 1e3
        self.clock = VirtualClock(0.0)
        self.sched = SimScheduler(self.clock)
        n_cells = int(opts["cells"])
        self.cell_ids = [f"c{i}" for i in range(n_cells)]
        self.dead_cells: Dict[str, bool] = {}
        self.cores: Dict[str, GatewayCore] = {}
        self.routers: Dict[str, CellSpillRouter] = {}
        self.in_slo = {cid: 0 for cid in self.cell_ids}
        self.blackout_lost = 0
        self.blackout_dropped = 0
        self.moved = 0
        self._pipe_free = {cid: 0.0 for cid in self.cell_ids}
        self._casts_in_flight = {cid: 0 for cid in self.cell_ids}
        self._arrived = 0
        self._last_activity = 0.0
        self._service_s = opts["service_ms"] / 1e3
        self._floor_s = opts["gw_service_us"] / 1e6
        self._stopped_replicas: Dict[str, bool] = {}
        self._batch: Dict[str, List] = {}
        self._last_poll: Dict[str, float] = {}
        self._cell_replicas: Dict[str, List[str]] = {
            cid: [] for cid in self.cell_ids
        }
        self._build_cells()

    # -- construction ------------------------------------------------------

    def _build_cells(self) -> None:
        opts = self.opts
        for cid in self.cell_ids:
            core = GatewayCore(
                GatewayConfig(
                    queue_cap=int(opts["queue_cap"]),
                    default_deadline_s=float(opts["deadline_s"]),
                ),
                clock=self.clock,
            )
            orig = core.observe_latency_ms

            def lat_obs(v, _o=orig, _c=cid):
                if _o is not None:
                    _o(v)
                if v <= opts["slo_ms"]:
                    self.in_slo[_c] += 1

            core.observe_latency_ms = lat_obs
            self.cores[cid] = core
        if self.mode == "spillover":
            for cid in self.cell_ids:
                sibs = {c: _SimCellTransport(self, c)
                        for c in self.cell_ids if c != cid}

                def view(_sibs=sibs):
                    return {
                        c: dict(self.cores[c].pressure(),
                                alive=c not in self.dead_cells)
                        for c in _sibs
                    }

                self.routers[cid] = CellSpillRouter(
                    cid, self.cores[cid], sibs,
                    policy=SpilloverPolicy(clock=self.clock),
                    view_fn=view, clock=self.clock,
                )
        for cid in self.cell_ids:
            for i in range(int(opts["replicas"])):
                self._start_replica(cid, f"{cid}-r{i}")

    def _start_replica(self, cid: str, rid: str) -> None:
        self.cores[cid].register(rid, int(self.opts["slots"]))
        self._cell_replicas[cid].append(rid)
        self.sched.push(self.clock(), "round", (cid, rid))

    # -- admission dispatch (shared with the sibling transport) ------------

    def dispatch_submit(self, cid: str, msg: ServeSubmit):
        router = self.routers.get(cid)
        if router is not None:
            return router.submit(msg)
        return self.cores[cid].submit(
            msg.req_id, msg.prompt, msg.max_new_tokens,
            msg.deadline_s, msg.prefix_len, msg.prefix_fp, msg.trace,
            spill_hops=msg.spill_hops,
        )

    # -- event handlers ----------------------------------------------------

    def _on_arrive(self, i: int) -> None:
        opts = self.opts
        at = self.times[i]
        hot = self.cell_ids[0]
        blackout_at = (opts["duration_s"] * opts["blackout_frac"]
                       if self.blackout else float("inf"))
        move_at = blackout_at + opts["move_delay_s"]
        if at >= blackout_at and hot not in self.dead_cells:
            self._kill_cell(hot)
        if (self.mode == "spillover" and self.blackout
                and self.moved == 0 and at >= move_at):
            survivor = next(c for c in self.cell_ids
                            if c not in self.dead_cells)
            for j in range(int(opts["replicas"])):
                self._start_replica(survivor, f"moved-r{j}")
                self.moved += 1
        cid = self.cell_ids[self.homes[i]]
        if cid in self.dead_cells:
            if self.mode == "static":
                self.blackout_lost += 1
                self._arrived += 1
                return
            cid = next(c for c in self.cell_ids
                       if c not in self.dead_cells)
        # The gateway pipeline: serialized, floored per message.
        t = max(self.clock(), self._pipe_free[cid]) + self._floor_s
        self._pipe_free[cid] = t
        self._casts_in_flight[cid] += 1
        self.sched.push(t, "gw_submit", (i, cid))
        self._arrived += 1

    def _on_gw_submit(self, i: int, cid: str) -> None:
        self._casts_in_flight[cid] -= 1
        if cid in self.dead_cells:
            # The cast was on the wire when the cell went dark.
            self.blackout_dropped += 1
            return
        opts = self.opts
        msg = ServeSubmit(
            req_id=f"{self.mode[0]}{int(self.blackout)}-{i}",
            prompt=list(range(1, int(opts["prompt_tokens"]) + 1)),
            max_new_tokens=int(opts["mnt"]),
            deadline_s=float(opts["deadline_s"]),
        )
        self.dispatch_submit(cid, msg)
        self._last_activity = self.clock()

    def _on_round(self, cid: str, rid: str) -> None:
        if cid in self.dead_cells or self._stopped_replicas.get(rid):
            return
        opts = self.opts
        core = self.cores[cid]
        self._last_poll[rid] = self.clock()
        # Report a paged-KV memory view (ISSUE 19) so the sim's
        # gateway exercises the real pools carry-through: the stub
        # models one token per block — outstanding batch tokens are
        # the blocks held, slot capacity the pool.  A saturated stub
        # (free_blocks == 0) hits the same admission gate a real
        # paged replica does.
        held = sum(t for _r, t in (self._batch.get(rid) or []))
        cap = int(opts["slots"]) * (
            int(opts["prompt_tokens"]) + int(opts["mnt"])
        )
        grants = core.poll(
            rid, free_slots=int(opts["slots"]), active=[],
            stats={
                "kv_occupancy": round(held / cap, 4) if cap else 0.0,
                "free_blocks": max(0, cap - held),
                "total_blocks": cap,
            },
        )
        now = self.clock()
        if not grants.requests:
            if (self._arrived >= len(self.times)
                    and self._casts_in_flight[cid] == 0
                    and core.pressure()["in_flight"] == 0):
                return  # the cell is drained; stop polling
            self.sched.push(now + float(opts["poll_interval"]),
                            "round", (cid, rid))
            return
        # The stub-decode loop: grab the whole granted batch, serve it
        # serially, poll again once it is gone.  Each item is a decode
        # charge followed by the completion report through the floored,
        # serialized gateway pipeline — the loop blocks on the report
        # before starting the next item, so pipeline pressure feeds
        # back into decode throughput exactly like the bench.
        self._batch[rid] = [
            (g.req_id, len(g.prompt) + int(g.max_new_tokens))
            for g in grants.requests
        ]
        self.sched.push(now + self._service_s + self.overhead_s,
                        "finish", (cid, rid))

    def _on_finish(self, cid: str, rid: str) -> None:
        """Decode of the batch head is done: book the completion
        report into the gateway pipeline (serialized, floored)."""
        if cid in self.dead_cells or self._stopped_replicas.get(rid):
            return
        tcomp = max(self.clock(), self._pipe_free[cid]) + self._floor_s
        self._pipe_free[cid] = tcomp
        self.sched.push(tcomp, "complete", (cid, rid))

    def _on_complete(self, cid: str, rid: str) -> None:
        if cid in self.dead_cells or self._stopped_replicas.get(rid):
            return  # in-core work dies with the cell: stranded
        batch = self._batch.get(rid)
        if not batch:
            return
        req_id, n_tok = batch.pop(0)
        self.cores[cid].complete(rid, req_id, [0] * n_tok, ok=True)
        self._last_activity = self.clock()
        now = self.clock()
        if batch:
            self.sched.push(now + self._service_s + self.overhead_s,
                            "finish", (cid, rid))
        else:
            # Batch drained: the loop ticks again, paced to the poll
            # interval like the replica runner.
            nxt = max(now, self._last_poll.get(rid, 0.0)
                      + float(self.opts["poll_interval"]))
            self.sched.push(nxt, "round", (cid, rid))

    def _kill_cell(self, cid: str) -> None:
        """The whole cell goes dark as ONE event (the bench's blackout
        semantics): gateway answers nothing, replicas stop un-drained,
        in-core work strands."""
        self.dead_cells[cid] = True
        for rid in self._cell_replicas[cid]:
            self._stopped_replicas[rid] = True

    # -- run ---------------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        for i, at in enumerate(self.times):
            self.sched.push(at, "arrive", i)
        handlers = {
            "arrive": lambda p: self._on_arrive(p),
            "gw_submit": lambda p: self._on_gw_submit(*p),
            "round": lambda p: self._on_round(*p),
            "finish": lambda p: self._on_finish(*p),
            "complete": lambda p: self._on_complete(*p),
        }
        while True:
            ev = self.sched.pop()
            if ev is None:
                break
            handlers[ev[2]](ev[3])
        return self._row()

    def _row(self) -> Dict[str, Any]:
        opts = self.opts
        last_at = self.times[-1] if self.times else 0.0
        elapsed = max(last_at, self._last_activity) + 0.05
        merged = merge_global_snapshots({
            cid: merge_snapshots(
                [self.cores[cid].stats_snapshot()]
            )
            for cid in self.cell_ids
        })
        counters = merged["counters"]
        stranded = merged["in_flight"]
        slo_total = sum(self.in_slo.values())
        arrivals = len(self.times)
        row = {
            "mode": self.mode,
            "blackout": self.blackout,
            "arrivals": arrivals,
            "hot_share": round(
                self.homes.count(0) / max(arrivals, 1), 3
            ),
            "blackout_lost": self.blackout_lost,
            "blackout_dropped": self.blackout_dropped,
            "wire_dropped": 0,
            "submitted_unique": merged["submitted_unique"],
            "spill_forwarded": merged["spill_forwarded"],
            "spill_ingress": merged["spill_ingress"],
            "spill_rebuffed": merged["spill_rebuffed"],
            "spill_adopted": merged["spill_adopted"],
            "accepted": counters.get("accepted", 0),
            "rejected": counters.get("rejected", 0),
            "completed": counters.get("completed", 0),
            "timeout": counters.get("timeout", 0),
            "failed": counters.get("failed", 0),
            "stranded": stranded,
            "completed_in_slo": slo_total,
            "goodput_rps": round(slo_total / max(elapsed, 1e-9), 1),
            "moved_replicas": self.moved,
            "elapsed_s": round(elapsed, 2),
            "cells": {
                c: dict(
                    in_flight=snap["in_flight"],
                    replicas_alive=snap["replicas_alive"],
                    **{k: snap["counters"].get(k, 0)
                       for k in ("submitted", "accepted", "rejected",
                                 "completed", "timeout", "failed",
                                 "spill_forwarded", "spill_ingress",
                                 "spill_rebuffed", "spill_adopted")},
                )
                for c, snap in merged["cells"].items()
            },
            "events": self.sched.popped,
        }
        row["conservation_ok"] = (
            arrivals == row["submitted_unique"] + row["wire_dropped"]
            + row["blackout_lost"] + row["blackout_dropped"]
            and row["accepted"] == row["completed"] + row["timeout"]
            + row["failed"] + row["stranded"]
        )
        _ = opts
        return row


def run_global_rows(opts: Dict[str, Any], times: Sequence[float],
                    homes: Sequence[int], overhead_ms: float,
                    shapes: Optional[List[bool]] = None,
                    ) -> List[Dict[str, Any]]:
    """The bench's row grid (static/spillover x blackout shapes) in
    virtual time; same row order as ``--global_bench``."""
    rows = []
    for blackout in ([False, True] if shapes is None else shapes):
        for mode in ("static", "spillover"):
            sim = GlobalServeSim(opts, mode, blackout, times, homes,
                                 overhead_ms=overhead_ms)
            rows.append(sim.run())
    return rows
