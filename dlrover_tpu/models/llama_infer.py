"""KV-cache autoregressive decoding for Llama.

The inference half the reference delegates to an external engine (its RL
stack shells out to vllm, ``atorch/atorch/rl/model_engine``) — TPU-first
here: a functional KV cache (one [B, KV, max_len, D] pair per layer kept
compact at the GQA kv-head count), a prefill step that scores the whole
prompt at once, and a ``lax.scan`` decode loop that reuses the cache so
each new token costs O(S) attention instead of the RL engine's
O(S^2)-per-token full recompute.

    cache = init_cache(cfg, batch, max_len)
    tokens = generate(params, cfg, prompts, max_new_tokens=64,
                      rng=jax.random.PRNGKey(0))
"""

from __future__ import annotations

import collections
import functools
import threading
import time

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.common.log import logger
from dlrover_tpu.models import llama
from dlrover_tpu.models.llama import LlamaConfig, _rope
from dlrover_tpu.ops.rmsnorm import rmsnorm


def init_cache(
    cfg: LlamaConfig, batch: int, max_len: int, *,
    ring_len: Optional[int] = None,
    quant_kv: bool = False,
    ring: bool = True,
) -> Dict:
    """Zeroed per-layer k/v cache (compact KV-head count) + write offset.

    With ``cfg.sliding_window > 0`` the cache is a ROLLING buffer of
    ``ring_len`` slots (default ``max_len``): writes wrap modulo the
    buffer and a per-slot absolute-position array drives the masks, so
    decode memory is O(window), not O(total sequence).  Constraints for
    a chunk of T new tokens: ``T <= ring_len`` always, and
    ``window + T - 1 <= ring_len`` when continuing past a non-empty
    cache (single-token decode only needs ``ring_len >= window``).

    ``quant_kv``: store k/v as int8 with a per-(sequence, head, slot)
    absmax scale — decode is HBM-bandwidth-bound, so halving the cache
    bytes speeds the token loop AND doubles the servable context (the
    fp8/int8 kv-cache mode of the serving engine the reference RL stack
    delegates to).  The attention reads the int8 codes directly (an
    operand dtype-convert fuses into the dot) and applies the scales to
    the small score/probability tensors — by construction nothing
    cache-sized is materialized in full precision.

    ``ring=False`` gives a windowed model a DENSE cache instead: the
    sliding-window mask still applies in attention (the ring is purely
    a memory optimization — O(window) instead of O(sequence)), but a
    dense layout supports ragged per-row offsets and rewind-by-offset,
    which is what the continuous-batching server and speculative
    decoding need.  Memory cost: the full max_len rows."""
    KV, D = cfg.n_kv_head, cfg.head_dim
    L = max_len
    if cfg.sliding_window > 0 and ring and ring_len is not None:
        L = min(max_len, ring_len)

    def _layer() -> Dict:
        if quant_kv:
            return {
                "k": jnp.zeros((batch, KV, L, D), jnp.int8),
                "v": jnp.zeros((batch, KV, L, D), jnp.int8),
                "ks": jnp.zeros((batch, KV, L), jnp.float32),
                "vs": jnp.zeros((batch, KV, L), jnp.float32),
            }
        return {
            "k": jnp.zeros((batch, KV, L, D), cfg.dtype),
            "v": jnp.zeros((batch, KV, L, D), cfg.dtype),
        }

    cache = {
        "layers": [_layer() for _ in range(cfg.n_layer)],
        "offset": jnp.zeros((), jnp.int32),
    }
    if cfg.sliding_window > 0 and ring:
        # Absolute position held by each ring slot (-1 = unwritten).
        cache["pos"] = jnp.full((L,), -1, jnp.int32)
    return cache


def _quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[B, KV, T, D] -> (int8 codes, f32 absmax scale [B, KV, T])."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    codes = jnp.clip(
        jnp.round(xf / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return codes, scale


def _cached_attention(x, layer, cfg, cache_layer, offset, positions,
                      slot_pos=None):
    """x: [B, T, C] new tokens; attends to cache[:offset] + itself.

    ``offset`` may be a scalar (all sequences aligned) or a [B] vector
    (ragged batch): each sequence writes its T-token chunk at its OWN
    slots ``offset[b]..offset[b]+T-1`` and masks causally against its
    own positions.

    ``slot_pos`` (ring mode, sliding-window models): the ALREADY-updated
    per-slot absolute positions; writes wrap modulo the buffer length
    and masks key on these positions instead of the slot index."""
    B, T, C = x.shape
    H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    dt = cfg.dtype
    q = (x @ layer["wq"].astype(dt)).reshape(B, T, H, D)
    k = (x @ layer["wk"].astype(dt)).reshape(B, T, KV, D)
    v = (x @ layer["wv"].astype(dt)).reshape(B, T, KV, D)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)

    quant = "ks" in cache_layer

    def _write(cur: jax.Array, new: jax.Array) -> jax.Array:
        """Write ``new`` [B, KV, T, ...] into cache array ``cur``
        [B, KV, L, ...] at the mode's slots (slot axis = 2).  Shared by
        the code arrays and (in quant mode) their scale arrays so the
        three write modes are spelled once."""
        if jnp.ndim(offset) == 1:
            # Ragged mode: sequence b's chunk lands at ITS slots
            # offset[b]..offset[b]+T-1 (one batched scatter; positions
            # == slot indices, so the standard kpos <= qpos mask below
            # stays correct per row).
            if T == 1:
                return cur.at[jnp.arange(B), :, offset].set(
                    new[:, :, 0]
                )
            b_idx = jnp.arange(B)[:, None]  # [B, 1]
            slots = offset[:, None] + jnp.arange(T)[None, :]  # [B, T]
            # new is [B, KV, T, ...]; index (b, t) pairs over the slot
            # axis with KV broadcast.
            return cur.at[b_idx, :, slots].set(
                jnp.moveaxis(new, 2, 1)  # [B, T, KV, ...]
            )
        if slot_pos is not None:
            ring_slots = slot_pos[0]
            if T == 1:
                # Decode hot path: a single contiguous slot — XLA
                # lowers a dynamic_update_slice far better than an
                # indexed scatter.
                start = (0, 0, ring_slots[0]) + (0,) * (new.ndim - 3)
                return jax.lax.dynamic_update_slice(cur, new, start)
            return cur.at[:, :, ring_slots].set(new)
        # Dense: the new k/v land at [offset, offset+T).
        start = (0, 0, offset) + (0,) * (new.ndim - 3)
        return jax.lax.dynamic_update_slice(cur, new, start)

    k_t = k.transpose(0, 2, 1, 3)  # [B, KV, T, D]
    v_t = v.transpose(0, 2, 1, 3)
    new_layer = dict(cache_layer)
    if quant:
        k_codes, k_scale = _quantize_kv(k_t)
        v_codes, v_scale = _quantize_kv(v_t)
        new_layer["k"] = _write(cache_layer["k"], k_codes)
        new_layer["v"] = _write(cache_layer["v"], v_codes)
        new_layer["ks"] = _write(cache_layer["ks"], k_scale)
        new_layer["vs"] = _write(cache_layer["vs"], v_scale)
        # The einsums below read the int8 CODES (a dtype convert on a
        # dot operand reliably fuses into the dot's read stream); the
        # scales — constant over D — are applied to the tiny [.., T, L]
        # score and probability tensors instead, so no full-size
        # [B, KV, L, D] dequantized product exists even if XLA declines
        # to fuse an elementwise producer into the MXU op.
        k_eff = new_layer["k"].astype(dt)
        v_eff = new_layer["v"].astype(dt)
    else:
        new_layer["k"] = _write(cache_layer["k"], k_t.astype(dt))
        new_layer["v"] = _write(cache_layer["v"], v_t.astype(dt))
        k_eff, v_eff = new_layer["k"], new_layer["v"]

    if slot_pos is not None:
        slot_pos = slot_pos[1]

    max_len = k_eff.shape[2]
    rep = H // KV
    # Grouped attention against the COMPACT cache, in its stored dtype:
    # no [B, H, max_len, D] repeat and no fp32 cache copy is ever
    # materialized — the einsums accumulate in fp32 via
    # preferred_element_type (only q, [B,KV,rep,T,D] with tiny T, is
    # upcast).
    qf = (
        q.transpose(0, 2, 1, 3)
        .reshape(B, KV, rep, T, D)
        .astype(k_eff.dtype)
    )
    s = jnp.einsum(
        "bgrtd,bgkd->bgrtk", qf, k_eff,
        preferred_element_type=jnp.float32,
    ) / np.sqrt(D)
    if quant:
        # s_k = (q . codes_k) * scale_k  ==  q . (codes_k * scale_k)
        s = s * new_layer["ks"][:, :, None, None, :]
    # Causal over absolute positions; unwritten slots are masked (ring
    # mode: pos -1; dense mode: slot index beyond offset+T).
    if slot_pos is not None:
        kpos = slot_pos[None, None, None, None, :]
    else:
        kpos = jnp.arange(max_len)[None, None, None, None, :]
    qpos = positions[:, None, None, :, None]
    s = jnp.where((kpos >= 0) & (kpos <= qpos), s, -1e30)
    if cfg.sliding_window > 0:
        # Sliding window: only the last `sliding_window` positions are
        # visible.
        s = jnp.where(qpos - kpos < cfg.sliding_window, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    if quant:
        # sum_k p_k * (codes_vk * vs_k)  ==  sum_k (p_k * vs_k) * codes_vk
        p = p * new_layer["vs"][:, :, None, None, :]
    out = jnp.einsum(
        "bgrtk,bgkd->bgrtd", p.astype(v_eff.dtype), v_eff,
        preferred_element_type=jnp.float32,
    )
    out = (
        out.reshape(B, H, T, D)
        .transpose(0, 2, 1, 3)
        .reshape(B, T, H * D)
        .astype(dt)
    )
    return out @ layer["wo"].astype(dt), new_layer


def forward_step(
    params: Dict,
    tokens: jax.Array,  # [B, T] new tokens
    cfg: LlamaConfig,
    cache: Dict,
    *,
    assume_empty_cache: bool = False,  # ring mode: offset-0 prefill
) -> Tuple[jax.Array, Dict]:
    """Score ``tokens`` continuing the cached context.  Returns
    (logits [B, T, vocab] fp32, updated cache).

    Reuses ``llama.block_apply`` with the cached attention plugged in,
    so the block wiring (norm/residual/mlp-or-moe order) cannot drift
    from the training forward.  MoE layers run with a no-drop capacity:
    at T=1 the config-derived capacity rounds so coarsely that batch
    rows colliding on an expert would be silently dropped."""
    B, T = tokens.shape
    dt = cfg.dtype
    offset = cache["offset"]
    x = params["embed"].astype(dt)[tokens]
    if jnp.ndim(offset) == 1:
        # Ragged batch: per-sequence write slots/positions — T=1 is the
        # decode hot path; T>1 scores a chunk continuing each row at
        # its OWN offset (batched speculative verify, chunked ragged
        # continuation).  (Ragged PREFILL from zero needs no special
        # handling — pad tokens written at their slot positions are
        # causally invisible to every later real query.)
        if "pos" in cache:
            raise ValueError(
                "ragged offsets are not supported with the sliding-"
                "window ring cache"
            )
        positions = offset[:, None] + jnp.broadcast_to(
            jnp.arange(T), (B, T)
        )
    else:
        positions = offset + jnp.broadcast_to(jnp.arange(T), (B, T))
    no_drop_capacity = B * T * cfg.top_k
    ring = None
    if "pos" in cache:  # ring mode (sliding-window models)
        L = cache["pos"].shape[0]
        W = cfg.sliding_window
        if T > L:
            raise ValueError(
                f"chunk of {T} tokens exceeds the {L}-slot ring cache"
            )
        if T > 1 and W + T - 1 > L and not assume_empty_cache:
            # A multi-token chunk on a NON-empty ring would overwrite
            # keys still inside earlier queries' windows (silently wrong
            # logits). Prefill at offset 0 is safe — callers declare it.
            raise ValueError(
                f"continuation chunk of {T} tokens needs ring_len >= "
                f"window + T - 1 = {W + T - 1}, have {L}; pass "
                "assume_empty_cache=True only for the offset-0 prefill"
            )
        slots = (offset + jnp.arange(T)) % L
        if T == 1:
            slot_pos = jax.lax.dynamic_update_slice(
                cache["pos"], offset[None] + jnp.arange(1), (slots[0],)
            )
        else:
            slot_pos = cache["pos"].at[slots].set(
                offset + jnp.arange(T)
            )
        ring = (slots, slot_pos)
    new_layers = []
    for layer, cache_layer in zip(params["layers"], cache["layers"]):
        cell = {}

        def attn_fn(h, layer_, cfg_, positions_, _cache=cache_layer,
                    _cell=cell):
            out, _cell["cache"] = _cached_attention(
                h, layer_, cfg_, _cache, offset, positions_,
                slot_pos=ring,
            )
            return out

        x, _aux = llama.block_apply(
            layer, x, cfg, positions,
            attn_fn=attn_fn, moe_capacity=no_drop_capacity,
        )
        new_layers.append(cell["cache"])
    x = rmsnorm(x, params["ln_f"], eps=cfg.rms_eps)
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    new_cache = {"layers": new_layers, "offset": offset + T}
    if ring is not None:
        new_cache["pos"] = ring[1]
    return logits, new_cache


def shard_params_for_decode(params: Dict, cfg: LlamaConfig, mesh):
    """Tensor-parallel serving layout: device_put ``params`` onto
    ``mesh`` (axis name ``'tp'``) with column-parallel wq/wk/wv and
    mlp-in, row-parallel wo/w_down, vocab-sharded lm_head — the layout
    vllm's TP serving uses, expressed as shardings instead of module
    surgery.  The decode computation itself needs no changes: jit the
    usual :func:`generate`/:func:`forward_step` and GSPMD partitions the
    einsums and inserts the row-parallel reductions (computation
    follows the data).  Returns (sharded_params, specs).

    GQA note: the KV cache follows the kv-head einsum operands, so tp
    greater than ``cfg.n_kv_head`` still works (XLA gathers k/v) but
    shards only the q-head work."""
    from dlrover_tpu.parallel import sharding as sh

    # Only the overrides: neutralize the training axes that have no
    # mesh axis here (batch/embed/expert); heads/mlp/vocab already map
    # to 'tp' in DEFAULT_RULES and keep tracking it.
    rules: sh.Rules = {"batch": None, "embed": None, "expert": None}
    specs = sh.tree_logical_to_specs(
        llama.param_logical_axes(cfg), rules
    )
    return sh.shard_tree(params, specs, mesh), specs


def _filter_logits(scaled: jax.Array, top_k: int,
                   top_p: float) -> jax.Array:
    """[B, V] temperature-scaled logits -> same with everything outside
    the top-k / top-p nucleus set to -inf (the top token always
    survives)."""
    if top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if top_p > 0.0:
        # Nucleus: keep the smallest prefix of the sorted
        # distribution whose mass reaches top_p.
        srt = jnp.sort(scaled, axis=-1)[:, ::-1]  # descending
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_p
        n_keep = jnp.maximum(1, jnp.sum(keep_sorted, axis=-1))
        cutoff = jnp.take_along_axis(
            srt, (n_keep - 1)[:, None], axis=-1
        )
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
    return scaled


def _make_sampler(temperature: float, top_k: int, top_p: float):
    """(logits [B, V], rng) -> [B] token picker: greedy at T=0, else
    categorical with optional top-k truncation / top-p nucleus."""

    def pick(logits_1, sub):
        if temperature <= 0.0:
            return jnp.argmax(logits_1, axis=-1)
        return jax.random.categorical(
            sub, _filter_logits(logits_1 / temperature, top_k, top_p)
        )

    return pick


def generate(
    params: Dict,
    cfg: LlamaConfig,
    prompts: jax.Array,  # [B, P] prompt token ids
    *,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,  # 0 = greedy
    top_k: int = 0,
    top_p: float = 0.0,  # 0 = off; else nucleus sampling
    quant_kv: bool = False,  # int8 kv cache (see init_cache)
) -> jax.Array:
    """[B, P + max_new_tokens] — prompt + sampled continuation.

    Prefill scores the prompt in one pass; decode is a ``lax.scan`` of
    single-token steps against the KV cache.  ``temperature=0`` is
    greedy (deterministic); otherwise categorical sampling with optional
    top-k truncation and/or top-p (nucleus) filtering — the sampling
    surface of the serving engine the reference RL stack delegates to.
    """
    if max_new_tokens == 0:
        return prompts
    B, P = prompts.shape
    max_len = P + max_new_tokens
    ring_len = None
    if cfg.sliding_window > 0:
        # Rolling buffer: prefill needs P slots, decode needs `window`
        # retained keys — memory O(max(P, window)), not O(P + N).
        ring_len = max(P, cfg.sliding_window)
    cache = init_cache(cfg, B, max_len, ring_len=ring_len,
                       quant_kv=quant_kv)
    logits, cache = forward_step(
        params, prompts, cfg, cache, assume_empty_cache=True
    )
    if rng is None:
        rng = jax.random.PRNGKey(0)

    pick = _make_sampler(temperature, top_k, top_p)
    rng, sub = jax.random.split(rng)
    first = pick(logits[:, -1, :], sub).astype(prompts.dtype)

    def step(carry, _):
        cache, tok, rng = carry
        logits, cache = forward_step(params, tok[:, None], cfg, cache)
        rng, sub = jax.random.split(rng)
        nxt = pick(logits[:, -1, :], sub).astype(tok.dtype)
        return (cache, nxt, rng), tok

    # Each step scores the carried token and samples the next; the scan
    # emits the SCORED token, so the outputs are exactly the generated
    # sequence [first, t2, ..., tN] (the final carry is an N+1-th sample
    # past the requested window — dropped).
    _, toks = jax.lax.scan(
        step, (cache, first, rng), None, length=max_new_tokens
    )
    return jnp.concatenate(
        [prompts, jnp.moveaxis(toks, 0, 1)], axis=1
    )


def generate_ragged(
    params: Dict,
    cfg: LlamaConfig,
    prompts: jax.Array,  # [B, P] right-padded prompt token ids
    prompt_lens: jax.Array,  # [B] true prompt lengths (1..P)
    *,
    max_new_tokens: int,
    eos_token: int = -1,  # >=0: per-sequence stop on this token
    pad_token: int = 0,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    quant_kv: bool = False,  # int8 kv cache (see init_cache)
) -> Tuple[jax.Array, jax.Array]:
    """Ragged batched decode: per-sequence lengths, per-sequence EOS.

    Returns ``(tokens [B, P + max_new_tokens], lengths [B])`` where row
    b holds ``prompt_b`` (its true ``prompt_lens[b]`` tokens), then its
    continuation immediately after (no pad gap), then ``pad_token``;
    ``lengths[b]`` is the total valid length.  The decode loop is a
    ``lax.while_loop`` that EXITS as soon as every sequence has emitted
    ``eos_token`` — a batch of short answers does not pay for
    ``max_new_tokens`` steps (the role per-sequence scheduling plays in
    the serving engine the reference RL stack delegates to,
    ``atorch/rl/model_engine/model_engine.py:35``).

    Correctness of the ragged PREFILL needs no masking tricks: padded
    tail tokens are written at their slot positions, and every later
    real query q for sequence b sits at position ``>=`` those slots only
    after they have been overwritten by real decode writes — until then
    the causal mask ``kpos <= qpos`` hides exactly the pad entries that
    are still stale, because sequence b's next query position IS its
    first stale slot.
    """
    B, P = prompts.shape
    N = max_new_tokens
    if N == 0:
        return prompts, jnp.asarray(prompt_lens, jnp.int32)
    prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
    # ring=False: windowed models run ragged on a DENSE cache (window
    # masking still applies; the ring layout cannot take per-row
    # offsets).
    cache = init_cache(cfg, B, P + N, quant_kv=quant_kv, ring=False)
    logits, cache = forward_step(params, prompts, cfg, cache)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    pick = _make_sampler(temperature, top_k, top_p)

    # First token: sampled from each sequence's OWN last-prompt logit.
    last = jnp.take_along_axis(
        logits, (prompt_lens - 1)[:, None, None], axis=1
    )[:, 0, :]
    rng, sub = jax.random.split(rng)
    first = pick(last, sub).astype(prompts.dtype)

    # Per-sequence decode offsets: sequence b continues at its length.
    cache = dict(cache, offset=prompt_lens)

    def cond(c):
        i, _, _, done, _, _ = c
        return (i < N) & ~jnp.all(done)

    def body(c):
        # ``done`` means "this row's EOS is already RECORDED" — the EOS
        # token itself must land in the buffer before the row freezes.
        i, buf, tok, done, cache, rng = c
        buf = buf.at[:, i].set(jnp.where(done, pad_token, tok))
        done_next = done | (
            (tok == eos_token) if eos_token >= 0
            else jnp.zeros((B,), bool)
        )
        logits, new_cache = forward_step(params, tok[:, None], cfg, cache)
        rng, sub = jax.random.split(rng)
        nxt = pick(logits[:, -1, :], sub).astype(tok.dtype)
        # Finished rows freeze: offset stops advancing so their cache
        # rows stop changing (their compute rides along masked).
        frozen = jnp.where(done_next, cache["offset"],
                           new_cache["offset"])
        new_cache = dict(new_cache, offset=frozen)
        return (i + 1, buf, jnp.where(done_next, tok, nxt),
                done_next, new_cache, rng)

    buf = jnp.full((B, N), pad_token, prompts.dtype)
    i, buf, _, done, _, _ = jax.lax.while_loop(
        cond, body,
        (jnp.zeros((), jnp.int32), buf, first,
         jnp.zeros((B,), bool), cache, rng),
    )
    # Number of valid generated tokens per row: the column of the first
    # pad-after-generation; EOS itself is kept as a valid token.
    written = jnp.minimum(
        jnp.where(
            jnp.any(buf == eos_token, axis=1) if eos_token >= 0
            else jnp.zeros((B,), bool),
            jnp.argmax(buf == eos_token, axis=1) + 1,
            i,
        ),
        i,
    ).astype(jnp.int32)

    # Compact each row: prompt tokens then continuation, no pad gap.
    j = jnp.arange(P + N)[None, :]
    gen_idx = jnp.clip(j - prompt_lens[:, None], 0, N - 1)
    gen_vals = jnp.take_along_axis(buf, gen_idx, axis=1)
    prompt_padded = jnp.pad(prompts, ((0, 0), (0, N)))
    lens = prompt_lens + written
    out = jnp.where(j < prompt_lens[:, None], prompt_padded, gen_vals)
    out = jnp.where(j < lens[:, None], out, pad_token)
    return out, lens


def _spec_accept_round(
    p: np.ndarray,  # [k+1, V] target probs at each speculated position
    q: np.ndarray,  # [k, V] draft probs the proposals were drawn from
    d: np.ndarray,  # [k] proposals
    rng: "np.random.Generator",
) -> Tuple[int, int]:
    """Rejection-sampling acceptance (Leviathan et al.): accept the
    i-th proposal with prob ``min(1, p_i[d_i] / q_i[d_i])``; on the
    first rejection draw the replacement from the residual
    ``norm(max(0, p_i - q_i))``; if all ``k`` survive, draw a bonus
    token from ``p_{k+1}``.  Returns ``(j, next_token)`` — ``j``
    accepted proposals plus the round's final token.  The emitted
    sequence is distributed EXACTLY as sequential target sampling,
    whatever the draft proposes (a bad draft only costs acceptance
    rate, never correctness)."""
    V = p.shape[1]
    k = len(d)
    for i in range(k):
        di = int(d[i])
        if rng.random() < p[i, di] / max(float(q[i, di]), 1e-30):
            continue
        resid = np.clip(p[i] - q[i], 0.0, None)
        s = float(resid.sum())
        if s <= 0.0:
            # p == q to numerical precision: the residual is empty;
            # any draw from p is distribution-correct.
            resid, s = p[i], float(p[i].sum())
        return i, int(rng.choice(V, p=resid / s))
    return k, int(rng.choice(V, p=p[k] / float(p[k].sum())))


def _spec_accept_batch(
    p: np.ndarray,  # [B, k+1, V] target probs per row/slot
    q: np.ndarray,  # [B, k, V] draft probs per row/slot
    d: np.ndarray,  # [B, k] draft proposals
    done: np.ndarray,  # [B] frozen rows (consume draws, results ignored)
    np_rng: "np.random.Generator",
    k_row: Optional[np.ndarray] = None,  # [B] per-row width <= k
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized rejection-sampling acceptance over the batch — the
    numpy-batched form of :func:`_spec_accept_round` (the scalar
    executable spec; a Monte-Carlo test asserts both implement the same
    law).  One pass, no per-row Python, so the serving hot loop pays a
    single host sync per round.  Returns ``(j, tok)``: per row the
    accepted-prefix length and the round's final sampled token.  Frozen
    rows draw uniforms they ignore; each active row's law is unchanged
    (independent draws).

    ``k_row`` (ISSUE 11, per-request adaptive k): row b behaves as a
    ``k_row[b]``-proposal round — proposals beyond its width are
    ignored, and a row accepting its full width draws the bonus token
    from the target law at that position (``k_row[b] == 0`` is plain
    target sampling).  The SAME uniforms are consumed with or without
    truncation, so each stream's law is exactly the scalar spec's at
    its own width."""
    B, k = d.shape
    V = p.shape[-1]
    rows = np.arange(B)
    cols = np.arange(k)
    p_sel = p[rows[:, None], cols[None, :], d]  # [B, k]
    q_sel = q[rows[:, None], cols[None, :], d]  # [B, k]
    acc = np_rng.random((B, k)) < p_sel / np.maximum(q_sel, 1e-30)
    # First rejected position (k if none): the accepted-prefix length.
    j = acc.astype(np.int64).cumprod(axis=1).sum(axis=1)
    if k_row is not None:
        kw = np.minimum(np.asarray(k_row, np.int64), k)
        j = np.minimum(j, kw)
    else:
        kw = np.full(B, k, np.int64)
    j = np.where(done, 0, j)
    # Rejected rows draw from the residual law at position j; fully
    # accepting rows (at their own width) draw the bonus token from
    # the target's p at that position.
    p_j = p[rows, j]  # [B, V]
    q_j = q[rows, np.minimum(j, k - 1)]  # [B, V]
    resid = np.where((j < kw)[:, None], np.clip(p_j - q_j, 0.0, None),
                     p_j)
    s = resid.sum(axis=1)
    # p == q to numerical precision: the residual is empty; any draw
    # from p is distribution-correct.
    empty = s <= 0.0
    if empty.any():
        resid = np.where(empty[:, None], p_j, resid)
        s = resid.sum(axis=1)
    # Inverse-CDF sample, one uniform per row.
    tok = (
        np.cumsum(resid, axis=1) < (np_rng.random(B) * s)[:, None]
    ).sum(axis=1)
    return j, np.minimum(tok, V - 1)


def generate_speculative(
    params: Dict,
    cfg: LlamaConfig,
    draft_params: Dict,
    draft_cfg: LlamaConfig,
    prompts: jax.Array,  # [1, P] — single-sequence (low-latency serving)
    *,
    max_new_tokens: int,
    k: int = 4,
    quant_kv: bool = False,
    temperature: float = 0.0,  # 0 = greedy; >0 = rejection sampling
    top_k: int = 0,
    top_p: float = 0.0,
    eos_token: int = -1,  # >=0: stop after emitting this token
    rng: Optional[jax.Array] = None,
    stats: Optional[Dict] = None,  # out-param: rounds, tokens_per_round
) -> jax.Array:
    """Single-stream speculative decoding: a small DRAFT model proposes
    ``k`` tokens per round; the TARGET model scores all of them in ONE
    chunked forward.  At ``temperature=0`` the longest argmax-matching
    prefix (+ the target's own next token) is accepted — output is
    EXACTLY the target model's greedy decode.  At ``temperature>0``
    proposals pass through rejection sampling
    (:func:`_spec_accept_round`) — output is distributed exactly as the
    target model's sampled decode.  Either way the draft only changes
    how many target forwards it takes (the speculative-decoding role of
    the serving engine the reference RL stack delegates to).

    The machinery lives in :func:`generate_speculative_batched` (this
    is its B=1 case); see there for the cache-rewind design, the
    filtered-law guarantee for ``top_k``/``top_p``, and the numerics
    caveat on chunked-vs-incremental scoring.

    ``eos_token >= 0`` stops at the first EOS: the result is then
    [1, P + n] with n <= max_new_tokens, ending at the EOS (variable
    length — this is a host-driven serving loop, not a fixed-shape
    jitted program)."""
    B, P = prompts.shape
    if B != 1:
        raise ValueError(
            f"speculative decode is single-sequence (got batch {B}); "
            "use generate_speculative_batched for ragged batches"
        )
    if max_new_tokens == 0:
        return prompts
    out, lens = generate_speculative_batched(
        params, cfg, draft_params, draft_cfg, prompts,
        jnp.asarray([P], jnp.int32),
        max_new_tokens=max_new_tokens, k=k, quant_kv=quant_kv,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token=eos_token, rng=rng, stats=stats,
    )
    return out[:, : int(lens[0])]


@functools.lru_cache(maxsize=32)
def _spec_programs(cfg: LlamaConfig, draft_cfg: LlamaConfig, k: int,
                   temperature: float, top_k: int, top_p: float) -> Dict:
    """Compiled speculative-decoding programs, memoized per
    (configs, k, sampling knobs): RL rollouts call
    generate_speculative_batched once per PPO iteration, and without
    this memo every call would re-trace and re-XLA-compile the draft
    scan, the (k+1)-token verify, and the catch-up step (jax.jit caches
    by function identity).  LlamaConfig is frozen/hashable."""
    sample = temperature > 0.0

    @jax.jit
    def prefill_t(tp, prompts, cache):
        return forward_step(tp, prompts, cfg, cache)

    @jax.jit
    def prefill_d(dp, prompts, cache):
        return forward_step(dp, prompts, draft_cfg, cache)

    @jax.jit
    def draft_roll(dp, cache, tok, key):
        def body(carry, sub):
            cache, tok = carry
            lg, cache = forward_step(dp, tok[:, None], draft_cfg, cache)
            lg1 = lg[:, -1, :]
            if sample:
                filt = _filter_logits(lg1 / temperature, top_k, top_p)
                nxt = jax.random.categorical(
                    sub, filt, axis=-1
                ).astype(tok.dtype)
                probs = jax.nn.softmax(filt, axis=-1)  # [B, V]
                return (cache, nxt), (nxt, probs)
            nxt = jnp.argmax(lg1, axis=-1).astype(tok.dtype)
            return (cache, nxt), nxt

        (cache, _), ys = jax.lax.scan(
            body, (cache, tok), jax.random.split(key, k)
        )
        toks, q = ys if sample else (ys, None)
        # toks [k, B] -> [B, k]; q [k, B, V] -> [B, k, V]
        return (
            jnp.moveaxis(toks, 0, 1),
            None if q is None else jnp.moveaxis(q, 0, 1),
            cache,
        )

    @jax.jit
    def target_verify(tp, cache, chunk):
        lg, cache = forward_step(tp, chunk, cfg, cache)
        if sample:
            filt = _filter_logits(
                lg.reshape(-1, lg.shape[-1]) / temperature, top_k, top_p
            ).reshape(lg.shape)
            return jax.nn.softmax(filt, axis=-1), cache  # [B, k+1, V]
        return jnp.argmax(lg, axis=-1).astype(chunk.dtype), cache

    @jax.jit
    def draft_catch_up(dp, cache, tok):
        _, cache = forward_step(dp, tok[:, None], draft_cfg, cache)
        return cache

    return {
        "prefill_t": prefill_t, "prefill_d": prefill_d,
        "draft_roll": draft_roll, "target_verify": target_verify,
        "draft_catch_up": draft_catch_up,
    }


def _spec_decode_round(
    progs: Dict,
    params: Dict,
    draft_params: Dict,
    cache_t: Dict,
    cache_d: Dict,
    cur: jax.Array,  # [B] current input token per row
    done: np.ndarray,  # [B] frozen rows (ride along masked)
    k: int,
    sample: bool,
    np_rng: "np.random.Generator",
    sub: jax.Array,  # draft-sampling key (dead in the greedy trace)
    max_off: Optional[np.ndarray] = None,  # [B] per-row offset bound
    k_row: Optional[np.ndarray] = None,  # [B] per-row width <= k
) -> Tuple[list, np.ndarray, Dict, Dict]:
    """ONE speculative round over a ragged batch: draft k proposals per
    row, one chunked (k+1)-token verify at per-row offsets, per-row
    acceptance, cache rewind + full-acceptance catch-up.  Frozen rows
    keep their offsets (their compute rides along masked).  Returns
    ``(accepted_rows, nxt, cache_t, cache_d)``: ``accepted_rows[b]`` is
    the round's emitted tokens for row b (empty when frozen) BEFORE any
    EOS/budget truncation — truncation only marks rows done, it never
    changes cache state, so callers (the batched generator, the
    speculative DecodeServer) own it.  ``k_row`` truncates each row to
    its own speculation width (see :func:`_spec_accept_batch`)."""
    B = int(cur.shape[0])
    n_dev = cache_t["offset"]  # [B] handle; fetched with the round's sync
    d, q, cache_d = progs["draft_roll"](draft_params, cache_d, cur, sub)
    chunk = jnp.concatenate([cur[:, None], d], axis=1)  # [B, k+1]
    g, cache_t = progs["target_verify"](params, cache_t, chunk)
    # ONE host sync per round: acceptance below is pure numpy over the
    # batch dimension (per-row Python loops + separate np.asarray syncs
    # serialized the serving hot loop on the host — r4 advisor).  Frozen
    # rows consume RNG draws they ignore; each active row's law is
    # unchanged (independent uniforms).
    rows = np.arange(B)
    cur_h = np.asarray(cur)
    if sample:
        n, d_host, g_raw, q_raw = jax.device_get((n_dev, d, g, q))
        g_host = np.asarray(g_raw, np.float64)  # [B, k+1, V]
        q_host = np.asarray(q_raw, np.float64)  # [B, k, V]
        j, tok = _spec_accept_batch(g_host, q_host, d_host, done, np_rng,
                                    k_row=k_row)
        nxt = np.where(done, cur_h, tok).astype(cur_h.dtype)
    else:
        n, d_host, g_host = jax.device_get((n_dev, d, g))  # g [B, k+1]
        match = (d_host == g_host[:, :k]).astype(np.int64)
        j = match.cumprod(axis=1).sum(axis=1)  # longest matching prefix
        if k_row is not None:
            # Per-row width: the greedy law at width k_b emits the
            # matched prefix up to k_b plus the target's own token at
            # the truncation point — still exactly the target's greedy
            # stream, whatever the draft proposed beyond the width.
            j = np.minimum(j, np.asarray(k_row, np.int64))
        j = np.where(done, 0, j)
        nxt = np.where(done, cur_h, g_host[rows, j]).astype(cur_h.dtype)
    n = np.asarray(n)
    # Per-row rewind; frozen rows keep their old offset.  ``max_off``
    # clamps rows finishing this round (emission stops at their budget/
    # EOS, so the clamp never loses live context) — without it a
    # full-acceptance final round leaves a frozen offset past the
    # capacity-checked bound, and later ride-along rounds would scatter
    # beyond max_len (silently dropped today, corruption under any
    # dense-write lowering).
    new_n = np.where(done, n, n + 1 + j)
    if max_off is not None:
        new_n = np.minimum(new_n, max_off)
    full = (~done) & (j == k)
    if full.any():
        # Batched 1-token catch-up: full-acceptance rows write the
        # missing d_k at slot n+k; everyone else harmlessly writes its
        # next token's kv at its own next slot.
        tok_cu = np.where(full, d_host[:, k - 1], nxt).astype(
            cur_h.dtype
        )
        pos_cu = np.where(full, n + k, new_n)
        cache_d = dict(cache_d, offset=jnp.asarray(pos_cu, jnp.int32))
        cache_d = progs["draft_catch_up"](
            draft_params, cache_d, jnp.asarray(tok_cu)
        )
    cache_d = dict(cache_d, offset=jnp.asarray(new_n, jnp.int32))
    cache_t = dict(cache_t, offset=jnp.asarray(new_n, jnp.int32))
    accepted_rows = [
        [] if done[b] else list(d_host[b, : j[b]]) + [nxt[b]]
        for b in range(B)
    ]
    return accepted_rows, nxt, cache_t, cache_d


def generate_speculative_batched(
    params: Dict,
    cfg: LlamaConfig,
    draft_params: Dict,
    draft_cfg: LlamaConfig,
    prompts: jax.Array,  # [B, P] right-padded prompts
    prompt_lens: jax.Array,  # [B] true prompt lengths
    *,
    max_new_tokens: int,
    k: int = 4,
    quant_kv: bool = False,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    eos_token: int = -1,
    pad_token: int = 0,
    rng: Optional[jax.Array] = None,
    stats: Optional[Dict] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Batched speculative decoding over a RAGGED batch: every row
    drafts ``k`` proposals, ONE (k+1)-token ragged verify scores all
    rows at their own offsets, and acceptance is per-row — combining
    :func:`generate_speculative`'s draft/verify economics with
    :func:`generate_ragged`'s per-sequence lengths and EOS exit (the
    batched speculative mode of the serving engine the reference RL
    stack delegates to).

    Output contract matches :func:`generate_ragged`: ``(tokens
    [B, P + max_new_tokens], lengths [B])``, row b = prompt then
    continuation then ``pad_token``.  The output law per row equals
    :func:`generate` with the same sampling knobs (greedy exactness at
    ``temperature=0``; rejection sampling otherwise).

    Cache bookkeeping is the per-row generalization of the
    single-stream version: rejection rewinds that row's offset (dense-
    cache slot masking hides its stale writes); rows that accepted all
    ``k`` get their missing ``d_k`` kv written by a batched 1-token
    catch-up whose other rows write their next token's kv early
    (harmless — the next roll rewrites the same value).  Finished rows
    freeze their offset and ride along masked."""
    B, P = prompts.shape
    N = max_new_tokens
    prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
    if N == 0:
        return prompts, prompt_lens
    sample = temperature > 0.0
    if rng is None:
        rng = jax.random.PRNGKey(0)
    rng, seed_key = jax.random.split(rng)
    np_rng = np.random.default_rng(
        int(jax.random.randint(seed_key, (), 0, 2**31 - 1))
    )
    max_len = P + N + k + 2
    progs = _spec_programs(cfg, draft_cfg, k, temperature, top_k, top_p)
    # ring=False: windowed models speculate on a DENSE cache — offset
    # rewind relies on slot masking to hide stale writes, which a ring
    # layout cannot provide (wrapped writes destroy live keys).
    cache_t = init_cache(cfg, B, max_len, quant_kv=quant_kv,
                         ring=False)
    cache_d = init_cache(draft_cfg, B, max_len, quant_kv=quant_kv,
                         ring=False)
    logits, cache_t = progs["prefill_t"](params, prompts, cache_t)
    _, cache_d = progs["prefill_d"](draft_params, prompts, cache_d)
    pick = _make_sampler(temperature, top_k, top_p)
    last = jnp.take_along_axis(
        logits, (prompt_lens - 1)[:, None, None], axis=1
    )[:, 0, :]
    rng, first_key = jax.random.split(rng)
    cur = pick(last, first_key).astype(prompts.dtype)
    # Per-row ragged offsets: each row continues at its true length.
    off = prompt_lens
    cache_t = dict(cache_t, offset=off)
    cache_d = dict(cache_d, offset=off)

    buf = np.full((B, N), pad_token, dtype=np.asarray(prompts).dtype)
    emitted = np.zeros(B, np.int64)
    done = np.zeros(B, bool)
    cur_h = np.asarray(cur)
    if eos_token >= 0:
        hit = cur_h == eos_token
    else:
        hit = np.zeros(B, bool)
    for b in range(B):
        buf[b, 0] = cur_h[b]
    emitted[:] = 1
    done |= hit
    rounds = 0
    active_row_rounds = 0  # sum over rounds of non-frozen rows
    greedy_key = jax.random.PRNGKey(0)  # dead in the greedy trace
    while not done.all() and (emitted < N).any():
        if sample:
            rng, sub = jax.random.split(rng)
        else:
            sub = greedy_key
        active_row_rounds += int((~done).sum())
        accepted_rows, nxt, cache_t, cache_d = _spec_decode_round(
            progs, params, draft_params, cache_t, cache_d, cur, done,
            k, sample, np_rng, sub,
            max_off=np.asarray(prompt_lens) + N,
        )
        # Emit per row (truncated at EOS and at the N budget).
        new_done = done.copy()
        for b in range(B):
            if done[b]:
                continue
            accepted = accepted_rows[b]
            if eos_token >= 0:
                for i, t in enumerate(accepted):
                    if int(t) == eos_token:
                        accepted = accepted[: i + 1]
                        new_done[b] = True
                        break
            room = N - int(emitted[b])
            if len(accepted) >= room:
                accepted = accepted[:room]
                new_done[b] = True
            for t in accepted:
                buf[b, emitted[b]] = t
                emitted[b] += 1
        done = new_done
        cur = jnp.asarray(nxt)
        rounds += 1
    if stats is not None:
        stats["rounds"] = rounds
        # Normalize by ACTIVE row-rounds, not rounds*B: frozen (done)
        # rows ride along masked for most of a ragged batch's rounds and
        # would dilute the per-row acceptance signal (r4 advisor).
        stats["tokens_per_round"] = (
            float(emitted.sum() - B) / active_row_rounds
            if active_row_rounds else 0.0
        )
    # Assemble the generate_ragged output contract.
    full_buf = np.full((B, P + N), pad_token, buf.dtype)
    prompts_h = np.asarray(prompts)
    lens = np.zeros(B, np.int64)
    pl = np.asarray(prompt_lens)
    for b in range(B):
        full_buf[b, : pl[b]] = prompts_h[b, : pl[b]]
        full_buf[b, pl[b]: pl[b] + emitted[b]] = buf[b, : emitted[b]]
        lens[b] = pl[b] + emitted[b]
    return (
        jnp.asarray(full_buf),
        jnp.asarray(lens, jnp.int32),
    )


def prefix_fingerprint(tokens) -> str:
    """Fingerprint of a shared prefix template: what requests carry for
    prefix-aware routing (ISSUE 8) and what keys the per-replica
    template store.  Canonical definition lives jax-free in
    ``serving.replica`` (the journal's prompt-hash family); this
    delegate keeps the model-side surface in one import."""
    from dlrover_tpu.serving.replica import prefix_fingerprint as _fp

    return _fp(tokens)


class KvSegmentError(ValueError):
    """A packed KV segment failed verification (torn bytes, CRC
    mismatch, or a shape/dtype/config mismatch with the importing
    server).  The decode side must NEVER admit such a segment — the
    fleet re-prefills instead (``ServeKvReject``)."""

    #: Duck-typed marker the replica runner branches on (the control
    #: plane must not import this jax-loaded module to classify an
    #: exception; test fakes raise their own marker-carrying error).
    KV_REJECT = True


KV_SEGMENT_VERSION = 1


def pack_kv_segment(layers, n: int, first_token: int,
                    quant: bool, block_size: int = 0) -> Tuple[bytes, int]:
    """Pack a prefilled KV segment for the prefill->decode handoff
    (ISSUE 8).  ``layers`` is the per-layer list of HOST arrays sliced
    to the ``n`` written slots (``[1, KV, n, D]`` codes — int8 +
    per-slot f32 scales when ``quant``, the model dtype otherwise).

    ``block_size > 0`` (ISSUE 19, paged servers) frames the payload as
    a BLOCK LIST instead of one monolithic byte run: the slot axis is
    split into ``ceil(n / block_size)`` fixed-size blocks (last block
    zero-padded), each block's bytes framed contiguously with its OWN
    CRC-32 in the meta — so a torn transfer is localized to a block,
    and a paged decode server can write the frames straight into pool
    blocks.  :func:`unpack_kv_segment` reassembles either framing into
    the same trimmed per-layer arrays; the consumer never cares which
    rode the wire.

    Returns ``(payload, fp32_bytes)``: a self-describing msgpack blob
    with the data CRC-32 embedded (verified by
    :func:`unpack_kv_segment`, the replica-ring payload contract), and
    the segment's un-quantized fp32 size — the int8 transfer saving is
    ``len(payload) / fp32_bytes``."""
    import msgpack
    import zlib

    keys = sorted(layers[0]) if layers else []
    shapes = {}
    meta_extra: Dict[str, Any] = {}
    if block_size > 0:
        bs = int(block_size)
        nblk = -(-int(n) // bs)
        for kk in keys:
            arr = layers[0][kk]
            shapes[kk] = [
                list(arr.shape[:2]) + [bs] + list(arr.shape[3:]),
                str(arr.dtype),
            ]
        frames = []
        bcrc = []
        for b in range(nblk):
            parts = []
            for lay in layers:
                for kk in keys:
                    arr = np.ascontiguousarray(lay[kk])
                    blk = arr[:, :, b * bs: (b + 1) * bs]
                    if blk.shape[2] < bs:
                        pad = [(0, 0)] * blk.ndim
                        pad[2] = (0, bs - blk.shape[2])
                        blk = np.pad(blk, pad)
                    parts.append(np.ascontiguousarray(blk).tobytes())
            frame = b"".join(parts)
            bcrc.append(zlib.crc32(frame))
            frames.append(frame)
        data = b"".join(frames)
        meta_extra = {"bs": bs, "nblk": nblk, "bcrc": bcrc}
        n_units = nblk
    else:
        chunks = []
        for kk in keys:
            arr = layers[0][kk]
            shapes[kk] = [list(arr.shape), str(arr.dtype)]
        for lay in layers:
            for kk in keys:
                arr = np.ascontiguousarray(lay[kk])
                if list(arr.shape) != shapes[kk][0]:
                    raise ValueError(
                        f"ragged KV segment: layer {kk} shape "
                        f"{arr.shape} != {shapes[kk][0]}"
                    )
                chunks.append(arr.tobytes())
        data = b"".join(chunks)
        n_units = 1
    # fp32 equivalent: the k/v codes at 4 bytes/element (scale arrays
    # only exist in the quant layout; they have no fp32 counterpart).
    fp32_bytes = 0
    for kk in ("k", "v"):
        if kk in shapes:
            fp32_bytes += n_units * len(layers) * int(
                np.prod(shapes[kk][0])
            ) * 4
    meta = {
        "v": KV_SEGMENT_VERSION,
        "n": int(n),
        "first": int(first_token),
        "quant": bool(quant),
        "layers": len(layers),
        "keys": keys,
        "shapes": shapes,
        **meta_extra,
    }
    payload = msgpack.packb(
        {"meta": meta, "crc": zlib.crc32(data), "data": data},
        use_bin_type=True,
    )
    return payload, fp32_bytes


def unpack_kv_segment(payload: bytes) -> Dict[str, Any]:
    """Verify + unpack a :func:`pack_kv_segment` blob.  Raises
    :class:`KvSegmentError` on ANY damage (unparseable envelope, CRC
    mismatch, inconsistent sizes) — a torn segment must be rejected,
    never decoded from.  Returns ``{"layers": [...], "n", "first",
    "quant"}`` with per-layer HOST arrays."""
    import msgpack
    import zlib

    try:
        obj = msgpack.unpackb(payload, raw=False)
        meta = obj["meta"]
        crc = int(obj["crc"])
        data = obj["data"]
        keys = list(meta["keys"])
        shapes = meta["shapes"]
        n_layers = int(meta["layers"])
    except Exception as e:
        raise KvSegmentError(f"undecodable KV segment: {e}") from None
    if meta.get("v") != KV_SEGMENT_VERSION:
        raise KvSegmentError(
            f"KV segment version {meta.get('v')} != "
            f"{KV_SEGMENT_VERSION}"
        )
    if zlib.crc32(data) != crc:
        raise KvSegmentError("KV segment CRC mismatch (torn payload)")
    sizes = {
        kk: int(np.prod(shapes[kk][0])) * np.dtype(shapes[kk][1]).itemsize
        for kk in keys
    }
    n = int(meta["n"])
    if "bs" in meta:
        # Block-list framing (ISSUE 19): per-block CRC first — a torn
        # transfer is localized to the block that tore — then the
        # blocks reassemble along the slot axis and trim to ``n``.
        import zlib as _zlib

        bs = int(meta["bs"])
        nblk = int(meta["nblk"])
        bcrc = list(meta["bcrc"])
        frame_size = sum(sizes.values()) * n_layers
        if bs < 1 or nblk < 1 or len(bcrc) != nblk or \
                not (nblk - 1) * bs < n <= nblk * bs:
            raise KvSegmentError(
                f"KV segment block meta incoherent: n={n} bs={bs} "
                f"nblk={nblk} crcs={len(bcrc)}"
            )
        if frame_size * nblk != len(data):
            raise KvSegmentError(
                f"KV segment size mismatch: {nblk} blocks of "
                f"{frame_size} bytes promised, have {len(data)}"
            )
        per_block: list = []
        for b in range(nblk):
            frame = data[b * frame_size: (b + 1) * frame_size]
            if _zlib.crc32(frame) != int(bcrc[b]):
                raise KvSegmentError(
                    f"KV segment block {b}/{nblk} CRC mismatch "
                    "(torn block)"
                )
            off = 0
            lays = []
            for _ in range(n_layers):
                lay = {}
                for kk in keys:
                    shape, dt = shapes[kk]
                    lay[kk] = np.frombuffer(
                        frame, dtype=np.dtype(dt),
                        count=int(np.prod(shape)), offset=off,
                    ).reshape(shape)
                    off += sizes[kk]
                lays.append(lay)
            per_block.append(lays)
        layers = [
            {
                kk: np.concatenate(
                    [per_block[b][li][kk] for b in range(nblk)], axis=2
                )[:, :, :n]
                for kk in keys
            }
            for li in range(n_layers)
        ]
        return {
            "layers": layers, "n": n,
            "first": int(meta["first"]),
            "quant": bool(meta["quant"]),
            "block_size": bs, "blocks": nblk,
        }
    if sum(sizes.values()) * n_layers != len(data):
        raise KvSegmentError(
            f"KV segment size mismatch: meta promises "
            f"{sum(sizes.values()) * n_layers} bytes, have {len(data)}"
        )
    layers = []
    off = 0
    for _ in range(n_layers):
        lay = {}
        for kk in keys:
            shape, dt = shapes[kk]
            lay[kk] = np.frombuffer(
                data, dtype=np.dtype(dt), count=int(np.prod(shape)),
                offset=off,
            ).reshape(shape)
            off += sizes[kk]
        layers.append(lay)
    return {
        "layers": layers,
        "n": n,
        "first": int(meta["first"]),
        "quant": bool(meta["quant"]),
    }


def _adapt_spec_k(cur_k: int, draft_k: int, acc: float) -> int:
    """The adaptive-speculation policy, pure so the arithmetic is
    directly testable (and registered as a sim-bound policy —
    graftcheck DET70x keeps it ambient-effect-free).  ``acc`` is measured tokens-per-active-row-round
    in [1, cur_k+1].  A weak draft (acc near 1) makes every round pay
    cur_k wasted draft forwards — halve.  A strong draft saturating its
    window (acc near cur_k+1) earns a bigger one — double, CAPPED at
    the construction-time ``draft_k``: serve()'s cache-headroom
    capacity check was sized with draft_k, and growing past it would
    let a full-acceptance round scatter beyond max_len."""
    if acc < 1.0 + 0.3 * cur_k and cur_k > 1:
        return max(1, cur_k // 2)
    if acc > 1.0 + 0.8 * cur_k and cur_k < draft_k:
        return min(draft_k, cur_k * 2)
    return cur_k


def _spec_k_request(ewma: float, draft_k: int, break_even: float) -> int:
    """Per-STREAM speculation width from its measured acceptance EWMA
    (ISSUE 11) — pure, so the serving arithmetic is directly testable.
    ``ewma`` is the stream's accepted-tokens-per-round (0 = no
    measurement yet: start at full width and let the first rounds
    decide).  Below ``break_even`` — the measured round-cost ratio
    ``(t_draft_roll + t_verify) / t_plain_step`` from
    ``SPEC_DECODE_CPU.json``'s components row — drafting costs more
    target-equivalent time than it saves, so the stream decodes PLAIN
    (k = 0): a bad draft can never make a request slower than a
    spec-less replica serves it.  Above break-even the stream keeps a
    width it actually fills (capped at ``draft_k``: the cache headroom
    was sized with it)."""
    if ewma <= 0.0:
        return draft_k
    if ewma < break_even:
        return 0
    return max(1, min(draft_k, int(ewma)))


def _spec_remote_round(
    progs: Dict,
    params: Dict,
    cache_t: Dict,
    cur: jax.Array,  # [B] current input token per row
    done: np.ndarray,  # [B] frozen rows
    d_host: np.ndarray,  # [B, k] proposals (remote draft; zeros ok)
    q_host: Optional[np.ndarray],  # [B, k, V] draft probs (sampled)
    k: int,
    sample: bool,
    np_rng: "np.random.Generator",
    k_row: Optional[np.ndarray] = None,
    max_off: Optional[np.ndarray] = None,
) -> Tuple[list, np.ndarray, Dict]:
    """ONE speculative round whose proposals arrived from a REMOTE
    draft replica (ISSUE 11): the target-side half of
    :func:`_spec_decode_round` — chunked verify, per-row acceptance,
    cache rewind — with no local draft cache to maintain (the draft
    replica keeps its own per-stream cache and catches up from the
    context deltas the next roll ships).  Acceptance laws are shared
    with the local path, so the emitted stream per row is identical to
    sequential target decoding whatever the remote draft proposes."""
    B = int(cur.shape[0])
    n_dev = cache_t["offset"]
    chunk = jnp.concatenate(
        [cur[:, None], jnp.asarray(d_host, jnp.int32)], axis=1
    )  # [B, k+1]
    g, cache_t = progs["target_verify"](params, cache_t, chunk)
    rows = np.arange(B)
    cur_h = np.asarray(cur)
    if sample:
        n, g_raw = jax.device_get((n_dev, g))
        g_h = np.asarray(g_raw, np.float64)  # [B, k+1, V]
        j, tok = _spec_accept_batch(
            g_h, np.asarray(q_host, np.float64), d_host, done, np_rng,
            k_row=k_row,
        )
        nxt = np.where(done, cur_h, tok).astype(cur_h.dtype)
    else:
        n, g_h = jax.device_get((n_dev, g))  # g [B, k+1]
        match = (d_host == g_h[:, :k]).astype(np.int64)
        j = match.cumprod(axis=1).sum(axis=1)
        if k_row is not None:
            j = np.minimum(j, np.asarray(k_row, np.int64))
        j = np.where(done, 0, j)
        nxt = np.where(done, cur_h, g_h[rows, j]).astype(cur_h.dtype)
    n = np.asarray(n)
    new_n = np.where(done, n, n + 1 + j)
    if max_off is not None:
        new_n = np.minimum(new_n, max_off)
    cache_t = dict(cache_t, offset=jnp.asarray(new_n, jnp.int32))
    accepted_rows = [
        [] if done[b] else list(d_host[b, : j[b]]) + [nxt[b]]
        for b in range(B)
    ]
    return accepted_rows, nxt, cache_t


# -- paged KV: block-table memory for the decode hot path (ISSUE 19) -----
#
# The slotted server reserves one contiguous [max_len] cache row per
# slot, so admitted-batch occupancy is bounded by WORST-CASE sequence
# length — most of that memory is stranded headroom.  The paged arena
# (the vllm/PagedAttention idiom) decouples a request's logical KV from
# physical placement: the cache is a pool of fixed-size blocks
# ([n_blocks + 1, KV, block_size, D] per layer, one shared block-id
# space across layers; the +1 row is a scratch block that absorbs
# writes through unallocated table entries), and each slot maps logical
# block i to a physical block through a host-owned [slots, max_blocks]
# table.  The decode/chunk/prefill jits re-index through the table:
# gather ``pool[table]`` -> the SAME dense [B, KV, max_len, D] view the
# slotted jits compute on (so the attention math — and the greedy token
# stream — is byte-identical by construction), then scatter the view
# back through the table.  Stale bytes in not-yet-written block slots
# are invisible: the causal mask sends every position > offset to
# -1e30 before softmax, an exactly-0.0 weight on both the score*ks and
# p*vs paths.

def _paged_block_split(x: jax.Array, n_blocks: int,
                       block_size: int) -> jax.Array:
    """[KV, L(, D)] -> [n_blocks, KV, block_size(, D)] (L >= nb*bs)."""
    x = x[:, : n_blocks * block_size]
    x = x.reshape(
        (x.shape[0], n_blocks, block_size) + x.shape[2:]
    )
    return jnp.moveaxis(x, 0, 1)


def _paged_dense_view(pool_layers: list, table: jax.Array) -> list:
    """Gather the per-slot dense cache view through the block table:
    pool [NB+1, KV, BS, ...] + table [B, MB] -> [B, KV, MB*BS, ...]."""
    out = []
    for pl in pool_layers:
        lay = {}
        for kk, arr in pl.items():
            g = arr[table]                      # [B, MB, KV, BS, ...]
            g = jnp.moveaxis(g, 2, 1)           # [B, KV, MB, BS, ...]
            lay[kk] = g.reshape(
                g.shape[:2] + (g.shape[2] * g.shape[3],) + g.shape[4:]
            )
        out.append(lay)
    return out


def _paged_scatter_back(pool_layers: list, dense_layers: list,
                        table: jax.Array) -> list:
    """Inverse of :func:`_paged_dense_view`: write the dense view back
    through the table.  Table entries may repeat (CoW-shared prefix
    blocks, the scratch sentinel): shared blocks are only ever written
    VALUES THEY ALREADY HOLD (writes land at >= the sharer's first
    owned position), so duplicate-index resolution order cannot change
    the result; the scratch block absorbs every write through an
    unallocated entry and is never meaningfully read (causal mask)."""
    B, MB = table.shape
    out = []
    for pl, dl in zip(pool_layers, dense_layers):
        lay = {}
        for kk, arr in pl.items():
            d = dl[kk]                          # [B, KV, MB*BS, ...]
            d = d.reshape(
                d.shape[:2] + (MB, d.shape[2] // MB) + d.shape[3:]
            )
            d = jnp.moveaxis(d, 1, 2)           # [B, MB, KV, BS, ...]
            lay[kk] = arr.at[table].set(d)
        out.append(lay)
    return out


def _paged_row_view(pool_layers: list, table_s: jax.Array) -> list:
    """One slot's dense [1, KV, MB*BS, ...] view (table_s: [MB])."""
    out = []
    for pl in pool_layers:
        lay = {}
        for kk, arr in pl.items():
            g = arr[table_s]                    # [MB, KV, BS, ...]
            g = jnp.moveaxis(g, 0, 1)           # [KV, MB, BS, ...]
            lay[kk] = g.reshape(
                (g.shape[0], g.shape[1] * g.shape[2]) + g.shape[3:]
            )[None]
        out.append(lay)
    return out


def _paged_row_scatter(pool_layers: list, dense_layers: list,
                       table_s: jax.Array) -> list:
    """Write one slot's dense [1, KV, MB*BS, ...] rows back through its
    table row (same duplicate-index safety as the batch scatter)."""
    MB = table_s.shape[0]
    out = []
    for pl, dl in zip(pool_layers, dense_layers):
        lay = {}
        for kk, arr in pl.items():
            d = dl[kk][0]                       # [KV, MB*BS, ...]
            lay[kk] = arr.at[table_s].set(
                _paged_block_split(d, MB, d.shape[1] // MB)
            )
        out.append(lay)
    return out


def init_paged_pool(cfg: LlamaConfig, n_blocks: int, block_size: int,
                    *, quant_kv: bool = False) -> Dict:
    """Zeroed paged KV pool: per-layer [n_blocks + 1, KV, block_size,
    D] arrays (+ absmax scales under ``quant_kv``), one block-id space
    shared by every layer (block i is backed at row i of EVERY layer's
    arrays, the vllm layout).  Row ``n_blocks`` is the scratch block —
    never allocated; unassigned table entries point here so stray
    writes land somewhere harmless."""
    KV, D = cfg.n_kv_head, cfg.head_dim
    NB = n_blocks + 1

    def _layer() -> Dict:
        if quant_kv:
            return {
                "k": jnp.zeros((NB, KV, block_size, D), jnp.int8),
                "v": jnp.zeros((NB, KV, block_size, D), jnp.int8),
                "ks": jnp.zeros((NB, KV, block_size), jnp.float32),
                "vs": jnp.zeros((NB, KV, block_size), jnp.float32),
            }
        return {
            "k": jnp.zeros((NB, KV, block_size, D), cfg.dtype),
            "v": jnp.zeros((NB, KV, block_size, D), cfg.dtype),
        }

    return {"layers": [_layer() for _ in range(cfg.n_layer)]}


class PagedKvArena:
    """Host-side allocator for the paged KV pool: the free list, the
    per-slot block table, and the per-block refcounts that make
    copy-on-write prefix sharing safe.  Pure bookkeeping — no device
    arrays; the serve loop uploads ``table`` per dispatch and the jits
    re-index through it.

    Conservation law (the tier-1 invariant): every block is either on
    the free list or referenced (by a slot table or a held template) —
    ``free_blocks + used_blocks == n_blocks`` always, where
    ``used_blocks`` counts each physical block ONCE however many
    tables share it.  The chaos site ``serving.block_leak`` models a
    dropped free (refcount reaches zero but the block never returns to
    the list); :meth:`scavenge` — run every serve-loop iteration — is
    the defense that rebuilds the free list from the refcounts, so the
    law holds after any chaos run."""

    def __init__(self, n_blocks: int, block_size: int, slots: int,
                 max_len: int):
        if max_len % block_size:
            raise ValueError(
                f"max_len {max_len} must be a multiple of block_size "
                f"{block_size} (the gathered dense view must match the "
                "slotted cache shape exactly for byte-identity)"
            )
        self.n_blocks = int(n_blocks)
        self.block_size = int(block_size)
        self.slots = int(slots)
        self.max_blocks = max_len // block_size
        #: Scratch sentinel: one past the last allocatable block (the
        #: pool arrays carry an extra physical row for it).
        self.scratch = self.n_blocks
        self.leaks_repaired = 0
        self.reset()

    def reset(self) -> None:
        self.table = np.full(
            (self.slots, self.max_blocks), self.scratch, np.int32
        )
        self.lens = np.zeros((self.slots,), np.int64)
        self.ref = np.zeros((self.n_blocks,), np.int64)
        self._free = list(range(self.n_blocks - 1, -1, -1))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """Physical blocks referenced at least once (shared prefix
        blocks count ONCE — this is real memory, not table entries)."""
        return int((self.ref > 0).sum())

    def table_tokens(self) -> int:
        """Total LOGICAL tokens of table capacity currently mapped
        (``sum(table lens)`` in block units x block_size) — the
        admitted-batch footprint the occupancy metric reports."""
        return int(self.lens.sum()) * self.block_size

    def blocks_for(self, tokens: int) -> int:
        return -(-int(tokens) // self.block_size)

    def conserved(self) -> bool:
        """``free_blocks + used_blocks == n_blocks`` AND the free list
        agrees with the refcounts — the invariant the block-leak chaos
        site attacks and :meth:`scavenge` defends."""
        return (
            len(self._free) + self.used_blocks == self.n_blocks
            and all(self.ref[b] == 0 for b in self._free)
        )

    def scavenge(self) -> int:
        """Rebuild the free list from the refcounts, reclaiming any
        block whose frees were dropped (the ``serving.block_leak``
        fault).  Returns the number of leaked blocks repaired."""
        free = [b for b in range(self.n_blocks) if self.ref[b] == 0]
        leaked = len(free) - len(self._free)
        if leaked > 0:
            self.leaks_repaired += leaked
        self._free = free
        return max(0, leaked)

    def _take(self) -> int:
        blk = self._free.pop()
        self.ref[blk] = 1
        return blk

    def alloc_upto(self, s: int, tokens: int) -> bool:
        """Grow slot ``s``'s table to cover ``tokens`` logical
        positions (grow-on-demand: a request only ever holds the
        blocks its CURRENT offset + this round's writes need).  False
        — with no state change — when the pool cannot cover it."""
        need = min(self.blocks_for(tokens), self.max_blocks)
        add = need - int(self.lens[s])
        if add <= 0:
            return True
        if add > len(self._free):
            return False
        for _ in range(add):
            self.table[s, self.lens[s]] = self._take()
            self.lens[s] += 1
        return True

    def share(self, s: int, blocks: list) -> None:
        """Map slot ``s``'s first logical blocks onto ``blocks``
        (prefix sharing: refcount up, zero copies).  Only legal on an
        empty slot row."""
        assert self.lens[s] == 0
        for i, b in enumerate(blocks):
            self.table[s, i] = b
            self.ref[b] += 1
        self.lens[s] = len(blocks)

    def hold(self, n: int) -> Optional[list]:
        """Allocate ``n`` blocks owned by a prefix TEMPLATE (refcount
        held by the store, not any slot).  None if the pool is too
        tight — the caller falls back to an untemplated admission."""
        if n > len(self._free):
            return None
        return [self._take() for _ in range(n)]

    def release(self, blocks: list) -> None:
        """Drop a template's hold on ``blocks`` (store eviction)."""
        for b in blocks:
            self._drop_ref(int(b))

    def _drop_ref(self, blk: int) -> None:
        self.ref[blk] -= 1
        if self.ref[blk] == 0:
            from dlrover_tpu import chaos
            if chaos.inject("serving.block_leak", block=blk):
                # Fault: the free is dropped — the block is referenced
                # by nobody and on no list.  scavenge() repairs.
                return
            self._free.append(blk)

    def free_slot(self, s: int) -> None:
        """Return slot ``s``'s blocks (abort, deadline shed, finish,
        preemption): refcount down, back on the free list at zero —
        shared prefix blocks survive for their other holders."""
        for i in range(int(self.lens[s])):
            self._drop_ref(int(self.table[s, i]))
        self.table[s, :] = self.scratch
        self.lens[s] = 0


class DecodeServer:
    """Continuous-batching greedy/sampled decode over fixed slots — the
    role vllm plays for the reference's RL engine
    (``atorch/rl/model_engine/model_engine.py:35``): admission of new
    prompts into slots as sequences finish, so a stream of requests
    keeps every slot busy instead of waiting for the batch's slowest
    member.

    TPU shape: ONE jitted single-token step over all ``slots`` (ragged
    per-slot offsets), plus one jitted per-bucket prefill that scores a
    new prompt into a single slot's cache rows.  The host loop only
    schedules; every FLOP runs under jit at static shapes.

        srv = DecodeServer(params, cfg, slots=8, max_len=512,
                           eos_token=2)
        outs = srv.serve(list_of_prompt_arrays, max_new_tokens=128)
    """

    def __init__(
        self,
        params: Dict,
        cfg: LlamaConfig,
        *,
        slots: int = 8,
        max_len: int = 512,
        eos_token: int = -1,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        prompt_buckets: Tuple[int, ...] = (16, 32, 64, 128, 256),
        seed: int = 0,
        quant_kv: bool = False,  # int8 kv cache (see init_cache)
        draft: Optional[Tuple[Dict, LlamaConfig]] = None,
        draft_k: int = 4,
        adapt_k: bool = False,  # shrink/regrow k from measured acceptance
        adapt_every: int = 16,  # rounds per adaptation window
        # Per-REQUEST adaptive k (ISSUE 11, the serving mode): each
        # stream carries its own acceptance EWMA and speculation width
        # (``_spec_k_request``); below ``spec_break_even`` the stream
        # decodes plain (k=0, probed again every ``spec_probe_every``
        # of its rounds), so a bad draft can never make a request
        # slower than a spec-less replica.  Mutually exclusive with the
        # global ``adapt_k`` window policy.
        adapt_k_per_request: bool = False,
        spec_break_even: float = 0.0,  # 0 = 1 + 0.6*draft_k (measured
        # shape of SPEC_DECODE_CPU.json's break-even at k=4)
        spec_probe_every: int = 32,
        spec_ewma_alpha: float = 0.25,
        # Remote-draft speculation (ISSUE 11): the server may be handed
        # a draft PROPOSAL handle (``set_remote_draft``) whose rolls
        # run on a separate draft replica; declaring the intent at
        # construction sizes the cache-write headroom for speculative
        # overshoot even before a draft is attached.
        spec_remote: bool = False,
        # Plain (non-speculative) decode: tokens per dispatch.  K > 1
        # runs K steps under one lax.scan dispatch — K x fewer device
        # round-trips and host emit loops.  The cost is admission
        # latency (a slot finishing mid-chunk waits out the remainder
        # before its slot re-admits) and up to K-1 wasted writes per
        # finishing slot (covered by the capacity check's headroom;
        # finished slots are re-zeroed at admission).
        decode_chunk: int = 1,
        # Warm prefix templates retained (ISSUE 8): the incremental
        # path caches one prefilled template per prefix fingerprint so
        # requests sharing a system prompt admit with a row copy + one
        # chunk score instead of a full prefill; the gateway routes
        # fp-carrying requests to replicas already holding the
        # template.  LRU-bounded — each template is n_layer full cache
        # rows of memory.
        prefix_cache_cap: int = 4,
        # Paged KV (ISSUE 19): the cache becomes a pool of fixed-size
        # blocks plus a per-slot block table; admission reserves only
        # the blocks a request needs NOW and grows on demand, prefix
        # templates share blocks copy-on-write, and abort/finish
        # return blocks to the pool instantly.  ``pool_blocks``
        # defaults to slots * max_len / block_size — exactly the
        # slotted layout's memory, so paged-vs-slotted comparisons are
        # at matched memory unless the caller says otherwise.  Greedy
        # output is byte-identical to slotted mode (the jits gather a
        # dense view through the table and run the SAME attention
        # program).
        paged: bool = False,
        block_size: int = 16,
        pool_blocks: Optional[int] = None,
    ):
        # Sliding-window models serve on a DENSE cache (init_cache
        # ring=False): the window mask still applies in attention; the
        # ring layout's O(window) memory is incompatible with the
        # per-slot ragged offsets and rewinds this server relies on.
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.max_len = max_len
        self.eos_token = eos_token
        self.quant_kv = quant_kv
        # ``draft=(draft_params, draft_cfg)``: serve() steps via
        # speculative rounds (draft proposes draft_k, ONE chunked
        # ragged verify over all slots, per-slot acceptance) —
        # continuous batching x speculation, the full vllm-spec-decode
        # shape.  Token law per request is unchanged.
        self.draft = draft
        self.draft_k = draft_k
        self.adapt_k = adapt_k
        self.adapt_every = max(1, adapt_every)
        self.adapt_k_per_request = adapt_k_per_request
        if adapt_k and adapt_k_per_request:
            raise ValueError(
                "adapt_k (global window) and adapt_k_per_request "
                "(per-stream EWMA) are mutually exclusive policies"
            )
        self.spec_break_even = (
            float(spec_break_even) if spec_break_even > 0
            else 1.0 + 0.6 * draft_k
        )
        self.spec_probe_every = max(1, int(spec_probe_every))
        self.spec_ewma_alpha = float(spec_ewma_alpha)
        self.spec_remote = bool(spec_remote)
        #: Remote draft-proposal handle (``propose(reqs, k, sample=,
        #: close=) -> {rid: {"d": [k] ints, "q": [k, V] or None}}``);
        #: set/cleared by the replica runner as draft replicas come and
        #: go.  Any handle failure degrades THIS serve loop to plain
        #: decode until a DIFFERENT handle is attached.
        self._remote_draft: Optional[Any] = None
        #: Reusable [slots, draft_k, V] draft-prob buffer for sampled
        #: remote rounds (a fresh float64 alloc per round would be MBs
        #: of churn at production vocab sizes; stale values in rows a
        #: round does not ship are never read past their width).
        self._spec_q_buf: Optional[np.ndarray] = None
        if spec_remote and draft is not None:
            raise ValueError(
                "spec_remote does not compose with a local draft "
                "model (one proposal source per server)"
            )
        if decode_chunk < 1:
            raise ValueError(f"decode_chunk must be >= 1, got "
                             f"{decode_chunk}")
        if decode_chunk > 1 and (draft is not None or spec_remote):
            # Speculative rounds already batch k+1 tokens per dispatch;
            # silently ignoring the flag would let a user believe they
            # are benchmarking the K-dispatch lever while measuring
            # plain speculative rounds.
            raise ValueError(
                "decode_chunk > 1 does not compose with a draft model "
                "(speculative rounds already batch tokens per "
                "dispatch); set one or the other"
            )
        self.decode_chunk = decode_chunk
        # Telemetry of the last serve() call, reset at the top of every
        # serve(): the speculative path reports rounds / acceptance /
        # the k trajectory; the plain and decode_chunk paths report
        # rounds and emitted tokens.
        self.last_stats: Dict[str, Any] = {}
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self._np_rng = np.random.default_rng(seed + 1)
        self.buckets = tuple(
            b for b in sorted(prompt_buckets) if b <= max_len
        )
        self._pick = _make_sampler(temperature, top_k, top_p)
        self._prefill_jit: Dict[Any, Any] = {}
        # Host-managed sampling stream: every step/prefill consumes a
        # FRESH subkey (a constant key would make non-greedy serving
        # degenerate — identical noise each step collapses samples into
        # short loops).
        self._rng = jax.random.PRNGKey(seed)
        # Incremental admission surface (ISSUE 5): ``submit`` enqueues
        # (rid, prompt, max_new_tokens) and the serve loop admits from
        # this deque as slots free — the fleet replica feeds gateway
        # grants in while decoding, instead of handing the full prompt
        # list up front.  The lock makes submit/cancel safe from a
        # second thread, though the fleet runner is single-threaded.
        self._pending: "collections.deque" = collections.deque()
        self._pending_mu = threading.Lock()
        self._abort_rids: set = set()
        # Prefix-template store (ISSUE 8): fp -> {"prefix", "p0",
        # "layers": {role: template layers}}, LRU order.  Hit/miss
        # counts feed the replica's poll stats so the gateway's
        # residency map self-corrects.
        self.prefix_cache_cap = max(1, int(prefix_cache_cap))
        # Paged KV arena (ISSUE 19).
        self.paged = bool(paged)
        self.block_size = int(block_size)
        if self.paged:
            if block_size < 1:
                raise ValueError(f"block_size must be >= 1, got "
                                 f"{block_size}")
            if max_len % self.block_size:
                raise ValueError(
                    f"paged mode needs max_len ({max_len}) to be a "
                    f"multiple of block_size ({block_size}): the "
                    "gathered view must match the slotted cache shape "
                    "exactly for byte-identical output"
                )
        self.pool_blocks = (
            int(pool_blocks) if pool_blocks is not None
            else slots * (max_len // self.block_size)
        ) if self.paged else 0
        self.kv_arena: Optional[PagedKvArena] = (
            PagedKvArena(self.pool_blocks, self.block_size, slots,
                         max_len)
            if self.paged else None
        )
        #: Preemptions this serve call (paged grow-on-demand sheds the
        #: youngest slot when the pool runs dry; the request requeues
        #: at the FRONT and greedy decode regenerates its stream).
        self.preemptions = 0
        #: rid -> tokens already delivered via on_token before a
        #: preemption (re-admission suppresses re-emitting them).
        self._preempt_emitted: Dict[Any, int] = {}
        #: Monotone serve-call counter: paged prefix templates
        #: materialize pool blocks per RUN (the pool is rebuilt each
        #: serve call) and tag them with this.
        self._paged_run_seq = 0
        self._prefix_store: "collections.OrderedDict" = \
            collections.OrderedDict()
        self.prefix_hits = 0
        self.prefix_misses = 0
        # Prefill-role exports (ISSUE 8): rid -> prefilled slot rows
        # awaiting export_kv (host arrays; dropped on export).
        self._kv_exports: Dict[Any, Dict[str, Any]] = {}
        # Per-request speculation telemetry (ISSUE 11): finished
        # requests park their accepted-tokens-per-round here until the
        # runner pops them into the ServeDone/journal record.  Bounded
        # oldest-first — the runner pops immediately, the cap only
        # guards a caller that never does.
        self._req_stats_out: "collections.OrderedDict" = \
            collections.OrderedDict()
        # Live views for the replica runner's poll report (valid while
        # a serve loop runs; empty otherwise).
        self._live_active: Any = None
        self._live_slot_req: Any = None

        def step(params, cache, toks, active, sub):
            logits, new_cache = forward_step(
                params, toks[:, None], cfg, cache
            )
            nxt = self._pick(logits[:, -1, :], sub)
            # Inactive slots freeze (offset unchanged -> cache rows
            # stable while awaiting admission).
            frozen = jnp.where(
                active, new_cache["offset"], cache["offset"]
            )
            return dict(new_cache, offset=frozen), nxt.astype(toks.dtype)

        self._step = jax.jit(step)

        def chunk_step(params, cache, toks, active, sub):
            # decode_chunk steps under ONE dispatch (lax.scan): on a
            # tunneled/async backend each dispatch costs real latency,
            # and the host emit loop costs more — K tokens per round
            # divides both by K.
            def body(carry, key):
                cache, toks = carry
                cache, nxt = step(params, cache, toks, active, key)
                return (cache, nxt), nxt

            (cache, toks), ys = jax.lax.scan(
                body, (cache, toks),
                jax.random.split(sub, self.decode_chunk),
            )
            return cache, toks, jnp.moveaxis(ys, 0, 1)  # [B, K]

        self._chunk_step = jax.jit(chunk_step)

        if self.paged:
            # The decode hot path re-indexed through the block table
            # (ISSUE 19): gather pool[table] -> the SAME dense view the
            # slotted jits compute on, run the identical step program,
            # scatter the view back.  One compiled program per shape,
            # memoized like every other jit here; the chunk variant
            # amortizes the gather/scatter over decode_chunk steps.
            def step_paged(params, pool_layers, table, offset, toks,
                           active, sub):
                dense = {
                    "layers": _paged_dense_view(pool_layers, table),
                    "offset": offset,
                }
                new_dense, nxt = step(params, dense, toks, active, sub)
                return (
                    _paged_scatter_back(
                        pool_layers, new_dense["layers"], table
                    ),
                    new_dense["offset"], nxt,
                )

            self._step_paged = jax.jit(step_paged)

            def chunk_step_paged(params, pool_layers, table, offset,
                                 toks, active, sub):
                dense = {
                    "layers": _paged_dense_view(pool_layers, table),
                    "offset": offset,
                }
                dense, toks, ys = chunk_step(
                    params, dense, toks, active, sub
                )
                return (
                    _paged_scatter_back(
                        pool_layers, dense["layers"], table
                    ),
                    dense["offset"], toks, ys,
                )

            self._chunk_step_paged = jax.jit(chunk_step_paged)

            # Whole-cache gather/scatter, for the speculative rounds:
            # the spec programs (_spec_decode_round and friends) run
            # unchanged on the gathered dense view, then the view
            # scatters back — two extra dispatches per spec round buy
            # zero drift from the slotted acceptance laws.
            def gather_all(pool_layers, table, offset):
                return {
                    "layers": _paged_dense_view(pool_layers, table),
                    "offset": offset,
                }

            self._paged_gather = jax.jit(gather_all)
            self._paged_scatter = jax.jit(_paged_scatter_back)

    def block_stats(self) -> Optional[Dict[str, Any]]:
        """Live block-pool telemetry (None on a slotted server): what
        the replica folds into its gateway poll so admission and
        autoscale see real memory headroom instead of slot counts."""
        arena = self.kv_arena
        if arena is None:
            return None
        used = arena.used_blocks
        return {
            "total_blocks": arena.n_blocks,
            "free_blocks": arena.free_blocks,
            "block_occupancy": used / max(1, arena.n_blocks),
            "preemptions": self.preemptions,
        }

    def _next_key(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt of {n} tokens exceeds largest bucket "
            f"{self.buckets[-1]}"
        )

    def _write_slack(self) -> int:
        """Cache-write headroom past the emission budget: speculative
        rounds (local OR remote draft) overshoot by up to draft_k+1
        slots before the rewind; chunked decode writes up to
        decode_chunk-1 slots past a mid-chunk finish.  An out-of-range
        scatter is silently DROPPED by JAX, so every capacity check
        must include this."""
        return (
            (self.draft_k + 1) if self.spec_capable
            else self.decode_chunk - 1
        )

    @property
    def spec_capable(self) -> bool:
        """This server can run speculative rounds: a local draft model,
        or the declared intent to accept a remote draft handle — what
        the replica advertises in ``ServeReplicaRegister.spec``."""
        return self.draft is not None or self.spec_remote

    def set_remote_draft(self, handle) -> None:
        """Attach (or with ``None`` detach) a remote draft-proposal
        handle.  Only legal on a ``spec_remote`` server — the cache
        headroom and capacity checks were sized for speculation at
        construction; attaching a draft to an unsized server could
        scatter past max_len."""
        if handle is not None and not self.spec_remote:
            raise ValueError(
                "set_remote_draft on a server built without "
                "spec_remote=True (capacity headroom not sized for "
                "speculative overshoot)"
            )
        self._remote_draft = handle

    def pop_request_stats(self, rid) -> Optional[Dict[str, Any]]:
        """Consume the per-request speculation telemetry recorded when
        ``rid`` finished: ``{"tokens_per_round", "spec_rounds",
        "k_last"}`` — what the runner folds into the ServeDone report
        and the journal record (so a replay reports the SAME
        acceptance the request earned live).  None for requests that
        never ran speculative rounds."""
        with self._pending_mu:
            return self._req_stats_out.pop(rid, None)

    def check_capacity(self, prompt_len: int, max_new_tokens: int,
                       prefix_len: int = 0) -> None:
        """Raise ValueError if a request of this shape could ever write
        past ``max_len`` (shared by serve()'s upfront sweep and
        submit()'s per-request admission check)."""
        need = prefix_len + prompt_len + max_new_tokens + \
            self._write_slack()
        if need > self.max_len:
            raise ValueError(
                (f"prefix {prefix_len} + " if prefix_len else "")
                + f"prompt {prompt_len} + max_new_tokens "
                f"{max_new_tokens} + headroom {self._write_slack()} "
                f"= {need} exceeds max_len {self.max_len}"
            )
        if self.paged:
            # Pool-wide law: a request that could never fit the WHOLE
            # pool (even alone) must reject at submit, not livelock
            # the admission loop waiting for blocks that cannot exist.
            blocks = self.kv_arena.blocks_for(need)
            if blocks > self.pool_blocks:
                raise ValueError(
                    f"request needs {blocks} KV blocks "
                    f"({need} tokens at block_size "
                    f"{self.block_size}) but the pool holds only "
                    f"{self.pool_blocks}"
                )

    def submit(self, rid, prompt, max_new_tokens: int,
               prefix_len: int = 0, prefix_fp: str = "") -> None:
        """Enqueue one request for incremental admission: the running
        serve loop (``serve_incremental``) admits it the next time a
        slot frees.  ``rid`` is the caller's request key (any hashable
        — the fleet uses gateway request-id strings).  Raises
        ValueError immediately if the request can never fit.

        ``prefix_len > 0`` declares ``prompt[:prefix_len]`` a shared
        template (ISSUE 8): admission rides the per-fingerprint prefix
        store — a warm template admits with a row copy + one chunk
        score; a cold one is prefilled once and retained (LRU) for the
        next request carrying the same fingerprint.  Results are
        byte-identical to the untemplated path."""
        p = np.asarray(prompt, np.int32)
        self.check_capacity(len(p), max_new_tokens)
        extra = None
        if prefix_len:
            if not 0 < int(prefix_len) < len(p):
                raise ValueError(
                    f"prefix_len {prefix_len} out of range for a "
                    f"{len(p)}-token prompt"
                )
            extra = {
                "prefix_len": int(prefix_len),
                "prefix_fp": prefix_fp
                or prefix_fingerprint(p[: int(prefix_len)]),
            }
        with self._pending_mu:
            self._pending.append((rid, p, int(max_new_tokens), extra))

    def cancel(self, rid) -> bool:
        """Drop a not-yet-admitted request (deadline expiry at the
        gateway).  Returns False when ``rid`` is unknown or already
        decoding — in-flight work is never interrupted."""
        with self._pending_mu:
            for i, item in enumerate(self._pending):
                if item[0] == rid:
                    del self._pending[i]
                    return True
        return False

    def abort(self, rid) -> bool:
        """Mid-decode load shedding (gateway deadline expiry): a
        pending ``rid`` is dropped immediately; an ACTIVE one is freed
        at the loop's next admission point, its partial output
        discarded — no ``on_finish``, no results entry, the slot
        re-admits.  Returns False for an unknown (or already finished)
        rid."""
        if self.cancel(rid):
            return True
        if rid in self.active_rids():
            with self._pending_mu:
                self._abort_rids.add(rid)
            return True
        return False

    def _pop_pending(self):
        with self._pending_mu:
            return self._pending.popleft() if self._pending else None

    def pending_count(self) -> int:
        with self._pending_mu:
            return len(self._pending)

    def pending_rids(self) -> list:
        with self._pending_mu:
            return [item[0] for item in self._pending]

    def active_rids(self) -> list:
        """Request ids currently decoding in slots (live only while a
        serve loop runs)."""
        act, req = self._live_active, self._live_slot_req
        if act is None or req is None:
            return []
        return [req[s] for s in range(self.slots) if act[s]]

    def free_slots(self) -> int:
        """Slots a new admission could use right now: total minus
        decoding minus already-queued — the load signal the replica
        reports to the gateway's least-loaded router."""
        act = self._live_active
        busy = int(act.sum()) if act is not None else 0
        return max(0, self.slots - busy - self.pending_count())

    # -- prefix templates & prefill/decode disaggregation (ISSUE 8) ------

    def warm_prefix_fps(self) -> list:
        """Fingerprints of the prefix templates currently held warm —
        what the replica reports in its gateway poll so the router can
        steer matching requests here."""
        with self._pending_mu:
            return list(self._prefix_store)

    def clear_prefix_templates(self) -> None:
        """Drop every warm template and zero the hit/miss counters —
        warmup hygiene: a compile-warming dummy must not occupy the
        LRU, report warm to the router, or skew the hit-rate."""
        with self._pending_mu:
            for entry in self._prefix_store.values():
                self._release_template_blocks(entry)
            self._prefix_store.clear()
            self.prefix_hits = 0
            self.prefix_misses = 0

    def _roles(self):
        roles = [("t", self.params, self.cfg)]
        if self.draft is not None:
            roles.append(("d", self.draft[0], self.draft[1]))
        return roles

    def _template_layers(self, role, mparams, mcfg, pref_dev, P0):
        """Prefill ``pref_dev`` [1, P0] into a fresh 1-row cache and
        return its layers — THE template build, shared by the batch
        path (``_build_prefix_templates``) and the fingerprint store.
        Memoized per (role, prefix length); only the cache is returned,
        so XLA dead-code-eliminates the lm_head matmul."""
        tc = init_cache(mcfg, 1, self.max_len,
                        quant_kv=self.quant_kv, ring=False)
        jkey = ("tmpl_prefill", role, P0)
        if jkey not in self._prefill_jit:
            def fn(p, pr, c, _cfg=mcfg):
                return forward_step(p, pr, _cfg, c)[1]

            self._prefill_jit[jkey] = jax.jit(fn)
        return self._prefill_jit[jkey](mparams, pref_dev, tc)["layers"]

    def _ensure_prefix_template(self, prefix, fp: str) -> Dict[str, Any]:
        """Template-store lookup/build for one fingerprint: a hit
        returns the warm entry (LRU-refreshed); a miss — or an entry
        whose stored prefix MISMATCHES the fingerprint's claimed tokens
        (collision, stale reuse) — prefills the template once and
        retains it, evicting the coldest past ``prefix_cache_cap``."""
        prefix = np.asarray(prefix, np.int32)
        # Store mutations ride _pending_mu (the readers —
        # warm_prefix_fps from a poll thread, clear_prefix_templates —
        # already do); the template BUILD runs outside the lock, it is
        # seconds of XLA on a cold fingerprint.
        with self._pending_mu:
            entry = self._prefix_store.get(fp)
            if entry is not None and (
                entry["p0"] != len(prefix)
                or not np.array_equal(entry["prefix"], prefix)
            ):
                # Fingerprint mismatch: never serve another prefix's
                # rows.
                del self._prefix_store[fp]
                self._release_template_blocks(entry)
                entry = None
            if entry is not None:
                self.prefix_hits += 1
                self._prefix_store.move_to_end(fp)
                return entry
            self.prefix_misses += 1
        P0 = len(prefix)
        pref_dev = jnp.asarray(prefix)[None, :]
        layers = {
            role: self._template_layers(role, mparams, mcfg,
                                        pref_dev, P0)
            for role, mparams, mcfg in self._roles()
        }
        entry = {"prefix": prefix, "p0": P0, "layers": layers}
        with self._pending_mu:
            self._prefix_store[fp] = entry
            while len(self._prefix_store) > self.prefix_cache_cap:
                _, old = self._prefix_store.popitem(last=False)
                self._release_template_blocks(old)
        return entry

    def _release_template_blocks(self, entry: Dict[str, Any]) -> None:
        """Return an evicted template's pool blocks (paged mode): the
        store's refcount hold drops; blocks a live slot still SHARES
        survive on that slot's own refcount."""
        pb = entry.pop("_paged", None)
        if (
            pb is not None
            and self.kv_arena is not None
            and pb.get("run") == self._paged_run_seq
        ):
            self.kv_arena.release(pb["ids"])

    def prefill_request(self, rid, prompt, max_new_tokens: int,
                        prefix_len: int = 0,
                        prefix_fp: str = "") -> int:
        """Prefill-role entry (ISSUE 8): score ``prompt`` into a fresh
        1-row cache (prefix templates honoured), sample the first
        token, and stage the written rows for :meth:`export_kv`.
        Returns the first token.  Host-synchronous — a prefill replica
        does nothing else with its slots."""
        if self.draft is not None:
            raise ValueError(
                "prefill/decode disaggregation does not compose with "
                "a draft model (the draft cache is not shipped)"
            )
        p = np.asarray(prompt, np.int32)
        n = len(p)
        self.check_capacity(n, max_new_tokens)
        C = self.buckets[-1]
        tmpl = None
        p0 = 0
        if prefix_len and n > C:
            p0 = int(prefix_len)
            if not 0 < p0 < n:
                raise ValueError(
                    f"prefix_len {prefix_len} out of range for a "
                    f"{n}-token prompt"
                )
            fp = prefix_fp or prefix_fingerprint(p[:p0])
            tmpl = self._ensure_prefix_template(p[:p0], fp)
        if tmpl is None and n <= C:
            # One bucketed prefill, memoized per bucket size.
            b = self._bucket(n)
            jkey = ("solo", b)
            if jkey not in self._prefill_jit:
                def fn(params, padded, plen, key):
                    c = init_cache(self.cfg, 1, self.max_len,
                                   quant_kv=self.quant_kv, ring=False)
                    logits, c = forward_step(params, padded, self.cfg, c)
                    first = self._pick(logits[0, plen - 1][None, :],
                                       key)[0]
                    return c["layers"], first

                self._prefill_jit[jkey] = jax.jit(fn)
            padded = np.zeros((b,), np.int32)
            padded[:n] = p
            layers, first = self._prefill_jit[jkey](
                self.params, jnp.asarray(padded)[None, :],
                jnp.asarray(n, jnp.int32), self._next_key(),
            )
        else:
            # Chunked prefill on the 1-row cache: every chunk is FULL,
            # the final window shifts back to [n-C, n) — the re-score
            # is value-identical (complete prefix, causal attention;
            # see admit_one_cache's derivation).
            if tmpl is not None:
                layers = tmpl["layers"]["t"]
                c_start = min(C * (p0 // C), n - C)
            else:
                layers = init_cache(
                    self.cfg, 1, self.max_len,
                    quant_kv=self.quant_kv, ring=False,
                )["layers"]
                c_start = 0
            jkey = ("solo_chunk", C)
            if jkey not in self._prefill_jit:
                def fn(params, layers_, chunk, off):
                    logits, c = forward_step(
                        params, chunk, self.cfg,
                        {"layers": layers_, "offset": off},
                    )
                    return c["layers"], logits[0]

                self._prefill_jit[jkey] = jax.jit(fn)
            step = self._prefill_jit[jkey]
            last = None
            for c0 in range(c_start, n, C):
                start = c0 if c0 + C <= n else n - C
                layers, logits = step(
                    self.params, layers,
                    jnp.asarray(p[start: start + C])[None, :],
                    jnp.asarray(start, jnp.int32),
                )
                if start + C >= n:
                    last = logits[(n - 1) - start]
            first = self._pick(last[None, :], self._next_key())[0]
        layers_host = [
            {kk: np.asarray(cl[kk])[:, :, :n] for kk in cl}
            for cl in layers
        ]
        first = int(first)
        self._kv_exports[rid] = {
            "layers": layers_host, "n": n, "first": first,
        }
        return first

    def export_kv(self, rid) -> Tuple[bytes, int]:
        """Package the staged prefill rows of ``rid`` for the handoff:
        ``(payload, fp32_bytes)`` from :func:`pack_kv_segment` (int8
        codes + per-slot scales when ``quant_kv``; CRC embedded).  The
        staged entry is consumed — a lost payload re-prefills."""
        info = self._kv_exports.pop(rid, None)
        if info is None:
            raise ValueError(f"no staged prefill for request {rid!r}")
        return pack_kv_segment(
            info["layers"], info["n"], info["first"], self.quant_kv,
            # Paged servers ship a BLOCK LIST (per-block CRCs; the
            # decode side writes frames straight into pool blocks).
            block_size=self.block_size if self.paged else 0,
        )

    def import_kv(self, rid, payload: bytes, prompt,
                  max_new_tokens: int) -> None:
        """Decode-role admission from a shipped KV segment: verify
        (:func:`unpack_kv_segment` CRC + shape/dtype/config coherence
        against THIS server), pad the rows to the slot length, and
        enqueue for the serve loop to write into a freeing slot.
        Raises :class:`KvSegmentError` on any mismatch — a torn or
        foreign segment is never decoded from."""
        if self.draft is not None:
            raise ValueError(
                "KV import does not compose with a draft model (the "
                "draft cache is not shipped)"
            )
        seg = unpack_kv_segment(payload)
        p = np.asarray(prompt, np.int32)
        n = seg["n"]
        if n != len(p):
            raise KvSegmentError(
                f"KV segment covers {n} tokens but the grant prompt "
                f"has {len(p)}"
            )
        if seg["quant"] != self.quant_kv:
            raise KvSegmentError(
                f"KV segment quant={seg['quant']} but this server has "
                f"quant_kv={self.quant_kv}"
            )
        self.check_capacity(n, max_new_tokens)
        cfg = self.cfg
        want_keys = {"k", "v", "ks", "vs"} if self.quant_kv else \
            {"k", "v"}
        if len(seg["layers"]) != cfg.n_layer:
            raise KvSegmentError(
                f"KV segment has {len(seg['layers'])} layers, model "
                f"has {cfg.n_layer}"
            )
        ref = init_cache(cfg, 1, 1, quant_kv=self.quant_kv, ring=False)
        ref_layer = ref["layers"][0]
        padded = []
        for lay in seg["layers"]:
            if set(lay) != want_keys:
                raise KvSegmentError(
                    f"KV segment keys {sorted(lay)} != "
                    f"{sorted(want_keys)}"
                )
            out = {}
            for kk, arr in lay.items():
                want_dt = np.dtype(ref_layer[kk].dtype)
                # Expectation from the REFERENCE layout, never from the
                # untrusted payload's own ndim — a mis-declared meta
                # must reject cleanly here, not crash the jitted
                # writeback inside the serve loop.
                want_shape = (1, cfg.n_kv_head, n) + (
                    (cfg.head_dim,) if ref_layer[kk].ndim == 4 else ()
                )
                if arr.shape != want_shape or \
                        np.dtype(arr.dtype) != want_dt:
                    raise KvSegmentError(
                        f"KV segment {kk}: shape {arr.shape} dtype "
                        f"{arr.dtype} != expected {want_shape} "
                        f"{want_dt}"
                    )
                if self.paged:
                    # Paged admission writes whole blocks: pad only to
                    # the block boundary, not the full slot length.
                    tail = self.kv_arena.blocks_for(n) \
                        * self.block_size - n
                else:
                    tail = self.max_len - n
                pad = [(0, 0)] * arr.ndim
                pad[2] = (0, tail)
                out[kk] = np.pad(arr, pad)
            padded.append(out)
        extra = {"kv": {
            "layers": padded, "n": n, "first": seg["first"],
        }}
        with self._pending_mu:
            self._pending.append(
                (rid, p, int(max_new_tokens), extra)
            )

    @staticmethod
    def _slot_subcache(cache: Dict, s) -> list:
        """Per-layer [1, ...] views of slot ``s``'s cache rows.
        Iterates the layer dict's KEYS so the int8 layout's scale
        arrays ("ks"/"vs") ride along with "k"/"v" (every cache array
        is [slots, ...]-leading)."""
        return [
            {
                kk: jax.lax.dynamic_slice_in_dim(cl[kk], s, 1, 0)
                for kk in cl
            }
            for cl in cache["layers"]
        ]

    @staticmethod
    def _slot_writeback(cache: Dict, sub_layers: list, s) -> list:
        """Write per-layer [1, ...] sub-rows back into slot ``s``."""
        return [
            {
                kk: jax.lax.dynamic_update_slice_in_dim(
                    cl[kk], sc[kk], s, 0
                )
                for kk in cl
            }
            for cl, sc in zip(cache["layers"], sub_layers)
        ]

    def _remote_propose(self, handle, k: int, k_arr, active, slot_req,
                        slot_prompt, slot_out, draft_mark, draft_open,
                        draft_close, sample: bool):
        """Collect per-stream context deltas and fetch one round of
        proposals from the remote draft handle (ISSUE 11).  Streams
        unknown to the draft ship their full prompt (``open``); known
        ones ship only the tokens emitted since the last roll — the
        draft catches its cache up from exactly that delta.  Returns
        ``(d [B, k], q [B, k, V] | None, k_arr)`` with rows the draft
        dropped (evicted stream) forced to width 0 for this round, or
        ``None`` on a handle failure — the caller degrades to plain
        decode, it never stalls."""
        import numpy as onp

        B = self.slots
        reqs = []
        shipped = []
        for s in range(B):
            if not active[s] or (k_arr is not None and k_arr[s] == 0):
                continue
            # rids normalize to str on the wire (msgpack map keys);
            # batch-mode int rids must round-trip identically.
            entry: Dict[str, Any] = {"rid": str(slot_req[s])}
            if draft_open[s]:
                entry["ctx"] = [
                    int(t) for t in slot_out[s][draft_mark[s]:]
                ]
            else:
                entry["open"] = [int(t) for t in slot_prompt[s]]
                entry["ctx"] = [int(t) for t in slot_out[s]]
            reqs.append(entry)
            shipped.append(s)
        close, draft_close[:] = list(draft_close), []
        try:
            props = handle.propose(reqs, k, sample=sample, close=close)
        except Exception as e:  # noqa: BLE001 - degrade, never stall
            draft_close.extend(close)  # undelivered; retry on re-attach
            logger.warning("remote draft proposal failed: %s", e)
            return None
        V = self.cfg.vocab_size
        d = onp.zeros((B, k), onp.int64)
        q = None
        if sample:
            # Width-0 / dropped rows never read their q past their
            # width — the uniform filler (and any stale probs from a
            # previous round) only keeps the batched arithmetic
            # finite, so the buffer is reused across rounds.
            if self._spec_q_buf is None:
                self._spec_q_buf = onp.full(
                    (B, self.draft_k, V), 1.0 / V, onp.float64
                )
            q = self._spec_q_buf[:, :k]
        if k_arr is None:
            k_arr = onp.where(
                onp.asarray(active, bool), k, 0
            ).astype(onp.int64)
        else:
            k_arr = onp.asarray(k_arr, onp.int64).copy()
        props = props or {}
        for s in shipped:
            got = props.get(str(slot_req[s]))
            if got is None:
                # The draft dropped/evicted this stream: plain law for
                # the round; re-open (full context) on the next roll.
                k_arr[s] = 0
                draft_open[s] = False
                continue
            dk = onp.asarray(got["d"], onp.int64).reshape(-1)[:k]
            d[s, : len(dk)] = dk
            if len(dk) < k:
                k_arr[s] = min(int(k_arr[s]), len(dk))
            if sample:
                qk = onp.asarray(got.get("q"), onp.float64)
                if qk.ndim != 2 or qk.shape[1] != V:
                    # A malformed proposal law is a broken draft, not a
                    # dropped stream: the worker already advanced its
                    # cache by this ctx, so re-shipping would corrupt
                    # its offsets — fail the handle instead.
                    logger.warning(
                        "remote draft returned malformed probs for "
                        "%s; dropping the draft", slot_req[s],
                    )
                    return None
                qn = min(k, qk.shape[0])
                q[s, :qn] = qk[:qn]
            draft_mark[s] = len(slot_out[s])
            draft_open[s] = True
        return d, q, k_arr

    def _prefill(self, bucket: int, cfg: Optional[LlamaConfig] = None):
        """Jitted: score one right-padded prompt into slot ``s``'s cache
        rows; returns (cache, first sampled token).  ``cfg`` defaults
        to the target model's (pass the draft's to admit into the
        draft cache)."""
        cfg = cfg or self.cfg

        def fn(params, cache, s, prompt, plen, key):
            # Fresh zero rows for this slot (slot reuse must not see a
            # previous occupant's keys beyond the causal mask).
            sub = {
                "layers": [
                    {kk: jnp.zeros_like(c[kk]) for kk in c}
                    for c in self._slot_subcache(cache, s)
                ],
                "offset": jnp.zeros((), jnp.int32),
            }
            logits, sub = forward_step(params, prompt[None, :], cfg, sub)
            last = logits[0, plen - 1, :]
            first = self._pick(last[None, :], key)[0]
            new_layers = self._slot_writeback(cache, sub["layers"], s)
            new_offset = cache["offset"].at[s].set(plen)
            return dict(cache, layers=new_layers, offset=new_offset), first

        return jax.jit(fn)

    def _prefill_chunk(self, C: int,
                       cfg: Optional[LlamaConfig] = None):
        """Jitted: score ONE full [1, C] chunk continuing slot ``s``'s
        sub-cache at offset ``off`` (``zero_first`` wipes the slot's
        rows for fresh admission).  Returns (cache, chunk logits
        [C, V]).  Looping this admits prompts of ANY length with one
        compiled program (see ``admit_chunked`` for the final-chunk
        window shift that keeps every write in bounds)."""
        cfg = cfg or self.cfg

        def fn(params, cache, s, chunk, off, zero_first):
            sub = {
                "layers": [
                    {
                        kk: jnp.where(
                            zero_first, jnp.zeros_like(c[kk]), c[kk]
                        )
                        for kk in c
                    }
                    for c in self._slot_subcache(cache, s)
                ],
                "offset": off,
            }
            logits, sub = forward_step(params, chunk, cfg, sub)
            new_layers = self._slot_writeback(cache, sub["layers"], s)
            new_offset = cache["offset"].at[s].set(off + C)
            return (
                dict(cache, layers=new_layers, offset=new_offset),
                logits[0],
            )

        return jax.jit(fn)

    def serve(self, prompts, max_new_tokens: int, on_finish=None,
              on_token=None, shared_prefix=None):
        """Decode every prompt (a list of 1-D int arrays); returns a
        list of 1-D arrays (prompt + continuation, EOS included).

        ``on_finish(rid, tokens)`` fires the moment request ``rid``
        completes (its slot is freed for re-admission) — the hook
        elastic serving journals completions through, so a worker kill
        mid-serve only costs the in-flight requests (replayed on
        restart), never the finished ones.

        ``on_token(rid, token)`` fires for every emitted token the
        round it lands on the host — token streaming (the role of
        vllm's streaming API), including each request's FIRST token
        (sampled at prefill).  With ``decode_chunk=K`` or a draft,
        tokens arrive in bursts of up to K / k+1 per round — that is
        the latency the dispatch batching buys throughput with.

        ``shared_prefix`` (1-D int array): PREFIX CACHING, the role of
        vllm's automatic prefix caching for the common case of one
        system prompt shared by every request.  The prefix prefills
        ONCE into a template; each admission copies the template's kv
        rows into its slot (one dynamic_update_slice per layer — a
        memory move, no FLOPs) and chunk-scores only from the first
        chunk containing its own tokens.  Results and the output law
        are EXACTLY ``serve([prefix + p for p in prompts])``; admission
        cost drops from O(prefix + prompt) to O(chunk + prompt) scoring
        FLOPs per request."""
        import numpy as onp

        prefix = None
        if shared_prefix is not None:
            prefix = onp.asarray(shared_prefix, onp.int32)
            if prefix.ndim != 1 or prefix.size == 0:
                raise ValueError(
                    "shared_prefix must be a non-empty 1-D token array"
                )
        P0 = 0 if prefix is None else len(prefix)
        for rid, prompt in enumerate(prompts):
            try:
                self.check_capacity(len(prompt), max_new_tokens, P0)
            except ValueError as e:
                raise ValueError(f"request {rid}: {e}") from None
        with self._pending_mu:
            if self._pending:
                # serve() and the incremental surface are exclusive
                # modes: silently clearing would DROP submitted
                # requests with no error and no on_finish.  (Checked
                # BEFORE the prefix-template prefill below — the error
                # must be immediate and free, not after seconds of
                # discarded XLA work.)
                raise RuntimeError(
                    f"serve() cannot run with {len(self._pending)} "
                    "incremental submission(s) queued; drain or "
                    "cancel them first (serve()/serve_incremental "
                    "are exclusive modes)"
                )
        templates = self._build_prefix_templates(prefix, prompts)
        with self._pending_mu:
            for rid, prompt in enumerate(prompts):
                self._pending.append(
                    (rid, onp.asarray(prompt, onp.int32),
                     int(max_new_tokens), None)
                )
        results = self._run(
            on_finish=on_finish, on_token=on_token,
            prefix=prefix, templates=templates,
        )
        return [results[i] for i in range(len(prompts))]

    def serve_incremental(self, tick=None, on_finish=None,
                          on_token=None, idle_wait: float = 0.002):
        """Serve requests fed in by :meth:`submit` — the fleet
        replica's decode loop (ISSUE 5).  ``tick()`` is called once per
        loop iteration (the admission point): the replica runner polls
        the gateway there, submits new grants, flushes token streams
        and reports completions.  Returning ``False`` from ``tick``
        drains the loop — in-flight and already-submitted requests
        finish, then the call returns (the scale-down contract: no
        admitted request ever observes the shrink).  With no pending or
        active work the loop idles at ``idle_wait`` granularity until
        ``tick`` stops it.  Completions are delivered via ``on_finish``
        ONLY (the batch-mode result dict is not retained — it would
        grow without bound over a replica's lifetime); returns {}."""
        return self._run(
            on_finish=on_finish, on_token=on_token,
            prefix=None, templates={}, tick=tick, idle_wait=idle_wait,
        )

    def _build_prefix_templates(self, prefix, prompts) -> Dict[str, Any]:
        """Prefix templates: the shared prefix prefilled ONCE per model
        into a 1-row cache with the server's row length, so admission
        can copy whole slot rows (zeros beyond P0 included — the copy
        doubles as the fresh-slot zeroing)."""
        templates: Dict[str, Any] = {}
        P0 = 0 if prefix is None else len(prefix)
        if prefix is not None and any(
            P0 + len(p) > self.buckets[-1] for p in prompts
        ):
            # (gated: if every combined prompt fits one bucket, every
            # admission scratch-prefills and the template would be
            # built for nothing)
            pref_dev = jnp.asarray(prefix)[None, :]
            for role, mparams, mcfg in self._roles():
                templates[role] = self._template_layers(
                    role, mparams, mcfg, pref_dev, P0
                )
        return templates

    def _run(self, on_finish=None, on_token=None, prefix=None,
             templates=None, tick=None, idle_wait: float = 0.002):
        """The decode loop shared by :meth:`serve` (batch mode: the
        pending queue is pre-filled and runs to drain) and
        :meth:`serve_incremental` (``tick`` feeds the queue while the
        loop runs).  Admission draws from ``self._pending``; every
        request carries its OWN max_new_tokens budget."""
        import numpy as onp

        # Telemetry contract: last_stats describes THIS call for every
        # decode path (stale stats from a previous speculative serve
        # must not survive into a plain one).
        self.last_stats = {}
        cfg = self.cfg
        B = self.slots
        templates = templates or {}
        P0 = 0 if prefix is None else len(prefix)
        results: Dict[Any, Any] = {}
        arena = self.kv_arena
        table_dev: Any = None  # device copy of arena.table, lazy
        if self.paged:
            # Fresh pool per serve call (the slotted path rebuilds its
            # cache per call too); templates re-materialize their
            # blocks lazily under the new run tag.
            arena.reset()
            self._paged_run_seq += 1
            self.preemptions = 0
            pool = init_paged_pool(
                cfg, self.pool_blocks, self.block_size,
                quant_kv=self.quant_kv,
            )
            cache = {
                "layers": pool["layers"],
                "offset": jnp.zeros((B,), jnp.int32),
            }
        else:
            cache = init_cache(cfg, B, self.max_len,
                               quant_kv=self.quant_kv, ring=False)
            cache = dict(cache, offset=jnp.zeros((B,), jnp.int32))

        def table_device():
            nonlocal table_dev
            if table_dev is None:
                table_dev = jnp.asarray(arena.table)
            return table_dev

        def table_dirty():
            nonlocal table_dev
            table_dev = None
        cache_d = None
        if self.draft is not None:
            cache_d = init_cache(self.draft[1], B, self.max_len,
                                 quant_kv=self.quant_kv, ring=False)
            cache_d = dict(cache_d, offset=jnp.zeros((B,), jnp.int32))
        toks = jnp.zeros((B,), jnp.int32)
        active = onp.zeros((B,), bool)
        slot_req: list = [None] * B  # request id per slot
        slot_prompt: list = [None] * B  # prefix+prompt per slot
        slot_out: list = [None] * B
        budget = [0] * B
        # Paged bookkeeping: the original queue item per slot (so a
        # preemption can requeue it verbatim), admission order (the
        # preemption victim policy sheds the YOUNGEST — vllm's
        # recompute-last), and per-slot counts of already-delivered
        # tokens to mute after a preempted request re-admits.
        slot_item: list = [None] * B
        admit_seq = [0] * B
        slot_mute = [0] * B
        admit_counter = 0
        # Per-slot offset bound (speculative rounds clamp finishing
        # rows here; see _spec_decode_round's max_off).
        slot_bound = onp.zeros((B,), onp.int64)
        # Per-slot speculation state (ISSUE 11): per-REQUEST width and
        # acceptance EWMA (adapt_k_per_request), per-request telemetry,
        # and the remote-draft context-sync marks (how many of the
        # slot's emitted tokens the draft replica has already scored).
        req_k = [self.draft_k] * B
        req_ewma = [0.0] * B
        req_rounds = [0] * B       # spec rounds this request rode
        req_tokens = [0] * B       # tokens those rounds accepted
        req_plain = [0] * B        # consecutive plain rounds at k == 0
        draft_mark = [0] * B       # slot_out tokens shipped to draft
        draft_open = [False] * B   # stream opened at the remote draft
        draft_close: list = []     # finished rids to close remotely

        def copy_template(c, tmpl_layers, slot, p0, role):
            """Slot rows := template rows (one dynamic_update_slice per
            layer array); slot offset := p0.  The template ARRAYS and
            the prefix length both ride as traced args — the compiled
            copy is memoized across serve() calls and across the
            fingerprint store's many templates."""
            jkey = ("tmplcopy", role)
            if jkey not in self._prefill_jit:
                def fn(cache, tmpl, s, p0_):
                    new_layers = self._slot_writeback(cache, tmpl, s)
                    return dict(
                        cache, layers=new_layers,
                        offset=cache["offset"].at[s].set(p0_),
                    )

                self._prefill_jit[jkey] = jax.jit(fn)
            return self._prefill_jit[jkey](
                c, tmpl_layers, jnp.asarray(slot),
                jnp.asarray(p0, jnp.int32),
            )

        # -- paged admission (ISSUE 19) -------------------------------
        batch_tmpl_memo: Dict[str, Any] = {}

        def blk_writer(nblk):
            """Jit that writes a dense [1, KV, >=nblk*BS, ...] row's
            first nblk blocks into pool blocks ``ids`` — template
            materialization and KV-segment import share it."""
            tk = ("blk_write", nblk)
            if tk not in self._prefill_jit:
                def ftb(pool_layers, row_layers, ids_):
                    out = []
                    for pl, rl in zip(pool_layers, row_layers):
                        lay = {}
                        for kk, v in pl.items():
                            lay[kk] = v.at[ids_].set(
                                _paged_block_split(
                                    jnp.asarray(rl[kk])[0], nblk,
                                    self.block_size,
                                )
                            )
                        out.append(lay)
                    return out

                self._prefill_jit[tk] = jax.jit(ftb)
            return self._prefill_jit[tk]

        def paged_template_ids(tmpl_t_layers, p0, store_entry):
            """Materialize (once per RUN — the pool is rebuilt each
            serve call) a prefix template's pool blocks from its dense
            1-row layers.  The store holds a refcount on them until
            eviction; admissions SHARE the fully-before-rescore blocks
            and copy the rest.  None when the pool is too tight — the
            caller admits untemplated instead."""
            nonlocal cache
            memo = store_entry if store_entry is not None \
                else batch_tmpl_memo
            pb = memo.get("_paged")
            if pb is not None and pb.get("run") == self._paged_run_seq:
                return pb["ids"]
            nblk = arena.blocks_for(p0)
            ids = arena.hold(nblk)
            if ids is None:
                return None
            cache = dict(cache, layers=blk_writer(nblk)(
                cache["layers"], tmpl_t_layers,
                jnp.asarray(ids, jnp.int32),
            ))
            memo["_paged"] = {"run": self._paged_run_seq, "ids": ids}
            return ids

        def drop_template_holds():
            """Release every template's materialized pool blocks (the
            admission gate's last resort when even an UNtemplated
            admission can't fit): check_capacity guarantees any single
            accepted request fits the bare pool, so after this the
            empty batch always re-admits."""
            for memo in [batch_tmpl_memo] + list(
                self._prefix_store.values()
            ):
                pb = memo.pop("_paged", None)
                if pb is not None and \
                        pb.get("run") == self._paged_run_seq:
                    arena.release(pb["ids"])

        def admit_paged(slot, prompt, n, tmpl, p0, store_entry):
            """Paged-target admission: SHARE whole template blocks
            strictly below the first re-scored position w0 (refcount
            up, zero copies — partial prefix overlap finally counts),
            COPY the template blocks in [w0, p0) — they are about to
            be re-written by the chunk re-score, which is exactly
            copy-on-first-divergent-write at block granularity — and
            allocate only the blocks the prompt needs now.  The token
            law matches the dense path byte-for-byte: positions below
            w0 carry template values, positions in [w0, n) carry the
            same chunk-program values dense admission writes."""
            nonlocal cache
            C = self.buckets[-1]
            BSZ = self.block_size
            ids = None
            w0 = 0
            if tmpl is not None and p0:
                w0 = min(C * (p0 // C), n - C)
                if w0 > 0:
                    ids = paged_template_ids(tmpl["t"], p0, store_entry)
            jkey = ("paged_chunk",)
            if jkey not in self._prefill_jit:
                def fnc(params, pool_layers, table_s, chunk, off,
                        zero_first):
                    sub_layers = [
                        {
                            kk: jnp.where(
                                zero_first, jnp.zeros_like(v), v
                            )
                            for kk, v in lay.items()
                        }
                        for lay in _paged_row_view(pool_layers, table_s)
                    ]
                    logits, sub = forward_step(
                        params, chunk, cfg,
                        {"layers": sub_layers, "offset": off},
                    )
                    return (
                        _paged_row_scatter(
                            pool_layers, sub["layers"], table_s
                        ),
                        logits[0],
                    )

                self._prefill_jit[jkey] = jax.jit(fnc)
            chunk_fn = self._prefill_jit[jkey]
            if ids is not None:
                share_n = w0 // BSZ
                arena.share(slot, ids[:share_n])
                copy_src = ids[share_n: arena.blocks_for(p0)]
            else:
                copy_src = []
            if not arena.alloc_upto(slot, n):
                raise RuntimeError(
                    "paged admission allocation failed after the "
                    "free-block gate — arena accounting bug"
                )
            table_dirty()
            if copy_src:
                dst = [
                    int(arena.table[slot, (w0 // BSZ) + i])
                    for i in range(len(copy_src))
                ]
                ck = ("paged_copy", len(copy_src))
                if ck not in self._prefill_jit:
                    def fcp(pool_layers, src, dst_):
                        return [
                            {
                                kk: v.at[dst_].set(v[src])
                                for kk, v in lay.items()
                            }
                            for lay in pool_layers
                        ]

                    self._prefill_jit[ck] = jax.jit(fcp)
                cache = dict(cache, layers=self._prefill_jit[ck](
                    cache["layers"],
                    jnp.asarray(copy_src, jnp.int32),
                    jnp.asarray(dst, jnp.int32),
                ))
            tbl_s = table_device()[slot]
            if ids is None and n <= self.buckets[-1]:
                b = self._bucket(n)
                sk = ("paged_solo", b)
                if sk not in self._prefill_jit:
                    def fns(params, pool_layers, table_s, padded,
                            plen, key):
                        # Mirror _prefill's trace on the row view:
                        # fresh zero rows, scalar offset, same pick.
                        sub = {
                            "layers": [
                                {
                                    kk: jnp.zeros_like(v)
                                    for kk, v in lay.items()
                                }
                                for lay in _paged_row_view(
                                    pool_layers, table_s
                                )
                            ],
                            "offset": jnp.zeros((), jnp.int32),
                        }
                        logits, sub = forward_step(
                            params, padded[None, :], cfg, sub
                        )
                        last = logits[0, plen - 1, :]
                        first = self._pick(last[None, :], key)[0]
                        return (
                            _paged_row_scatter(
                                pool_layers, sub["layers"], table_s
                            ),
                            first,
                        )

                    self._prefill_jit[sk] = jax.jit(fns)
                padded = onp.zeros((b,), onp.int32)
                padded[:n] = prompt
                new_layers, first = self._prefill_jit[sk](
                    self.params, cache["layers"], tbl_s,
                    jnp.asarray(padded), jnp.asarray(n, jnp.int32),
                    self._next_key(),
                )
                cache = dict(cache, layers=new_layers)
            else:
                # Chunked prefill through the table (fresh blocks when
                # untemplated; from w0 when sharing — the first chunk
                # must NOT zero, that would wipe shared blocks).
                c_start = w0 if ids is not None else 0
                zero_ok = ids is None
                last = None
                for c0 in range(c_start, n, C):
                    start = c0 if c0 + C <= n else n - C
                    piece = prompt[start: start + C]
                    new_layers, logits = chunk_fn(
                        self.params, cache["layers"], tbl_s,
                        jnp.asarray(piece)[None],
                        jnp.asarray(start, jnp.int32),
                        jnp.asarray(zero_ok and start == 0),
                    )
                    cache = dict(cache, layers=new_layers)
                    if start + C >= n:
                        last = logits[(n - 1) - start]
                first = self._pick(last[None, :], self._next_key())[0]
            cache = dict(
                cache, offset=cache["offset"].at[slot].set(n)
            )
            return first

        def paged_admit_need(item, bare=False) -> int:
            """Blocks this admission takes from the pool RIGHT NOW
            (the ISSUE 19 admission law — not a full-slot
            reservation).  ``bare`` prices the untemplated fallback."""
            rid_, prompt_, mnt_, extra_ = item
            extra_ = extra_ or {}
            if "kv" in extra_:
                return arena.blocks_for(extra_["kv"]["n"])
            n = len(prompt_) + (P0 if prefix is not None else 0)
            need = arena.blocks_for(n)
            if bare:
                return need
            p0, entry = 0, None
            C = self.buckets[-1]
            if prefix is not None and n > C and templates:
                p0, entry = P0, batch_tmpl_memo
            elif extra_.get("prefix_len") and len(prompt_) > C:
                p0 = int(extra_["prefix_len"])
                with self._pending_mu:
                    entry = self._prefix_store.get(
                        extra_.get("prefix_fp") or ""
                    )
            if p0:
                w0 = min(C * (p0 // C), n - C)
                if w0 > 0:
                    pb = (entry or {}).get("_paged")
                    if pb and pb.get("run") == self._paged_run_seq:
                        # Warm template: the shared blocks arrive free.
                        need -= w0 // self.block_size
                    else:
                        # Cold: materializing the template costs its
                        # blocks too.
                        need += arena.blocks_for(p0)
            return max(0, need)

        def preempt(victim):
            """Shed a slot when the pool runs dry (grow-on-demand's
            escape hatch): its blocks return to the pool instantly and
            the request re-queues at the FRONT.  Greedy decode
            regenerates the identical stream; tokens already delivered
            through on_token are muted on re-admission."""
            rid = slot_req[victim]
            self.preemptions += 1
            if draft_open[victim]:
                draft_close.append(rid)
            self._preempt_emitted[rid] = len(slot_out[victim])
            with self._pending_mu:
                self._pending.appendleft(slot_item[victim])
            arena.free_slot(victim)
            table_dirty()
            active[victim] = False
            slot_req[victim] = None
            slot_prompt[victim] = None
            slot_out[victim] = None

        def ensure_round_blocks(round_need: int) -> None:
            """Grow every active slot to cover this round's writes —
            INCLUDING the speculative / chunked overshoot, whose
            accepted prefix becomes real KV after the rewind — before
            the dispatch.  Oldest admissions grow first; when the pool
            cannot cover someone, the youngest admission is preempted
            (vllm's recompute-last policy) until the rest fit."""
            off = onp.asarray(cache["offset"])
            order = sorted(
                (s for s in range(B) if active[s]),
                key=lambda s: admit_seq[s],
            )
            for s in order:
                while active[s] and not arena.alloc_upto(
                    s, int(off[s]) + round_need
                ):
                    if arena.scavenge():
                        continue
                    victim = max(
                        (v for v in range(B) if active[v]),
                        key=lambda v: admit_seq[v],
                    )
                    preempt(victim)
            table_dirty()

        def admit_one_cache(slot, prompt, n, c, mparams, mcfg, role,
                            tmpl=None, p0=0):
            """Prefill ``prompt`` into ``c``'s slot rows under one
            model (target or draft); returns (new cache, first sampled
            token — meaningful for the target only; the draft role uses
            a CONSTANT key so its discarded pick never shifts the
            sampling stream).  ``tmpl`` (a {role: layers} dict):
            ``prompt`` is the prefix+request combined array; slot rows
            start as a copy of the prefix template and chunk scoring
            begins at the first chunk containing a non-prefix token
            (positions re-scored inside that chunk recompute identical
            kv — complete prefix, causal attention)."""
            use_template = tmpl is not None
            if use_template or n > self.buckets[-1]:
                # Chunked prefill: every chunk is FULL — the final
                # chunk's window shifts back to [n-C, n), re-scoring
                # already-written positions.  The re-score is value-
                # identical because by the time the window shifts back,
                # every cache slot before it is already correctly
                # populated and attention is causal: position t's k/v
                # recompute from the same complete prefix that produced
                # them the first time.  (NOT because k/v depend only on
                # token+position — for layers > 0 they depend on the
                # whole prefix through the residual stream; re-scoring
                # with an INCOMPLETE prefix would not be identical.)
                # So no chunk pads past the prompt or writes beyond slot
                # n-1 (a padded tail could run past max_len, where the
                # dense write's dynamic_update_slice CLAMPS the start
                # and silently corrupts live rows).
                C = self.buckets[-1]
                jkey = ("chunk", role)
                if jkey not in self._prefill_jit:
                    self._prefill_jit[jkey] = self._prefill_chunk(
                        C, mcfg
                    )
                step = self._prefill_jit[jkey]
                c_start = 0
                if use_template:
                    c = copy_template(c, tmpl[role], slot, p0, role)
                    # Skip chunks fully inside the prefix (their kv
                    # just arrived via the template copy); the copy
                    # also zeroed the slot, so no chunk needs
                    # zero_first.  Clamp to n - C so at least one
                    # chunk always runs — an EMPTY request prompt with
                    # p0 a multiple of C would otherwise skip the loop
                    # entirely and leave no last-logits to sample the
                    # first token from.
                    c_start = min(C * (p0 // C), n - C)
                last = None
                for c0 in range(c_start, n, C):
                    start = c0 if c0 + C <= n else n - C
                    piece = prompt[start: start + C]
                    c, logits = step(
                        mparams, c, slot, jnp.asarray(piece)[None],
                        jnp.asarray(start, jnp.int32),
                        jnp.asarray(start == 0 and not use_template),
                    )
                    if start + C >= n:
                        last = logits[(n - 1) - start]
                # True prompt length, not the chunk-rounded offset.
                c = dict(c, offset=c["offset"].at[slot].set(n))
                if role != "t":
                    return c, None
                return c, self._pick(last[None, :], self._next_key())[0]
            b = self._bucket(n)
            padded = onp.zeros((b,), onp.int32)
            padded[:n] = prompt
            jkey = (b, role)
            if jkey not in self._prefill_jit:
                self._prefill_jit[jkey] = self._prefill(b, mcfg)
            key = (self._next_key() if role == "t"
                   else jax.random.PRNGKey(0))
            return self._prefill_jit[jkey](
                mparams, c, slot, jnp.asarray(padded),
                jnp.asarray(n, jnp.int32), key,
            )

        def seat(slot, rid, prompt, n, mnt, first):
            """Shared post-admission bookkeeping: the slot is live,
            its first token (sampled at prefill or shipped with the KV
            segment) is emitted, EOS/budget-0 finishes immediately."""
            nonlocal admit_counter
            slot_bound[slot] = n + mnt
            active[slot] = True
            slot_req[slot] = rid
            slot_prompt[slot] = prompt
            slot_out[slot] = [int(first)]
            budget[slot] = mnt - 1
            admit_counter += 1
            admit_seq[slot] = admit_counter
            # A preempted request regenerates its stream from scratch;
            # tokens the caller already received stay muted (greedy
            # decode makes the regenerated prefix identical).
            slot_mute[slot] = self._preempt_emitted.pop(rid, 0)
            # Fresh per-request speculation state: every request
            # starts at full width and earns its own EWMA.
            req_k[slot] = self.draft_k
            req_ewma[slot] = 0.0
            req_rounds[slot] = req_tokens[slot] = req_plain[slot] = 0
            draft_mark[slot] = 0
            draft_open[slot] = False
            if slot_mute[slot] > 0:
                slot_mute[slot] -= 1
            elif on_token is not None:
                on_token(rid, int(first))
            if int(first) == self.eos_token or budget[slot] <= 0:
                finish(slot)

        def admit_imported(slot, rid, prompt, mnt, kvinfo):
            """Admission from a shipped KV segment (ISSUE 8): the
            verified, max_len-padded rows are written straight into
            the slot — a memory move, zero prefill FLOPs; decode
            continues from the segment's first token."""
            nonlocal cache, toks
            if self.paged:
                # Paged target: the import rows are padded to the block
                # boundary — allocate exactly the blocks the segment
                # occupies and block-write them (same writer the
                # templates use).
                n_ = int(kvinfo["n"])
                if not arena.alloc_upto(slot, n_):
                    raise RuntimeError(
                        "paged import allocation failed after the "
                        "free-block gate — arena accounting bug"
                    )
                table_dirty()
                nblk = arena.blocks_for(n_)
                ids = [int(arena.table[slot, i]) for i in range(nblk)]
                cache = dict(
                    cache,
                    layers=blk_writer(nblk)(
                        cache["layers"], kvinfo["layers"],
                        jnp.asarray(ids, jnp.int32),
                    ),
                    offset=cache["offset"].at[slot].set(n_),
                )
            else:
                jkey = ("kvimport",)
                if jkey not in self._prefill_jit:
                    def fn(c, arrs, s, n_):
                        new_layers = self._slot_writeback(c, arrs, s)
                        return dict(
                            c, layers=new_layers,
                            offset=c["offset"].at[s].set(n_),
                        )

                    self._prefill_jit[jkey] = jax.jit(fn)
                cache = self._prefill_jit[jkey](
                    cache, kvinfo["layers"], jnp.asarray(slot),
                    jnp.asarray(kvinfo["n"], jnp.int32),
                )
            toks = toks.at[slot].set(kvinfo["first"])
            seat(slot, rid, prompt, kvinfo["n"], mnt, kvinfo["first"])

        def admit(slot, item, paged_no_tmpl=False):
            rid, prompt, mnt, extra = item
            extra = extra or {}
            slot_item[slot] = item
            if "kv" in extra:
                admit_imported(slot, rid, prompt, mnt, extra["kv"])
                return
            tmpl = None
            p0 = 0
            store_entry = None
            if prefix is not None:
                # Output contract matches serve([prefix + p ...]).
                prompt = onp.concatenate([prefix, prompt])
                # Short combined prompts fit one bucketed prefill
                # anyway — the template saves nothing there;
                # scratch-prefill them.
                if len(prompt) > self.buckets[-1] and templates:
                    tmpl, p0 = templates, P0
            elif extra.get("prefix_len") and \
                    len(prompt) > self.buckets[-1]:
                # Incremental path (ISSUE 8): per-request template from
                # the fingerprint store — warm admits copy rows, cold
                # ones prefill the template once and warm the replica.
                entry = self._ensure_prefix_template(
                    prompt[: extra["prefix_len"]],
                    extra.get("prefix_fp")
                    or prefix_fingerprint(prompt[: extra["prefix_len"]]),
                )
                tmpl, p0 = entry["layers"], entry["p0"]
                store_entry = entry
            n = len(prompt)
            nonlocal cache, cache_d, toks
            if self.paged:
                first = admit_paged(
                    slot, prompt, n,
                    None if paged_no_tmpl else tmpl,
                    p0, store_entry,
                )
            else:
                cache, first = admit_one_cache(
                    slot, prompt, n, cache, self.params, self.cfg, "t",
                    tmpl=tmpl, p0=p0,
                )
            if self.draft is not None:
                # The draft's tiny cache stays dense even under paged
                # target KV — it is a constant-size side array, not the
                # stranded-memory cost the arena exists to reclaim.
                cache_d, _ = admit_one_cache(
                    slot, prompt, n, cache_d, self.draft[0],
                    self.draft[1], "d", tmpl=tmpl, p0=p0,
                )
            toks = toks.at[slot].set(first.astype(toks.dtype))
            seat(slot, rid, prompt, n, mnt, first)

        def finish(slot):
            rid = slot_req[slot]
            out = onp.concatenate(
                [slot_prompt[slot], onp.asarray(slot_out[slot], onp.int32)]
            )
            if self.spec_capable and req_rounds[slot]:
                # Park the request's earned acceptance for the runner
                # to fold into ServeDone + the journal (ISSUE 11).
                with self._pending_mu:
                    self._req_stats_out[rid] = {
                        "tokens_per_round": (
                            req_tokens[slot] / req_rounds[slot]
                        ),
                        "spec_rounds": req_rounds[slot],
                        "k_last": req_k[slot],
                    }
                    while len(self._req_stats_out) > 512:
                        self._req_stats_out.popitem(last=False)
            if draft_open[slot]:
                draft_close.append(rid)
            if tick is None:
                # Batch mode returns the result dict; the incremental
                # loop delivers via on_finish ONLY — retaining every
                # completion would grow without bound for the life of
                # a fleet replica.
                results[rid] = out
            if self.paged:
                # Blocks return to the pool the instant the slot
                # frees — the next admission can take them this same
                # loop iteration.
                arena.free_slot(slot)
                table_dirty()
            active[slot] = False
            slot_req[slot] = None
            slot_prompt[slot] = None
            slot_out[slot] = None
            if on_finish is not None:
                on_finish(rid, out)

        def emit_rows(rows):
            """THE per-slot emit/finish law, shared by every decode
            path (1-token step, K-token chunk, speculative round):
            append each of slot s's new tokens until its EOS or budget,
            then free the slot; the path's remaining tokens for a
            finished slot are discarded (rows re-zero at admission,
            capacity slack covered the extra writes).  Returns the
            number of tokens actually appended (the emitted-token
            telemetry for the non-speculative paths)."""
            appended = 0
            for s in range(B):
                if not active[s]:
                    continue
                for t in rows[s]:
                    slot_out[s].append(int(t))
                    appended += 1
                    budget[s] -= 1
                    if slot_mute[s] > 0:
                        # Re-serving after a paged preemption: this
                        # token was already delivered before the shed.
                        slot_mute[s] -= 1
                    elif on_token is not None:
                        on_token(slot_req[s], int(t))
                    if (
                        int(t) == self.eos_token
                        or budget[s] <= 0
                    ):
                        finish(s)
                        break
            return appended

        sample = self.temperature > 0.0
        greedy_key = jax.random.PRNGKey(0)  # dead in the greedy trace
        cur_k = self.draft_k
        # Acceptance telemetry (whole serve + current adaptation
        # window): tokens_per_round over ACTIVE row-rounds is the
        # speculation-efficiency signal adapt_k steers on.
        spec_rounds = spec_row_rounds = spec_tokens = 0
        spec_fallback_rounds = 0  # plain dispatches by a spec server
        spec_draft_failures = 0   # remote-draft handle failures
        win_row_rounds = win_tokens = 0
        plain_rounds = plain_tokens = 0
        k_history = [cur_k]
        remote_seen: Any = None   # handle identity (re-attach resets)
        remote_dead = False

        def publish_stats():
            """Refresh ``last_stats`` from the running counters —
            called every loop iteration so an incremental tick (the
            fleet replica's poll) reports LIVE telemetry, not the
            previous call's final numbers."""
            if self.spec_capable:
                self.last_stats = {
                    "path": "spec",
                    "rounds": spec_rounds,
                    "active_row_rounds": spec_row_rounds,
                    "accepted_tokens": spec_tokens,
                    "tokens_per_round": (
                        spec_tokens / spec_row_rounds
                        if spec_row_rounds else 0.0
                    ),
                    "k_final": cur_k,
                    "k_history": k_history,
                    # Plain dispatches this spec-capable server ran —
                    # every stream below break-even, no draft attached,
                    # or the remote draft dead (ISSUE 11).
                    "spec_fallback_rounds": spec_fallback_rounds,
                    "spec_draft_failures": spec_draft_failures,
                }
            else:
                self.last_stats = {
                    "path": ("decode_chunk" if self.decode_chunk > 1
                             else "plain"),
                    "rounds": plain_rounds,
                    "emitted_tokens": plain_tokens,
                    "tokens_per_round": (
                        plain_tokens / plain_rounds
                        if plain_rounds else 0.0
                    ),
                }
            if self.paged:
                # The stats-drift fix (ISSUE 19 satellite): under
                # paged mode ``occupancy`` IS block-pool utilization —
                # tokens held, not slots seated — so gateway admission
                # and autoscale hysteresis see real memory headroom
                # with no discontinuity at the flag flip.
                used = int(arena.used_blocks)
                self.last_stats.update(
                    paged=True,
                    total_blocks=arena.n_blocks,
                    free_blocks=arena.free_blocks,
                    block_occupancy=used / max(1, arena.n_blocks),
                    occupancy=used / max(1, arena.n_blocks),
                    preemptions=self.preemptions,
                    leaks_repaired=arena.leaks_repaired,
                )
            else:
                self.last_stats["occupancy"] = (
                    float(active.sum()) / max(1, B)
                )

        self._live_active = active
        self._live_slot_req = slot_req
        while True:
            publish_stats()
            keep = True
            if tick is not None:
                keep = tick() is not False
            if self._abort_rids:
                with self._pending_mu:
                    doomed, self._abort_rids = self._abort_rids, set()
                for s in range(B):
                    if active[s] and slot_req[s] in doomed:
                        # Shed the slot: partial output discarded, no
                        # on_finish; admission re-zeros the rows.
                        if draft_open[s]:
                            draft_close.append(slot_req[s])
                        if self.paged:
                            # Abort/deadline shed returns blocks to
                            # the pool INSTANTLY (ISSUE 19c) — the
                            # chaos site inside _drop_ref models a
                            # lost free here.
                            arena.free_slot(s)
                            table_dirty()
                        active[s] = False
                        slot_req[s] = None
                        slot_prompt[s] = None
                        slot_out[s] = None
            if self.paged:
                # Leak-repair sweep (the conservation law's defense):
                # any block whose refcount says free but which sits on
                # no free list — e.g. a chaos-dropped free — is
                # rebuilt into the pool before admission prices it.
                arena.scavenge()
            for s in range(B):
                if not active[s]:
                    item = self._pop_pending()
                    if item is None:
                        break
                    no_tmpl = False
                    if self.paged:
                        need = paged_admit_need(item)
                        if arena.free_blocks < need:
                            if active.any():
                                # The blocks it needs NOW aren't
                                # free: wait for decode to release
                                # some before seating it.
                                with self._pending_mu:
                                    self._pending.appendleft(item)
                                break
                            # Empty batch: the request MUST admit —
                            # give up the template (and, if still
                            # tight, every template's held blocks)
                            # rather than livelock.
                            no_tmpl = True
                            bare = paged_admit_need(item, bare=True)
                            if arena.free_blocks < bare:
                                drop_template_holds()
                    admit(s, item, no_tmpl)
            if not active.any():
                if self.pending_count() == 0:
                    if tick is None or not keep:
                        break
                    # Idle incremental loop: nothing to decode until
                    # the next tick feeds the queue.
                    time.sleep(idle_wait)
                continue
            rd = self._remote_draft
            if rd is not remote_seen:
                # A (re)attached draft handle: fresh streams (the new
                # draft holds no caches), fresh chance after a failure.
                remote_seen = rd
                remote_dead = False
                for s in range(B):
                    draft_open[s] = False
                    draft_mark[s] = 0
            spec_live = self.draft is not None or (
                rd is not None and not remote_dead
            )
            if spec_live:
                # Per-row widths (ISSUE 11 per-request adaptive k): a
                # stream below break-even rides at width 0 (plain law,
                # zero draft work charged to it) and is re-probed at
                # width 1 every spec_probe_every of its plain rounds.
                if self.adapt_k_per_request:
                    k_arr = onp.zeros(B, onp.int64)
                    for s in range(B):
                        if not active[s]:
                            continue
                        ks = req_k[s]
                        if ks == 0 and \
                                req_plain[s] >= self.spec_probe_every:
                            ks = 1
                            req_plain[s] = 0
                        k_arr[s] = ks
                    round_k = int(k_arr.max()) if B else 0
                else:
                    round_k = cur_k
                    k_arr = None
                spec_live = round_k > 0
            if spec_live:
                progs = _spec_programs(
                    cfg,
                    self.draft[1] if self.draft is not None else cfg,
                    round_k, self.temperature, self.top_k, self.top_p,
                )
                if self.paged:
                    # Paged target under speculation: grow every slot
                    # to cover the round's k+1 verify writes, then run
                    # the UNCHANGED spec round on a gathered dense
                    # view and scatter the result back through the
                    # table — two extra dispatches buy byte-exact
                    # reuse of the whole acceptance machinery.
                    ensure_round_blocks(round_k + 1)
                    if not active.any():
                        continue
                    pool_layers = cache["layers"]
                    dense = self._paged_gather(
                        pool_layers, table_device(), cache["offset"]
                    )
                else:
                    pool_layers = None
                    dense = cache
                if self.draft is not None:
                    # Local draft: one batched roll over all slots,
                    # one chunked ragged verify, per-slot acceptance;
                    # idle slots ride along frozen (done mask).
                    accepted_rows, nxt, dense, cache_d = \
                        _spec_decode_round(
                            progs, self.params, self.draft[0], dense,
                            cache_d, toks, ~active, round_k, sample,
                            self._np_rng,
                            self._next_key() if sample else greedy_key,
                            max_off=slot_bound, k_row=k_arr,
                        )
                else:
                    # Remote draft (ISSUE 11): context deltas out,
                    # proposals back over the draft replica's segment
                    # path; ANY failure degrades to plain decode (a
                    # dead draft must never stall the serve loop).
                    got = self._remote_propose(
                        rd, round_k, k_arr, active, slot_req,
                        slot_prompt, slot_out, draft_mark, draft_open,
                        draft_close, sample,
                    )
                    if got is None:
                        remote_dead = True
                        spec_draft_failures += 1
                        continue
                    d_host, q_host, k_arr = got
                    accepted_rows, nxt, dense = _spec_remote_round(
                        progs, self.params, dense, toks, ~active,
                        d_host, q_host, round_k, sample, self._np_rng,
                        k_row=k_arr, max_off=slot_bound,
                    )
                if self.paged:
                    cache = {
                        "layers": self._paged_scatter(
                            pool_layers, dense["layers"],
                            table_device(),
                        ),
                        "offset": dense["offset"],
                    }
                else:
                    cache = dense
                toks = jnp.asarray(nxt)
                # Acceptance BEFORE EOS/budget truncation — what the
                # draft earned, the signal k adapts on.  Only rows
                # that actually SPECULATED this round (width > 0)
                # count: width-0 riders earn exactly 1 plain token
                # each and would dilute tokens_per_round toward 1.0,
                # starving the DraftRole/arbiter signal of the value
                # the speculating streams really get.
                round_spec_rows = 0
                round_tokens = 0
                # Per-request EWMA + width BEFORE emit (emit can free
                # the slot; seat() resets the arrays on re-admission).
                for s in range(B):
                    if not active[s]:
                        continue
                    width = round_k if k_arr is None else int(k_arr[s])
                    if width <= 0:
                        req_plain[s] += 1
                        continue
                    earned = len(accepted_rows[s])
                    round_spec_rows += 1
                    round_tokens += earned
                    req_rounds[s] += 1
                    req_tokens[s] += earned
                    if self.adapt_k_per_request:
                        a = self.spec_ewma_alpha
                        req_ewma[s] = (
                            float(earned) if req_ewma[s] <= 0.0
                            else a * earned + (1 - a) * req_ewma[s]
                        )
                        req_k[s] = _spec_k_request(
                            req_ewma[s], self.draft_k,
                            self.spec_break_even,
                        )
                emit_rows(accepted_rows)
                spec_rounds += 1
                spec_row_rounds += round_spec_rows
                spec_tokens += round_tokens
                win_row_rounds += round_spec_rows
                win_tokens += round_tokens
                if (
                    self.adapt_k
                    and spec_rounds % self.adapt_every == 0
                    and win_row_rounds
                ):
                    new_k = _adapt_spec_k(
                        cur_k, self.draft_k,
                        win_tokens / win_row_rounds,
                    )
                    if new_k != cur_k:
                        cur_k = new_k
                        k_history.append(cur_k)
                    win_row_rounds = win_tokens = 0
                continue
            if self.spec_capable:
                # A spec-capable server running a plain dispatch:
                # every stream below break-even, no draft attached
                # yet, or the remote draft dead — the degradation the
                # gateway's spec_fallbacks counter measures.
                spec_fallback_rounds += 1
                for s in range(B):
                    if active[s]:
                        req_plain[s] += 1
            if self.decode_chunk > 1:
                if self.paged:
                    ensure_round_blocks(self.decode_chunk)
                    if not active.any():
                        continue
                    new_layers, offs, toks, chunk = \
                        self._chunk_step_paged(
                            self.params, cache["layers"],
                            table_device(), cache["offset"], toks,
                            jnp.asarray(active), self._next_key(),
                        )
                    cache = {"layers": new_layers, "offset": offs}
                else:
                    cache, toks, chunk = self._chunk_step(
                        self.params, cache, toks, jnp.asarray(active),
                        self._next_key(),
                    )
                plain_rounds += 1
                plain_tokens += emit_rows(onp.asarray(chunk))  # [B, K]
                continue
            if self.paged:
                ensure_round_blocks(1)
                if not active.any():
                    continue
                new_layers, offs, nxt = self._step_paged(
                    self.params, cache["layers"], table_device(),
                    cache["offset"], toks, jnp.asarray(active),
                    self._next_key(),
                )
                cache = {"layers": new_layers, "offset": offs}
            else:
                cache, nxt = self._step(
                    self.params, cache, toks, jnp.asarray(active),
                    self._next_key(),
                )
            toks = nxt
            plain_rounds += 1
            plain_tokens += emit_rows(onp.asarray(nxt)[:, None])
        self._live_active = None
        self._live_slot_req = None
        publish_stats()
        return results


def serve_journaled(
    server: "DecodeServer",
    prompts: list,
    max_new_tokens: int,
    journal_path: str,
    on_serve=None,
) -> list:
    """Elastic serving: an append-only completion journal + idempotent
    replay — the serving analogue of the trainer's flash checkpoint.

    A KV cache dies with its process, so the recovery unit for serving
    is the REQUEST, not device state: every completed request is
    fsync'd to ``journal_path`` (one JSON line, keyed by request id
    AND a hash of the prompt tokens) the moment its slot frees; a
    restarted worker loads the journal, skips finished requests whose
    prompt hash still matches, and re-serves only the in-flight
    remainder.  The hash keying makes replay safe against journal-path
    reuse: running a DIFFERENT prompt list against an old journal
    re-serves everything instead of returning stale completions.  Replay is
    byte-identical because greedy decode is deterministic AND the
    server's compiled program shapes are fixed by its construction
    (``slots``/buckets), not by the request subset: each slot row's
    result is computationally independent of what rides in the other
    slots, so serving fewer requests after a restart reproduces each
    remaining request exactly — at any dtype.  (Comparing against a
    B=1 solo decode is a DIFFERENT program shape, where bf16 argmax
    can flip near ties — that's why the tests pin float32.)  A torn
    final line from a SIGKILL mid-append is ignored and that request is
    simply replayed.  The reference has no elastic serving story at all
    (its RL stack shells out to a vllm the job master never supervises,
    atorch/rl/model_engine/model_engine.py:35) — this composes the
    continuous-batching server with the same kill-tolerance contract
    the trainer gets from agent restart + warm restore.

    Returns the full result list in request order.  ``on_serve(rid,
    tokens)`` additionally fires for every newly served (non-replayed)
    completion — progress reporting for the elastic agent's hang
    detector.
    """
    import hashlib as _hashlib
    import json as _json
    import os as _os

    if server.temperature > 0.0:
        # Replay determinism is the whole contract: a restarted worker
        # re-serves only the in-flight subset, so a sampling server's
        # RNG stream and admission order differ across incarnations and
        # the results would silently mix two different draws.
        raise ValueError(
            "serve_journaled requires a greedy server "
            "(temperature=0): sampled replay after a restart is not "
            "byte-identical"
        )
    def _phash(p) -> str:
        return _hashlib.sha1(
            np.asarray(p, np.int32).tobytes()
        ).hexdigest()[:16]

    # Journal records are keyed by (rid, prompt hash), not rid alone:
    # rerunning against an existing journal with a DIFFERENT prompt
    # list must re-serve, not silently replay the old run's completion
    # for a colliding rid.  Records whose hash mismatches (or predates
    # the hash field) are ignored and the request is simply re-served.
    want = {rid: _phash(p) for rid, p in enumerate(prompts)}
    done: Dict[int, np.ndarray] = {}
    try:
        with open(journal_path, "r+") as f:
            content = f.read()
            # Torn tail from a kill mid-append: TRUNCATE to the last
            # complete line before any new append — otherwise the next
            # record concatenates onto the partial one and both become
            # unparseable (losing a FINISHED request on a later
            # restart).
            cut = content.rfind("\n") + 1
            if cut < len(content):
                f.truncate(cut)
            for line in content[:cut].split("\n"):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = _json.loads(line)
                except ValueError:
                    continue  # a torn line persisted by an old writer
                rid = int(rec["rid"])
                if want.get(rid) != rec.get("ph"):
                    continue  # different prompt set: stale record
                done[rid] = np.asarray(rec["tokens"], np.int32)
    except OSError:
        pass
    todo = [
        (rid, p) for rid, p in enumerate(prompts) if rid not in done
    ]
    if todo:
        jf = open(journal_path, "a")
        try:
            def _journal(local_rid, tokens):
                rid = todo[local_rid][0]
                jf.write(_json.dumps({
                    "rid": rid,
                    "ph": want[rid],
                    "tokens": [int(t) for t in tokens],
                }) + "\n")
                jf.flush()
                _os.fsync(jf.fileno())
                done[rid] = np.asarray(tokens, np.int32)
                if on_serve is not None:
                    on_serve(rid, tokens)

            server.serve(
                [p for _, p in todo], max_new_tokens,
                on_finish=_journal,
            )
        finally:
            jf.close()
    return [done[r] for r in range(len(prompts))]
