"""KV-cache autoregressive decoding for Llama.

The inference half the reference delegates to an external engine (its RL
stack shells out to vllm, ``atorch/atorch/rl/model_engine``) — TPU-first
here: a functional KV cache (one [B, KV, max_len, D] pair per layer kept
compact at the GQA kv-head count), a prefill step that scores the whole
prompt at once, and a ``lax.scan`` decode loop that reuses the cache so
each new token costs O(S) attention instead of the RL engine's
O(S^2)-per-token full recompute.

    cache = init_cache(cfg, batch, max_len)
    tokens = generate(params, cfg, prompts, max_new_tokens=64,
                      rng=jax.random.PRNGKey(0))
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models import llama
from dlrover_tpu.models.llama import LlamaConfig, _rope
from dlrover_tpu.ops.rmsnorm import rmsnorm


def init_cache(
    cfg: LlamaConfig, batch: int, max_len: int, *,
    ring_len: Optional[int] = None,
) -> Dict:
    """Zeroed per-layer k/v cache (compact KV-head count) + write offset.

    With ``cfg.sliding_window > 0`` the cache is a ROLLING buffer of
    ``ring_len`` slots (default ``max_len``): writes wrap modulo the
    buffer and a per-slot absolute-position array drives the masks, so
    decode memory is O(window), not O(total sequence).  Constraints for
    a chunk of T new tokens: ``T <= ring_len`` always, and
    ``window + T - 1 <= ring_len`` when continuing past a non-empty
    cache (single-token decode only needs ``ring_len >= window``)."""
    KV, D = cfg.n_kv_head, cfg.head_dim
    L = max_len
    if cfg.sliding_window > 0 and ring_len is not None:
        L = min(max_len, ring_len)
    cache = {
        "layers": [
            {
                "k": jnp.zeros((batch, KV, L, D), cfg.dtype),
                "v": jnp.zeros((batch, KV, L, D), cfg.dtype),
            }
            for _ in range(cfg.n_layer)
        ],
        "offset": jnp.zeros((), jnp.int32),
    }
    if cfg.sliding_window > 0:
        # Absolute position held by each ring slot (-1 = unwritten).
        cache["pos"] = jnp.full((L,), -1, jnp.int32)
    return cache


def _cached_attention(x, layer, cfg, cache_layer, offset, positions,
                      slot_pos=None):
    """x: [B, T, C] new tokens; attends to cache[:offset] + itself.

    ``slot_pos`` (ring mode, sliding-window models): the ALREADY-updated
    per-slot absolute positions; writes wrap modulo the buffer length
    and masks key on these positions instead of the slot index."""
    B, T, C = x.shape
    H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    dt = cfg.dtype
    q = (x @ layer["wq"].astype(dt)).reshape(B, T, H, D)
    k = (x @ layer["wk"].astype(dt)).reshape(B, T, KV, D)
    v = (x @ layer["wv"].astype(dt)).reshape(B, T, KV, D)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)

    if slot_pos is not None:
        # Ring write (slot mapping computed ONCE by forward_step).
        ring_slots, slot_pos = slot_pos
        if T == 1:
            # Decode hot path: a single contiguous slot — XLA lowers a
            # dynamic_update_slice far better than an indexed scatter.
            k_cache = jax.lax.dynamic_update_slice(
                cache_layer["k"],
                k.transpose(0, 2, 1, 3).astype(dt),
                (0, 0, ring_slots[0], 0),
            )
            v_cache = jax.lax.dynamic_update_slice(
                cache_layer["v"],
                v.transpose(0, 2, 1, 3).astype(dt),
                (0, 0, ring_slots[0], 0),
            )
        else:
            k_cache = cache_layer["k"].at[:, :, ring_slots].set(
                k.transpose(0, 2, 1, 3).astype(dt)
            )
            v_cache = cache_layer["v"].at[:, :, ring_slots].set(
                v.transpose(0, 2, 1, 3).astype(dt)
            )
    else:
        # Write the new k/v into the cache at [offset, offset+T).
        k_cache = jax.lax.dynamic_update_slice(
            cache_layer["k"], k.transpose(0, 2, 1, 3).astype(dt),
            (0, 0, offset, 0),
        )
        v_cache = jax.lax.dynamic_update_slice(
            cache_layer["v"], v.transpose(0, 2, 1, 3).astype(dt),
            (0, 0, offset, 0),
        )

    max_len = k_cache.shape[2]
    rep = H // KV
    # Grouped attention against the COMPACT cache, in its stored dtype:
    # no [B, H, max_len, D] repeat and no fp32 cache copy is ever
    # materialized — the einsums accumulate in fp32 via
    # preferred_element_type (only q, [B,KV,rep,T,D] with tiny T, is
    # upcast).
    qf = (
        q.transpose(0, 2, 1, 3)
        .reshape(B, KV, rep, T, D)
        .astype(k_cache.dtype)
    )
    s = jnp.einsum(
        "bgrtd,bgkd->bgrtk", qf, k_cache,
        preferred_element_type=jnp.float32,
    ) / np.sqrt(D)
    # Causal over absolute positions; unwritten slots are masked (ring
    # mode: pos -1; dense mode: slot index beyond offset+T).
    if slot_pos is not None:
        kpos = slot_pos[None, None, None, None, :]
    else:
        kpos = jnp.arange(max_len)[None, None, None, None, :]
    qpos = positions[:, None, None, :, None]
    s = jnp.where((kpos >= 0) & (kpos <= qpos), s, -1e30)
    if cfg.sliding_window > 0:
        # Sliding window: only the last `sliding_window` positions are
        # visible.
        s = jnp.where(qpos - kpos < cfg.sliding_window, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bgrtk,bgkd->bgrtd", p.astype(k_cache.dtype), v_cache,
        preferred_element_type=jnp.float32,
    )
    out = (
        out.reshape(B, H, T, D)
        .transpose(0, 2, 1, 3)
        .reshape(B, T, H * D)
        .astype(dt)
    )
    return out @ layer["wo"].astype(dt), {"k": k_cache, "v": v_cache}


def forward_step(
    params: Dict,
    tokens: jax.Array,  # [B, T] new tokens
    cfg: LlamaConfig,
    cache: Dict,
    *,
    assume_empty_cache: bool = False,  # ring mode: offset-0 prefill
) -> Tuple[jax.Array, Dict]:
    """Score ``tokens`` continuing the cached context.  Returns
    (logits [B, T, vocab] fp32, updated cache).

    Reuses ``llama.block_apply`` with the cached attention plugged in,
    so the block wiring (norm/residual/mlp-or-moe order) cannot drift
    from the training forward.  MoE layers run with a no-drop capacity:
    at T=1 the config-derived capacity rounds so coarsely that batch
    rows colliding on an expert would be silently dropped."""
    B, T = tokens.shape
    dt = cfg.dtype
    offset = cache["offset"]
    x = params["embed"].astype(dt)[tokens]
    positions = offset + jnp.broadcast_to(jnp.arange(T), (B, T))
    no_drop_capacity = B * T * cfg.top_k
    ring = None
    if "pos" in cache:  # ring mode (sliding-window models)
        L = cache["pos"].shape[0]
        W = cfg.sliding_window
        if T > L:
            raise ValueError(
                f"chunk of {T} tokens exceeds the {L}-slot ring cache"
            )
        if T > 1 and W + T - 1 > L and not assume_empty_cache:
            # A multi-token chunk on a NON-empty ring would overwrite
            # keys still inside earlier queries' windows (silently wrong
            # logits). Prefill at offset 0 is safe — callers declare it.
            raise ValueError(
                f"continuation chunk of {T} tokens needs ring_len >= "
                f"window + T - 1 = {W + T - 1}, have {L}; pass "
                "assume_empty_cache=True only for the offset-0 prefill"
            )
        slots = (offset + jnp.arange(T)) % L
        if T == 1:
            slot_pos = jax.lax.dynamic_update_slice(
                cache["pos"], offset[None] + jnp.arange(1), (slots[0],)
            )
        else:
            slot_pos = cache["pos"].at[slots].set(
                offset + jnp.arange(T)
            )
        ring = (slots, slot_pos)
    new_layers = []
    for layer, cache_layer in zip(params["layers"], cache["layers"]):
        cell = {}

        def attn_fn(h, layer_, cfg_, positions_, _cache=cache_layer,
                    _cell=cell):
            out, _cell["cache"] = _cached_attention(
                h, layer_, cfg_, _cache, offset, positions_,
                slot_pos=ring,
            )
            return out

        x, _aux = llama.block_apply(
            layer, x, cfg, positions,
            attn_fn=attn_fn, moe_capacity=no_drop_capacity,
        )
        new_layers.append(cell["cache"])
    x = rmsnorm(x, params["ln_f"], eps=cfg.rms_eps)
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    new_cache = {"layers": new_layers, "offset": offset + T}
    if ring is not None:
        new_cache["pos"] = ring[1]
    return logits, new_cache


def generate(
    params: Dict,
    cfg: LlamaConfig,
    prompts: jax.Array,  # [B, P] prompt token ids
    *,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,  # 0 = greedy
    top_k: int = 0,
    top_p: float = 0.0,  # 0 = off; else nucleus sampling
) -> jax.Array:
    """[B, P + max_new_tokens] — prompt + sampled continuation.

    Prefill scores the prompt in one pass; decode is a ``lax.scan`` of
    single-token steps against the KV cache.  ``temperature=0`` is
    greedy (deterministic); otherwise categorical sampling with optional
    top-k truncation and/or top-p (nucleus) filtering — the sampling
    surface of the serving engine the reference RL stack delegates to.
    """
    if max_new_tokens == 0:
        return prompts
    B, P = prompts.shape
    max_len = P + max_new_tokens
    ring_len = None
    if cfg.sliding_window > 0:
        # Rolling buffer: prefill needs P slots, decode needs `window`
        # retained keys — memory O(max(P, window)), not O(P + N).
        ring_len = max(P, cfg.sliding_window)
    cache = init_cache(cfg, B, max_len, ring_len=ring_len)
    logits, cache = forward_step(
        params, prompts, cfg, cache, assume_empty_cache=True
    )
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def pick(logits_1, sub):
        if temperature <= 0.0:
            return jnp.argmax(logits_1, axis=-1)
        scaled = logits_1 / temperature
        if top_k > 0:
            kth = jnp.sort(scaled, axis=-1)[:, -top_k, None]
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        if top_p > 0.0:
            # Nucleus: keep the smallest prefix of the sorted
            # distribution whose mass reaches top_p (the top token
            # always survives).
            srt = jnp.sort(scaled, axis=-1)[:, ::-1]  # descending
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_sorted = cum - probs < top_p
            n_keep = jnp.maximum(1, jnp.sum(keep_sorted, axis=-1))
            cutoff = jnp.take_along_axis(
                srt, (n_keep - 1)[:, None], axis=-1
            )
            scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
        return jax.random.categorical(sub, scaled)

    rng, sub = jax.random.split(rng)
    first = pick(logits[:, -1, :], sub).astype(prompts.dtype)

    def step(carry, _):
        cache, tok, rng = carry
        logits, cache = forward_step(params, tok[:, None], cfg, cache)
        rng, sub = jax.random.split(rng)
        nxt = pick(logits[:, -1, :], sub).astype(tok.dtype)
        return (cache, nxt, rng), tok

    # Each step scores the carried token and samples the next; the scan
    # emits the SCORED token, so the outputs are exactly the generated
    # sequence [first, t2, ..., tN] (the final carry is an N+1-th sample
    # past the requested window — dropped).
    _, toks = jax.lax.scan(
        step, (cache, first, rng), None, length=max_new_tokens
    )
    return jnp.concatenate(
        [prompts, jnp.moveaxis(toks, 0, 1)], axis=1
    )
