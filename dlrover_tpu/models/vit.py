"""Vision Transformer: the non-LLM model family.

The reference trains arbitrary torch models (its examples include
vision/CV workloads alongside Llama); this ViT shows the framework's
model-agnostic surface — ``accelerate()``, the Trainer, flash ckpt and
the conf executor all operate on (init_fn, loss_fn) pairs, so a vision
model needs nothing framework-side.  TPU notes: patch embedding is a
single reshaped matmul (not a conv — XLA maps it onto the MXU
directly), attention reuses the Pallas flash-attention dispatcher, and
shapes are static throughout.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp

from dlrover_tpu.ops.flash_attention import flash_attention


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    d_model: int = 384
    n_layer: int = 12
    n_head: int = 6
    d_ff: int = 1536
    num_classes: int = 1000

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.patch_size**2

    @classmethod
    def tiny(cls, **over) -> "ViTConfig":
        base = dict(
            image_size=32, patch_size=8, channels=3, d_model=64,
            n_layer=2, n_head=4, d_ff=128, num_classes=10,
        )
        base.update(over)
        return cls(**base)

    @classmethod
    def base_86m(cls) -> "ViTConfig":
        return cls(d_model=768, n_layer=12, n_head=12, d_ff=3072)


def _dense(key, n_in, n_out, scale=None):
    scale = scale if scale is not None else (2.0 / (n_in + n_out)) ** 0.5
    return jax.random.normal(key, (n_in, n_out), jnp.float32) * scale


def init_params(rng: jax.Array, cfg: ViTConfig) -> Dict:
    keys = jax.random.split(rng, cfg.n_layer + 3)
    params: Dict = {
        "patch_embed": _dense(keys[0], cfg.patch_dim, cfg.d_model),
        "pos_embed": jax.random.normal(
            keys[1], (cfg.n_patches + 1, cfg.d_model), jnp.float32
        ) * 0.02,
        "cls_token": jnp.zeros((cfg.d_model,), jnp.float32),
        "head": _dense(keys[2], cfg.d_model, cfg.num_classes),
        "ln_f": {"g": jnp.ones((cfg.d_model,)), "b": jnp.zeros((cfg.d_model,))},
        "layers": [],
    }
    for i in range(cfg.n_layer):
        k = jax.random.split(keys[3 + i], 4)
        params["layers"].append(
            {
                "ln1": {"g": jnp.ones((cfg.d_model,)),
                        "b": jnp.zeros((cfg.d_model,))},
                "qkv": _dense(k[0], cfg.d_model, 3 * cfg.d_model),
                "proj": _dense(k[1], cfg.d_model, cfg.d_model),
                "ln2": {"g": jnp.ones((cfg.d_model,)),
                        "b": jnp.zeros((cfg.d_model,))},
                "fc1": _dense(k[2], cfg.d_model, cfg.d_ff),
                "fc2": _dense(k[3], cfg.d_ff, cfg.d_model),
            }
        )
    return params


def _layernorm(x, p):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * p["g"] + p["b"]


def patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, n_patches, patch_dim] via reshape/transpose —
    the MXU-friendly formulation of the patch conv."""
    B = images.shape[0]
    P = cfg.patch_size
    g = cfg.image_size // P
    x = images.reshape(B, g, P, g, P, cfg.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, g * g, cfg.patch_dim)


def forward(params: Dict, images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] float images -> [B, num_classes] logits."""
    B = images.shape[0]
    x = patchify(images.astype(jnp.bfloat16), cfg)
    x = x @ params["patch_embed"].astype(jnp.bfloat16)
    cls = jnp.broadcast_to(
        params["cls_token"].astype(jnp.bfloat16), (B, 1, cfg.d_model)
    )
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(jnp.bfloat16)

    hd = cfg.d_model // cfg.n_head
    for lp in params["layers"]:
        h = _layernorm(x.astype(jnp.float32), lp["ln1"]).astype(
            jnp.bfloat16
        )
        qkv = h @ lp["qkv"].astype(jnp.bfloat16)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        S = x.shape[1]

        def heads(t):
            return t.reshape(B, S, cfg.n_head, hd).transpose(0, 2, 1, 3)

        # Bidirectional attention (no causal mask) over patches+cls.
        att = flash_attention(
            heads(q), heads(k), heads(v), causal=False
        )
        att = att.transpose(0, 2, 1, 3).reshape(B, S, cfg.d_model)
        x = x + att @ lp["proj"].astype(jnp.bfloat16)

        h = _layernorm(x.astype(jnp.float32), lp["ln2"]).astype(
            jnp.bfloat16
        )
        h = jax.nn.gelu(h @ lp["fc1"].astype(jnp.bfloat16))
        x = x + h @ lp["fc2"].astype(jnp.bfloat16)

    x = _layernorm(x.astype(jnp.float32), params["ln_f"])
    return (x[:, 0, :] @ params["head"]).astype(jnp.float32)


def loss_fn(params: Dict, batch: Dict, cfg: ViTConfig) -> jax.Array:
    """Softmax cross-entropy over classes; batch = {images, labels}."""
    from dlrover_tpu.ops.cross_entropy import softmax_cross_entropy

    logits = forward(params, batch["images"], cfg)
    return jnp.mean(softmax_cross_entropy(logits, batch["labels"]))


def num_params(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
