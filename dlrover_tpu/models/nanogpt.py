"""nanoGPT: a minimal causal-transformer LM as a functional JAX model.

The end-to-end baseline model (BASELINE.json configs[0]; reference example
``examples/pytorch/nanogpt``) and the smoke-test workhorse.  Pure-functional:
``init_params`` -> param pytree, ``forward(params, tokens)`` -> logits,
``param_specs`` -> a matching pytree of ``PartitionSpec`` so the parallel
layer can apply DP/FSDP/TP without model surgery.

TPU notes: weights/activations default to bfloat16 compute with float32
params (MXU-friendly); attention uses a fused softmax formulation XLA maps
well, with a Pallas flash-attention drop-in available via
``dlrover_tpu.ops.flash_attention``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    block_size: int = 1024
    dropout: float = 0.0  # functional model: dropout folded out by default
    dtype: Any = jnp.bfloat16  # compute dtype; params stay float32

    @classmethod
    def tiny(cls) -> "GPTConfig":
        """Sub-second-compile config for CPU tests."""
        return cls(vocab_size=128, n_layer=1, n_head=2, n_embd=32,
                   block_size=32)

    @classmethod
    def small(cls) -> "GPTConfig":
        return cls(vocab_size=50304, n_layer=6, n_head=6, n_embd=384,
                   block_size=256)


def init_params(rng: jax.Array, cfg: GPTConfig) -> Dict:
    """GPT-2-style init: normal(0.02), residual projections scaled by
    1/sqrt(2*n_layer)."""
    k_wte, k_wpe, k_blocks = jax.random.split(rng, 3)
    std = 0.02
    res_std = std / jnp.sqrt(2.0 * cfg.n_layer)

    def dense(key, fan_in, fan_out, scale):
        return {
            "kernel": (jax.random.normal(key, (fan_in, fan_out), jnp.float32)
                       * scale),
            "bias": jnp.zeros((fan_out,), jnp.float32),
        }

    blocks = []
    for i in range(cfg.n_layer):
        k = jax.random.fold_in(k_blocks, i)
        k_qkv, k_proj, k_fc, k_out = jax.random.split(k, 4)
        blocks.append(
            {
                "ln1": {"scale": jnp.ones((cfg.n_embd,), jnp.float32),
                        "bias": jnp.zeros((cfg.n_embd,), jnp.float32)},
                "attn": {
                    "qkv": dense(k_qkv, cfg.n_embd, 3 * cfg.n_embd, std),
                    "proj": dense(k_proj, cfg.n_embd, cfg.n_embd, res_std),
                },
                "ln2": {"scale": jnp.ones((cfg.n_embd,), jnp.float32),
                        "bias": jnp.zeros((cfg.n_embd,), jnp.float32)},
                "mlp": {
                    "fc": dense(k_fc, cfg.n_embd, 4 * cfg.n_embd, std),
                    "proj": dense(k_out, 4 * cfg.n_embd, cfg.n_embd, res_std),
                },
            }
        )
    return {
        "wte": jax.random.normal(
            k_wte, (cfg.vocab_size, cfg.n_embd), jnp.float32) * std,
        "wpe": jax.random.normal(
            k_wpe, (cfg.block_size, cfg.n_embd), jnp.float32) * std,
        "blocks": blocks,
        "ln_f": {"scale": jnp.ones((cfg.n_embd,), jnp.float32),
                 "bias": jnp.zeros((cfg.n_embd,), jnp.float32)},
    }


def param_specs(cfg: GPTConfig, tp_axis: Optional[str] = None,
                fsdp_axis: Optional[str] = None) -> Dict:
    """PartitionSpec tree mirroring :func:`init_params`.

    ``tp_axis`` shards attention heads / MLP hidden (Megatron layout:
    column-parallel qkv+fc, row-parallel proj).  ``fsdp_axis`` shards the
    remaining largest dimension (ZeRO-3-style parameter sharding).
    """
    t, f = tp_axis, fsdp_axis

    def ln():
        return {"scale": P(), "bias": P()}

    block = {
        "ln1": ln(),
        "attn": {
            "qkv": {"kernel": P(f, t), "bias": P(t)},
            "proj": {"kernel": P(t, f), "bias": P()},
        },
        "ln2": ln(),
        "mlp": {
            "fc": {"kernel": P(f, t), "bias": P(t)},
            "proj": {"kernel": P(t, f), "bias": P()},
        },
    }
    return {
        "wte": P(t, f),
        "wpe": P(None, f),
        "blocks": [block] * cfg.n_layer,
        "ln_f": ln(),
    }


def _layer_norm(x, p, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def _attention(x, p, cfg: GPTConfig):
    B, T, C = x.shape
    H = cfg.n_head
    qkv = x @ p["qkv"]["kernel"].astype(cfg.dtype) + p["qkv"]["bias"].astype(
        cfg.dtype
    )
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, C // H).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, C // H).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, C // H).transpose(0, 2, 1, 3)
    scale = 1.0 / jnp.sqrt(C // H).astype(cfg.dtype)
    att = (q @ k.transpose(0, 1, 3, 2)) * scale
    mask = jnp.tril(jnp.ones((T, T), bool))
    att = jnp.where(mask, att, jnp.finfo(cfg.dtype).min)
    att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(cfg.dtype)
    y = att @ v
    y = y.transpose(0, 2, 1, 3).reshape(B, T, C)
    return y @ p["proj"]["kernel"].astype(cfg.dtype) + p["proj"][
        "bias"
    ].astype(cfg.dtype)


def _mlp(x, p, cfg: GPTConfig):
    h = x @ p["fc"]["kernel"].astype(cfg.dtype) + p["fc"]["bias"].astype(
        cfg.dtype
    )
    h = jax.nn.gelu(h)
    return h @ p["proj"]["kernel"].astype(cfg.dtype) + p["proj"][
        "bias"
    ].astype(cfg.dtype)


def forward(params: Dict, tokens: jax.Array, cfg: GPTConfig) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab] (float32)."""
    B, T = tokens.shape
    x = params["wte"].astype(cfg.dtype)[tokens] + params["wpe"].astype(
        cfg.dtype
    )[:T]
    for blk in params["blocks"]:
        x = x + _attention(_layer_norm(x, blk["ln1"]), blk["attn"], cfg)
        x = x + _mlp(_layer_norm(x, blk["ln2"]), blk["mlp"], cfg)
    x = _layer_norm(x, params["ln_f"])
    # Weight-tied LM head (nanoGPT convention).
    logits = x @ params["wte"].astype(cfg.dtype).T
    return logits.astype(jnp.float32)


def loss_fn(params: Dict, tokens: jax.Array, targets: jax.Array,
            cfg: GPTConfig) -> jax.Array:
    logits = forward(params, tokens, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def num_params(params: Dict) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
