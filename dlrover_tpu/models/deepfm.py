"""DeepFM for sparse recommendation — the framework's PS/sparse-path model
(reference examples: ``examples/tensorflow/criteo_deeprec`` DeepFM built on
tfplus KvVariable embeddings; system test ``dlrover-system-test-criteo``).

Architecture (Guo et al., 2017): shared sparse embeddings feed
- a first-order linear term (1-d embedding per feature),
- an FM second-order term: 0.5 * ((sum_f e_f)^2 - sum_f e_f^2),
- a deep MLP over the concatenated field embeddings,
summed into one logit.  The dense half is pure jit (MXU); the unbounded
sparse tables live in :mod:`dlrover_tpu.embedding` host/servers.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DeepFMConfig:
    num_fields: int = 10
    embed_dim: int = 16
    mlp_hidden: Tuple[int, ...] = (64, 32)

    @classmethod
    def tiny(cls) -> "DeepFMConfig":
        return cls(num_fields=4, embed_dim=8, mlp_hidden=(16,))


def init_dense_params(rng, cfg: DeepFMConfig) -> Dict:
    """Dense (MLP + bias) parameters; embeddings live in the KV store."""
    sizes = [cfg.num_fields * cfg.embed_dim, *cfg.mlp_hidden, 1]
    params = {"bias": jnp.zeros(())}
    keys = jax.random.split(rng, len(sizes) - 1)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        params[f"w{i}"] = jax.random.normal(
            keys[i], (fan_in, fan_out)
        ) * jnp.sqrt(2.0 / fan_in)
        params[f"b{i}"] = jnp.zeros((fan_out,))
    return params


def forward(
    params: Dict,
    emb: jnp.ndarray,     # [B, F, D] field embeddings (from the KV store)
    emb1: jnp.ndarray,    # [B, F, 1] first-order weights
    cfg: DeepFMConfig,
) -> jnp.ndarray:
    """Returns logits [B]."""
    b = emb.shape[0]
    first_order = jnp.sum(emb1.reshape(b, -1), axis=1)
    # FM second order over fields.
    sum_emb = jnp.sum(emb, axis=1)                 # [B, D]
    sum_sq = sum_emb * sum_emb
    sq_sum = jnp.sum(emb * emb, axis=1)            # [B, D]
    fm = 0.5 * jnp.sum(sum_sq - sq_sum, axis=1)    # [B]
    # Deep part.
    h = emb.reshape(b, -1)
    n = len(cfg.mlp_hidden) + 1
    for i in range(n):
        h = h @ params[f"w{i}"] + params[f"b{i}"]
        if i < n - 1:
            h = jax.nn.relu(h)
    deep = h[:, 0]
    return first_order + fm + deep + params["bias"]


def loss_fn(
    params: Dict,
    emb: jnp.ndarray,
    emb1: jnp.ndarray,
    labels: jnp.ndarray,  # [B] in {0, 1}
    cfg: DeepFMConfig,
) -> jnp.ndarray:
    logits = forward(params, emb, emb1, cfg)
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * labels
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def make_train_step(cfg: DeepFMConfig, tx):
    """Builds the jitted step: grads flow to dense params AND to the pulled
    embedding row blocks (whose grads go back to the sparse optimizer)."""

    def step(params, opt_state, rows, inv, rows1, inv1, labels):
        b = labels.shape[0]

        def loss_of(p, r, r1):
            emb = jnp.take(r, inv, axis=0).reshape(
                b, cfg.num_fields, cfg.embed_dim
            )
            emb1 = jnp.take(r1, inv1, axis=0).reshape(b, cfg.num_fields, 1)
            return loss_fn(p, emb, emb1, labels, cfg)

        loss, grads = jax.value_and_grad(loss_of, argnums=(0, 1, 2))(
            params, rows, rows1
        )
        import optax

        p_grads, rows_grad, rows1_grad = grads
        updates, opt_state = tx.update(p_grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, rows_grad, rows1_grad

    return jax.jit(step)


def make_cached_train_step(cfg: DeepFMConfig, tx, *, emb_lr: float,
                           eps: float = 1e-8):
    """Device-cache variant of :func:`make_train_step`: the embedding
    gather AND the sparse adagrad update run inside the jitted step
    against the device-resident cache tables (the SparseCore shape;
    reference tfplus trains through in-graph KvVariable kernels,
    ``kv_variable_ops.cc:1`` + ``training_ops.cc``).  The grad of the
    in-step ``jnp.take`` is the segment-sum over duplicate slots, so no
    host-side dedup/scatter is needed at all."""
    from dlrover_tpu.embedding.device_cache import adagrad_update

    def step(params, opt_state, table, accum, slots,
             table1, accum1, slots1, labels):
        b = labels.shape[0]

        def loss_of(p, t, t1):
            emb = jnp.take(t, slots.reshape(-1), axis=0).reshape(
                b, cfg.num_fields, cfg.embed_dim
            )
            emb1 = jnp.take(t1, slots1.reshape(-1), axis=0).reshape(
                b, cfg.num_fields, 1
            )
            return loss_fn(p, emb, emb1, labels, cfg)

        loss, (p_grads, t_grad, t1_grad) = jax.value_and_grad(
            loss_of, argnums=(0, 1, 2)
        )(params, table, table1)
        import optax

        updates, opt_state = tx.update(p_grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        table, accum = adagrad_update(
            table, accum, t_grad, lr=emb_lr, eps=eps
        )
        table1, accum1 = adagrad_update(
            table1, accum1, t1_grad, lr=emb_lr, eps=eps
        )
        return params, opt_state, table, accum, table1, accum1, loss

    return jax.jit(step, donate_argnums=(2, 3, 5, 6))
