"""Model zoo: functional JAX models with explicit parameter pytrees and
partition-spec trees, so the parallel layer can shard them without
framework-specific introspection."""
