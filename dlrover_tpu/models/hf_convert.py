"""HuggingFace Llama checkpoint import.

A user of the reference fine-tunes HF checkpoints (the atorch llama2
example trains ``LlamaForCausalLM`` weights); this converter maps an HF
``LlamaForCausalLM`` state dict onto this framework's functional param
tree so those checkpoints train/serve here directly.

Layout notes (verified by the logit-parity test):
- torch ``nn.Linear`` stores ``[out, in]``; our projections are
  ``[in, out]`` -> every projection transposes.
- HF's rotary embedding is the split-half convention (rotate_half on
  ``[..., :D/2]`` / ``[..., D/2:]``) — identical to ``llama._rope``'s
  (d, d + D/2) pairing, so no permutation of head dims is needed.
- GQA: ``k_proj``/``v_proj`` carry ``KV * head_dim`` rows in the same
  [KV, head_dim] order our reshape expects.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from dlrover_tpu.models.llama import LlamaConfig


def config_from_hf(hf_config: Any) -> LlamaConfig:
    """transformers ``LlamaConfig`` -> :class:`LlamaConfig`."""
    derived_hd = int(hf_config.hidden_size) // int(
        hf_config.num_attention_heads
    )
    explicit_hd = getattr(hf_config, "head_dim", None)
    if explicit_hd is not None and int(explicit_hd) != derived_hd:
        raise ValueError(
            f"HF config has head_dim={explicit_hd} != hidden_size // "
            f"num_attention_heads = {derived_hd}; this LlamaConfig "
            "derives head_dim and cannot represent decoupled head dims"
        )
    return LlamaConfig(
        vocab_size=int(hf_config.vocab_size),
        n_layer=int(hf_config.num_hidden_layers),
        n_head=int(hf_config.num_attention_heads),
        n_kv_head=int(
            getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads
        ),
        d_model=int(hf_config.hidden_size),
        d_ff=int(hf_config.intermediate_size),
        max_seq_len=int(
            getattr(hf_config, "max_position_embeddings", 4096)
        ),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        rms_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
        sliding_window=int(getattr(hf_config, "sliding_window", 0) or 0),
    )


def _np(t) -> np.ndarray:
    try:  # torch tensor
        return t.detach().cpu().float().numpy()
    except AttributeError:
        return np.asarray(t, np.float32)


def from_hf_llama(
    model_or_state: Any,
    cfg: Optional[LlamaConfig] = None,
    *,
    dtype=jnp.float32,
) -> Tuple[Dict, LlamaConfig]:
    """(HF ``LlamaForCausalLM`` | its state_dict) -> (params, cfg).

    With a model, the config converts automatically; a bare state dict
    needs ``cfg``.  Tied embeddings (no ``lm_head.weight``) reuse the
    embedding transposed, matching HF's tie_word_embeddings."""
    import dataclasses

    if hasattr(model_or_state, "state_dict"):
        state = model_or_state.state_dict()
        if cfg is None:
            # Compute dtype follows the conversion dtype (the default
            # bf16 config under f32 weights would silently cost ~1e-3
            # of logit fidelity vs the source model).
            cfg = dataclasses.replace(
                config_from_hf(model_or_state.config), dtype=dtype
            )
    else:
        state = dict(model_or_state)
        if cfg is None:
            raise ValueError("a bare state dict needs an explicit cfg")

    def get(name: str) -> np.ndarray:
        for key in (name, f"model.{name}"):
            if key in state:
                return _np(state[key])
        raise KeyError(
            f"HF checkpoint missing {name!r}; keys start with "
            f"{sorted(state)[:3]}"
        )

    def lin(name: str) -> jnp.ndarray:
        # torch Linear [out, in] -> ours [in, out]
        return jnp.asarray(get(name).T, dtype)

    embed = jnp.asarray(get("embed_tokens.weight"), dtype)
    try:
        lm_head = jnp.asarray(get("lm_head.weight").T, dtype)
    except KeyError:  # tied embeddings
        lm_head = embed.T
    params: Dict = {
        "embed": embed,
        "lm_head": lm_head,
        "ln_f": jnp.asarray(get("norm.weight"), dtype),
        "layers": [],
    }
    bias_keys = [k for k in state if k.endswith(".bias")]
    if bias_keys:
        raise ValueError(
            "HF checkpoint carries bias tensors this architecture has "
            f"no slot for (e.g. {bias_keys[0]!r}); converting would "
            "silently drop them"
        )
    for i in range(cfg.n_layer):
        p = f"layers.{i}."
        params["layers"].append({
            "ln1": jnp.asarray(get(p + "input_layernorm.weight"), dtype),
            "wq": lin(p + "self_attn.q_proj.weight"),
            "wk": lin(p + "self_attn.k_proj.weight"),
            "wv": lin(p + "self_attn.v_proj.weight"),
            "wo": lin(p + "self_attn.o_proj.weight"),
            "ln2": jnp.asarray(
                get(p + "post_attention_layernorm.weight"), dtype
            ),
            "mlp": {
                "w_gate": lin(p + "mlp.gate_proj.weight"),
                "w_up": lin(p + "mlp.up_proj.weight"),
                "w_down": lin(p + "mlp.down_proj.weight"),
            },
        })
    return params, cfg
