"""HuggingFace Llama checkpoint import.

A user of the reference fine-tunes HF checkpoints (the atorch llama2
example trains ``LlamaForCausalLM`` weights); this converter maps an HF
``LlamaForCausalLM`` state dict onto this framework's functional param
tree so those checkpoints train/serve here directly.

Layout notes (verified by the logit-parity test):
- torch ``nn.Linear`` stores ``[out, in]``; our projections are
  ``[in, out]`` -> every projection transposes.
- HF's rotary embedding is the split-half convention (rotate_half on
  ``[..., :D/2]`` / ``[..., D/2:]``) — identical to ``llama._rope``'s
  (d, d + D/2) pairing, so no permutation of head dims is needed.
- GQA: ``k_proj``/``v_proj`` carry ``KV * head_dim`` rows in the same
  [KV, head_dim] order our reshape expects.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from dlrover_tpu.models.llama import LlamaConfig


def config_from_hf(hf_config: Any) -> LlamaConfig:
    """transformers ``LlamaConfig`` -> :class:`LlamaConfig`."""
    derived_hd = int(hf_config.hidden_size) // int(
        hf_config.num_attention_heads
    )
    explicit_hd = getattr(hf_config, "head_dim", None)
    if explicit_hd is not None and int(explicit_hd) != derived_hd:
        raise ValueError(
            f"HF config has head_dim={explicit_hd} != hidden_size // "
            f"num_attention_heads = {derived_hd}; this LlamaConfig "
            "derives head_dim and cannot represent decoupled head dims"
        )
    return LlamaConfig(
        vocab_size=int(hf_config.vocab_size),
        n_layer=int(hf_config.num_hidden_layers),
        n_head=int(hf_config.num_attention_heads),
        n_kv_head=int(
            getattr(hf_config, "num_key_value_heads", None)
            or hf_config.num_attention_heads
        ),
        d_model=int(hf_config.hidden_size),
        d_ff=int(hf_config.intermediate_size),
        max_seq_len=int(
            getattr(hf_config, "max_position_embeddings", 4096)
        ),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        rms_eps=float(getattr(hf_config, "rms_norm_eps", 1e-5)),
        sliding_window=int(getattr(hf_config, "sliding_window", 0) or 0),
    )


def _np(t) -> np.ndarray:
    try:  # torch tensor
        return t.detach().cpu().float().numpy()
    except AttributeError:
        return np.asarray(t, np.float32)


def config_from_hf_dir(path: str) -> LlamaConfig:
    """``config.json`` in an HF checkpoint dir -> :class:`LlamaConfig`."""
    import json
    import os
    import types

    with open(os.path.join(path, "config.json")) as f:
        d = json.load(f)
    return config_from_hf(types.SimpleNamespace(**d))


class _StreamingReader:
    """Per-tensor access to an HF checkpoint directory without ever
    materializing the whole state dict.

    safetensors files are read lazily (``safe_open`` + one
    ``get_tensor`` at a time, via torch so bf16 sources work);
    ``pytorch_model*.bin`` falls back to ``torch.load`` per shard —
    bounded by the shard size, not the checkpoint size."""

    def __init__(self, path: str):
        import json
        import os

        self.path = path
        self.weight_map: Dict[str, str] = {}
        # ONE shard handle at a time: a safetensors handle keeps its
        # file mmapped, and touched pages count toward RSS — caching
        # every shard's handle would re-materialize the whole
        # checkpoint's worth of resident pages, exactly what streaming
        # exists to avoid.  Dropping the old handle unmaps it.
        self._st_handle: Optional[Tuple[str, Any]] = None
        self._bin_cache: Optional[Tuple[str, Dict]] = None
        st_index = os.path.join(path, "model.safetensors.index.json")
        bin_index = os.path.join(path, "pytorch_model.bin.index.json")
        if os.path.exists(st_index):
            with open(st_index) as f:
                self.weight_map = json.load(f)["weight_map"]
        elif os.path.exists(os.path.join(path, "model.safetensors")):
            from safetensors import safe_open

            fname = "model.safetensors"
            with safe_open(
                os.path.join(path, fname), framework="pt"
            ) as h:
                self.weight_map = {k: fname for k in h.keys()}
        elif os.path.exists(bin_index):
            with open(bin_index) as f:
                self.weight_map = json.load(f)["weight_map"]
        elif os.path.exists(os.path.join(path, "pytorch_model.bin")):
            import torch

            fname = "pytorch_model.bin"
            sd = torch.load(
                os.path.join(path, fname), map_location="cpu",
                weights_only=True,
            )
            self._bin_cache = (fname, sd)
            self.weight_map = {k: fname for k in sd}
        else:
            raise FileNotFoundError(
                f"no model.safetensors[.index.json] or "
                f"pytorch_model.bin[.index.json] under {path!r}"
            )

    def keys(self):
        return self.weight_map.keys()

    def get(self, name: str) -> np.ndarray:
        import os

        fname = self.weight_map.get(name)
        if fname is None:
            raise KeyError(name)
        full = os.path.join(self.path, fname)
        if fname.endswith(".safetensors"):
            from safetensors import safe_open

            if self._st_handle is None or self._st_handle[0] != fname:
                self._st_handle = (fname, safe_open(full, framework="pt"))
            return _np(self._st_handle[1].get_tensor(name))
        import torch

        if self._bin_cache is None or self._bin_cache[0] != fname:
            # One .bin shard resident at a time.
            self._bin_cache = (
                fname,
                torch.load(full, map_location="cpu", weights_only=True),
            )
        return _np(self._bin_cache[1][name])


def _build_params(
    get: Any,  # (hf name) -> np.ndarray, raising KeyError when absent
    all_keys: Any,  # () -> iterable of raw checkpoint keys
    cfg: LlamaConfig,
    dtype,
    shardings: Any = None,
) -> Dict:
    """The single HF-Llama -> params layout table, shared by the
    in-memory and streaming importers (key names, transposes,
    tied-embedding fallback, bias rejection live HERE only)."""
    bias_keys = [k for k in all_keys() if k.endswith(".bias")]
    if bias_keys:
        raise ValueError(
            "HF checkpoint carries bias tensors this architecture has "
            f"no slot for (e.g. {bias_keys[0]!r}); converting would "
            "silently drop them"
        )

    def place(arr: jnp.ndarray, spec_path) -> jnp.ndarray:
        if shardings is None:
            return arr
        leaf = shardings
        for p in spec_path:
            leaf = leaf[p]
        import jax

        return jax.device_put(arr, leaf)

    def leaf(name: str, spec_path, transpose=False) -> jnp.ndarray:
        a = get(name)
        if transpose:
            a = a.T
        return place(jnp.asarray(a, dtype), spec_path)

    params: Dict = {
        "embed": leaf("embed_tokens.weight", ("embed",)),
        "ln_f": leaf("norm.weight", ("ln_f",)),
        "layers": [],
    }
    try:
        params["lm_head"] = leaf(
            "lm_head.weight", ("lm_head",), transpose=True
        )
    except KeyError:  # tied embeddings: reload rather than hold both
        params["lm_head"] = place(
            jnp.asarray(get("embed_tokens.weight").T, dtype),
            ("lm_head",),
        )
    for i in range(cfg.n_layer):
        p = f"layers.{i}."
        lp = ("layers", i)
        params["layers"].append({
            "ln1": leaf(p + "input_layernorm.weight", lp + ("ln1",)),
            "wq": leaf(p + "self_attn.q_proj.weight", lp + ("wq",),
                       transpose=True),
            "wk": leaf(p + "self_attn.k_proj.weight", lp + ("wk",),
                       transpose=True),
            "wv": leaf(p + "self_attn.v_proj.weight", lp + ("wv",),
                       transpose=True),
            "wo": leaf(p + "self_attn.o_proj.weight", lp + ("wo",),
                       transpose=True),
            "ln2": leaf(p + "post_attention_layernorm.weight",
                        lp + ("ln2",)),
            "mlp": {
                "w_gate": leaf(p + "mlp.gate_proj.weight",
                               lp + ("mlp", "w_gate"), transpose=True),
                "w_up": leaf(p + "mlp.up_proj.weight",
                             lp + ("mlp", "w_up"), transpose=True),
                "w_down": leaf(p + "mlp.down_proj.weight",
                               lp + ("mlp", "w_down"), transpose=True),
            },
        })
    return params


def from_hf_llama_dir(
    path: str,
    cfg: Optional[LlamaConfig] = None,
    *,
    dtype=jnp.bfloat16,
    shardings: Any = None,
) -> Tuple[Dict, LlamaConfig]:
    """Streaming import of an HF Llama checkpoint DIRECTORY.

    Unlike :func:`from_hf_llama` (which takes an in-memory model/state
    dict — fine for tests, ~4x the checkpoint in host RAM for a real
    7B), this loads ONE tensor at a time: read -> convert (transpose
    projections, cast to ``dtype``) -> optionally ``device_put`` onto
    the matching leaf of ``shardings`` (a params-tree of NamedSharding,
    e.g. ``job.state_sharding["frozen"]``) -> free before the next
    tensor.  Peak host RSS stays ~one tensor above the output tree (or
    ~one tensor total when placing straight to device), which is what
    lets a Llama-2-7B checkpoint load on one v5e host (the role of the
    reference's deferred/meta init,
    ``atorch/atorch/utils/meta_model_utils.py``)."""
    if cfg is None:
        cfg = config_from_hf_dir(path)
        import dataclasses

        cfg = dataclasses.replace(cfg, dtype=dtype)
    reader = _StreamingReader(path)

    def get(name: str) -> np.ndarray:
        for key in (name, f"model.{name}"):
            try:
                return reader.get(key)
            except KeyError:
                continue
        raise KeyError(
            f"HF checkpoint missing {name!r}; available keys start "
            f"with {sorted(reader.keys())[:3]}"
        )

    params = _build_params(get, reader.keys, cfg, dtype, shardings)
    return params, cfg


def from_hf_llama(
    model_or_state: Any,
    cfg: Optional[LlamaConfig] = None,
    *,
    dtype=jnp.float32,
) -> Tuple[Dict, LlamaConfig]:
    """(HF ``LlamaForCausalLM`` | its state_dict) -> (params, cfg).

    With a model, the config converts automatically; a bare state dict
    needs ``cfg``.  Tied embeddings (no ``lm_head.weight``) reuse the
    embedding transposed, matching HF's tie_word_embeddings."""
    import dataclasses

    if hasattr(model_or_state, "state_dict"):
        state = model_or_state.state_dict()
        if cfg is None:
            # Compute dtype follows the conversion dtype (the default
            # bf16 config under f32 weights would silently cost ~1e-3
            # of logit fidelity vs the source model).
            cfg = dataclasses.replace(
                config_from_hf(model_or_state.config), dtype=dtype
            )
    else:
        state = dict(model_or_state)
        if cfg is None:
            raise ValueError("a bare state dict needs an explicit cfg")

    def get(name: str) -> np.ndarray:
        for key in (name, f"model.{name}"):
            if key in state:
                return _np(state[key])
        raise KeyError(
            f"HF checkpoint missing {name!r}; keys start with "
            f"{sorted(state)[:3]}"
        )

    params = _build_params(get, lambda: state.keys(), cfg, dtype)
    return params, cfg
