"""LoRA fine-tuning for the llama family.

Parity target: the reference's llama2 fine-tuning path trains with and
without LoRA (``atorch/examples/llama2`` — its headline FSDP numbers are
quoted "no LoRA" because LoRA is the default cheap mode).  TPU-first
shape: no module wrapping — LoRA is a PYTREE of (A, B) factors plus a
pure ``merge`` that computes ``W_eff = W + scale * (A @ B)`` for the
targeted projection leaves.  The merged tree feeds the UNCHANGED llama
loss/decode machinery, so every path (flash attention, fp8, remat,
pipeline, KV cache) works under LoRA for free; only the factors are
trainable (``optax.masked`` via :func:`trainable_mask`).

    lora = init_lora(rng, params, rank=8)
    loss = llama.loss_fn(merge(params, lora), batch, cfg)
    grads = jax.grad(lambda l: llama.loss_fn(merge(params, l), ...))(lora)
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

# Projection leaves LoRA can target (2-D [in, out] weights).
ATTN_TARGETS = ("wq", "wk", "wv", "wo")
MLP_TARGETS = ("w_gate", "w_up", "w_down")
DEFAULT_TARGETS = ATTN_TARGETS


def init_lora(
    rng: jax.Array,
    params: Dict,
    *,
    rank: int = 8,
    alpha: float = 16.0,
    targets: Sequence[str] = DEFAULT_TARGETS,
) -> Dict:
    """Per-layer (A, B) factors for every targeted projection.

    A ~ N(0, 1/rank) [in, r]; B = 0 [r, out] — the standard init: the
    merged model starts EXACTLY at the base model."""
    layers = []
    for layer in params["layers"]:
        cell: Dict[str, Any] = {}
        for name in targets:
            w = layer.get(name)
            if w is None and "mlp" in layer:
                w = layer["mlp"].get(name)
            if w is None or w.ndim != 2:
                continue
            rng, k = jax.random.split(rng)
            d_in, d_out = w.shape
            cell[name] = {
                "a": jax.random.normal(k, (d_in, rank), jnp.float32)
                / jnp.sqrt(rank),
                "b": jnp.zeros((rank, d_out), jnp.float32),
            }
        layers.append(cell)
    # scale rides the tree as an INEXACT scalar (jax.grad rejects int
    # leaves); trainable_mask excludes it from updates.
    return {
        "layers": layers,
        "scale": jnp.float32(alpha / rank),
    }


def merge(params: Dict, lora: Dict) -> Dict:
    """Base params + LoRA deltas -> a tree the llama fns consume as-is.

    Differentiable in ``lora`` (train with grads wrt the factors only);
    untouched leaves are passed through by reference, not copied."""
    if len(params["layers"]) != len(lora["layers"]):
        raise ValueError(
            f"LoRA tree has {len(lora['layers'])} layers, model has "
            f"{len(params['layers'])} (config drift?)"
        )
    scale = jax.lax.stop_gradient(lora["scale"])
    out = dict(params)
    new_layers = []
    for layer, cell in zip(params["layers"], lora["layers"]):
        nl = dict(layer)
        for name, ab in cell.items():
            delta = (ab["a"] @ ab["b"]) * scale
            if name in nl:
                nl[name] = nl[name] + delta.astype(nl[name].dtype)
            else:
                mlp = dict(nl["mlp"])
                mlp[name] = mlp[name] + delta.astype(mlp[name].dtype)
                nl["mlp"] = mlp
        new_layers.append(nl)
    out["layers"] = new_layers
    return out


def trainable_mask(lora: Dict) -> Dict:
    """optax.masked-compatible mask: True for the (A, B) factors, False
    for the scalar config leaves riding the tree."""
    return jax.tree_util.tree_map(
        lambda x: hasattr(x, "ndim") and x.ndim == 2, lora
    )


def num_lora_params(lora: Dict) -> int:
    return sum(
        int(x.size)
        for x in jax.tree_util.tree_leaves(lora)
        if hasattr(x, "ndim") and getattr(x, "ndim", 0) == 2
    )
