"""Llama-family decoder LM — the flagship model (BASELINE.json configs[1]).

Functional JAX, TPU-first (analogue of the reference's Llama2 ATorch example
``atorch/examples/llama2`` + HF modeling it wraps): RMSNorm (fused Pallas op),
RoPE, grouped-query attention with pluggable attention backends
(XLA-fused reference / Pallas flash / ring for long context / Ulysses SP),
SwiGLU MLP, optional MoE layers (expert-parallel), weight-untied LM head.

Sharding: :func:`param_logical_axes` names every parameter with logical axes
('embed'/'heads'/'mlp'/'vocab'/'expert'), mapped to mesh axes by
``dlrover_tpu.parallel.sharding`` rules — DP/FSDP/TP/SP/EP are rule changes,
not model changes.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from dlrover_tpu.ops.cross_entropy import (
    linear_softmax_cross_entropy,
    softmax_cross_entropy,
)
from dlrover_tpu.ops.flash_attention import flash_attention
from dlrover_tpu.ops.rmsnorm import rmsnorm


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 32
    d_model: int = 4096
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # MoE: >0 turns every `moe_every`-th MLP into an expert layer.
    num_experts: int = 0
    top_k: int = 2
    moe_every: int = 2
    capacity_factor: float = 1.25
    # Sliding-window attention (>0: each position attends the last
    # `sliding_window` positions only — Mistral-style long-context;
    # flash path only, kernels skip out-of-window blocks).
    sliding_window: int = 0
    # Per-block rematerialization: save only the residual stream at layer
    # boundaries, recompute attention/MLP internals in the backward pass.
    # Far better peak-HBM than whole-loss remat policies, which either
    # save every dot output (``dots_saveable``) or re-run a forward whose
    # own intermediates still peak the same (``nothing_saveable``).
    remat_block: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    def is_moe_layer(self, i: int) -> bool:
        """Single source of truth for MoE placement (init_params,
        param_logical_axes and init_fp8_states must agree)."""
        return self.num_experts > 0 and (
            i % self.moe_every == self.moe_every - 1
        )

    @classmethod
    def llama2_7b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def tiny(cls, **over) -> "LlamaConfig":
        base = dict(
            vocab_size=256, n_layer=2, n_head=4, n_kv_head=2, d_model=64,
            d_ff=128, max_seq_len=128,
        )
        base.update(over)
        return cls(**base)

    @classmethod
    def small_300m(cls) -> "LlamaConfig":
        return cls(
            vocab_size=32000, n_layer=12, n_head=16, n_kv_head=16,
            d_model=1024, d_ff=2816, max_seq_len=2048,
        )

    @classmethod
    def medium_800m(cls) -> "LlamaConfig":
        """~780M params: d_model 1536 keeps matmuls MXU-sized (the 300M
        config's 1024-wide GEMMs leave systolic-array lanes idle)."""
        return cls(
            vocab_size=32000, n_layer=24, n_head=16, n_kv_head=16,
            d_model=1536, d_ff=4096, max_seq_len=2048,
        )


def _dense(key, fan_in, fan_out, std=0.02):
    return jax.random.normal(key, (fan_in, fan_out), jnp.float32) * std


def init_params(rng: jax.Array, cfg: LlamaConfig) -> Dict:
    keys = jax.random.split(rng, cfg.n_layer + 3)
    params: Dict = {
        "embed": _dense(keys[0], cfg.vocab_size, cfg.d_model),
        "lm_head": _dense(keys[1], cfg.d_model, cfg.vocab_size),
        "ln_f": jnp.ones((cfg.d_model,), jnp.float32),
        "layers": [],
    }
    hd = cfg.head_dim
    for i in range(cfg.n_layer):
        k = jax.random.split(keys[2 + i], 8)
        layer = {
            "ln1": jnp.ones((cfg.d_model,), jnp.float32),
            "wq": _dense(k[0], cfg.d_model, cfg.n_head * hd),
            "wk": _dense(k[1], cfg.d_model, cfg.n_kv_head * hd),
            "wv": _dense(k[2], cfg.d_model, cfg.n_kv_head * hd),
            "wo": _dense(k[3], cfg.n_head * hd, cfg.d_model),
            "ln2": jnp.ones((cfg.d_model,), jnp.float32),
        }
        if cfg.is_moe_layer(i):
            layer["moe"] = {
                "router": _dense(k[4], cfg.d_model, cfg.num_experts),
                "wi": jax.random.normal(
                    k[5], (cfg.num_experts, cfg.d_model, cfg.d_ff),
                    jnp.float32) * 0.02,
                "wg": jax.random.normal(
                    k[6], (cfg.num_experts, cfg.d_model, cfg.d_ff),
                    jnp.float32) * 0.02,
                "wo": jax.random.normal(
                    k[7], (cfg.num_experts, cfg.d_ff, cfg.d_model),
                    jnp.float32) * 0.02,
            }
        else:
            layer["mlp"] = {
                "w_gate": _dense(k[4], cfg.d_model, cfg.d_ff),
                "w_up": _dense(k[5], cfg.d_model, cfg.d_ff),
                "w_down": _dense(k[6], cfg.d_ff, cfg.d_model),
            }
        params["layers"].append(layer)
    return params


def param_logical_axes(cfg: LlamaConfig) -> Dict:
    """Logical-axis names per parameter (consumed by
    ``parallel.sharding.tree_logical_to_specs``)."""

    def layer_axes(has_moe: bool) -> Dict:
        ax = {
            "ln1": (None,),
            "wq": ("embed", "heads"),
            "wk": ("embed", "heads"),
            "wv": ("embed", "heads"),
            "wo": ("heads", "embed"),
            "ln2": (None,),
        }
        if has_moe:
            ax["moe"] = {
                "router": (None, None),
                "wi": ("expert", "embed", "expert_mlp"),
                "wg": ("expert", "embed", "expert_mlp"),
                "wo": ("expert", "expert_mlp", "embed"),
            }
        else:
            ax["mlp"] = {
                "w_gate": ("embed", "mlp"),
                "w_up": ("embed", "mlp"),
                "w_down": ("mlp", "embed"),
            }
        return ax

    layers = []
    for i in range(cfg.n_layer):
        layers.append(layer_axes(cfg.is_moe_layer(i)))
    return {
        "embed": ("vocab", "embed"),
        "lm_head": ("embed", "vocab"),
        "ln_f": (None,),
        "layers": layers,
    }


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, S, H, D]; rotate pairs (d, d + D/2)."""
    B, S, H, D = x.shape
    half = D // 2
    freqs = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, S, 1, half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    ).astype(x.dtype)


def _fp8_proj(x, w, st, dt):
    """[..., K] @ [K, N] through ops.fp8.fp8_dot (delayed scaling).
    Returns (out [..., N] in compute dtype, new Fp8State)."""
    from dlrover_tpu.ops.fp8 import fp8_dot

    out, new = fp8_dot(
        x.reshape(-1, x.shape[-1]), w.astype(dt), st
    )
    return out.reshape(x.shape[:-1] + (w.shape[-1],)), new


def _attention(
    x, layer, cfg: LlamaConfig, positions, attn_impl: str, mesh,
    segment_ids=None, fp8_layer=None,
):
    """Returns ``(out, new_fp8_layer)``; ``new_fp8_layer`` is None unless
    ``fp8_layer`` (a dict of ``ops.fp8.Fp8State`` for wq/wk/wv/wo) routes
    the projections through e4m3/e5m2 fp8_dot — the reference's
    ``Fp8Optimization`` rewrite of eligible linears
    (``atorch/auto/opt_lib/amp_optimization.py:396``) as a functional
    strategy knob."""
    B, S, C = x.shape
    H, KV, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    dt = cfg.dtype
    new_fp8 = None
    if fp8_layer is not None:
        new_fp8 = {}
        q, new_fp8["wq"] = _fp8_proj(x, layer["wq"], fp8_layer["wq"], dt)
        k, new_fp8["wk"] = _fp8_proj(x, layer["wk"], fp8_layer["wk"], dt)
        v, new_fp8["wv"] = _fp8_proj(x, layer["wv"], fp8_layer["wv"], dt)
        q = q.reshape(B, S, H, D)
        k = k.reshape(B, S, KV, D)
        v = v.reshape(B, S, KV, D)
    else:
        q = (x @ layer["wq"].astype(dt)).reshape(B, S, H, D)
        k = (x @ layer["wk"].astype(dt)).reshape(B, S, KV, D)
        v = (x @ layer["wv"].astype(dt)).reshape(B, S, KV, D)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    if KV != H and attn_impl in ("ring", "ulysses") and mesh is not None:
        # Ring/Ulysses shard over heads and need the full head count; the
        # flash path handles GQA in-kernel (no materialized repeat).
        rep = H // KV
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    if cfg.sliding_window > 0 and attn_impl in ("ring", "ulysses"):
        raise NotImplementedError(
            "sliding_window requires the flash attention path"
        )
    if attn_impl == "ring" and mesh is not None:
        if segment_ids is not None:
            raise NotImplementedError(
                "packed sequences (segment_ids) require the flash "
                "attention path, not ring"
            )
        from dlrover_tpu.parallel.ring_attention import ring_attention

        out = ring_attention(q, k, v, mesh, causal=True)
    elif attn_impl == "ulysses" and mesh is not None:
        if segment_ids is not None:
            raise NotImplementedError(
                "packed sequences (segment_ids) require the flash "
                "attention path, not ulysses"
            )
        from dlrover_tpu.parallel.sequence import ulysses_attention

        out = ulysses_attention(q, k, v, mesh, causal=True)
    else:
        # [B,S,H,D] -> [B,H,S,D] for the flash kernel.
        o = flash_attention(
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            causal=True,
            segment_ids=segment_ids,
            backend=None if attn_impl == "auto" else attn_impl,
            window=cfg.sliding_window,
        )
        out = o.transpose(0, 2, 1, 3)
    out = out.reshape(B, S, H * D)
    if fp8_layer is not None:
        out, new_fp8["wo"] = _fp8_proj(out, layer["wo"],
                                       fp8_layer["wo"], dt)
        return out, new_fp8
    return out @ layer["wo"].astype(dt), None


def _swiglu(x, mlp, dt, fp8_mlp=None):
    """Returns ``(out, new_fp8_mlp)``; fp8 routing as in
    :func:`_attention` when ``fp8_mlp`` carries Fp8States for
    w_gate/w_up/w_down."""
    if fp8_mlp is not None:
        new = {}
        g, new["w_gate"] = _fp8_proj(x, mlp["w_gate"],
                                     fp8_mlp["w_gate"], dt)
        u, new["w_up"] = _fp8_proj(x, mlp["w_up"], fp8_mlp["w_up"], dt)
        out, new["w_down"] = _fp8_proj(
            jax.nn.silu(g) * u, mlp["w_down"], fp8_mlp["w_down"], dt
        )
        return out, new
    g = x @ mlp["w_gate"].astype(dt)
    u = x @ mlp["w_up"].astype(dt)
    return (jax.nn.silu(g) * u) @ mlp["w_down"].astype(dt), None


def _moe_swiglu(x, moe, cfg: LlamaConfig, capacity: Optional[int] = None,
                valid=None, fp8_moe=None):
    """Expert-parallel SwiGLU MoE (dense capacity dispatch, see
    ``parallel.moe`` for the mechanism).  ``capacity`` overrides the
    config-derived expert capacity — decode passes a no-drop value,
    since at T=1 the rounded capacity is so coarse that two batch rows
    landing on one expert would silently drop the second.

    ``valid`` [B, S] bool marks real tokens in packed-sequence training:
    pad positions are excluded from expert routing — they take no
    capacity slots (the position-ordered cumsum would otherwise let a
    pad displace a real token that follows it in the flattened order)
    and contribute nothing to the load-balance aux statistics.

    ``fp8_moe`` (a dict of ``ops.fp8.Fp8State`` for wg/wi/wo) routes the
    expert projections — the bulk of a MoE model's FLOPs — through the
    batched e4m3/e5m2 path (``ops.fp8.fp8_batched_dot``); the router and
    the dispatch/combine einsums stay in fp32/compute dtype (they are
    permutation-weighted sums, not GEMM hot spots).  Returns a third
    element (the new fp8 dict) when set — the reference rewrites every
    eligible expert linear the same way
    (``atorch/auto/opt_lib/amp_optimization.py:396``)."""
    B, S, C = x.shape
    E, K = cfg.num_experts, cfg.top_k
    N = B * S
    dt = cfg.dtype
    tokens = x.reshape(N, C)
    logits = tokens.astype(jnp.float32) @ moe["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, K)
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, -1, keepdims=True), 1e-9
    )
    valid_n = None if valid is None else valid.reshape(N)
    if capacity is None:
        capacity = int(max(1, round(cfg.capacity_factor * N * K / E)))
    onehot_e = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)
    if valid_n is not None:
        # Pads claim no expert slot: drop them before the capacity
        # cumsum so they can't displace later real tokens.
        onehot_e = onehot_e * valid_n[:, None, None].astype(jnp.int32)
    # Rank within the expert: the -1 must come AFTER the sum over E —
    # inside it, every non-selected expert column contributes a spurious
    # -1 (pos = rank - (E-1)), and rank-0 assignments land on pos -1
    # where one_hot() is all-zero: each expert's first token silently
    # vanished from the dispatch.
    pos = (jnp.cumsum(onehot_e.reshape(N * K, E), axis=0)
           * onehot_e.reshape(N * K, E)).reshape(N, K, E).sum(-1) - 1
    keep = pos < capacity
    if valid_n is not None:
        keep = keep & valid_n[:, None]
    dispatch = (
        jax.nn.one_hot(gate_idx, E, dtype=dt)[..., None]
        * jax.nn.one_hot(pos, capacity, dtype=dt)[..., None, :]
        * keep[..., None, None].astype(dt)
    )  # [N, K, E, C]
    xin = jnp.einsum("nd,nkec->ecd", tokens.astype(dt), dispatch)
    if fp8_moe is not None:
        from dlrover_tpu.ops.fp8 import fp8_batched_dot

        new_fp8 = {}
        g, new_fp8["wg"] = fp8_batched_dot(
            xin, moe["wg"].astype(dt), fp8_moe["wg"]
        )
        u, new_fp8["wi"] = fp8_batched_dot(
            xin, moe["wi"].astype(dt), fp8_moe["wi"]
        )
        h = jax.nn.silu(g) * u
        xout, new_fp8["wo"] = fp8_batched_dot(
            h, moe["wo"].astype(dt), fp8_moe["wo"]
        )
    else:
        new_fp8 = None
        g = jnp.einsum("ecd,edf->ecf", xin, moe["wg"].astype(dt))
        u = jnp.einsum("ecd,edf->ecf", xin, moe["wi"].astype(dt))
        h = jax.nn.silu(g) * u
        xout = jnp.einsum("ecf,efd->ecd", h, moe["wo"].astype(dt))
    combine = dispatch * gate_vals[..., None, None].astype(dt)
    out = jnp.einsum("ecd,nkec->nd", xout, combine)
    # Aux load-balance loss, returned via a side dict by forward().
    if valid_n is None:
        me = jnp.mean(probs, axis=0)
        ce = jnp.mean(
            jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32), axis=0
        )
    else:
        w = valid_n.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(w), 1.0)
        me = jnp.sum(probs * w[:, None], axis=0) / denom
        ce = jnp.sum(
            jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32)
            * w[:, None], axis=0,
        ) / denom
    aux = E * jnp.sum(me * ce)
    if fp8_moe is not None:
        return out.reshape(B, S, C), aux, new_fp8
    return out.reshape(B, S, C), aux


def block_apply(
    layer: Dict,
    x: jax.Array,
    cfg: LlamaConfig,
    positions: jax.Array,
    *,
    attn_impl: str = "auto",
    mesh=None,
    segment_ids=None,
    attn_fn=None,  # (h, layer, cfg, positions) -> attn out; overrides
    moe_capacity: Optional[int] = None,
    fp8_layer=None,
) -> tuple:
    """One transformer block: (x, layer) -> (x, moe_aux scalar).  The unit
    the pipeline stage partitioner groups (``models.llama_pp``).
    ``attn_fn`` swaps the attention implementation (the KV-cache decoder
    plugs in here, so train and decode share one block wiring).

    With ``fp8_layer`` (per-layer Fp8State dict from
    :func:`init_fp8_states`) the attention/MLP projections run through
    fp8_dot and the return becomes a 3-tuple
    ``(x, moe_aux, new_fp8_layer)``; on MoE layers the expert
    projections (the bulk of the layer's FLOPs) go through the batched
    fp8 grouped dot as well — only the router and dispatch/combine stay
    in the compute dtype."""
    h = rmsnorm(x, layer["ln1"], eps=cfg.rms_eps)
    if attn_fn is not None:
        if fp8_layer is not None:
            raise ValueError(
                "block_apply: fp8_layer is not supported with a custom "
                "attn_fn (fp8 is a training-path strategy; the KV-cache "
                "decode path stays in the compute dtype)"
            )
        attn, new_fp8_attn = attn_fn(h, layer, cfg, positions), None
    else:
        attn, new_fp8_attn = _attention(
            h, layer, cfg, positions, attn_impl, mesh, segment_ids,
            fp8_layer=fp8_layer,
        )
    x = x + attn
    h = rmsnorm(x, layer["ln2"], eps=cfg.rms_eps)
    if "moe" in layer:
        res = _moe_swiglu(
            h, layer["moe"], cfg, capacity=moe_capacity,
            valid=None if segment_ids is None else segment_ids >= 0,
            fp8_moe=None if fp8_layer is None else fp8_layer["moe"],
        )
        if fp8_layer is not None:
            delta, aux, new_fp8_attn["moe"] = res
            return x + delta, aux, new_fp8_attn
        delta, aux = res
        return x + delta, aux
    out_m, new_fp8_mlp = _swiglu(
        h, layer["mlp"], cfg.dtype,
        fp8_mlp=None if fp8_layer is None else fp8_layer["mlp"],
    )
    if fp8_layer is not None:
        new_fp8_attn["mlp"] = new_fp8_mlp
        return x + out_m, jnp.zeros((), jnp.float32), new_fp8_attn
    return x + out_m, jnp.zeros((), jnp.float32)


def segment_positions(segment_ids: jax.Array) -> jax.Array:
    """[B, S] segment ids -> [B, S] within-segment positions (rope resets
    at every packed-sequence boundary)."""
    S = segment_ids.shape[-1]
    idx = jnp.arange(S)
    change = jnp.concatenate(
        [
            jnp.ones(segment_ids.shape[:-1] + (1,), bool),
            segment_ids[..., 1:] != segment_ids[..., :-1],
        ],
        axis=-1,
    )
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(change, idx, 0), axis=-1
    )
    return idx - start


def init_fp8_states(cfg: LlamaConfig):
    """Per-layer delayed-scaling Fp8State pytree for :func:`loss_fn`'s
    ``fp8_states`` (one state per rewritten linear: wq/wk/wv/wo, plus
    w_gate/w_up/w_down on dense-MLP layers and the stacked wg/wi/wo
    expert tensors on MoE layers).  Thread through the train
    state and feed each step's output back in — the functional analogue
    of the reference's TE amax history
    (``atorch/auto/opt_lib/amp_optimization.py:396``)."""
    from dlrover_tpu.ops.fp8 import Fp8State

    states = []
    for i in range(cfg.n_layer):
        st = {k: Fp8State.init() for k in ("wq", "wk", "wv", "wo")}
        if cfg.is_moe_layer(i):
            st["moe"] = {
                k: Fp8State.init() for k in ("wg", "wi", "wo")
            }
        else:
            st["mlp"] = {
                k: Fp8State.init()
                for k in ("w_gate", "w_up", "w_down")
            }
        states.append(st)
    return states


def forward_hidden(
    params: Dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    attn_impl: str = "auto",
    mesh=None,
    segment_ids=None,
    fp8_states=None,
) -> tuple:
    """tokens [B, S] -> (final-norm hidden [B, S, D], aux dict).

    ``segment_ids`` [B, S] enables packed-sequence training: attention is
    restricted to same-segment pairs (flash-kernel mask) and rope
    positions reset at each segment boundary.  ``fp8_states`` (from
    :func:`init_fp8_states`) routes the block linears through fp8 and
    adds the updated states to the aux dict as ``aux["fp8_states"]``."""
    B, S = tokens.shape
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens]
    if segment_ids is not None:
        positions = segment_positions(segment_ids)
    else:
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    moe_aux = jnp.zeros((), jnp.float32)
    apply = functools.partial(
        block_apply, attn_impl=attn_impl, mesh=mesh,
        segment_ids=segment_ids,
    )
    if cfg.remat_block:
        apply = jax.checkpoint(apply, static_argnums=(2,))
    new_fp8 = [] if fp8_states is not None else None
    for i, layer in enumerate(params["layers"]):
        if fp8_states is None:
            x, aux = apply(layer, x, cfg, positions)
        else:
            x, aux, nf = apply(
                layer, x, cfg, positions, fp8_layer=fp8_states[i]
            )
            new_fp8.append(nf)
        # Identity unless a remat policy references the name: lets
        # Strategy(remat="offload") park the inter-block residual
        # stream in host DRAM (reference
        # selective_offloading_checkpoint.py:252) while everything
        # inside the block rematerializes.
        x = checkpoint_name(x, "block_out")
        moe_aux = moe_aux + aux
    x = rmsnorm(x, params["ln_f"], eps=cfg.rms_eps)
    out_aux = {"moe_aux": moe_aux}
    if new_fp8 is not None:
        out_aux["fp8_states"] = new_fp8
    return x, out_aux


def forward(
    params: Dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    *,
    attn_impl: str = "auto",
    mesh=None,
    segment_ids=None,
    fp8_states=None,
) -> tuple:
    """tokens [B, S] -> (logits [B, S, vocab] fp32, aux dict)."""
    x, aux = forward_hidden(
        params, tokens, cfg, attn_impl=attn_impl, mesh=mesh,
        segment_ids=segment_ids, fp8_states=fp8_states,
    )
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, aux


def uses_fused_lm_head(cfg: LlamaConfig) -> bool:
    """Default policy for routing the loss through the chunked fused
    lm-head cross-entropy (single source of truth — bench reporting and
    ``loss_fn`` must agree on what was actually measured)."""
    return cfg.vocab_size >= 4096


def split_batch(batch: Dict[str, jax.Array]) -> tuple:
    """{"tokens": [B,S+1]} or {"tokens","targets"} -> (tokens, targets)."""
    if "targets" in batch:
        return batch["tokens"], batch["targets"]
    return batch["tokens"][:, :-1], batch["tokens"][:, 1:]


def loss_fn(
    params: Dict,
    batch: Dict[str, jax.Array],  # {"tokens": [B,S+1]} or tokens/targets
    cfg: LlamaConfig,
    *,
    attn_impl: str = "auto",
    mesh=None,
    moe_aux_weight: float = 1e-2,
    fused_lm_head: Optional[bool] = None,
    fp8_states=None,
) -> jax.Array:
    """Next-token loss.  ``fused_lm_head`` (default: auto — on for large
    vocabs) routes the projection through the chunked fused lm-head
    cross-entropy so the [B, S, vocab] logits never hit HBM.  A
    ``batch["segment_ids"]`` entry ([B, S] or [B, S+1] matching tokens)
    enables packed-sequence training.  Prefer the [B, S+1] form (what
    ``data.packing.pack_sequences`` returns at ``seq_len = S+1``): it is
    lossless, while the [B, S] form cannot see the last position's
    target segment and conservatively masks that token's loss."""
    tokens, targets = split_batch(batch)
    seg_full = batch.get("segment_ids")
    seg = valid = None
    if seg_full is not None:
        S = tokens.shape[-1]
        if seg_full.shape[-1] == S + 1:
            seg = seg_full[:, :-1]  # align with the input tokens
            # A position's target is the NEXT token: drop pairs that
            # cross a packed-sequence boundary — and padding (segment
            # < 0, e.g. the packer's -1 fill), or pad->pad pairs would
            # train "predict pad from pad" and deflate the loss.
            valid = (
                (seg_full[:, 1:] == seg_full[:, :-1])
                & (seg_full[:, :-1] >= 0)
            ).astype(jnp.float32)
        else:
            seg = seg_full
            # [B, S] form can't see the target of the LAST position (it
            # lives at S, outside this view) — mask it conservatively;
            # pass the [B, S+1] form to keep that token's loss.
            valid = jnp.concatenate(
                [
                    (
                        (seg[:, 1:] == seg[:, :-1]) & (seg[:, :-1] >= 0)
                    ).astype(jnp.float32),
                    jnp.zeros(seg.shape[:-1] + (1,), jnp.float32),
                ],
                axis=-1,
            )
    if fused_lm_head is None:
        fused_lm_head = uses_fused_lm_head(cfg)
    if fused_lm_head:
        x, aux = forward_hidden(
            params, tokens, cfg, attn_impl=attn_impl, mesh=mesh,
            segment_ids=seg, fp8_states=fp8_states,
        )
        per_tok = linear_softmax_cross_entropy(
            x, params["lm_head"].astype(cfg.dtype), targets
        )
    else:
        logits, aux = forward(
            params, tokens, cfg, attn_impl=attn_impl, mesh=mesh,
            segment_ids=seg, fp8_states=fp8_states,
        )
        per_tok = softmax_cross_entropy(logits, targets)
    if valid is not None:
        ce = jnp.sum(per_tok * valid) / jnp.maximum(jnp.sum(valid), 1.0)
    else:
        ce = jnp.mean(per_tok)
    loss = ce + moe_aux_weight * aux["moe_aux"]
    if fp8_states is not None:
        # (loss, new_fp8_states): use under value_and_grad(has_aux=True)
        # and feed the states back in next step (delayed scaling).
        return loss, aux["fp8_states"]
    return loss


def num_params(params: Dict) -> int:
    return sum(int(np.prod(x.shape))
               for x in jax.tree_util.tree_leaves(params))


def flops_per_token(cfg: LlamaConfig) -> float:
    """~6 * non-embedding params + attention FLOPs (for MFU accounting)."""
    p_layer = (
        cfg.d_model * cfg.n_head * cfg.head_dim  # wq
        + 2 * cfg.d_model * cfg.n_kv_head * cfg.head_dim  # wk, wv
        + cfg.n_head * cfg.head_dim * cfg.d_model  # wo
        + 3 * cfg.d_model * cfg.d_ff  # swiglu
    )
    dense = cfg.n_layer * p_layer + 2 * cfg.vocab_size * cfg.d_model
    attn = 2 * cfg.n_layer * cfg.max_seq_len * cfg.n_head * cfg.head_dim
    return 6.0 * dense + 6.0 * attn
