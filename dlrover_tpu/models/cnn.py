"""Small convolutional classifier: the MNIST-class model family.

Parity target: the reference's CNN example job
(``examples/pytorch/mnist/cnn_train.py`` — the smallest end-to-end model
family its elastic stack is exercised with).  TPU-first shape choices:
NHWC layout (the TPU-native convolution layout), bf16 compute with fp32
params, channel counts in MXU-friendly multiples, and a pure functional
(init / forward / loss) surface matching the other families so it drops
into ``accelerate()`` / the Trainer / the conf executor unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.ops.cross_entropy import softmax_cross_entropy


@dataclasses.dataclass(frozen=True)
class CNNConfig:
    image_size: int = 28
    channels: int = 1
    num_classes: int = 10
    widths: tuple = (32, 64)   # conv channels per block (3x3, stride 2)
    hidden: int = 128
    dtype: Any = jnp.bfloat16

    @classmethod
    def tiny(cls, **over) -> "CNNConfig":
        base = dict(image_size=16, channels=3, num_classes=10,
                    widths=(16, 32), hidden=64)
        base.update(over)
        return cls(**base)

    @property
    def final_spatial(self) -> int:
        s = self.image_size
        for _ in self.widths:
            s = -(-s // 2)  # stride-2 ceil
        return s


def init_params(rng: jax.Array, cfg: CNNConfig) -> Dict:
    keys = jax.random.split(rng, len(cfg.widths) + 2)
    params: Dict[str, Any] = {"convs": []}
    c_in = cfg.channels
    for i, c_out in enumerate(cfg.widths):
        fan_in = 3 * 3 * c_in
        params["convs"].append({
            "w": jax.random.normal(
                keys[i], (3, 3, c_in, c_out), jnp.float32
            ) * np.sqrt(2.0 / fan_in),
            "b": jnp.zeros((c_out,), jnp.float32),
        })
        c_in = c_out
    flat = cfg.final_spatial ** 2 * c_in
    params["fc1"] = {
        "w": jax.random.normal(keys[-2], (flat, cfg.hidden), jnp.float32)
        * np.sqrt(2.0 / flat),
        "b": jnp.zeros((cfg.hidden,), jnp.float32),
    }
    params["head"] = {
        "w": jax.random.normal(
            keys[-1], (cfg.hidden, cfg.num_classes), jnp.float32
        ) * np.sqrt(1.0 / cfg.hidden),
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params


def forward(params: Dict, images: jax.Array, cfg: CNNConfig) -> jax.Array:
    """images [B, H, W, C] (NHWC) -> logits [B, num_classes] fp32."""
    dt = cfg.dtype
    x = images.astype(dt)
    for conv in params["convs"]:
        x = jax.lax.conv_general_dilated(
            x, conv["w"].astype(dt),
            window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + conv["b"].astype(dt)
        x = jax.nn.relu(x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"]["w"].astype(dt)
                    + params["fc1"]["b"].astype(dt))
    logits = (x @ params["head"]["w"].astype(dt)
              + params["head"]["b"].astype(dt))
    return logits.astype(jnp.float32)


def loss_fn(params: Dict, batch: Dict, cfg: CNNConfig) -> jax.Array:
    """batch = {images [B,H,W,C], labels [B]} -> scalar."""
    logits = forward(params, batch["images"], cfg)
    return jnp.mean(softmax_cross_entropy(logits, batch["labels"]))


def accuracy(params: Dict, batch: Dict, cfg: CNNConfig) -> jax.Array:
    logits = forward(params, batch["images"], cfg)
    return jnp.mean(
        (jnp.argmax(logits, -1) == batch["labels"]).astype(jnp.float32)
    )
