"""Pipeline-parallel Llama: stage partitioner + pipelined loss/grads.

The analogue of the reference's pipeline model partitioner + PipelineStage
(``atorch/pipeline_parallel/pipe_module.py``, ``PipelineStage.py``): Llama
blocks are grouped into ``n_stages`` equal stages with a stacked leading
stage axis sharded on the mesh's 'pp' axis; the embedding runs as the
stage-0 entry (``pre_fn``) and final-norm + lm-head + loss as the last-stage
exit (``post_fn``).  Schedules: differentiable GPipe
(:func:`pipeline_loss_fn`) or true 1F1B with recompute backward
(:func:`pipeline_train_grads` -> ``parallel.pipeline.pipeline_value_and_grad``).

Stage homogeneity: each stage must contain the same *pattern* of blocks
(e.g. with ``moe_every=2`` use layers-per-stage divisible by 2) so stage
trees stack.  The MoE aux loss is not propagated through the pipeline
(weight it 0 for parity checks).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dlrover_tpu.models import llama
from dlrover_tpu.models.llama import LlamaConfig
from dlrover_tpu.ops.cross_entropy import softmax_cross_entropy
from dlrover_tpu.ops.rmsnorm import rmsnorm
from dlrover_tpu.parallel.pipeline import (
    deinterleave_stage_grads,
    interleave_stage_params,
    pipeline_apply,
    pipeline_value_and_grad,
    pipeline_value_and_grad_interleaved,
    stack_stage_params,
)


def split_layer_groups(params: Dict, n_groups: int) -> list:
    """Llama layers -> ``n_groups`` contiguous equal groups (each a list
    of block trees).  L must divide evenly and every group must share a
    block pattern (dense/moe) so the group trees stack."""
    layers = params["layers"]
    L = len(layers)
    if L % n_groups != 0:
        raise ValueError(f"n_layer={L} not divisible by {n_groups} groups")
    per = L // n_groups
    return [layers[g * per:(g + 1) * per] for g in range(n_groups)]


def head_tail_params(params: Dict) -> Tuple[Dict, Dict]:
    """(pre, post) halves of the non-block params: embedding enters at
    the first (virtual) stage, final-norm + lm-head leave at the last."""
    return (
        {"embed": params["embed"]},
        {"ln_f": params["ln_f"], "lm_head": params["lm_head"]},
    )


def split_stage_params(
    params: Dict, n_stages: int
) -> Tuple[Any, Dict, Dict]:
    """Llama params -> (stacked_blocks [n_stages, ...], pre, post)."""
    stacked = stack_stage_params(split_layer_groups(params, n_stages))
    pre, post = head_tail_params(params)
    return stacked, pre, post


def merge_stage_grads(
    d_blocks: Any, d_pre: Dict, d_post: Dict, n_stages: int
) -> Dict:
    """Inverse of :func:`split_stage_params` for gradient trees."""
    layers = []
    per = len(d_blocks)  # list of per-position block trees, stage-stacked
    for s in range(n_stages):
        for i in range(per):
            layers.append(
                jax.tree_util.tree_map(lambda g: g[s], d_blocks[i])
            )
    return {
        "embed": d_pre["embed"],
        "layers": layers,
        "ln_f": d_post["ln_f"],
        "lm_head": d_post["lm_head"],
    }


def _stage_fn(cfg: LlamaConfig):
    def fn(stage_blocks, x):
        B = x.shape[0]
        pos = jnp.broadcast_to(jnp.arange(x.shape[1]), (B, x.shape[1]))
        for layer in stage_blocks:  # list of block trees (leading axis gone)
            x, _aux = llama.block_apply(layer, x, cfg, pos)
        return x

    return fn


def _pre_fn(cfg: LlamaConfig):
    def fn(pre, tokens):
        return pre["embed"].astype(cfg.dtype)[tokens]

    return fn


def _post_fn(cfg: LlamaConfig):
    def fn(post, x, targets):
        x = rmsnorm(x, post["ln_f"], eps=cfg.rms_eps)
        logits = (x @ post["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
        return jnp.mean(softmax_cross_entropy(logits, targets))

    return fn


def pipeline_loss_fn(
    params: Dict,
    batch: Dict[str, jax.Array],
    cfg: LlamaConfig,
    mesh: Mesh,
    *,
    n_microbatches: int,
    pp_axis: str = "pp",
) -> jax.Array:
    """Differentiable GPipe loss: split -> pipeline_apply -> head loss.
    Use under ``jax.value_and_grad`` like ``llama.loss_fn``."""
    tokens, targets = llama.split_batch(batch)
    n_stages = mesh.shape[pp_axis]
    stacked, pre, post = split_stage_params(params, n_stages)
    x = _pre_fn(cfg)(pre, tokens)
    out = pipeline_apply(
        _stage_fn(cfg), stacked, x, mesh,
        n_microbatches=n_microbatches, pp_axis=pp_axis,
    )
    return _post_fn(cfg)(post, out, targets)


def pipeline_train_grads(
    params: Dict,
    batch: Dict[str, jax.Array],
    cfg: LlamaConfig,
    mesh: Mesh,
    *,
    n_microbatches: int,
    n_chunks: int = 1,
    pp_axis: str = "pp",
) -> Tuple[jax.Array, Dict]:
    """1F1B loss + grads in ``params``' tree structure (the drop-in
    replacement for ``jax.value_and_grad(llama.loss_fn)`` when pipelining).

    ``n_chunks > 1`` selects the interleaved schedule: each physical
    stage hosts ``n_chunks`` virtual stages (layer groups), shrinking the
    pipeline bubble by ~``n_chunks`` at the price of ``n_chunks``x the
    ring hops (reference ``StageInterleaver``)."""
    tokens, targets = llama.split_batch(batch)
    n_stages = mesh.shape[pp_axis]
    if n_chunks <= 1:
        stacked, pre, post = split_stage_params(params, n_stages)
        loss, (d_blocks, d_pre, d_post) = pipeline_value_and_grad(
            _stage_fn(cfg),
            _pre_fn(cfg),
            _post_fn(cfg),
            stacked, pre, post, tokens, targets, mesh,
            n_microbatches=n_microbatches, pp_axis=pp_axis,
        )
        grads = merge_stage_grads(d_blocks, d_pre, d_post, n_stages)
        return loss, grads

    # Interleaved: layers split into S*V virtual stages in layer order;
    # virtual j lives on physical j % S.
    SV = n_stages * n_chunks
    virt = split_layer_groups(params, SV)
    stacked = interleave_stage_params(virt, n_stages)
    pre, post = head_tail_params(params)
    loss, (d_blocks, d_pre, d_post) = pipeline_value_and_grad_interleaved(
        _stage_fn(cfg),
        _pre_fn(cfg),
        _post_fn(cfg),
        stacked, pre, post, tokens, targets, mesh,
        n_microbatches=n_microbatches, n_chunks=n_chunks,
        pp_axis=pp_axis,
    )
    virt_grads = deinterleave_stage_grads(d_blocks, n_stages, n_chunks)
    grad_layers = []
    for j in range(SV):
        # virt_grads[j] is the list of this virtual stage's block trees.
        grad_layers.extend(virt_grads[j])
    grads = {
        "embed": d_pre["embed"],
        "layers": grad_layers,
        "ln_f": d_post["ln_f"],
        "lm_head": d_post["lm_head"],
    }
    return loss, grads


def strategy_loss_builder(cfg: LlamaConfig, *, devices=None,
                          n_microbatches=None, **loss_kw):
    """``accelerate(loss_fn_builder=...)`` bridge: candidates rewrite
    the MODEL the way the reference's opt_lib transforms do.

    - ``remat == "block"`` -> ``cfg.remat_block=True`` (per-block
      checkpointing inside the model);
    - ``mesh.pp > 1`` -> the GPipe pipelined loss over the candidate's
      own mesh (so the BO search can genuinely score pipeline points
      instead of treating the pp axis as replication);
    - otherwise the plain :func:`llama.loss_fn`.
    """
    import dataclasses as _dc

    from dlrover_tpu.parallel.mesh import build_mesh

    def builder(strategy):
        c = (
            _dc.replace(cfg, remat_block=True)
            if strategy.remat == "block" else cfg
        )
        spec = strategy.mesh
        if spec.pp > 1:
            # The pipelined loss has no moe_aux/fused-lm-head knobs: a
            # pp candidate silently training a DIFFERENT objective than
            # its dp peers would corrupt the search — reject loudly
            # (the sweep logs it and moves on).  moe_aux_weight=0.0 is
            # equivalent (the pipeline head never adds aux).
            unsupported = {
                k: v for k, v in loss_kw.items()
                if not (k == "moe_aux_weight" and v == 0.0)
            }
            if unsupported:
                raise ValueError(
                    "strategy_loss_builder: pipeline path cannot honor "
                    f"loss kwargs {sorted(unsupported)}"
                )
            mesh = build_mesh(spec, devices)  # defaults + normalizes
            M = n_microbatches or max(2, spec.pp)

            def pp_loss(params, batch):
                return pipeline_loss_fn(
                    params, batch, c, mesh, n_microbatches=M
                )

            return pp_loss

        def loss(params, batch):
            return llama.loss_fn(params, batch, c, **loss_kw)

        return loss

    return builder
