"""Elastic data input: dynamic sharding client + elastic dataloaders."""
