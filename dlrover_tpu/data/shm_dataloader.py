"""Coworker input pipeline: a producer process feeding a shm ring buffer.

Parity with the reference shm dataloader + coworker preprocessing
(``atorch/data/shm_dataloader.py:138 ShmDataloader``,
``atorch/data/shm_context.py`` the shared-memory queue of serialized
batches, ``coworker_dataset.py:13`` CPU-coworker preprocessing): batch
materialization (decode, augmentation, tokenization — host CPU work) runs
in a separate OS process so it overlaps device step time, with batches
crossing process boundaries through POSIX shared memory instead of pickle
pipes.

TPU-first notes: on TPU-VM hosts the input pipeline competes with the
runtime for the same cores, so the producer is a *separate process* (GIL-
free) and the transport is zero-copy-read shm.  The ring is crash-aware:
slots move EMPTY -> WRITING -> READY, the consumer detects a dead
producer, drains the READY backlog, and respawns the producer from the
exact next batch index — no sample is lost or duplicated (the elasticity
contract the flash-checkpoint sampler state depends on).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import struct
import time
from multiprocessing import shared_memory
from typing import Any, Callable, Dict, Iterator, Optional

import msgpack
import numpy as np

from dlrover_tpu.common.log import logger

# Slot states.
_EMPTY, _WRITING, _READY = 0, 1, 2
_SLOT_HEADER = struct.Struct("<BxxxxxxxQQ")  # state, payload len, seq


def _pack_batch(batch: Any) -> bytes:
    """Pytree of np arrays -> one buffer (msgpack meta + raw tensor bytes).
    Only flat dicts of arrays are supported — the standard batch shape."""
    metas: Dict[str, dict] = {}
    blobs = []
    offset = 0
    for key, arr in batch.items():
        arr = np.ascontiguousarray(arr)
        metas[key] = {
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
            "offset": offset,
            "nbytes": arr.nbytes,
        }
        blobs.append(arr.tobytes())
        offset += arr.nbytes
    head = msgpack.packb(metas, use_bin_type=True)
    return struct.pack("<I", len(head)) + head + b"".join(blobs)


def _unpack_batch(buf: memoryview) -> Dict[str, np.ndarray]:
    (hlen,) = struct.unpack_from("<I", buf, 0)
    metas = msgpack.unpackb(bytes(buf[4 : 4 + hlen]), raw=False)
    base = 4 + hlen
    out = {}
    for key, m in metas.items():
        arr = np.frombuffer(
            buf, dtype=np.dtype(m["dtype"]),
            count=int(np.prod(m["shape"])) if m["shape"] else 1,
            offset=base + m["offset"],
        ).reshape(m["shape"])
        out[key] = arr.copy()  # detach from the ring before the slot frees
    return out


class ShmRing:
    """Fixed-slot SPSC ring over one POSIX shm segment.

    Layout: ``n_slots * (slot_header + slot_bytes)``.  The single producer
    writes slot ``seq % n_slots`` (waiting for EMPTY); the single consumer
    reads in seq order (waiting for READY).  State bytes are the fences:
    state is flipped to READY only after the payload memcpy completes, and
    to EMPTY only after the consumer has copied out.
    """

    def __init__(self, name: str, slot_bytes: int, n_slots: int,
                 create: bool):
        self.name = name
        self.slot_bytes = slot_bytes
        self.n_slots = n_slots
        self._stride = _SLOT_HEADER.size + slot_bytes
        size = self._stride * n_slots
        if create:
            try:
                old = shared_memory.SharedMemory(name=name)
                old.close()
                old.unlink()
            except FileNotFoundError:
                pass
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=size
            )
            self._shm.buf[:size] = b"\x00" * size
        else:
            self._shm = shared_memory.SharedMemory(name=name)
        self._created = create

    # -- slot access ---------------------------------------------------------
    def _hdr(self, slot: int):
        off = slot * self._stride
        return _SLOT_HEADER.unpack_from(self._shm.buf, off)

    def _set_hdr(self, slot: int, state: int, length: int, seq: int):
        off = slot * self._stride
        _SLOT_HEADER.pack_into(self._shm.buf, off, state, length, seq)

    def state(self, slot: int) -> int:
        return self._hdr(slot)[0]

    def put(self, seq: int, payload: bytes,
            stop: Optional[Callable[[], bool]] = None,
            timeout: float = 60.0) -> bool:
        """Producer side: write batch ``seq``; False on timeout/stop."""
        if len(payload) > self.slot_bytes:
            raise ValueError(
                f"batch of {len(payload)}B exceeds slot size "
                f"{self.slot_bytes}B"
            )
        slot = seq % self.n_slots
        start = time.monotonic()
        deadline = start + timeout
        while self.state(slot) != _EMPTY:
            if stop is not None and stop():
                return False
            now = time.monotonic()
            if now > deadline:
                return False
            # Fine-grained at first (consumer usually frees a slot within
            # a step), coarse once clearly stalled — an orphaned producer
            # must not spin a core for the whole stall window.
            time.sleep(0.0002 if now - start < 1.0 else 0.02)
        off = slot * self._stride
        self._set_hdr(slot, _WRITING, len(payload), seq)
        self._shm.buf[
            off + _SLOT_HEADER.size : off + _SLOT_HEADER.size + len(payload)
        ] = payload
        self._set_hdr(slot, _READY, len(payload), seq)
        return True

    def get(self, seq: int, *, wait: bool = True,
            alive: Optional[Callable[[], bool]] = None,
            timeout: float = 60.0) -> Optional[Dict[str, np.ndarray]]:
        """Consumer side: read batch ``seq``; None if not READY (and not
        waiting, or the producer died, or timeout)."""
        slot = seq % self.n_slots
        deadline = time.monotonic() + timeout
        while True:
            st, length, got_seq = self._hdr(slot)
            if st == _READY and got_seq == seq:
                off = slot * self._stride + _SLOT_HEADER.size
                batch = _unpack_batch(self._shm.buf[off : off + length])
                self._set_hdr(slot, _EMPTY, 0, 0)
                return batch
            if not wait:
                return None
            if alive is not None and not alive():
                # Producer is gone; only drain what is already READY.
                if st != _READY or got_seq != seq:
                    return None
            if time.monotonic() > deadline:
                return None
            time.sleep(0.0002)

    def reset(self) -> None:
        for s in range(self.n_slots):
            self._set_hdr(s, _EMPTY, 0, 0)

    def close(self, unlink: bool = False) -> None:
        self._shm.close()
        if unlink or self._created:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


# Producer exit codes the consumer gives meaning to.
_EXIT_OVERSIZED = 13  # a batch exceeded the slot: deterministic, no respawn


def _producer_main(
    ring_name: str,
    slot_bytes: int,
    n_slots: int,
    fetch_batch: Callable[[np.ndarray], Any],
    index_batches: list,
    start_seq: int,
    put_timeout: float,
    crash_after: int = -1,
) -> None:
    """Runs in the coworker process: materialize batches, fill the ring."""
    ring = ShmRing(ring_name, slot_bytes, n_slots, create=False)
    # The consumer is this process's parent (mp spawn); reparenting means
    # it died — stop instead of busy-waiting out the stall timeout.
    ppid0 = os.getppid()
    orphaned = lambda: os.getppid() != ppid0  # noqa: E731
    try:
        for seq in range(start_seq, len(index_batches)):
            if crash_after >= 0 and seq >= crash_after:
                os._exit(17)  # fault injection: die mid-stream
            batch = fetch_batch(np.asarray(index_batches[seq]))
            try:
                payload = _pack_batch(batch)
                ok = ring.put(
                    seq, payload, stop=orphaned, timeout=put_timeout
                )
            except ValueError:
                # Oversized batch: retrying can never succeed — signal a
                # fatal, non-respawnable condition to the consumer.
                os._exit(_EXIT_OVERSIZED)
            if not ok:
                return
    finally:
        ring.close()


class ShmDataLoader:
    """Prefetching loader: a coworker process keeps the ring full while
    the training process consumes (reference ``ShmDataloader``).

    ``index_batches``: the epoch's per-step index arrays (e.g. from
    ``list(ElasticSampler)``); the full list is shipped to the producer at
    spawn so the coworker needs no live sampler.  ``fetch_batch`` must be
    picklable (top-level function / partial) — it runs in the coworker.
    """

    def __init__(
        self,
        fetch_batch: Callable[[np.ndarray], Any],
        index_batches,
        *,
        slot_bytes: int = 0,
        n_slots: int = 4,
        name: str = "",
        max_respawns: int = 3,
        batch_timeout: float = 600.0,
        stall_timeout: float = 3600.0,
        _crash_after: int = -1,  # test hook
    ):
        """``batch_timeout``: how long the consumer waits for one batch
        from a LIVE producer before giving up (cover the coworker's spawn
        imports + the slowest single fetch).  ``stall_timeout``: how long
        the producer waits for a free slot before concluding the consumer
        is gone — cover the longest consumer pause (eval pass, checkpoint
        persist, re-mesh recompiles)."""
        self.fetch_batch = fetch_batch
        self.index_batches = [np.asarray(b) for b in index_batches]
        self.n_slots = max(2, n_slots)
        self.max_respawns = max_respawns
        self.batch_timeout = batch_timeout
        self.stall_timeout = stall_timeout
        self._crash_after = _crash_after
        self.name = name or f"dlrtpu_ring_{os.getpid()}_{id(self) & 0xFFFF}"
        if slot_bytes <= 0 and self.index_batches:
            sample = _pack_batch(fetch_batch(self.index_batches[0]))
            slot_bytes = int(len(sample) * 1.25) + 1024
        self.slot_bytes = slot_bytes
        self._ring = ShmRing(
            self.name, self.slot_bytes, self.n_slots, create=True
        )
        self._proc: Optional[mp.Process] = None
        self._consumed = 0
        self._respawns = 0

    # -- producer lifecycle --------------------------------------------------
    def _spawn(self, start_seq: int) -> None:
        ctx = mp.get_context("spawn")
        self._proc = ctx.Process(
            target=_producer_main,
            args=(
                self.name, self.slot_bytes, self.n_slots,
                self.fetch_batch, self.index_batches, start_seq,
                self.stall_timeout, self._crash_after,
            ),
            daemon=True,
        )
        self._proc.start()

    def _producer_alive(self) -> bool:
        return self._proc is not None and self._proc.is_alive()

    # -- consumer ------------------------------------------------------------
    def __len__(self) -> int:
        return len(self.index_batches)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self._proc is None:
            self._spawn(self._consumed)
        while self._consumed < len(self.index_batches):
            seq = self._consumed
            batch = self._ring.get(
                seq, alive=self._producer_alive,
                timeout=self.batch_timeout,
            )
            if batch is None:
                if self._producer_alive():
                    raise TimeoutError(
                        f"shm dataloader: batch {seq} not produced within "
                        f"batch_timeout={self.batch_timeout}s; raise it if "
                        "single-batch materialization is legitimately "
                        "slower"
                    )
                code = self._proc.exitcode if self._proc else None
                if code == _EXIT_OVERSIZED:
                    raise ValueError(
                        f"shm dataloader: batch {seq} exceeds the "
                        f"{self.slot_bytes}B slot — pass a larger "
                        "slot_bytes (auto-sizing uses batch 0 + 25% "
                        "headroom, which variable-shaped batches can "
                        "overflow); not respawning a deterministic failure"
                    )
                # Producer died with nothing READY for us: respawn it at
                # exactly the next needed batch (no loss, no duplicates).
                self._respawns += 1
                if self._respawns > self.max_respawns:
                    raise RuntimeError(
                        "shm dataloader: producer died "
                        f"{self._respawns} times; giving up"
                    )
                logger.warning(
                    "shm dataloader: producer died (exit=%s); respawning "
                    "at batch %d", code, seq,
                )
                self._crash_after = -1  # the injected fault fires once
                self._ring.reset()
                self._spawn(seq)
                continue
            self._consumed = seq + 1
            yield batch

    @classmethod
    def from_sampler(cls, sampler, fetch_batch, **kw) -> "ShmDataLoader":
        """Snapshot the sampler's remaining epoch into a prefetching
        loader (integrates with the elastic sampler without mutating its
        checkpointable position)."""
        shadow = sampler.reshard(sampler.num_processes, sampler.process_id)
        return cls(fetch_batch, list(shadow), **kw)

    def close(self) -> None:
        if self._proc is not None and self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5.0)
        self._ring.close(unlink=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
