"""Device prefetcher: overlap host->device transfer with compute.

Analogue of the reference's data preloader (``atorch/atorch/data/
preloader.py`` — CUDA-stream prefetch of the next batch).  On TPU the
same overlap falls out of JAX's async dispatch: ``jax.device_put`` of
batch N+1..N+depth is enqueued while the step consuming batch N runs, so
the input pipeline hides behind compute instead of serializing with it.

    loader = DevicePrefetcher(host_batches, sharding=job.batch_sharding)
    for batch in loader:              # batch is already device-resident
        state, metrics = job.train_step(state, batch)
"""

from __future__ import annotations

import collections
from typing import Any, Iterable, Iterator, Optional

import jax


class DevicePrefetcher:
    """Wraps a host-batch iterable; yields device-resident batches with
    ``depth`` transfers in flight ahead of the consumer."""

    def __init__(
        self,
        batches: Iterable[Any],
        sharding: Any = None,
        depth: int = 2,
    ):
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1")
        self._batches = batches
        self._sharding = sharding
        self.depth = depth

    def _put(self, batch: Any) -> Any:
        if self._sharding is None:
            return jax.tree_util.tree_map(jax.device_put, batch)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), batch, self._sharding
        )

    def __iter__(self) -> Iterator[Any]:
        queue: collections.deque = collections.deque()
        it = iter(self._batches)
        exhausted = False
        while True:
            while not exhausted and len(queue) < self.depth:
                try:
                    queue.append(self._put(next(it)))
                except StopIteration:
                    exhausted = True
            if not queue:
                return
            yield queue.popleft()


def prefetch_to_device(
    batches: Iterable[Any], sharding: Any = None, depth: int = 2
) -> Iterator[Any]:
    """Functional form of :class:`DevicePrefetcher`."""
    return iter(DevicePrefetcher(batches, sharding=sharding, depth=depth))
