"""Sequence packing: variable-length samples -> fixed [B, S] rows.

The host-side half of packed-sequence training (the kernel half is the
segment-id mask in ``ops/flash_attention.py``; the model half is
``models/llama.py``'s per-segment rope + boundary loss mask).  Parity
target: the packing the reference's pack-mask flash-attn variants
consume (``flash_attn_func_ext.py`` GLM/pack masks).

Greedy first-fit packing: documents are placed into the first open row
with room; rows close when full.  Remainder positions are filled with
``pad_id`` under segment ``-1`` (matches no real segment, so padded
positions are masked out of attention AND loss).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def pack_sequences(
    docs: Sequence[np.ndarray],
    seq_len: int,
    *,
    pad_id: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Pack 1-D token arrays into rows of ``seq_len``.

    Returns ``(tokens [B, seq_len], segment_ids [B, seq_len])`` where
    segment ids number the documents within each row (0, 1, ...) and
    padding carries segment ``-1``.  Documents longer than ``seq_len``
    are split into ``seq_len``-sized pieces (each piece its own
    segment — attention never spans a split).

    For next-token training with ``models.llama.loss_fn`` pack to
    ``seq_len = train_seq + 1`` and pass the returned segment_ids
    whole: the ``[B, S+1]`` form (aligned with the un-split tokens) is
    the *lossless* one.  With the ``[B, S]`` form the loss cannot see
    whether the last position's target continues its segment and must
    conservatively mask that token, so the same data yields a slightly
    smaller effective token count.
    """
    pieces: List[np.ndarray] = []
    for doc in docs:
        doc = np.asarray(doc).reshape(-1)
        if doc.size == 0:
            continue
        for lo in range(0, doc.size, seq_len):
            pieces.append(doc[lo:lo + seq_len])

    # First-fit: rows = list of (used, [piece, ...]).
    rows: List[Tuple[int, List[np.ndarray]]] = []
    for piece in pieces:
        for i, (used, items) in enumerate(rows):
            if used + piece.size <= seq_len:
                items.append(piece)
                rows[i] = (used + piece.size, items)
                break
        else:
            rows.append((piece.size, [piece]))

    B = max(1, len(rows))
    tokens = np.full((B, seq_len), pad_id, dtype=np.int32)
    segs = np.full((B, seq_len), -1, dtype=np.int32)
    for r, (_, items) in enumerate(rows):
        at = 0
        for s, piece in enumerate(items):
            tokens[r, at:at + piece.size] = piece
            segs[r, at:at + piece.size] = s
            at += piece.size
    return tokens, segs


def packing_efficiency(segment_ids: np.ndarray) -> float:
    """Fraction of positions holding real tokens (segment != -1)."""
    return float((segment_ids >= 0).mean())
