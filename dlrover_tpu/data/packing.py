"""Sequence packing: variable-length samples -> fixed [B, S] rows.

The host-side half of packed-sequence training (the kernel half is the
segment-id mask in ``ops/flash_attention.py``; the model half is
``models/llama.py``'s per-segment rope + boundary loss mask).  Parity
target: the packing the reference's pack-mask flash-attn variants
consume (``flash_attn_func_ext.py`` GLM/pack masks).

Greedy first-fit packing: documents are placed into the first open row
with room; rows close when full.  Remainder positions are filled with
``pad_id`` under segment ``-1`` (matches no real segment, so padded
positions are masked out of attention AND loss).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import logger


def _packer_lib():
    """ctypes handle for the native first-fit core (None -> fallback)."""
    from dlrover_tpu.common.native import packer_lib

    return packer_lib()


def pack_sequences(
    docs: Sequence[np.ndarray],
    seq_len: int,
    *,
    pad_id: int = 0,
    backend: str = "auto",  # auto | native | python
) -> Tuple[np.ndarray, np.ndarray]:
    """Pack 1-D token arrays into rows of ``seq_len``.

    Returns ``(tokens [B, seq_len], segment_ids [B, seq_len])`` where
    segment ids number the documents within each row (0, 1, ...) and
    padding carries segment ``-1``.  Documents longer than ``seq_len``
    are split into ``seq_len``-sized pieces (each piece its own
    segment — attention never spans a split).

    For next-token training with ``models.llama.loss_fn`` pack to
    ``seq_len = train_seq + 1`` and pass the returned segment_ids
    whole: the ``[B, S+1]`` form (aligned with the un-split tokens) is
    the *lossless* one.  With the ``[B, S]`` form the loss cannot see
    whether the last position's target continues its segment and must
    conservatively mask that token, so the same data yields a slightly
    smaller effective token count.
    """
    pieces: List[np.ndarray] = []
    for doc in docs:
        doc = np.asarray(doc).reshape(-1)
        if doc.size == 0:
            continue
        for lo in range(0, doc.size, seq_len):
            pieces.append(doc[lo:lo + seq_len])
    if not pieces:
        return (
            np.full((1, seq_len), pad_id, np.int32),
            np.full((1, seq_len), -1, np.int32),
        )

    if backend not in ("auto", "native", "python"):
        raise ValueError(f"pack_sequences: unknown backend {backend!r}")
    lib = _packer_lib() if backend in ("auto", "native") else None
    if backend == "native" and lib is None:
        raise RuntimeError("native packer unavailable (no toolchain?)")
    if lib is not None:
        # Native first-fit core (byte-identical layout to the Python
        # loop below) + fully vectorized scatter: the interpreter never
        # touches per-token or per-row work.
        n = len(pieces)
        lengths = np.fromiter(
            (p.size for p in pieces), np.int64, count=n
        )
        row = np.empty(n, np.int32)
        off = np.empty(n, np.int32)
        seg = np.empty(n, np.int32)
        n_rows = int(
            lib.pack_first_fit(lengths, n, seq_len, row, off, seg)
        )
        if n_rows > 0:
            tokens = np.full((n_rows, seq_len), pad_id, np.int32)
            segs = np.full((n_rows, seq_len), -1, np.int32)
            flat = np.concatenate(pieces).astype(np.int32)
            total = int(lengths.sum())
            # Destination of token t of piece i:
            #   row[i]*seq_len + off[i] + (t - piece_start[i])
            starts = np.repeat(
                row.astype(np.int64) * seq_len + off, lengths
            )
            within = np.arange(total) - np.repeat(
                np.cumsum(lengths) - lengths, lengths
            )
            dest = starts + within
            tokens.reshape(-1)[dest] = flat
            segs.reshape(-1)[dest] = np.repeat(seg, lengths)
            return tokens, segs
        logger.warning("native packer rejected input; python fallback")

    # Pure-Python first-fit: rows = list of (used, [piece, ...]).
    rows: List[Tuple[int, List[np.ndarray]]] = []
    for piece in pieces:
        for i, (used, items) in enumerate(rows):
            if used + piece.size <= seq_len:
                items.append(piece)
                rows[i] = (used + piece.size, items)
                break
        else:
            rows.append((piece.size, [piece]))

    B = max(1, len(rows))
    tokens = np.full((B, seq_len), pad_id, dtype=np.int32)
    segs = np.full((B, seq_len), -1, dtype=np.int32)
    for r, (_, items) in enumerate(rows):
        at = 0
        for s, piece in enumerate(items):
            tokens[r, at:at + piece.size] = piece
            segs[r, at:at + piece.size] = s
            at += piece.size
    return tokens, segs


def packing_efficiency(segment_ids: np.ndarray) -> float:
    """Fraction of positions holding real tokens (segment != -1)."""
    return float((segment_ids >= 0).mean())
