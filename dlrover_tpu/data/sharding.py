"""Worker-side dynamic sharding client.

Parity with reference ``elastic_agent/sharding/client.py`` (``ShardingClient
:29``, ``IndexShardingClient :234``): workers *pull* index shards from the
master's task manager instead of owning a static partition, report completion,
and can checkpoint/restore the dataset position — the input-pipeline half of
elasticity.
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.log import logger


class ShardingClient:
    """Task-level client: one task == one index shard [start, end)."""

    def __init__(
        self,
        client: MasterClient,
        dataset_name: str,
        *,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "table",
        batch_size: int = 0,
    ):
        self._client = client
        self.dataset_name = dataset_name
        self._lock = threading.Lock()
        self._current_task = None
        client.report_dataset_shard_params(
            dataset_name=dataset_name,
            dataset_size=dataset_size,
            shard_size=shard_size,
            num_epochs=num_epochs,
            shuffle=shuffle,
            storage_type=storage_type,
            batch_size=batch_size,
        )

    def fetch_task(self):
        task = self._client.get_task(self.dataset_name)
        if task.task_id < 0:
            return None
        with self._lock:
            self._current_task = task
        return task

    def report_task_done(self, task_id: int, success: bool = True) -> None:
        self._client.report_task_result(
            self.dataset_name, task_id, success=success
        )
        with self._lock:
            if self._current_task is not None and (
                self._current_task.task_id == task_id
            ):
                self._current_task = None

    def checkpoint(self) -> str:
        return self._client.get_shard_checkpoint(self.dataset_name)

    def restore(self, content: str) -> bool:
        return self._client.restore_shard_checkpoint(self.dataset_name, content)


class IndexShardingClient(ShardingClient):
    """Record-index iterator over dynamically fetched shards
    (reference ``IndexShardingClient :234``).

    ``iter_indices`` yields global record indices; each exhausted shard is
    acked so the master can account completion, and a crash before the ack
    re-queues the whole shard (at-least-once delivery — pair with stateless
    or idempotent batch consumption).
    """

    def iter_indices(self) -> Iterator[int]:
        while True:
            task = self.fetch_task()
            if task is None:
                return
            for idx in range(task.start, task.end):
                yield idx
            self.report_task_done(task.task_id)

    def iter_batches(self, batch_size: int) -> Iterator[List[int]]:
        """Yield fixed-size index batches, spanning shard boundaries;
        trailing partial batch is yielded last."""
        batch: List[int] = []
        for idx in self.iter_indices():
            batch.append(idx)
            if len(batch) == batch_size:
                yield batch
                batch = []
        if batch:
            yield batch
