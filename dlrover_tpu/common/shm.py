"""POSIX shared-memory tensor arena — the flash-checkpoint staging area.

TPU-native re-design of the reference's shm scheme
(``elastic_agent/torch/ckpt_saver.py:73 TensorMeta``, ``:148
_create_shared_memory``, ``:218 SharedMemoryHandler``): a worker process
stages a flattened state (dict of numpy arrays, produced from the addressable
shards of a sharded jax pytree) into one named shm segment; the agent process
maps the same segment and persists it to storage asynchronously.

Segment layout::

    [ header 64B | meta region (msgpack, fixed capacity) | tensor data ]

Write protocol (single writer, fenced by a SharedLock at the engine layer):
tensor bytes first, then meta, then the header's ``meta_len``/``commit_count``
— a reader that sees a consistent header+crc sees consistent data.

Two backends: the C++ native one (``native/shm_arena.cc`` via ctypes —
shm_open/mmap with multi-threaded memcpy, no Python resource-tracker
interference) and a ``multiprocessing.shared_memory`` fallback.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import struct
from typing import Dict, Optional, Tuple

import msgpack
import numpy as np

from dlrover_tpu import chaos
from dlrover_tpu.common.byte_audit import audit
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.native import shm_lib

MAGIC = 0x44_4C_52_54_50_55_01_00  # "DLRTPU\x01\x00"
HEADER_SIZE = 64
DEFAULT_META_CAPACITY = 8 << 20  # 8 MB of msgpack metadata
# header: magic u64 | data_capacity u64 | meta_capacity u64 | meta_len u64 |
#         commit_count u64 | meta_crc u32 | dirty u32 | pad
# ``dirty`` is set before tensor bytes are overwritten and cleared by the
# final header write: a writer killed mid-write leaves dirty=1, and readers
# treat the arena as holding no valid state (tensor bytes are torn; the CRC
# only covers the meta blob).
_HEADER_FMT = "<QQQQQII"


@dataclasses.dataclass
class TensorMeta:
    """Placement of one tensor inside the arena (reference
    ``ckpt_saver.py:73``)."""

    dtype: str
    shape: tuple
    offset: int
    nbytes: int


def _required_size(flat: Dict[str, np.ndarray], meta_capacity: int) -> int:
    data = sum(int(a.nbytes) for a in flat.values())
    # Round each tensor start to 128B for aligned copies.
    data += 128 * max(1, len(flat))
    return HEADER_SIZE + meta_capacity + data


class _NativeSegment:
    """shm_open/mmap backend via native/shm_arena.cc."""

    def __init__(self, name: str, size: int, create: bool):
        self._lib = shm_lib()
        if self._lib is None:
            raise OSError("native shm library unavailable")
        cname = ("/" + name.lstrip("/")).encode()
        self.name = name
        if create:
            fd = self._lib.shm_arena_create(cname, size)
        else:
            fd = self._lib.shm_arena_open(cname)
        if fd < 0:
            raise OSError(-fd, f"shm open failed for {name}")
        real = self._lib.shm_arena_size(fd)
        if real < 0:
            self._lib.shm_arena_close(fd)
            raise OSError(-real, f"fstat failed for {name}")
        self.size = int(real) if not create else max(int(real), size)
        ptr = self._lib.shm_arena_map(fd, self.size)
        if not ptr:
            self._lib.shm_arena_close(fd)
            raise OSError(f"mmap failed for {name}")
        self._fd = fd
        self._ptr = ptr
        self.buf = np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_ubyte)), shape=(self.size,)
        )

    def memcpy_in(self, offset: int, src: np.ndarray) -> None:
        src = np.ascontiguousarray(src)
        n = src.nbytes
        if n >= (1 << 22):
            self._lib.shm_parallel_memcpy(
                self._ptr + offset, src.ctypes.data, n, 0
            )
        else:
            self.buf[offset : offset + n] = src.reshape(-1).view(np.uint8)

    def crc32(self, offset: int, n: int) -> int:
        return int(self._lib.shm_crc32(self._ptr + offset, n, 0))

    def close(self, unlink: bool = False) -> None:
        try:
            self._lib.shm_arena_unmap(self._ptr, self.size)
            self._lib.shm_arena_close(self._fd)
            if unlink:
                self._lib.shm_arena_unlink(("/" + self.name.lstrip("/")).encode())
        # graftcheck: disable=CC104 -- teardown path: the peer may have
        # already unmapped/unlinked the segment; close must not raise
        except Exception:  # noqa: BLE001
            pass


class _PySegment:
    """multiprocessing.shared_memory fallback backend."""

    def __init__(self, name: str, size: int, create: bool):
        from multiprocessing import resource_tracker, shared_memory

        self.name = name
        if create:
            try:
                self._shm = shared_memory.SharedMemory(
                    name=name, create=True, size=size
                )
            except FileExistsError:
                # A stale segment from a crashed run may be smaller than we
                # need (this backend cannot ftruncate-grow): replace it.
                existing = shared_memory.SharedMemory(name=name)
                if existing.size >= size:
                    self._shm = existing
                else:
                    existing.close()
                    existing.unlink()
                    self._shm = shared_memory.SharedMemory(
                        name=name, create=True, size=size
                    )
        else:
            self._shm = shared_memory.SharedMemory(name=name)
        # Detach from the resource tracker: segment lifetime is managed by the
        # agent (creator), not whichever process exits first.
        try:
            resource_tracker.unregister(f"/{name}", "shared_memory")
        # graftcheck: disable=CC104 -- unregister is best-effort: the
        # tracker API differs across Python versions and a miss only
        # re-enables the default cleanup
        except Exception:  # noqa: BLE001
            pass
        self.size = self._shm.size
        self.buf = np.frombuffer(self._shm.buf, dtype=np.uint8)

    def memcpy_in(self, offset: int, src: np.ndarray) -> None:
        src = np.ascontiguousarray(src)
        n = src.nbytes
        self.buf[offset : offset + n] = src.reshape(-1).view(np.uint8)

    def crc32(self, offset: int, n: int) -> int:
        import zlib

        # zlib hashes the mapped bytes through the buffer protocol —
        # no tobytes() copy of the whole region just to checksum it.
        return zlib.crc32(self.buf[offset : offset + n]) & 0xFFFFFFFF

    def close(self, unlink: bool = False) -> None:
        try:
            self.buf = None
            self._shm.close()
            if unlink:
                self._shm.unlink()
        # graftcheck: disable=CC104 -- teardown path: double-close and
        # unlink-after-peer-unlink are expected during agent restarts
        except Exception:  # noqa: BLE001
            pass


def _shm_stat(name: str):
    """(st_ino, st_size) of the backing /dev/shm file, or None.  Both
    backends materialize the segment there on Linux, so this is the shared
    source of truth for 'has the writer re-created the segment?'."""
    try:
        st = os.stat(f"/dev/shm/{name.lstrip('/')}")
        return (st.st_ino, st.st_size)
    except OSError:
        return None


def _open_segment(name: str, size: int, create: bool):
    if shm_lib() is not None:
        try:
            return _NativeSegment(name, size, create)
        except OSError as e:
            if not create:
                raise FileNotFoundError(
                    f"shm segment {name} not found: {e}"
                ) from e
            logger.warning("native shm open failed (%s); python fallback", e)
    # No native toolchain: both read and write sides use the Python backend
    # (they interoperate — same /dev/shm file).
    try:
        return _PySegment(name, size, create)
    except FileNotFoundError:
        raise
    except OSError as e:
        if not create:
            raise FileNotFoundError(f"shm segment {name} not found: {e}") from e
        raise


class SharedMemoryArena:
    """One named arena holding one staged checkpoint state.

    Writers (worker processes) call :meth:`write_state`; readers (agent saver
    daemon, or a restarted worker doing a warm restore) call
    :meth:`read_state` / :meth:`metadata`.
    """

    def __init__(
        self,
        name: str,
        create: bool = False,
        size: int = 0,
        meta_capacity: int = DEFAULT_META_CAPACITY,
    ):
        self.name = name
        self._meta_capacity = meta_capacity
        self._seg = None
        if create and size:
            self._seg = _open_segment(name, size, create=True)

    # -- writer side --------------------------------------------------------
    def write_state(
        self, flat: Dict[str, np.ndarray], extra: Optional[dict] = None
    ) -> None:
        """Stage a flat ``path -> ndarray`` state (+ JSON-able ``extra`` such
        as step, treedef, sharding info) into the arena, growing it if needed.
        """
        need = _required_size(flat, self._meta_capacity)
        if self._seg is None or self._seg.size < need:
            if self._seg is not None:
                self._seg.close(unlink=True)
            self._seg = _open_segment(self.name, need, create=True)
            self._seg_stat = _shm_stat(self.name)
        seg = self._seg

        # Mark the write in progress BEFORE touching tensor bytes, so a
        # writer killed mid-copy cannot be mistaken for a committed state
        # (the fencing lock may be stolen from a dead holder).
        prev = self._read_header()
        prev_commit = prev[4] if prev else 0
        dirty_header = struct.pack(
            _HEADER_FMT, MAGIC, seg.size, self._meta_capacity,
            prev[3] if prev else 0, prev_commit, prev[5] if prev else 0, 1,
        )
        seg.buf[: len(dirty_header)] = np.frombuffer(
            dirty_header, dtype=np.uint8
        )

        offset = HEADER_SIZE + self._meta_capacity
        metas: Dict[str, dict] = {}
        for path, arr in flat.items():
            arr = np.asarray(arr)
            offset = (offset + 127) & ~127  # 128B alignment
            seg.memcpy_in(offset, arr)
            # dtype.name round-trips extended types (bfloat16/fp8 via
            # ml_dtypes) where dtype.str degrades to raw void ('<V2').
            try:
                dtype_key = (
                    arr.dtype.name
                    if np.dtype(arr.dtype.name) == arr.dtype
                    else arr.dtype.str
                )
            except TypeError:
                dtype_key = arr.dtype.str
            metas[path] = dataclasses.asdict(
                TensorMeta(
                    dtype=dtype_key, shape=tuple(arr.shape),
                    offset=offset, nbytes=int(arr.nbytes),
                )
            )
            offset += arr.nbytes

        meta_blob = msgpack.packb(
            {"tensors": metas, "extra": extra or {}}, use_bin_type=True
        )
        if len(meta_blob) > self._meta_capacity:
            raise ValueError(
                f"checkpoint metadata ({len(meta_blob)}B) exceeds meta region "
                f"({self._meta_capacity}B); raise meta_capacity"
            )
        seg.buf[HEADER_SIZE : HEADER_SIZE + len(meta_blob)] = np.frombuffer(
            meta_blob, dtype=np.uint8
        )
        crc = seg.crc32(HEADER_SIZE, len(meta_blob))
        header = struct.pack(
            _HEADER_FMT,
            MAGIC,
            seg.size,
            self._meta_capacity,
            len(meta_blob),
            prev_commit + 1,
            crc,
            0,  # clear dirty: state is consistent again
        )
        seg.buf[: len(header)] = np.frombuffer(header, dtype=np.uint8)
        # mmap stores do not reliably bump the tmpfs file's mtime, so a
        # live arena written only through memcpy looks idle forever.
        # Touch it explicitly: the launcher's startup GC keys "live" on
        # mtime freshness and must never wipe a sibling run's staged
        # checkpoint on a shared host.
        try:
            os.utime(f"/dev/shm/{self.name.lstrip('/')}")
        except OSError:  # pragma: no cover - segment raced away
            pass

    # -- reader side --------------------------------------------------------
    def _ensure_open(self) -> None:
        if self._seg is None:
            self._seg = _open_segment(self.name, 0, create=False)
            self._seg_stat = _shm_stat(self.name)

    def _read_header(self):
        if self._seg is None:
            return None
        raw = bytes(self._seg.buf[: struct.calcsize(_HEADER_FMT)])
        vals = struct.unpack(_HEADER_FMT, raw)
        if vals[0] != MAGIC:
            return None
        return vals

    def reopen(self) -> None:
        """Re-map the segment (it may have been re-created bigger)."""
        if self._seg is not None:
            self._seg.close()
            self._seg = None
        self._ensure_open()

    def metadata(self) -> Optional[dict]:
        """Read {tensors: {path: TensorMeta-dict}, extra: {...}} or None if
        the arena holds no committed state."""
        try:
            self._ensure_open()
        except FileNotFoundError:
            return None
        # Growth re-creates the named segment (new inode): a long-attached
        # reader must notice and remap, or it would serve stale state forever.
        cur_stat = _shm_stat(self.name)
        if cur_stat is not None and cur_stat != getattr(self, "_seg_stat", None):
            try:
                self.reopen()
            except FileNotFoundError:
                return None
        hdr = self._read_header()
        if hdr is None:
            return None
        _, data_cap, meta_cap, meta_len, commit, crc, dirty = hdr
        if chaos.inject("shm.torn_read") is not None:
            # Behave exactly as if the writer died mid-write: readers see
            # no valid state and must take their storage-fallback path.
            logger.warning(
                "chaos: shm.torn_read — arena %s reports torn state",
                self.name,
            )
            return None
        if dirty:
            logger.warning(
                "shm arena %s: writer died mid-write (dirty); no valid state",
                self.name,
            )
            return None
        if commit == 0 or meta_len == 0:
            return None
        if self._seg.crc32(HEADER_SIZE, meta_len) != crc:
            logger.warning("shm arena %s: meta crc mismatch (torn write?)", self.name)
            return None
        blob = bytes(self._seg.buf[HEADER_SIZE : HEADER_SIZE + meta_len])
        meta = msgpack.unpackb(blob, raw=False, strict_map_key=False)
        meta["commit_count"] = commit
        return meta

    def read_state(
        self, copy: bool = True
    ) -> Optional[Tuple[Dict[str, np.ndarray], dict]]:
        """Read the staged state.

        ``copy=False`` returns **views into the live shm mapping** — the
        flash-checkpoint zero-copy fast path.  Lifetime contract: the
        views are valid only while (a) this arena object stays mapped (no
        concurrent :meth:`reopen`/:meth:`close` — callers serialize on
        their arena mutex) and (b) the writer is fenced out (the per-rank
        SharedLock), since a concurrent :meth:`write_state` would rewrite
        the bytes under them.  Use ``copy=True`` whenever the consumer
        outlives those guarantees (e.g. the replica push, whose payload
        is shipped after the lock is released)."""
        meta = self.metadata()
        if meta is None:
            return None
        out: Dict[str, np.ndarray] = {}
        nbytes_total = 0
        for path, tm in meta["tensors"].items():
            dtype = np.dtype(tm["dtype"])
            n = tm["nbytes"]
            view = self._seg.buf[tm["offset"] : tm["offset"] + n]
            arr = view.view(dtype).reshape(tuple(tm["shape"]))
            out[path] = arr.copy() if copy else arr
            nbytes_total += n
        if copy:
            audit.record_copy(nbytes_total, "arena_read_copy")
        return out, meta["extra"]

    def close(self, unlink: bool = False) -> None:
        if self._seg is not None:
            self._seg.close(unlink=unlink)
            self._seg = None


def arena_name(job_name: str, local_rank: int, purpose: str = "ckpt") -> str:
    """Canonical per-rank arena naming (reference ``_get_shm_name``),
    scoped by the launcher run id so a fresh launch never reads a stale
    arena left by a previous job of the same name."""
    from dlrover_tpu.common.env import run_scoped

    safe = run_scoped(job_name).replace("/", "_")
    return f"dlrtpu_{safe}_{purpose}_{local_rank}"
