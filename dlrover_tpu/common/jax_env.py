"""JAX platform/bootstrap helpers shared by workers, tests and bench.

Some PJRT plugin shims prepend their platform to ``jax_platforms`` at import
time, overriding the ``JAX_PLATFORMS`` env var (observed with tunneled-TPU
plugins).  ``ensure_platform`` re-asserts the env var's choice explicitly so
``JAX_PLATFORMS=cpu`` behaves as documented; call it after ``import jax`` and
before first backend use.
"""

from __future__ import annotations

import os
from typing import Optional


def ensure_platform(platform: Optional[str] = None) -> None:
    """Force the jax platform list to ``platform`` (default: the
    ``JAX_PLATFORMS`` env var, if set).  No-op when neither is given."""
    want = platform or os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    cur = jax.config.jax_platforms
    if cur != want:
        jax.config.update("jax_platforms", want)


def enable_compilation_cache(cache_dir: Optional[str] = None) -> bool:
    """Persistent XLA compilation cache (SURVEY §7 'warm-restart design:
    cache compiled executables keyed by topology').

    Elastic recovery is recompile-dominated: a restarted worker rebuilds
    the SAME jitted step the pre-kill worker already compiled, so a
    disk-backed cache turns most of that downtime into a cache read.
    Controlled by ``DLROVER_TPU_COMPILE_CACHE``: unset/1 -> on at
    ``~/.cache/dlrover_tpu/xla`` (or ``cache_dir``), a path -> on
    there, ``0``/``off`` -> disabled.  Returns True when enabled."""
    env = os.environ.get("DLROVER_TPU_COMPILE_CACHE", "")
    if env.lower() in ("0", "off", "false"):
        return False
    if env and env not in ("1", "on", "true"):
        cache_dir = env
    if not cache_dir:
        cache_dir = os.path.expanduser("~/.cache/dlrover_tpu/xla")
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every executable: recovery cares about the long tail of
        # small programs too (the defaults skip fast compiles).
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # The cache backend LATCHES its directory (or a "no cache"
        # decision) at the first compile and silently ignores config
        # updates afterwards — a process that already jitted anything
        # (warm-up probe, an earlier job in the same interpreter) would
        # keep writing to the old location forever.  Drop the latch so
        # the next compile re-binds from the config just set.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception as e:  # noqa: BLE001 - private API; losing the
            # reset only re-creates the old latched-dir behaviour
            from dlrover_tpu.common.log import logger

            logger.debug("compilation-cache unlatch unavailable: %s", e)
        return True
    except Exception:  # noqa: BLE001 - cache is an optimization only
        return False


def initialize_distributed_from_env() -> bool:
    """Run ``jax.distributed.initialize`` from the agent-provided env
    contract (reference analogue: torchelastic's c10d store bootstrap, here
    replaced by master rendezvous -> coordinator election, SURVEY.md §5
    'Distributed communication backend').

    Returns True if a multi-process runtime was initialized.
    """
    from dlrover_tpu.common.env import (
        get_coordinator,
        get_num_processes,
        get_process_id,
    )

    ensure_platform()
    coordinator = get_coordinator()
    nproc = get_num_processes()
    if not coordinator or nproc <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=nproc,
        process_id=get_process_id(),
    )
    return True
