"""JAX platform/bootstrap helpers shared by workers, tests and bench.

Some PJRT plugin shims prepend their platform to ``jax_platforms`` at import
time, overriding the ``JAX_PLATFORMS`` env var (observed with tunneled-TPU
plugins).  ``ensure_platform`` re-asserts the env var's choice explicitly so
``JAX_PLATFORMS=cpu`` behaves as documented; call it after ``import jax`` and
before first backend use.
"""

from __future__ import annotations

import os
from typing import Optional


def ensure_platform(platform: Optional[str] = None) -> None:
    """Force the jax platform list to ``platform`` (default: the
    ``JAX_PLATFORMS`` env var, if set).  No-op when neither is given."""
    want = platform or os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    cur = jax.config.jax_platforms
    if cur != want:
        jax.config.update("jax_platforms", want)


def initialize_distributed_from_env() -> bool:
    """Run ``jax.distributed.initialize`` from the agent-provided env
    contract (reference analogue: torchelastic's c10d store bootstrap, here
    replaced by master rendezvous -> coordinator election, SURVEY.md §5
    'Distributed communication backend').

    Returns True if a multi-process runtime was initialized.
    """
    from dlrover_tpu.common.env import (
        get_coordinator,
        get_num_processes,
        get_process_id,
    )

    ensure_platform()
    coordinator = get_coordinator()
    nproc = get_num_processes()
    if not coordinator or nproc <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=nproc,
        process_id=get_process_id(),
    )
    return True
