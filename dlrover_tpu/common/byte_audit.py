"""Byte-traffic audit for the flash-checkpoint persist path.

The paper's Flash Checkpoint claim is that persistence is bounded by
device->host (and host->storage) bandwidth, not host-side byte shuffling.
This module is how we *prove* our path holds that property: every site
that materializes a full copy of state bytes (`SharedMemoryArena.read_state
(copy=True)`, ``pack_shard``'s per-tensor ``tobytes`` + join) and every
site that streams them (``ShardStreamWriter``) reports here, and the
checkpoint bench / interop tests assert the streaming path does **zero
intermediate copies and exactly one write pass** over the state.

Disabled by default: each instrumented site costs one attribute check.
Enable only in benches/tests (``audit.enable()``); production saves never
pay the lock.
"""

from __future__ import annotations

import threading
from typing import Dict


class ByteAudit:
    """Thread-safe counters of state-byte traffic, grouped by site."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._copied: Dict[str, int] = {}
        self._written = 0
        self._passes: Dict[str, int] = {}

    def enable(self) -> "ByteAudit":
        self.reset()
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._copied = {}
            self._written = 0
            self._passes = {}

    # -- instrumented sites --------------------------------------------------
    def record_copy(self, nbytes: int, site: str) -> None:
        """A full-size intermediate buffer of state bytes materialized."""
        if not self.enabled:
            return
        with self._lock:
            self._copied[site] = self._copied.get(site, 0) + int(nbytes)

    def record_write(self, nbytes: int) -> None:
        """State bytes handed to the storage sink (no userspace buffer)."""
        if not self.enabled:
            return
        with self._lock:
            self._written += int(nbytes)

    def record_pass(self, kind: str) -> None:
        """One full traversal of the state's bytes began (write or CRC)."""
        if not self.enabled:
            return
        with self._lock:
            self._passes[kind] = self._passes.get(kind, 0) + 1

    # -- readout -------------------------------------------------------------
    @property
    def copied_bytes(self) -> int:
        with self._lock:
            return sum(self._copied.values())

    @property
    def written_bytes(self) -> int:
        with self._lock:
            return self._written

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "copied_bytes": sum(self._copied.values()),
                "copied_by_site": dict(self._copied),
                "written_bytes": self._written,
                "passes": dict(self._passes),
            }


#: Process-global audit instance every instrumented site reports to.
audit = ByteAudit()
