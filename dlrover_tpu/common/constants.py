"""Framework-wide constants.

Capability parity with the reference's ``dlrover/python/common/constants.py``,
re-cast for TPU: node types are TPU-slice roles (no parameter servers on the
GPU-style data plane — the sparse/PS analogue lives in ``dlrover_tpu.embedding``),
and the communication plane is XLA collectives over ICI/DCN instead of NCCL.
"""

from __future__ import annotations


class NodeType:
    """Roles a node (one TPU-VM host / one process in local mode) can take.

    Reference: ``dlrover/python/common/constants.py`` NodeType (master/worker/
    ps/chief/evaluator).  TPU build keeps master/worker; `chief` maps to the
    worker that hosts the JAX coordinator; PS/evaluator become embedding-store
    and eval roles.
    """

    MASTER = "master"
    WORKER = "worker"
    CHIEF = "chief"
    EVALUATOR = "evaluator"
    # Host-side sparse embedding store servers (TFPlus KvVariable analogue).
    EMBEDDING = "embedding"
    # Serving front-door gateways supervised as a fleet role (ISSUE 10):
    # spawned/relaunched by the job manager, health = serve-registry lease.
    GATEWAY = "gateway"


class NodeStatus:
    """Node lifecycle states and the terminal set.

    Mirrors reference ``NodeStatus`` + status flow
    (``master/node/status_flow.py:136``).
    """

    INITIAL = "initial"
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    DELETED = "deleted"
    FINISHED = "finished"
    BREAKDOWN = "breakdown"  # health-check verdict: faulty hardware
    UNKNOWN = "unknown"

    TERMINAL = frozenset({SUCCEEDED, FAILED, DELETED, FINISHED, BREAKDOWN})


class NodeEventType:
    ADDED = "added"
    MODIFIED = "modified"
    DELETED = "deleted"


class NodeExitReason:
    """Why a node exited; drives the relaunch decision
    (reference ``common/constants.py NodeExitReason``)."""

    KILLED = "killed"
    OOM = "oom"
    FATAL_ERROR = "fatal_error"
    HARDWARE_ERROR = "hardware_error"  # TPU chip / ICI failure
    PREEMPTED = "preempted"  # spot/preemptible TPU reclaim
    RELAUNCHED = "relaunched"
    UNKNOWN_ERROR = "unknown_error"
    SUCCEEDED = "succeeded"


class JobStage:
    """Coarse job lifecycle used by the master run-loop
    (reference ``dist_master.py:226``)."""

    INIT = "init"
    PENDING = "pending"
    RUNNING = "running"
    SUSPENDED = "suspended"
    STOPPING = "stopping"
    STOPPED = "stopped"


class JobExitReason:
    SUCCEEDED = "succeeded"
    CODE_ERROR = "code_error"
    NODE_OOM = "node_oom"
    NODE_ERROR = "node_error"
    HANG_ERROR = "hang_error"
    RDZV_TIMEOUT = "rdzv_timeout"
    PENDING_TIMEOUT = "pending_timeout"
    UNKNOWN = "unknown"


class RendezvousName:
    """The two master-side rendezvous services (reference
    ``master/elastic_training/rdzv_manager.py``)."""

    TRAINING = "elastic-training"
    NETWORK_CHECK = "network-check"


class PlatformType:
    """Where nodes run.  LOCAL = subprocesses on this host (test/dev,
    reference ``PlatformType.LOCAL``); PROCESS = multi-process one-host
    elastic cluster; GKE = TPU node pools via Kubernetes (reference K8S);
    RAY kept as an API-compatible stub."""

    LOCAL = "local"
    PROCESS = "process"
    GKE = "gke"
    RAY = "ray"


class TrainingExceptionLevel:
    INFO = "info"
    WARNING = "warning"
    ERROR = "error"
    NODE_ERROR = "node_error"
    RDZV_ERROR = "rdzv_error"
    PROCESS_ERROR = "process_error"


class DiagnosisActionType:
    """What the agent should do after a failure/heartbeat diagnosis
    (reference ``diagnosis/common/constants.py`` + ``training.py:934``)."""

    NONE = "no_action"
    RESTART_WORKER = "restart_worker"  # in-place process restart, keep node
    RELAUNCH_WORKER = "relaunch_worker"  # replace the node (pod/VM relaunch)
    STOP_JOB = "stop_job"
    EVENT = "event"


class CheckpointConstant:
    """Flash-checkpoint file naming (reference ``ckpt_saver.py`` commit
    protocol: done files + tracker file)."""

    TRACKER_FILE = "latest_checkpointed_step.txt"
    DONE_FILE = ".done"
    META_FILE = "checkpoint.meta"
    SHARD_FILE_TMPL = "shard_{}.ckpt"
    TMP_DIR_PREFIX = "._tmp_"


class NodeEnv:
    """Environment variables the agent/worker contract is built on
    (reference ``common/constants.py NodeEnv``)."""

    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    MASTER_ADDR = "DLROVER_TPU_MASTER_ADDR"
    NODE_ID = "DLROVER_TPU_NODE_ID"
    NODE_RANK = "DLROVER_TPU_NODE_RANK"
    NODE_NUM = "DLROVER_TPU_NODE_NUM"
    # JAX distributed bootstrap (set by the agent for each worker process).
    COORDINATOR_ADDR = "DLROVER_TPU_COORDINATOR"
    PROCESS_ID = "DLROVER_TPU_PROCESS_ID"
    NUM_PROCESSES = "DLROVER_TPU_NUM_PROCESSES"
    RESTART_COUNT = "DLROVER_TPU_RESTART_COUNT"
    DEVICES_PER_PROC = "DLROVER_TPU_DEVICES_PER_PROC"
    # Monitoring
    MONITOR_INTERVAL = "DLROVER_TPU_MONITOR_INTERVAL"


class GRPC:
    # 256 MB: control plane carries shard metadata / straggler reports, never
    # tensors; generous cap (reference uses unlimited pickled payloads).
    MAX_MESSAGE_LENGTH = 256 * 1024 * 1024


class TrainingLoopStatus:
    START = 1
    END = 2
    PENDING = 3


# Default timing knobs (overridable via Context, see global_context.py).
class Defaults:
    HEARTBEAT_INTERVAL = 15  # seconds, agent -> master
    HEARTBEAT_TIMEOUT = 300  # master declares node dead
    RDZV_TIMEOUT = 600
    PENDING_TIMEOUT = 900
    MONITOR_INTERVAL = 5
    SCALE_INTERVAL = 30
    SECONDS_TO_WAIT_FAILED_PS = 600
