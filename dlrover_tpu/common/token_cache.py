"""Bounded idempotency-token cache shared by master-side services.

The RPC layer retries UNAVAILABLE (and, for tokened calls,
DEADLINE_EXCEEDED); the master dedupes a retried mutation by caching
``token -> first result`` here.  One implementation so eviction policy
changes land everywhere at once (kv add, task fetch).

Not thread-safe by itself: callers mutate it under their own service
lock, which they already hold to apply the mutation being deduped.
"""

from __future__ import annotations

import collections
from typing import Any, Optional


class BoundedTokenCache:
    """FIFO-bounded ``token -> result`` map.  The bound is far larger than
    any plausible in-flight retry window; it exists so a long job cannot
    leak memory one token per call."""

    def __init__(self, capacity: int = 4096):
        self._capacity = capacity
        self._items: "collections.OrderedDict[str, Any]" = (
            collections.OrderedDict()
        )

    def get(self, token: str) -> Optional[Any]:
        if not token:
            return None
        return self._items.get(token)

    def put(self, token: str, result: Any) -> None:
        if not token:
            return
        self._items[token] = result
        while len(self._items) > self._capacity:
            self._items.popitem(last=False)

    def __len__(self) -> int:
        return len(self._items)

    # -- HA snapshot surface (ISSUE 13) ---------------------------------
    # The master's control-state snapshot must carry the dedupe caches:
    # replaying a journal tail that overlaps the snapshot re-applies
    # tokened mutations, and only the token cache makes that re-apply
    # idempotent (same token -> first result, no double effect).
    def dump_state(self) -> list:
        """Insertion-ordered ``[token, result]`` pairs."""
        return [[t, r] for t, r in self._items.items()]

    def load_state(self, items: list) -> None:
        self._items.clear()
        for token, result in items:
            self.put(token, result)
