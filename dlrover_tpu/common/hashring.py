"""Consistent hashing — the fleet's one ownership primitive.

Extracted from ``serving/tier.py`` (ISSUE 15): the gateway tier proved
the shape (requests hashed to gateways, death = the successor adopts
the dead range, zero cross-owner coordination), and the multi-cell
control plane reuses it verbatim for NODE -> CELL ownership.  One
implementation, because two rings that drift is a split brain: every
layer that answers "who owns this id?" must compute the identical
answer from the identical member set.

``serving.tier`` re-exports :class:`HashRing`/:func:`ring_hash`, so
existing imports keep working; ring assignments are pinned by unit
tests across the move (no ownership churn from the refactor).

Registered as a sim-bound pure policy (graftcheck DET70x, ISSUE 16):
same member set ⇒ same ring, no ambient effects — sha1, never
``hash()`` (PYTHONHASHSEED must not move ownership).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Tuple


def ring_hash(text: str) -> int:
    """Stable 32-bit ring position.  sha1, not ``hash()``: must agree
    across processes and interpreter runs (PYTHONHASHSEED)."""
    return int.from_bytes(
        hashlib.sha1(text.encode()).digest()[:4], "big"
    )


class HashRing:
    """Consistent-hash ring over a member id set (gateway ids, cell
    ids).

    Each member owns ``vnodes`` points; a key's owner is the first
    point clockwise from its hash.  Removing a dead member hands each
    of its arcs to the SUCCESSOR point's member — the "adopts the dead
    one's hash range" failover event, with no other ownership moving
    (consistent hashing's whole point: a member death reshuffles only
    the dead range)."""

    def __init__(self, member_ids, vnodes: int = 64):
        self.member_ids = tuple(sorted(set(member_ids)))
        self.vnodes = int(vnodes)
        points: List[Tuple[int, str]] = []
        for mid in self.member_ids:
            for v in range(self.vnodes):
                points.append((ring_hash(f"{mid}#{v}"), mid))
        points.sort()
        self._points = points

    # The serving tier named the member set after its members; kept as
    # an alias so tier-era callers and reprs read unchanged.
    @property
    def gateway_ids(self) -> Tuple[str, ...]:
        return self.member_ids

    def owner(self, key: str) -> Optional[str]:
        if not self._points:
            return None
        h = ring_hash(key)
        # Binary search for the first point >= h (wrap to the start).
        lo, hi = 0, len(self._points)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._points[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        return self._points[lo % len(self._points)][1]
