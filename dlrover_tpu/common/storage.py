"""Checkpoint storage abstraction.

Parity with reference ``dlrover/python/common/storage.py`` (``CheckpointStorage``
ABC ``:21``, ``PosixDiskStorage :128``): a minimal write/read surface that the
async saver daemon targets, pluggable so GCS/NFS backends can slot in without
touching the saver.  ``ClassMeta`` lets the trainer process tell the agent-side
saver (a different OS process) which storage class to instantiate.
"""

from __future__ import annotations

import abc
import dataclasses
import importlib
import os
import shutil
from typing import Optional


@dataclasses.dataclass
class ClassMeta:
    """Importable constructor spec, shippable over the control plane
    (reference ``storage.py ClassMeta``)."""

    module_path: str = "dlrover_tpu.common.storage"
    class_name: str = "PosixDiskStorage"
    kwargs: dict = dataclasses.field(default_factory=dict)

    def build(self) -> "CheckpointStorage":
        mod = importlib.import_module(self.module_path)
        cls = getattr(mod, self.class_name)
        return cls(**self.kwargs)


class CheckpointStorage(abc.ABC):
    """Byte-level storage surface used by the flash-checkpoint saver."""

    @abc.abstractmethod
    def write(self, content: bytes | str, path: str) -> None: ...

    @abc.abstractmethod
    def read(self, path: str, mode: str = "rb") -> Optional[bytes | str]: ...

    @abc.abstractmethod
    def safe_rmtree(self, dirpath: str) -> None: ...

    @abc.abstractmethod
    def safe_remove(self, path: str) -> None: ...

    @abc.abstractmethod
    def safe_makedirs(self, dirpath: str) -> None: ...

    @abc.abstractmethod
    def commit(self, step: int, success: bool) -> None:
        """Hook invoked after all shards of ``step`` are persisted."""

    @abc.abstractmethod
    def exists(self, path: str) -> bool: ...

    @abc.abstractmethod
    def listdir(self, path: str) -> list[str]: ...

    def rename_dir(self, src: str, dst: str) -> bool:
        """Atomically rename a directory (used by checkpoint quarantine).
        Backends that cannot (object stores: a prefix rename is a full
        copy) return ``False`` and callers fall back to a marker file."""
        return False


class PosixDiskStorage(CheckpointStorage):
    """Local/NFS POSIX filesystem backend (reference ``storage.py:128``)."""

    def write(self, content: bytes | str, path: str) -> None:
        mode = "wb" if isinstance(content, bytes) else "w"
        tmp = path + ".tmp"
        with open(tmp, mode) as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic publish

    def read(self, path: str, mode: str = "rb") -> Optional[bytes | str]:
        if not os.path.exists(path):
            return None
        with open(path, mode) as f:
            return f.read()

    def safe_rmtree(self, dirpath: str) -> None:
        shutil.rmtree(dirpath, ignore_errors=True)

    def safe_remove(self, path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass

    def safe_makedirs(self, dirpath: str) -> None:
        os.makedirs(dirpath, exist_ok=True)

    def rename_dir(self, src: str, dst: str) -> bool:
        try:
            os.replace(src, dst)
            return True
        except OSError:
            # A concurrent rank may have won the rename race, or dst may
            # be an earlier non-empty quarantine dir; callers fall back
            # to the marker file.
            return False

    def commit(self, step: int, success: bool) -> None:
        pass

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> list[str]:
        try:
            return sorted(os.listdir(path))
        except OSError:
            return []


class ObjectStoreStorage(CheckpointStorage):
    """Object-store backend over a tensorstore KvStore driver.

    Fills the reference's GCS/object-store slot (``storage.py`` pluggable
    backends) the TPU-native way: tensorstore ships with jax/orbax and
    speaks ``gs://`` (driver="gcs"), s3, http and local file/memory —
    one backend, any bucket.  Paths handed to the saver are keys under
    the configured root; "directories" are key prefixes (deletes are
    prefix deletes, makedirs is a no-op), so the flash-ckpt layout maps
    directly onto flat object namespaces.

    ``spec`` examples::

        {"driver": "gcs", "bucket": "my-ckpts"}
        {"driver": "file", "path": "/mnt/share/ckpts/"}
        {"driver": "memory"}   # tests
    """

    def __init__(self, spec: dict):
        import tensorstore as ts

        self._spec = dict(spec)
        self._kv = ts.KvStore.open(self._spec).result()

    @staticmethod
    def _key(path: str) -> str:
        return path.lstrip("/")

    @staticmethod
    def _prefix_range(prefix: str):
        """KeyRange covering every key under ``prefix`` (exclusive max =
        prefix with its last byte incremented; checkpoint paths are
        ASCII so the 0xFF carry case cannot arise)."""
        import tensorstore as ts

        succ = prefix[:-1] + chr(ord(prefix[-1]) + 1)
        return ts.KvStore.KeyRange(prefix, succ)

    def write(self, content: bytes | str, path: str) -> None:
        if isinstance(content, str):
            content = content.encode()
        # Object stores publish atomically per key; no tmp+rename dance.
        self._kv.write(self._key(path), content).result()

    def read(self, path: str, mode: str = "rb") -> Optional[bytes | str]:
        res = self._kv.read(self._key(path)).result()
        if res.state != "value":
            return None
        raw = bytes(res.value)
        return raw.decode() if "b" not in mode else raw

    def safe_rmtree(self, dirpath: str) -> None:
        prefix = self._key(dirpath).rstrip("/") + "/"
        self._kv.delete_range(self._prefix_range(prefix)).result()

    def safe_remove(self, path: str) -> None:
        try:
            # kvstore deletes are writes of None.
            self._kv.write(self._key(path), None).result()
        # graftcheck: disable=CC104 -- delete-of-absent-key: kv
        # backends disagree on the error type and safe_remove is
        # idempotent by contract
        except Exception:  # noqa: BLE001 - absent key
            pass

    def safe_makedirs(self, dirpath: str) -> None:
        pass  # prefixes need no creation

    def commit(self, step: int, success: bool) -> None:
        pass

    def exists(self, path: str) -> bool:
        res = self._kv.read(self._key(path)).result()
        if res.state == "value":
            return True
        # A "directory" exists if any key lives under the prefix.
        return bool(self.listdir(path))

    def listdir(self, path: str) -> list[str]:
        prefix = self._key(path).rstrip("/") + "/"
        # An absent prefix lists as empty — so any exception here is a
        # REAL failure (auth/network/bucket) and must propagate: an
        # elastic restore that mistook an outage for "no checkpoint"
        # would silently cold-start and discard the run's progress.
        keys = self._kv.list(self._prefix_range(prefix)).result()
        children = set()
        for k in keys:
            rest = k.decode()[len(prefix):]
            children.add(rest.split("/", 1)[0])
        return sorted(children)


def get_checkpoint_storage(meta: Optional[ClassMeta] = None) -> CheckpointStorage:
    return (meta or ClassMeta()).build()
