"""Checkpoint storage abstraction.

Parity with reference ``dlrover/python/common/storage.py`` (``CheckpointStorage``
ABC ``:21``, ``PosixDiskStorage :128``): a minimal write/read surface that the
async saver daemon targets, pluggable so GCS/NFS backends can slot in without
touching the saver.  ``ClassMeta`` lets the trainer process tell the agent-side
saver (a different OS process) which storage class to instantiate.
"""

from __future__ import annotations

import abc
import contextlib
import dataclasses
import importlib
import io
import os
import shutil
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Optional, Tuple


@dataclasses.dataclass
class ClassMeta:
    """Importable constructor spec, shippable over the control plane
    (reference ``storage.py ClassMeta``)."""

    module_path: str = "dlrover_tpu.common.storage"
    class_name: str = "PosixDiskStorage"
    kwargs: dict = dataclasses.field(default_factory=dict)

    def build(self) -> "CheckpointStorage":
        mod = importlib.import_module(self.module_path)
        cls = getattr(mod, self.class_name)
        return cls(**self.kwargs)


class ShardSink:
    """Random-access write target for one streamed file, published
    atomically when its ``stream_writer`` context exits cleanly.

    ``parallel_safe`` declares whether concurrent ``write_at`` calls from
    multiple threads are allowed (POSIX pwrite: yes; in-memory buffer
    fallback: serialized by a lock, so "safe" but pointless to fan out)."""

    parallel_safe = False

    def write_at(self, data, offset: int) -> int:
        raise NotImplementedError

    def read_at(self, n: int, offset: int) -> bytes:
        raise NotImplementedError

    def truncate(self, size: int) -> None:
        raise NotImplementedError


class _BufferShardSink(ShardSink):
    """Grow-on-demand in-memory sink — the sequential fallback for
    backends without positional file writes (object stores)."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._mu = threading.Lock()

    def write_at(self, data, offset: int) -> int:
        # Splice through the buffer protocol — bytes(data) here would
        # add a redundant full copy of every streamed chunk on exactly
        # the (object-store) backends already paying for the buffering.
        view = memoryview(data)
        n = len(view)
        with self._mu:
            end = offset + n
            if end > len(self._buf):
                self._buf.extend(b"\x00" * (end - len(self._buf)))
            self._buf[offset:end] = view
        return n

    def read_at(self, n: int, offset: int) -> bytes:
        with self._mu:
            return bytes(self._buf[offset : offset + n])

    def truncate(self, size: int) -> None:
        with self._mu:
            if size < len(self._buf):
                del self._buf[size:]
            else:
                self._buf.extend(b"\x00" * (size - len(self._buf)))

    def getvalue(self) -> bytes:
        with self._mu:
            return bytes(self._buf)


class _PosixShardSink(ShardSink):
    """Direct-fd sink over a ``.tmp`` file; pwrite/pread are positional
    syscalls, safe for concurrent range writers."""

    parallel_safe = True

    def __init__(self, fd: int) -> None:
        self._fd = fd

    def write_at(self, data, offset: int) -> int:
        view = memoryview(data)
        total = 0
        while total < len(view):
            total += os.pwrite(self._fd, view[total:], offset + total)
        return total

    def read_at(self, n: int, offset: int) -> bytes:
        return os.pread(self._fd, n, offset)

    def truncate(self, size: int) -> None:
        os.ftruncate(self._fd, size)


class CheckpointStorage(abc.ABC):
    """Byte-level storage surface used by the flash-checkpoint saver."""

    @abc.abstractmethod
    def write(self, content: bytes | str, path: str) -> None: ...

    @abc.abstractmethod
    def read(self, path: str, mode: str = "rb") -> Optional[bytes | str]: ...

    @abc.abstractmethod
    def safe_rmtree(self, dirpath: str) -> None: ...

    @abc.abstractmethod
    def safe_remove(self, path: str) -> None: ...

    @abc.abstractmethod
    def safe_makedirs(self, dirpath: str) -> None: ...

    @abc.abstractmethod
    def commit(self, step: int, success: bool) -> None:
        """Hook invoked after all shards of ``step`` are persisted."""

    @abc.abstractmethod
    def exists(self, path: str) -> bool: ...

    @abc.abstractmethod
    def listdir(self, path: str) -> list[str]: ...

    def rename_dir(self, src: str, dst: str) -> bool:
        """Atomically rename a directory (used by checkpoint quarantine).
        Backends that cannot (object stores: a prefix rename is a full
        copy) return ``False`` and callers fall back to a marker file."""
        return False

    # -- streaming surface (flash-ckpt fast path) ---------------------------
    @contextlib.contextmanager
    def stream_writer(self, path: str):
        """Context manager yielding a :class:`ShardSink` for ``path``.

        The file is published atomically (all-or-nothing) on clean exit
        and discarded on error.  Default implementation buffers in memory
        and publishes through :meth:`write` — correct on any backend
        (object stores publish per-key atomically); POSIX backends
        override with a direct ``.tmp``-file fast path."""
        sink = _BufferShardSink()
        yield sink
        self.write(sink.getvalue(), path)

    def open_read(self, path: str):
        """Readable seekable binary file-like for ``path`` (or ``None``
        when absent).  Default materializes the whole object — POSIX
        backends override so fsck can verify shards larger than RAM."""
        data = self.read(path)
        if data is None:
            return None
        return io.BytesIO(data)

    def write_shard_ranges(
        self,
        path: str,
        total_size: int,
        ranges: Iterable[Tuple[int, Iterable]],
        *,
        workers: int = 1,
        finalize=None,
    ) -> None:
        """Atomically write a file assembled from byte ranges.

        ``ranges`` is ``[(offset, chunk_iterable), ...]``; each range's
        chunks land sequentially starting at its offset.  With
        ``workers > 1`` on a ``parallel_safe`` sink, ranges are drained
        concurrently (POSIX pwrite fast path); otherwise sequentially
        (object-store fallback).  ``finalize(sink)``, if given, runs
        after every range landed and before the atomic publish — the
        streamed-shard writer uses it to patch the header+meta region
        that depends on CRCs computed during the range pass."""
        with self.stream_writer(path) as sink:
            if total_size:
                sink.truncate(total_size)
            drain_ranges(sink, list(ranges), workers)
            if finalize is not None:
                finalize(sink)


def drain_ranges(sink: ShardSink, ranges: list, workers: int = 1) -> None:
    """Write every ``(offset, chunk_iterable)`` range into ``sink``.

    Chunk iterables may carry side effects (the streamed-shard writer's
    generators fold CRC-32 as they yield), so each range is consumed
    in-order by exactly one thread."""

    def _one(rng) -> None:
        offset, chunks = rng
        pos = offset
        for chunk in chunks:
            pos += sink.write_at(chunk, pos)

    if workers <= 1 or not sink.parallel_safe or len(ranges) <= 1:
        for rng in ranges:
            _one(rng)
        return
    with ThreadPoolExecutor(
        max_workers=min(workers, len(ranges)),
        thread_name_prefix="shard-range",
    ) as pool:
        # list() forces completion and re-raises the first worker error.
        list(pool.map(_one, ranges))


class PosixDiskStorage(CheckpointStorage):
    """Local/NFS POSIX filesystem backend (reference ``storage.py:128``)."""

    def write(self, content: bytes | str, path: str) -> None:
        mode = "wb" if isinstance(content, bytes) else "w"
        tmp = path + ".tmp"
        with open(tmp, mode) as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic publish

    def read(self, path: str, mode: str = "rb") -> Optional[bytes | str]:
        if not os.path.exists(path):
            return None
        with open(path, mode) as f:
            return f.read()

    def safe_rmtree(self, dirpath: str) -> None:
        shutil.rmtree(dirpath, ignore_errors=True)

    def safe_remove(self, path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass

    def safe_makedirs(self, dirpath: str) -> None:
        os.makedirs(dirpath, exist_ok=True)

    def rename_dir(self, src: str, dst: str) -> bool:
        try:
            os.replace(src, dst)
            return True
        except OSError:
            # A concurrent rank may have won the rename race, or dst may
            # be an earlier non-empty quarantine dir; callers fall back
            # to the marker file.
            return False

    @contextlib.contextmanager
    def stream_writer(self, path: str):
        """Direct-fd fast path: chunks go straight to a ``.tmp`` file
        (pwrite — safe for parallel range workers), then fsync + atomic
        rename publish, mirroring :meth:`write`'s crash contract."""
        tmp = path + ".tmp"
        fd = os.open(tmp, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            yield _PosixShardSink(fd)
            os.fsync(fd)
        except BaseException:
            os.close(fd)
            self.safe_remove(tmp)
            raise
        os.close(fd)
        os.replace(tmp, path)  # atomic publish

    def open_read(self, path: str):
        try:
            return open(path, "rb")
        except OSError:
            return None

    def commit(self, step: int, success: bool) -> None:
        pass

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> list[str]:
        try:
            return sorted(os.listdir(path))
        except OSError:
            return []


class ObjectStoreStorage(CheckpointStorage):
    """Object-store backend over a tensorstore KvStore driver.

    Fills the reference's GCS/object-store slot (``storage.py`` pluggable
    backends) the TPU-native way: tensorstore ships with jax/orbax and
    speaks ``gs://`` (driver="gcs"), s3, http and local file/memory —
    one backend, any bucket.  Paths handed to the saver are keys under
    the configured root; "directories" are key prefixes (deletes are
    prefix deletes, makedirs is a no-op), so the flash-ckpt layout maps
    directly onto flat object namespaces.

    ``spec`` examples::

        {"driver": "gcs", "bucket": "my-ckpts"}
        {"driver": "file", "path": "/mnt/share/ckpts/"}
        {"driver": "memory"}   # tests
    """

    def __init__(self, spec: dict):
        import tensorstore as ts

        self._spec = dict(spec)
        self._kv = ts.KvStore.open(self._spec).result()

    @staticmethod
    def _key(path: str) -> str:
        return path.lstrip("/")

    @staticmethod
    def _prefix_range(prefix: str):
        """KeyRange covering every key under ``prefix`` (exclusive max =
        prefix with its last byte incremented; checkpoint paths are
        ASCII so the 0xFF carry case cannot arise)."""
        import tensorstore as ts

        succ = prefix[:-1] + chr(ord(prefix[-1]) + 1)
        return ts.KvStore.KeyRange(prefix, succ)

    def write(self, content: bytes | str, path: str) -> None:
        if isinstance(content, str):
            content = content.encode()
        # Object stores publish atomically per key; no tmp+rename dance.
        self._kv.write(self._key(path), content).result()

    def read(self, path: str, mode: str = "rb") -> Optional[bytes | str]:
        res = self._kv.read(self._key(path)).result()
        if res.state != "value":
            return None
        raw = bytes(res.value)
        return raw.decode() if "b" not in mode else raw

    def safe_rmtree(self, dirpath: str) -> None:
        prefix = self._key(dirpath).rstrip("/") + "/"
        self._kv.delete_range(self._prefix_range(prefix)).result()

    def safe_remove(self, path: str) -> None:
        try:
            # kvstore deletes are writes of None.
            self._kv.write(self._key(path), None).result()
        # graftcheck: disable=CC104 -- delete-of-absent-key: kv
        # backends disagree on the error type and safe_remove is
        # idempotent by contract
        except Exception:  # noqa: BLE001 - absent key
            pass

    def safe_makedirs(self, dirpath: str) -> None:
        pass  # prefixes need no creation

    def commit(self, step: int, success: bool) -> None:
        pass

    def exists(self, path: str) -> bool:
        res = self._kv.read(self._key(path)).result()
        if res.state == "value":
            return True
        # A "directory" exists if any key lives under the prefix.
        return bool(self.listdir(path))

    def listdir(self, path: str) -> list[str]:
        prefix = self._key(path).rstrip("/") + "/"
        # An absent prefix lists as empty — so any exception here is a
        # REAL failure (auth/network/bucket) and must propagate: an
        # elastic restore that mistook an outage for "no checkpoint"
        # would silently cold-start and discard the run's progress.
        keys = self._kv.list(self._prefix_range(prefix)).result()
        children = set()
        for k in keys:
            rest = k.decode()[len(prefix):]
            children.add(rest.split("/", 1)[0])
        return sorted(children)


def get_checkpoint_storage(meta: Optional[ClassMeta] = None) -> CheckpointStorage:
    return (meta or ClassMeta()).build()
