"""Checkpoint storage abstraction.

Parity with reference ``dlrover/python/common/storage.py`` (``CheckpointStorage``
ABC ``:21``, ``PosixDiskStorage :128``): a minimal write/read surface that the
async saver daemon targets, pluggable so GCS/NFS backends can slot in without
touching the saver.  ``ClassMeta`` lets the trainer process tell the agent-side
saver (a different OS process) which storage class to instantiate.
"""

from __future__ import annotations

import abc
import dataclasses
import importlib
import os
import shutil
from typing import Optional


@dataclasses.dataclass
class ClassMeta:
    """Importable constructor spec, shippable over the control plane
    (reference ``storage.py ClassMeta``)."""

    module_path: str = "dlrover_tpu.common.storage"
    class_name: str = "PosixDiskStorage"
    kwargs: dict = dataclasses.field(default_factory=dict)

    def build(self) -> "CheckpointStorage":
        mod = importlib.import_module(self.module_path)
        cls = getattr(mod, self.class_name)
        return cls(**self.kwargs)


class CheckpointStorage(abc.ABC):
    """Byte-level storage surface used by the flash-checkpoint saver."""

    @abc.abstractmethod
    def write(self, content: bytes | str, path: str) -> None: ...

    @abc.abstractmethod
    def read(self, path: str, mode: str = "rb") -> Optional[bytes | str]: ...

    @abc.abstractmethod
    def safe_rmtree(self, dirpath: str) -> None: ...

    @abc.abstractmethod
    def safe_remove(self, path: str) -> None: ...

    @abc.abstractmethod
    def safe_makedirs(self, dirpath: str) -> None: ...

    @abc.abstractmethod
    def commit(self, step: int, success: bool) -> None:
        """Hook invoked after all shards of ``step`` are persisted."""

    @abc.abstractmethod
    def exists(self, path: str) -> bool: ...

    @abc.abstractmethod
    def listdir(self, path: str) -> list[str]: ...


class PosixDiskStorage(CheckpointStorage):
    """Local/NFS POSIX filesystem backend (reference ``storage.py:128``)."""

    def write(self, content: bytes | str, path: str) -> None:
        mode = "wb" if isinstance(content, bytes) else "w"
        tmp = path + ".tmp"
        with open(tmp, mode) as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic publish

    def read(self, path: str, mode: str = "rb") -> Optional[bytes | str]:
        if not os.path.exists(path):
            return None
        with open(path, mode) as f:
            return f.read()

    def safe_rmtree(self, dirpath: str) -> None:
        shutil.rmtree(dirpath, ignore_errors=True)

    def safe_remove(self, path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass

    def safe_makedirs(self, dirpath: str) -> None:
        os.makedirs(dirpath, exist_ok=True)

    def commit(self, step: int, success: bool) -> None:
        pass

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> list[str]:
        try:
            return sorted(os.listdir(path))
        except OSError:
            return []


def get_checkpoint_storage(meta: Optional[ClassMeta] = None) -> CheckpointStorage:
    return (meta or ClassMeta()).build()
