"""Global tunables singleton (reference ``dlrover/python/common/global_context.py:59``).

One process-wide ``Context`` with every timing/size knob, overridable from
environment variables (``DLROVER_TPU_<UPPER_NAME>``).  The master pushes a
subset to agents via ``ElasticRunConfig`` so a job-level override reaches every
node without per-node env plumbing.
"""

from __future__ import annotations

import os
import threading
from typing import Any

from dlrover_tpu.common.constants import Defaults


class Context:
    _instance = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self.master_port: int = 0
        self.node_heartbeat_interval: float = Defaults.HEARTBEAT_INTERVAL
        self.node_heartbeat_timeout: float = Defaults.HEARTBEAT_TIMEOUT
        self.rdzv_timeout: float = Defaults.RDZV_TIMEOUT
        # While waiting for a world, agents re-send their join (same
        # attempt id — a no-op on a healthy master) every this-many
        # seconds, so a restarted master that lost its rendezvous state
        # re-learns the membership instead of stalling the round forever.
        # Must exceed the master's lastcall waiting window (default 3s) or
        # re-joins would keep re-arming it.
        self.rdzv_rejoin_interval: float = 10.0
        self.pending_timeout: float = Defaults.PENDING_TIMEOUT
        self.monitor_interval: float = Defaults.MONITOR_INTERVAL
        self.scale_interval: float = Defaults.SCALE_INTERVAL
        self.relaunch_always: bool = False
        self.relaunch_on_worker_failure: int = 3
        self.network_check: bool = False
        self.straggler_threshold: float = 1.6  # x median elapsed => straggler
        self.hang_timeout_s: float = 1800.0
        self.train_speed_record_num: int = 50
        self.seconds_to_autoscale_worker: float = 1800.0
        self.ckpt_shard_io_workers: int = 4
        # Streamed-persist range workers (shm -> storage, per shard): 1 =
        # sequential single pass; N > 1 splits the shard into contiguous
        # tensor ranges written concurrently via pwrite into the
        # preallocated file (POSIX backends only — object stores fall
        # back to sequential).  Worth raising when storage bandwidth
        # exceeds a single core's CRC+write throughput.
        self.ckpt_persist_workers: int = 1
        # Zero-copy persist streams from the shm mapping holding the
        # per-rank fencing lock for the WHOLE persist (the trainer's next
        # save waits that long).  On slow/flaky storage where that hold
        # is worse than one extra state copy, set False to restore the
        # old bounded stall: copy under the lock, persist from the copy.
        self.ckpt_zero_copy: bool = True
        # Scale-out checkpoint (ISSUE 7).  Sliced persist: when a tensor
        # is replicated (or partially replicated) across dp replicas,
        # each owning rank streams only a disjoint, byte-balanced slice
        # of it, so aggregate save bandwidth scales with world size; the
        # commit protocol then requires the slice set to provably cover
        # every tensor (the reshard planner's tiling proof, reused).
        self.ckpt_sliced_persist: bool = True
        # Incremental saves: skip tensors whose per-tensor CRC fence has
        # not tripped since the last step this rank persisted, writing a
        # meta reference to the holder step's bytes instead (rotation
        # keeps referenced steps; fsck verifies the chain).
        self.ckpt_incremental: bool = True
        # Commit gate: refuse to advance the tracker when the present
        # shards' slices do not tile every tensor (a rank that died after
        # a partial slice write must never produce a "committed" step
        # that cannot be restored).
        self.ckpt_commit_coverage: bool = True
        self.auto_tune: bool = False
        # Cross-node in-memory checkpoint replicas (flash-ckpt replica.py
        # analogue); off by default — costs DCN bandwidth per save.
        self.ckpt_replica: bool = False
        # Live (restart-free) resharding on world change (ISSUE 6): a
        # resize is first announced as a reshard epoch so surviving
        # workers can move state mesh-to-mesh; any failure or deadline
        # lapse falls back to the checkpoint-restart ladder unchanged.
        self.live_reshard: bool = True
        # How long the master waits for every worker's ok before
        # declaring the live path failed and letting the restart ladder
        # run.  Bounded: live reshard may never make recovery slower
        # than the <90s restart path it replaces.
        self.reshard_deadline_s: float = 60.0
        # Worker-side throttle for the resize-epoch poll that rides the
        # step-report path.
        self.reshard_poll_interval: float = 2.0
        # Master HA (ISSUE 13).  ``ha_lease_s`` is the READER-side leader
        # lease: the warm standby declares the primary dead once the
        # journal/lease file stops changing for this long on the
        # standby's OWN clock (writer and reader wall clocks are never
        # compared — the PR-9 registry idiom).  ``ha_lease_interval_s``
        # is how often the primary's keeper bumps the lease file;
        # must be well under ha_lease_s.
        self.ha_lease_s: float = 4.0
        self.ha_lease_interval_s: float = 1.0
        # Standby journal-tail poll period.
        self.ha_tail_poll_s: float = 0.2
        # Snapshot + WAL compaction every this-many appended records.
        self.ha_snapshot_every: int = 1000
        # Throttle for journaling SpeedMonitor step baselines (each
        # report is a gauge; only a periodic baseline needs durability).
        self.ha_speed_journal_s: float = 15.0
        self._apply_env_overrides()

    def _apply_env_overrides(self) -> None:
        for name, cur in list(vars(self).items()):
            if name.startswith("_"):
                continue
            env = os.environ.get(f"DLROVER_TPU_{name.upper()}")
            if env is None:
                continue
            if isinstance(cur, bool):
                setattr(self, name, env.lower() in ("1", "true", "yes"))
            elif isinstance(cur, int):
                setattr(self, name, int(env))
            elif isinstance(cur, float):
                setattr(self, name, float(env))
            else:
                setattr(self, name, env)

    def update(self, **kwargs: Any) -> None:
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)

    def to_dict(self) -> dict:
        return {k: v for k, v in vars(self).items() if not k.startswith("_")}

    @classmethod
    def singleton_instance(cls) -> "Context":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


def get_context() -> Context:
    return Context.singleton_instance()
