"""Control-plane RPC: one gRPC route carrying typed msgpack messages.

Parity with the reference's channel layer (``dlrover/python/common/grpc.py:30``
``build_channel`` + the two-method ``elastic_training.proto`` service), built
on gRPC *generic handlers* so no protoc codegen step is needed: a single
``/dlrover_tpu.Master/call`` unary-unary method whose payload is a registered
``Message`` (see ``messages.py``).  The servicer dispatches on message type.

Retry policy mirrors reference ``retry_grpc_request`` (master_client.py:38):
exponential backoff, bounded attempts, for transient UNAVAILABLE during
master relaunches.
"""

from __future__ import annotations

import socket
import time
from concurrent import futures
from typing import Callable, Optional

import grpc

from dlrover_tpu.common.constants import GRPC
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.messages import (
    BaseResponse,
    Message,
    deserialize,
    serialize,
)

SERVICE_NAME = "dlrover_tpu.Master"
METHOD = f"/{SERVICE_NAME}/call"

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_MESSAGE_LENGTH),
    ("grpc.enable_retries", 1),
]


def find_free_port(host: str = "") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def addr_connectable(addr: str, timeout: float = 3.0) -> bool:
    """TCP-probe an ``host:port`` address, retrying until ``timeout``
    (reference ``elastic_run.py:277 _check_dlrover_master_available``,
    which polls for up to 300s).  A refused connection fails in
    microseconds, so a single attempt would make multi-node launches
    race the master's startup."""
    try:
        host, port_s = addr.rsplit(":", 1)
        port = int(port_s)
    except ValueError:
        return False
    deadline = time.time() + timeout
    while True:
        try:
            with socket.create_connection(
                (host, port), timeout=max(1.0, deadline - time.time())
            ):
                return True
        except OSError:
            if time.time() >= deadline:
                return False
            time.sleep(0.5)


def local_ip() -> str:
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


class RpcServer:
    """gRPC server with a single generic unary-unary dispatch method.

    ``handler(msg) -> Optional[Message]`` receives the deserialized request;
    a ``None`` return is sent as a success ``BaseResponse``.  Exceptions are
    caught and returned as failed ``BaseResponse`` (the control plane must
    never take down the master; reference servicer logs-and-continues).
    """

    def __init__(
        self,
        port: int,
        handler: Callable[[Message], Optional[Message]],
        max_workers: int = 64,
        host: str = "0.0.0.0",
    ):
        self._handler = handler
        self._port = port
        self._host = host
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="rpc"
            ),
            options=_CHANNEL_OPTIONS,
        )

        def _unary(request: bytes, context) -> bytes:
            try:
                msg = deserialize(request)
                resp = self._handler(msg)
                if resp is None:
                    resp = BaseResponse(success=True)
            except Exception as e:  # noqa: BLE001 - control plane stays up
                logger.exception("RPC handler error")
                resp = BaseResponse(success=False, reason=f"{type(e).__name__}: {e}")
            return serialize(resp)

        method_handler = grpc.unary_unary_rpc_method_handler(
            _unary,
            request_deserializer=None,  # raw bytes
            response_serializer=None,
        )
        generic = grpc.method_handlers_generic_handler(
            SERVICE_NAME, {"call": method_handler}
        )
        self._server.add_generic_rpc_handlers((generic,))
        self._bound_port = self._server.add_insecure_port(f"{host}:{port}")

    @property
    def port(self) -> int:
        return self._bound_port

    def start(self) -> None:
        self._server.start()
        logger.info("RPC server listening on %s:%s", self._host, self._bound_port)

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)


class RpcClient:
    """Client side of the single-route control plane with bounded retry.

    Reference: ``MasterClient`` channel handling + ``retry_grpc_request``
    (``elastic_agent/master_client.py:38-60``).
    """

    def __init__(self, addr: str, timeout: float = 30.0):
        self._addr = addr
        self._timeout = timeout
        self._channel = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
        self._call = self._channel.unary_unary(
            METHOD, request_serializer=None, response_deserializer=None
        )

    @property
    def addr(self) -> str:
        return self._addr

    def call(
        self,
        msg: Message,
        timeout: Optional[float] = None,
        retries: int = 5,
        backoff: float = 0.5,
    ) -> Message:
        last_err: Optional[Exception] = None
        for attempt in range(retries):
            try:
                data = self._call(
                    serialize(msg), timeout=timeout or self._timeout
                )
                return deserialize(data)
            except grpc.RpcError as e:
                last_err = e
                code = e.code() if hasattr(e, "code") else None
                # Only UNAVAILABLE (connection-level, request not executed)
                # is retried.  DEADLINE_EXCEEDED may mean the master already
                # executed the request — re-sending would double-execute
                # non-idempotent ops (kv add, task fetch, rendezvous join).
                if code == grpc.StatusCode.UNAVAILABLE:
                    if attempt + 1 >= retries:
                        break
                    sleep = min(backoff * (2**attempt), 8.0)
                    logger.warning(
                        "RPC %s to %s failed (%s), retry %d/%d in %.1fs",
                        type(msg).__name__,
                        self._addr,
                        code,
                        attempt + 1,
                        retries,
                        sleep,
                    )
                    time.sleep(sleep)
                    continue
                raise
        assert last_err is not None
        raise last_err

    def close(self) -> None:
        self._channel.close()
