"""Control-plane RPC: one gRPC route carrying typed msgpack messages.

Parity with the reference's channel layer (``dlrover/python/common/grpc.py:30``
``build_channel`` + the two-method ``elastic_training.proto`` service), built
on gRPC *generic handlers* so no protoc codegen step is needed: a single
``/dlrover_tpu.Master/call`` unary-unary method whose payload is a registered
``Message`` (see ``messages.py``).  The servicer dispatches on message type.

Retry policy mirrors reference ``retry_grpc_request`` (master_client.py:38):
jittered exponential backoff under a total deadline budget, bounded
attempts, for transient UNAVAILABLE during master relaunches.  Calls the
caller marks ``idempotent`` (pure reads, or writes carrying an idempotency
token the master dedupes on) additionally retry DEADLINE_EXCEEDED.
"""

from __future__ import annotations

import itertools
import random
import socket
import threading
import time
from concurrent import futures
from typing import Callable, Optional

import grpc

from dlrover_tpu import chaos
from dlrover_tpu.common.constants import GRPC
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.messages import (
    BaseResponse,
    Message,
    deserialize,
    serialize,
)

SERVICE_NAME = "dlrover_tpu.Master"
METHOD = f"/{SERVICE_NAME}/call"

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_MESSAGE_LENGTH),
    ("grpc.enable_retries", 1),
]


def find_free_port(host: str = "") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def addr_connectable(addr: str, timeout: float = 3.0) -> bool:
    """TCP-probe an ``host:port`` address, retrying until ``timeout``
    (reference ``elastic_run.py:277 _check_dlrover_master_available``,
    which polls for up to 300s).  A refused connection fails in
    microseconds, so a single attempt would make multi-node launches
    race the master's startup."""
    try:
        host, port_s = addr.rsplit(":", 1)
        port = int(port_s)
    except ValueError:
        return False
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        try:
            # Clamp the per-attempt connect timeout to the remaining
            # budget: a blackholed host must not overshoot the deadline,
            # but a reachable-yet-slow one may use the whole budget.
            with socket.create_connection(
                (host, port), timeout=max(0.1, remaining)
            ):
                return True
        except OSError:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            time.sleep(min(0.5, remaining))


def local_ip() -> str:
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


class RpcServer:
    """gRPC server with a single generic unary-unary dispatch method.

    ``handler(msg) -> Optional[Message]`` receives the deserialized request;
    a ``None`` return is sent as a success ``BaseResponse``.  Exceptions are
    caught and returned as failed ``BaseResponse`` (the control plane must
    never take down the master; reference servicer logs-and-continues).
    """

    def __init__(
        self,
        port: int,
        handler: Callable[[Message], Optional[Message]],
        max_workers: int = 64,
        host: str = "0.0.0.0",
    ):
        self._handler = handler
        self._port = port
        self._host = host
        #: Requests served (monotone; itertools.count is GIL-atomic).
        #: The load-bench calibration divides a process's measured CPU
        #: by this to get real per-message admission cost.
        self._calls = itertools.count()
        self._calls_now = 0
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="rpc"
            ),
            options=_CHANNEL_OPTIONS,
        )

        def _unary(request: bytes, context) -> bytes:
            self._calls_now = next(self._calls) + 1
            try:
                msg = deserialize(request)
            except Exception as e:  # noqa: BLE001 - control plane stays up
                logger.exception("RPC deserialize error")
                return serialize(
                    BaseResponse(
                        success=False, reason=f"{type(e).__name__}: {e}"
                    )
                )
            if chaos.inject("rpc.drop", method=type(msg).__name__) is not None:
                # Simulate the request evaporating mid-flight: the client
                # sees UNAVAILABLE and the handler never ran.
                context.abort(
                    grpc.StatusCode.UNAVAILABLE, "chaos: rpc.drop"
                )
            try:
                resp = self._handler(msg)
                if resp is None:
                    resp = BaseResponse(success=True)
            except Exception as e:  # noqa: BLE001 - control plane stays up
                logger.exception("RPC handler error")
                resp = BaseResponse(success=False, reason=f"{type(e).__name__}: {e}")
            return serialize(resp)

        method_handler = grpc.unary_unary_rpc_method_handler(
            _unary,
            request_deserializer=None,  # raw bytes
            response_serializer=None,
        )
        generic = grpc.method_handlers_generic_handler(
            SERVICE_NAME, {"call": method_handler}
        )
        self._server.add_generic_rpc_handlers((generic,))
        self._bound_port = self._server.add_insecure_port(f"{host}:{port}")

    @property
    def port(self) -> int:
        return self._bound_port

    @property
    def calls(self) -> int:
        """Requests served so far (including failed dispatches)."""
        return self._calls_now

    def start(self) -> None:
        self._server.start()
        logger.info("RPC server listening on %s:%s", self._host, self._bound_port)

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)


class ChaosRpcError(grpc.RpcError):
    """A synthetic gRPC error raised by chaos injection (client side), so
    the retry loop exercises exactly the code path a real flap would."""

    def __init__(self, code: grpc.StatusCode, details: str = "chaos"):
        super().__init__()
        self._code = code
        self._details = details

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._details

    def __str__(self) -> str:
        return f"ChaosRpcError({self._code}, {self._details!r})"


class RpcClient:
    """Client side of the single-route control plane with bounded retry.

    Reference: ``MasterClient`` channel handling + ``retry_grpc_request``
    (``elastic_agent/master_client.py:38-60``).
    """

    #: Default total retry budget per call, seconds.  Attempts stop once
    #: the budget is spent even if ``retries`` remain — many agents
    #: hammering a restarting master must converge, not queue forever.
    DEFAULT_DEADLINE = 60.0

    def __init__(self, addr: str, timeout: float = 30.0,
                 addr_provider: Optional[Callable[[], str]] = None):
        self._addr = addr
        self._timeout = timeout
        # Optional re-resolve hook (ISSUE 13): consulted on every channel
        # rebuild.  Returning a different address re-homes the client —
        # the master-failover path (a warm standby published its address
        # after takeover, so retries land on the new leader instead of
        # hammering the dead one).
        self._addr_provider = addr_provider
        self._reconnect_mu = threading.Lock()
        self._connect()

    def _connect(self) -> None:
        self._channel = grpc.insecure_channel(
            self._addr, options=_CHANNEL_OPTIONS
        )
        self._call = self._channel.unary_unary(
            METHOD, request_serializer=None, response_deserializer=None
        )
        self._connected_at = time.monotonic()

    def reconnect(self, force: bool = False) -> None:
        """Tear down and rebuild the channel.  A subchannel that rode out a
        master outage can stay wedged in TRANSIENT_FAILURE (its reconnect
        backoff grows toward minutes) even after a replacement master is
        listening on the same port; ``call`` invokes this automatically
        after repeated UNAVAILABLE attempts.  Rate-limited (unless
        ``force``) so many concurrently-failing threads share one rebuild;
        in-flight calls on the old channel fail with an RpcError they were
        already handling."""
        with self._reconnect_mu:
            if not force and time.monotonic() - self._connected_at < 2.0:
                return  # another caller just rebuilt it
            if self._addr_provider is not None:
                try:
                    fresh = self._addr_provider()
                except Exception as e:  # noqa: BLE001 - resolve is best-effort
                    logger.warning("RPC addr re-resolve failed: %s", e)
                    fresh = ""
                if fresh and fresh != self._addr:
                    logger.warning(
                        "RPC client re-homing %s -> %s", self._addr, fresh
                    )
                    self._addr = fresh
            old = self._channel
            self._connect()
            # Retire the old channel instead of closing it immediately:
            # a concurrent thread may have a healthy in-flight RPC on it,
            # and an instant close would fail that call with CANCELLED
            # (not retriable).  It is closed on the NEXT reconnect — a
            # full rebuild cycle of grace — or at client close().
            prev, self._retired_channel = (
                getattr(self, "_retired_channel", None), old
            )
            if prev is not None:
                try:
                    prev.close()
                except Exception as e:  # noqa: BLE001
                    logger.debug("retired channel close failed: %s", e)
            logger.info("RPC channel to %s rebuilt", self._addr)

    @property
    def addr(self) -> str:
        return self._addr

    def call(
        self,
        msg: Message,
        timeout: Optional[float] = None,
        retries: int = 5,
        backoff: float = 0.5,
        deadline: Optional[float] = None,
        idempotent: bool = False,
    ) -> Message:
        """Send ``msg`` with bounded, jittered-exponential retry.

        Only UNAVAILABLE (connection-level, request not executed) is
        retried unconditionally.  DEADLINE_EXCEEDED may mean the master
        already executed the request, so it is retried only for
        ``idempotent`` calls: pure reads, or writes that carry an
        idempotency token the master dedupes on (kv add, task fetch,
        rendezvous join).  ``deadline`` is the total wall-clock budget for
        all attempts and backoff sleeps combined.
        """
        # An explicitly configured per-call/per-client timeout is never
        # silently shortened: the default budget stretches to cover it.
        budget = (
            max(self.DEFAULT_DEADLINE, timeout or self._timeout)
            if deadline is None
            else deadline
        )
        start = time.monotonic()
        last_err: Optional[Exception] = None
        name = type(msg).__name__
        for attempt in range(retries):
            try:
                chaos.inject("rpc.latency", method=name)
                if chaos.inject("rpc.unavailable", method=name) is not None:
                    raise ChaosRpcError(
                        grpc.StatusCode.UNAVAILABLE, "chaos: rpc.unavailable"
                    )
                remaining = budget - (time.monotonic() - start)
                if remaining <= 0:
                    break
                data = self._call(
                    serialize(msg),
                    timeout=min(timeout or self._timeout, remaining),
                )
                gray = chaos.inject("net.gray", method=name)
                if gray is not None:
                    # Gray network: the call SUCCEEDED but the reply
                    # comes back late, and the request hits the wire a
                    # second time (a spurious retransmit the server
                    # executes again) — the receiver's dedupe, not the
                    # retry machinery, is what must absorb it.
                    if gray.delay > 0:
                        time.sleep(gray.delay)
                    try:
                        self._call(
                            serialize(msg),
                            timeout=timeout or self._timeout,
                        )
                    except grpc.RpcError:
                        pass  # the duplicate may lose the race; fine
                return deserialize(data)
            except grpc.RpcError as e:
                last_err = e
                code = e.code() if hasattr(e, "code") else None
                retriable = code == grpc.StatusCode.UNAVAILABLE or (
                    idempotent and code == grpc.StatusCode.DEADLINE_EXCEEDED
                )
                if not retriable:
                    raise
                if attempt + 1 >= retries:
                    break
                # Half-jittered exponential backoff: a fleet of agents
                # whose master just came back must not stampede it in
                # lockstep (the fixed backoff*2**attempt schedule did).
                base = min(backoff * (2**attempt), 8.0)
                sleep = random.uniform(0.5 * base, base)
                remaining = budget - (time.monotonic() - start)
                if remaining <= sleep:
                    break  # the budget is spent; re-raise below
                logger.warning(
                    "RPC %s to %s failed (%s), retry %d/%d in %.1fs",
                    name, self._addr, code, attempt + 1, retries, sleep,
                )
                time.sleep(sleep)
                if code == grpc.StatusCode.UNAVAILABLE and attempt >= 1:
                    # Two strikes: the outage may be a restarted master
                    # this channel refuses to re-dial; rebuild it.
                    self.reconnect()
        if last_err is None:
            raise TimeoutError(
                f"RPC {name} to {self._addr}: deadline budget "
                f"{budget:.1f}s spent before the first attempt"
            )
        raise last_err

    def close(self) -> None:
        retired = getattr(self, "_retired_channel", None)
        if retired is not None:
            try:
                retired.close()
            except Exception as e:  # noqa: BLE001
                logger.debug("retired channel close failed: %s", e)
        self._channel.close()
