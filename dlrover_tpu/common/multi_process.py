"""Cross-process primitives served over unix-domain sockets.

Parity with reference ``dlrover/python/common/multi_process.py``
(``_create_socket_server :59``, ``SharedLock :246``, ``SharedQueue :375``,
``SharedDict :489``): the *agent* process hosts one socket server per named
primitive; worker processes on the same host connect as clients.  Used for

- ``SharedLock``  — fencing shm arena writes against the async saver,
- ``SharedQueue`` — worker -> agent checkpoint save events,
- ``SharedDict``  — small shared metadata (e.g. ckpt step -> path).

Framing: 4-byte big-endian length + msgpack ``[op, args...]`` request and
``[ok, value]`` response.  Connections are per-call: simple, reconnect-free
across worker restarts (the common case in elastic training).
"""

from __future__ import annotations

import collections
import os
import socket
import socketserver
import threading
import time
from typing import Any, Dict, Optional

import msgpack

from dlrover_tpu.common.log import logger

_SOCK_DIR = os.environ.get("DLROVER_TPU_SOCK_DIR", "/tmp/dlrover_tpu_sock")


def _proc_start_time(pid: int) -> Optional[int]:
    """Process start time in clock ticks (/proc/<pid>/stat field 22) — the
    (pid, starttime) pair uniquely identifies a process across PID reuse."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("utf-8", "replace")
        # Field 2 (comm) may contain spaces/parens; fields after the last
        # ')' are well-formed.
        rest = stat.rsplit(")", 1)[1].split()
        return int(rest[19])  # field 22 overall
    except (OSError, IndexError, ValueError):
        return None


def socket_path(kind: str, name: str) -> str:
    os.makedirs(_SOCK_DIR, exist_ok=True)
    path = os.path.join(_SOCK_DIR, f"{kind}_{name}.sock")
    if len(path) >= 100:  # AF_UNIX sun_path limit is 108
        import hashlib

        digest = hashlib.md5(name.encode()).hexdigest()[:16]
        path = os.path.join(_SOCK_DIR, f"{kind}_{digest}.sock")
    return path


def _send_msg(sock: socket.socket, obj: Any) -> None:
    data = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(len(data).to_bytes(4, "big") + data)


def _recv_msg(sock: socket.socket) -> Any:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            raise ConnectionError("socket closed")
        head += chunk
    n = int.from_bytes(head, "big")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 16, n - len(buf)))
        if not chunk:
            raise ConnectionError("socket closed")
        buf += chunk
    return msgpack.unpackb(bytes(buf), raw=False, strict_map_key=False)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = _recv_msg(self.request)
            op, args = req[0], req[1:]
            fn = getattr(self.server.owner, f"op_{op}", None)
            if fn is None:
                _send_msg(self.request, [False, f"unknown op {op}"])
                return
            _send_msg(self.request, [True, fn(*args)])
        except (ConnectionError, OSError):
            pass
        except Exception as e:  # noqa: BLE001
            try:
                _send_msg(self.request, [False, f"{type(e).__name__}: {e}"])
            except OSError:
                pass


class _ThreadedUnixServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class LocalSocketServer:
    """Base for the server side of a named primitive (reference
    ``multi_process.py LocalSocketComm`` server role)."""

    KIND = "base"

    def __init__(self, name: str):
        self.name = name
        self.path = socket_path(self.KIND, name)
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._server = _ThreadedUnixServer(self.path, _Handler)
        self._server.owner = self
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"{self.KIND}-{name}",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
            if os.path.exists(self.path):
                os.unlink(self.path)
        except OSError:
            pass


class _Client:
    def __init__(self, kind: str, name: str):
        self._path = socket_path(kind, name)

    # Extra slack past the server-side op timeout: for ops the server may
    # WAIT on (lock acquire, queue get) its wait is bounded by the op
    # timeout it receives, so with this margin it always answers before
    # the client socket deadline — a reply is only lost on a real crash,
    # never on a close race.  Ops the server answers immediately (dict
    # get/set) pass a small ``reply_margin`` instead: against a hung
    # server whose kernel backlog still accepts connects, the margin IS
    # the caller's real latency bound past its timeout, and 30s there
    # defeats the short budgets the save path and scrape handler rely on.
    _REPLY_MARGIN = 30.0

    def request(
        self, op: str, *args: Any, timeout: float = 60.0,
        reply_margin: Optional[float] = None,
    ) -> Any:
        margin = self._REPLY_MARGIN if reply_margin is None else reply_margin
        deadline = time.monotonic() + timeout
        last: Optional[Exception] = None
        while True:
            sent = False
            try:
                with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                    s.settimeout(
                        max(0.1, deadline - time.monotonic()) + margin
                    )
                    s.connect(self._path)
                    _send_msg(s, [op, *args])
                    sent = True
                    ok, val = _recv_msg(s)
                    if not ok:
                        raise RuntimeError(val)
                    return val
            except (ConnectionError, FileNotFoundError, socket.timeout, OSError) as e:
                if sent:
                    # The op may have executed server-side (e.g. a queue item
                    # popped).  Re-sending could double-execute; surface the
                    # failure instead of guessing.
                    raise ConnectionError(
                        f"request {op} to {self._path} failed after send: {e}"
                    ) from e
                last = e
                if time.time() >= deadline:
                    break
                time.sleep(0.1)
        raise TimeoutError(f"request {op} to {self._path} failed: {last}")


# ---------------------------------------------------------------------------
# SharedLock
# ---------------------------------------------------------------------------


class SharedLockServer(LocalSocketServer):
    KIND = "lock"

    def __init__(self, name: str):
        self._owner: Optional[str] = None
        self._cond = threading.Condition()
        super().__init__(name)

    @staticmethod
    def _holder_alive(holder: Optional[str]) -> bool:
        # Holders are "pid-<pid>-<starttime>" on this host; a holder whose
        # process died (e.g. a worker SIGKILLed mid-checkpoint) must not
        # wedge the lock.  The start time guards against PID reuse: a
        # recycled pid has a different /proc start time.
        if not holder or not holder.startswith("pid-"):
            return True
        parts = holder.split("-")
        try:
            pid = int(parts[1])
            os.kill(pid, 0)
        except (ProcessLookupError, ValueError, IndexError):
            return False
        except PermissionError:
            return True
        if len(parts) >= 3:
            start = _proc_start_time(pid)
            if start is not None and str(start) != parts[2]:
                return False  # pid was recycled
        return True

    def op_acquire(self, holder: str, blocking: bool, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._owner is not None and self._owner != holder:
                if not self._holder_alive(self._owner):
                    logger.warning(
                        "lock %s: stealing from dead holder %s",
                        self.name, self._owner,
                    )
                    self._owner = None
                    break
                if not blocking:
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 1.0))
            self._owner = holder
            return True

    def op_release(self, holder: str) -> bool:
        with self._cond:
            if self._owner == holder:
                self._owner = None
                self._cond.notify_all()
                return True
            return False

    def op_locked(self) -> bool:
        with self._cond:
            return self._owner is not None


class SharedLock:
    """Client handle; ``holder`` defaults to pid so re-acquire by the same
    process is idempotent (fencing semantics of reference ``SharedLock:246``).
    """

    def __init__(self, name: str, create: bool = False):
        self.name = name
        self._server = SharedLockServer(name) if create else None
        self._client = _Client(SharedLockServer.KIND, name)
        start = _proc_start_time(os.getpid())
        self._holder = f"pid-{os.getpid()}-{start if start is not None else 0}"

    def acquire(self, blocking: bool = True, timeout: float = 60.0) -> bool:
        return bool(
            self._client.request(
                "acquire", self._holder, blocking, timeout, timeout=timeout + 5
            )
        )

    def release(self) -> bool:
        return bool(self._client.request("release", self._holder))

    def locked(self) -> bool:
        return bool(self._client.request("locked"))

    def __enter__(self):
        # A fencing lock that silently proceeds unfenced would let a worker
        # write the shm arena concurrently with the saver's read.
        if not self.acquire():
            raise TimeoutError(f"could not acquire shared lock {self.name}")
        return self

    def __exit__(self, *exc):
        self.release()

    def close(self) -> None:
        if self._server:
            self._server.close()


# ---------------------------------------------------------------------------
# SharedQueue
# ---------------------------------------------------------------------------


class SharedQueueServer(LocalSocketServer):
    KIND = "queue"

    def __init__(self, name: str, maxsize: int = 0):
        self._q: collections.deque = collections.deque()
        self._maxsize = maxsize
        self._cond = threading.Condition()
        super().__init__(name)

    def op_put(self, item: Any, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._maxsize and len(self._q) >= self._maxsize:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 1.0))
            self._q.append(item)
            self._cond.notify_all()
            return True

    def op_get(self, timeout: float) -> list:
        deadline = time.monotonic() + timeout
        with self._cond:
            while not self._q:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [False, None]
                self._cond.wait(min(remaining, 1.0))
            item = self._q.popleft()
            self._cond.notify_all()
            return [True, item]

    def op_qsize(self) -> int:
        with self._cond:
            return len(self._q)

    def op_clear(self) -> bool:
        with self._cond:
            self._q.clear()
            return True


class SharedQueue:
    def __init__(self, name: str, create: bool = False, maxsize: int = 0):
        self.name = name
        self._server = SharedQueueServer(name, maxsize) if create else None
        self._client = _Client(SharedQueueServer.KIND, name)

    def put(self, item: Any, timeout: float = 60.0) -> bool:
        return bool(self._client.request("put", item, timeout, timeout=timeout + 5))

    def get(self, timeout: float = 60.0) -> Any:
        ok, item = self._client.request("get", timeout, timeout=timeout + 5)
        if not ok:
            raise TimeoutError(f"queue {self.name} get timed out")
        return item

    def get_nowait(self) -> Any:
        ok, item = self._client.request("get", 0.0)
        if not ok:
            raise TimeoutError(f"queue {self.name} empty")
        return item

    def qsize(self) -> int:
        return int(self._client.request("qsize"))

    def empty(self) -> bool:
        return self.qsize() == 0

    def clear(self) -> None:
        self._client.request("clear")

    def close(self) -> None:
        if self._server:
            self._server.close()


# ---------------------------------------------------------------------------
# SharedDict
# ---------------------------------------------------------------------------


class SharedDictServer(LocalSocketServer):
    KIND = "dict"

    def __init__(self, name: str):
        self._d: Dict[str, Any] = {}
        self._lock = threading.Lock()
        super().__init__(name)

    def op_set(self, key: str, value: Any) -> bool:
        with self._lock:
            self._d[key] = value
            return True

    def op_get(self, key: str) -> list:
        with self._lock:
            if key in self._d:
                return [True, self._d[key]]
            return [False, None]

    def op_update(self, other: dict) -> bool:
        with self._lock:
            self._d.update(other)
            return True

    def op_dict(self) -> dict:
        with self._lock:
            return dict(self._d)

    def op_delete(self, key: str) -> bool:
        with self._lock:
            return self._d.pop(key, None) is not None


class SharedDict:
    # Dict ops are answered immediately (no server-side wait), so the
    # reply margin only needs to cover serialization/scheduling latency —
    # a hung-but-accepting server then costs callers timeout+2s, not
    # timeout+30s (the save path and metrics scrape pass timeout=2.0 and
    # rely on that bound actually holding).
    _REPLY_MARGIN = 2.0

    def __init__(self, name: str, create: bool = False):
        self.name = name
        self._server = SharedDictServer(name) if create else None
        self._client = _Client(SharedDictServer.KIND, name)

    def set(self, key: str, value: Any, timeout: float = 60.0) -> None:
        self._client.request("set", key, value, timeout=timeout,
                             reply_margin=self._REPLY_MARGIN)

    def get(self, key: str, default: Any = None,
            timeout: float = 60.0) -> Any:
        ok, val = self._client.request("get", key, timeout=timeout,
                                       reply_margin=self._REPLY_MARGIN)
        return val if ok else default

    def update(self, other: dict, timeout: float = 60.0) -> None:
        self._client.request("update", other, timeout=timeout,
                             reply_margin=self._REPLY_MARGIN)

    def to_dict(self, timeout: float = 60.0) -> dict:
        return self._client.request("dict", timeout=timeout,
                                    reply_margin=self._REPLY_MARGIN)

    def delete(self, key: str) -> None:
        self._client.request("delete", key,
                             reply_margin=self._REPLY_MARGIN)

    def close(self) -> None:
        if self._server:
            self._server.close()
