"""Loader for the C++ native runtime pieces (built from ``native/``).

Auto-builds ``libshm_arena.so`` with ``make`` on first use (cached); every
consumer has a pure-Python fallback so the framework degrades gracefully on
hosts without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from dlrover_tpu.common.log import logger

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LOCK = threading.Lock()
_LIBS: dict = {}


def _build(lib: str) -> Optional[str]:
    path = os.path.abspath(os.path.join(_NATIVE_DIR, lib))
    if os.path.exists(path):
        return path
    try:
        subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR), lib],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return path if os.path.exists(path) else None
    except (subprocess.SubprocessError, OSError) as e:
        logger.warning("native build of %s failed: %s", lib, e)
        return None


def load_library(lib: str) -> Optional[ctypes.CDLL]:
    with _LOCK:
        if lib in _LIBS:
            return _LIBS[lib]
        path = _build(lib)
        handle = None
        if path:
            try:
                handle = ctypes.CDLL(path)
            except OSError as e:
                logger.warning("loading %s failed: %s", path, e)
        _LIBS[lib] = handle
        return handle


def shm_lib() -> Optional[ctypes.CDLL]:
    lib = load_library("libshm_arena.so")
    if lib is not None and not getattr(lib, "_sigs_set", False):
        lib.shm_arena_create.restype = ctypes.c_int
        lib.shm_arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shm_arena_open.restype = ctypes.c_int
        lib.shm_arena_open.argtypes = [ctypes.c_char_p]
        lib.shm_arena_size.restype = ctypes.c_int64
        lib.shm_arena_size.argtypes = [ctypes.c_int]
        lib.shm_arena_map.restype = ctypes.c_void_p
        lib.shm_arena_map.argtypes = [ctypes.c_int, ctypes.c_uint64]
        lib.shm_arena_unmap.restype = ctypes.c_int
        lib.shm_arena_unmap.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shm_arena_unlink.restype = ctypes.c_int
        lib.shm_arena_unlink.argtypes = [ctypes.c_char_p]
        lib.shm_arena_close.restype = ctypes.c_int
        lib.shm_arena_close.argtypes = [ctypes.c_int]
        lib.shm_parallel_memcpy.restype = None
        lib.shm_parallel_memcpy.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_int,
        ]
        lib.shm_crc32.restype = ctypes.c_uint32
        lib.shm_crc32.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32]
        lib._sigs_set = True
    return lib


def packer_lib() -> Optional[ctypes.CDLL]:
    """Native first-fit sequence packer (``native/packer.cc``)."""
    lib = load_library("libpacker.so")
    if lib is not None and not getattr(lib, "_sigs_set", False):
        import numpy as np

        i64 = ctypes.c_int64
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.pack_first_fit.restype = i64
        lib.pack_first_fit.argtypes = [i64p, i64, i64, i32p, i32p, i32p]
        lib._sigs_set = True
    return lib
