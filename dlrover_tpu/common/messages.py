"""Typed control-plane messages (agent <-> master).

Capability parity with the ~60 message dataclasses of the reference
(``dlrover/python/common/grpc.py:161-512``), but serialized as **msgpack of a
typed registry** rather than pickle-over-gRPC (a reference wart — pickle is
version-fragile and unsafe across trust boundaries).  Only control-plane data
travels here: shard indices, rendezvous worlds, heartbeats, metrics.  Tensors
never do — they ride the shm arena (``dlrover_tpu.common.shm``) or ICI.

Every message is a dataclass registered by class name via
``__init_subclass__``; nested messages / lists / dicts of messages round-trip.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import msgpack

_REGISTRY: Dict[str, type] = {}

#: Per-class field-name tuples, filled lazily on first encode.  Lazy
#: because ``__init_subclass__`` runs BEFORE the ``@dataclass``
#: decorator processes the class body, so fields aren't knowable at
#: registration time.
_FIELD_CACHE: Dict[type, tuple] = {}


class Message:
    """Base for all wire messages.  Subclasses must be dataclasses.

    ``_WIRE_OPTIONAL`` names fields that are OMITTED from the encoded
    form while empty/falsy (decode fills them from the dataclass
    default).  This is how a message grows a field — the observability
    trace context (ISSUE 12) — without changing the bytes of messages
    that don't carry it: the serving fast path stays byte-identical,
    and mixed-version peers interoperate (a missing key decodes to the
    default)."""

    _WIRE_OPTIONAL: frozenset = frozenset()

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        _REGISTRY[cls.__name__] = cls


def _fields_of(cls: type) -> tuple:
    """(field names, wire-optional names) for ``cls``, cached."""
    entry = _FIELD_CACHE.get(cls)
    if entry is None:
        names = tuple(
            f.name for f in dataclasses.fields(cls)  # type: ignore[arg-type]
        )
        entry = (names, cls._WIRE_OPTIONAL)
        _FIELD_CACHE[cls] = entry
    return entry


# The encode/decode pair below is the serving tier's admission hot
# path (every submit/grant/poll crosses it; ISSUE 9's load-harness
# profile named it).  Two fast paths keep it cheap without losing
# generality:
#
# - per-class field names come from ``_FIELD_CACHE`` instead of a
#   ``dataclasses.fields`` reflection walk per message;
# - scalar containers pass through UNTOUCHED: a prompt of 200 ints (or
#   a stats dict of floats) needs no per-element _encode call and no
#   copied list — msgpack packs the original directly.  Only containers
#   actually holding a Message / dict / list keep the recursive walk.
#
# ``serialize_baseline`` keeps the original reflection-everywhere
# implementation alive as the load bench's measured reference point.

_RECURSE = (Message, dict, list, tuple)


def _encode(obj: Any) -> Any:
    if isinstance(obj, Message):
        cls = type(obj)
        out = {}
        names, optional = _fields_of(cls)
        for name in names:
            v = getattr(obj, name)
            if not v and name in optional:
                continue  # wire-optional and empty: omit (byte compat)
            out[name] = _encode(v) if isinstance(v, _RECURSE) else v
        return {"__msg__": cls.__name__, "f": out}
    if isinstance(obj, dict):
        for v in obj.values():
            if isinstance(v, _RECURSE):
                return {k: _encode(v) for k, v in obj.items()}
        return obj
    if isinstance(obj, (list, tuple)):
        for v in obj:
            if isinstance(v, _RECURSE):
                return [_encode(v) for v in obj]
        return obj if isinstance(obj, list) else list(obj)
    return obj


def _decode(obj: Any) -> Any:
    if isinstance(obj, dict):
        if "__msg__" in obj:
            cls = _REGISTRY[obj["__msg__"]]
            fields = {
                k: _decode(v) if isinstance(v, (dict, list)) else v
                for k, v in obj["f"].items()
            }
            return cls(**fields)
        for v in obj.values():
            if isinstance(v, (dict, list)):
                return {k: _decode(v) for k, v in obj.items()}
        return obj
    if isinstance(obj, list):
        for v in obj:
            if isinstance(v, (dict, list)):
                return [_decode(v) for v in obj]
        return obj
    return obj


def serialize(msg: Message) -> bytes:
    return msgpack.packb(_encode(msg), use_bin_type=True)


def deserialize(data: bytes) -> Message:
    return _decode(msgpack.unpackb(data, raw=False, strict_map_key=False))


def _encode_generic(obj: Any) -> Any:
    """The pre-fast-path encoder (reflection + per-element recursion
    everywhere) — kept as the measured baseline for ``bench.py
    --load_bench``'s serialization profile; not used on any wire path."""
    if isinstance(obj, Message):
        optional = type(obj)._WIRE_OPTIONAL
        return {
            "__msg__": type(obj).__name__,
            "f": {
                f.name: _encode_generic(getattr(obj, f.name))
                for f in dataclasses.fields(obj)  # type: ignore[arg-type]
                if getattr(obj, f.name) or f.name not in optional
            },
        }
    if isinstance(obj, dict):
        return {k: _encode_generic(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_encode_generic(v) for v in obj]
    return obj


def serialize_baseline(msg: Message) -> bytes:
    """Byte-identical to :func:`serialize`, via the slow generic walk."""
    return msgpack.packb(_encode_generic(msg), use_bin_type=True)


# ---------------------------------------------------------------------------
# Generic envelope / responses
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BaseResponse(Message):
    success: bool = True
    reason: str = ""


@dataclasses.dataclass
class Empty(Message):
    """No-op probe: deliberately handler-less — tests ping servicers
    with it to exercise the unhandled-message path."""

    pass


# ---------------------------------------------------------------------------
# Node identity & lifecycle
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class NodeMeta(Message):
    """Agent self-registration (reference ``grpc.py NodeMeta``)."""

    node_type: str = "worker"
    node_id: int = 0
    node_rank: int = -1
    host: str = ""
    agent_port: int = 0
    slice_id: str = ""
    host_id: str = ""
    tpu_chips: int = 0
    local_world_size: int = 1


@dataclasses.dataclass
class ReportNodeStatus(Message):
    node_id: int = 0
    node_type: str = "worker"
    status: str = ""
    exit_reason: str = ""
    restart_count: int = 0


@dataclasses.dataclass
class NodeFailure(Message):
    """Agent-reported worker failure (reference ``grpc.py NodeFailure`` /
    ``report_failures master_client.py``)."""

    node_id: int = 0
    node_rank: int = -1
    error_data: str = ""
    level: str = "error"
    restart_count: int = 0


@dataclasses.dataclass
class Heartbeat(Message):
    node_id: int = 0
    timestamp: float = 0.0


@dataclasses.dataclass
class DiagnosisAction(Message):
    """Master's instruction piggybacked on the heartbeat reply (reference
    ``HeartbeatResponse`` carrying ``DiagnosisAction`` s)."""

    action_type: str = "no_action"
    instance: str = ""
    reason: str = ""
    payload: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class HeartbeatResponse(Message):
    actions: List[DiagnosisAction] = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------------------
# Rendezvous
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class JoinRendezvous(Message):
    """(reference ``grpc.py JoinRendezvousRequest``)"""

    node_id: int = 0
    node_rank: int = -1
    local_world_size: int = 1
    rdzv_name: str = "elastic-training"
    node_ip: str = ""
    slice_id: str = ""
    # Unique per join *attempt*: lets the master tell an RPC-retried
    # duplicate (same id -> no-op) from a genuine re-join after restart
    # (new id -> evict the stale world membership).
    attempt_id: str = ""


@dataclasses.dataclass
class RendezvousRound(Message):
    round: int = 0


@dataclasses.dataclass
class CommWorldRequest(Message):
    node_id: int = 0
    rdzv_name: str = "elastic-training"


@dataclasses.dataclass
class CommWorld(Message):
    """The agreed world of one rendezvous round: ``world`` maps node_rank ->
    meta dict (id, local_world_size, host, slice).  ``group`` distinguishes
    paired sub-worlds in the network-check rendezvous
    (reference ``grpc.py CommWorldResponse`` / ``rdzv_manager.py:335``)."""

    rdzv_name: str = "elastic-training"
    round: int = 0
    group: int = 0
    world: dict = dataclasses.field(default_factory=dict)
    coordinator: str = ""  # host:port of the elected JAX coordinator


@dataclasses.dataclass
class WaitingNodeNumRequest(Message):
    rdzv_name: str = "elastic-training"


@dataclasses.dataclass
class WaitingNodeNum(Message):
    waiting_num: int = 0


# ---------------------------------------------------------------------------
# Master-hosted KV store (bootstrap plane, reference master_kv_store.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class KVStoreSet(Message):
    key: str = ""
    value: bytes = b""


@dataclasses.dataclass
class KVStoreGet(Message):
    key: str = ""


@dataclasses.dataclass
class KVStoreValue(Message):
    key: str = ""
    value: bytes = b""
    found: bool = False


@dataclasses.dataclass
class KVStoreMultiSet(Message):
    kvs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class KVStoreMultiGet(Message):
    keys: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class KVStoreMultiValue(Message):
    kvs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class KVStoreAdd(Message):
    key: str = ""
    delta: int = 1
    # Idempotency token: the master caches token -> result, so an
    # RPC-retried add is applied exactly once (missing field on old
    # senders decodes to "" = no dedup, preserving wire compat).
    token: str = ""


@dataclasses.dataclass
class KVStoreCount(Message):
    value: int = 0


@dataclasses.dataclass
class KVStoreScan(Message):
    """Prefix scan (ISSUE 9): the serving tier's shared registry lists
    its gateway/replica entries (``serve/{job}/gw/``,
    ``serve/{job}/rep/``) without maintaining a racy index key."""

    prefix: str = ""


@dataclasses.dataclass
class KVStoreScanResult(Message):
    kvs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class KVStoreDelete(Message):
    """Delete one key (ISSUE 9): registry GC of stale gateway/replica
    leases needs removal, not just overwrite.

    ``token`` (ISSUE 14, graftcheck PC403): the delete is retried
    ``idempotent=True``, but its reply carries whether THIS call
    removed the key — a DEADLINE-retried duplicate whose first reply
    was lost would answer found=False for a delete that actually
    happened.  The master caches token -> first answer, the same
    exactly-once contract as ``KVStoreAdd``."""

    key: str = ""
    token: str = ""


# ---------------------------------------------------------------------------
# Dynamic data sharding (reference master/shard + grpc.py Task* messages)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DatasetShardParams(Message):
    """Worker -> master: register a dataset for dynamic sharding
    (reference ``grpc.py DatasetShardParams``)."""

    dataset_name: str = ""
    dataset_size: int = 0
    shard_size: int = 0
    batch_size: int = 0
    num_epochs: int = 1
    shuffle: bool = False
    task_type: str = "training"
    storage_type: str = "text"
    num_minibatches_per_shard: int = 0


@dataclasses.dataclass
class TaskRequest(Message):
    dataset_name: str = ""
    worker_id: int = 0
    # Idempotency token: a retried fetch returns the SAME task instead of
    # popping (and leaking) a second shard.
    token: str = ""


@dataclasses.dataclass
class Task(Message):
    """One unit of data to consume: an index range [start, end) of a shard
    (reference ``grpc.py Task``).  ``task_id < 0`` means no task available."""

    task_id: int = -1
    task_type: str = "training"
    dataset_name: str = ""
    start: int = 0
    end: int = 0
    epoch: int = 0


@dataclasses.dataclass
class TaskResult(Message):
    dataset_name: str = ""
    task_id: int = -1
    worker_id: int = 0
    success: bool = True
    err_message: str = ""


@dataclasses.dataclass
class ShardCheckpointRequest(Message):
    dataset_name: str = ""


@dataclasses.dataclass
class ShardCheckpoint(Message):
    """Serialized dataset progress for exactly-once resume
    (reference ``base_dataset_manager.py:60 DatasetShardCheckpoint``)."""

    dataset_name: str = ""
    content: str = ""  # JSON


# ---------------------------------------------------------------------------
# Health check / straggler detection
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class NetworkCheckResult(Message):
    """Per-node result of the paired matmul+psum pre-flight benchmark
    (reference ``report_network_check_status`` + ``grpc.py NetworkStatus``)."""

    node_id: int = 0
    succeeded: bool = True
    elapsed: float = 0.0
    round: int = 0


@dataclasses.dataclass
class NetworkReadyRequest(Message):
    pass


@dataclasses.dataclass
class FaultNodeRequest(Message):
    pass


@dataclasses.dataclass
class FaultNodes(Message):
    nodes: List[int] = dataclasses.field(default_factory=list)
    reason: str = ""


@dataclasses.dataclass
class StragglerRequest(Message):
    pass


@dataclasses.dataclass
class Stragglers(Message):
    nodes: List[int] = dataclasses.field(default_factory=list)
    times: dict = dataclasses.field(default_factory=dict)
    # True when the latest check round has results from every rendezvous
    # participant — agents poll until this settles instead of guessing.
    complete: bool = False


# ---------------------------------------------------------------------------
# Metrics / monitoring
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GlobalStep(Message):
    """(reference ``grpc.py GlobalStepRecord`` -> SpeedMonitor)"""

    node_id: int = 0
    step: int = 0
    timestamp: float = 0.0


@dataclasses.dataclass
class CkptPerf(Message):
    """Per-save flash-checkpoint timings (ISSUE 4): the worker's
    save_to_memory stall feeds the master's goodput accounting — a
    synchronous stall is lost train time even without a restart.
    ISSUE 7 adds the scale-out gauges: the node's AGGREGATE persist
    throughput (sliced persist sums the ranks' disjoint-slice writes)
    and the dirty-fence skip count of the last incremental save."""

    node_id: int = 0
    step: int = 0
    stall_ms: float = 0.0
    staged_mbps: float = 0.0
    persist_mbps: float = 0.0
    agg_persist_mbps: float = 0.0
    # -1 = "not measured by this report" (stall-only reports must not
    # zero a node's skip gauge); >= 0 is a real count.
    tensors_skipped: int = -1


@dataclasses.dataclass
class UsedResource(Message):
    node_id: int = 0
    cpu_percent: float = 0.0
    memory_mb: float = 0.0
    tpu_duty_cycle: float = 0.0
    hbm_used_mb: float = 0.0


@dataclasses.dataclass
class ModelInfo(Message):
    num_params: int = 0
    flops_per_step: float = 0.0
    batch_size_per_step: int = 0
    extra: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DiagnosisReport(Message):
    """Agent -> master periodic diagnosis payload (reference
    ``diagnosis/common/diagnosis_data.py``)."""

    node_id: int = 0
    data_type: str = ""
    content: str = ""
    timestamp: float = 0.0


# ---------------------------------------------------------------------------
# Sync service (named barriers, reference sync_service.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SyncJoin(Message):
    sync_name: str = ""
    node_id: int = 0
    node_rank: int = -1


@dataclasses.dataclass
class SyncFinish(Message):
    sync_name: str = ""


@dataclasses.dataclass
class SyncQuery(Message):
    sync_name: str = ""


# ---------------------------------------------------------------------------
# Checkpoint coordination
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CheckpointSync(Message):
    """Cross-node shard-step consistency barrier before commit
    (reference ``servicer._sync_checkpoint :609``)."""

    node_id: int = 0
    step: int = 0


# ---------------------------------------------------------------------------
# Config push (reference get_elastic_run_config / ParallelConfig)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ElasticRunConfigRequest(Message):
    pass


@dataclasses.dataclass
class ElasticRunConfig(Message):
    configs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ParallelConfigRequest(Message):
    node_id: int = 0


@dataclasses.dataclass
class ParallelConfig(Message):
    """Master-tuned runtime knobs hot-reloaded by the trainer (reference
    ``grpc.py ParallelConfig/DataLoaderConfig/OptimizerConfig:439-483``)."""

    dataloader: dict = dataclasses.field(default_factory=dict)
    optimizer: dict = dataclasses.field(default_factory=dict)
    mesh: dict = dataclasses.field(default_factory=dict)
    restart: bool = False
    version: int = 0


@dataclasses.dataclass
class JobExitRequest(Message):
    node_id: int = 0
    reason: str = ""
    success: bool = True


# ---------------------------------------------------------------------------
# Checkpoint replicas (agent <-> agent; reference flash_checkpoint/replica.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ReplicaPush(Message):
    """Backup one process's staged checkpoint shard onto a peer node
    (reference ``CkptReplicaManger.backup replica.py:57``)."""

    owner_node: int = 0
    process_id: int = 0
    step: int = 0
    payload: bytes = b""


@dataclasses.dataclass
class ReplicaFetch(Message):
    process_id: int = 0
    min_step: int = -1


@dataclasses.dataclass
class ReplicaData(Message):
    found: bool = False
    step: int = -1
    payload: bytes = b""


# ---------------------------------------------------------------------------
# Serving fleet (gateway <-> clients, gateway <-> replicas; ISSUE 5).
# The reference has no serving control plane at all (its RL stack shells
# out to an unsupervised vllm, atorch/rl/model_engine/model_engine.py:35);
# these messages are the typed wire surface of dlrover_tpu.serving.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ServeSubmit(Message):
    """Client -> gateway: one inference request.  ``req_id`` doubles as
    the idempotency token (BoundedTokenCache dedupe): a retried submit
    of a completed request returns the cached result instead of
    decoding twice.

    Prefix-aware routing (ISSUE 8): ``prompt`` carries the FULL token
    sequence; ``prefix_len > 0`` declares its leading tokens a shared
    template whose fingerprint ``prefix_fp`` the gateway routes on
    (warm replicas first) and the replica prefix-caches.

    The same dataclass is the gateway -> replica grant: ``stage``
    selects the path (``full`` = prefill+decode on one replica;
    ``prefill`` = score the prompt and hand the KV segment back;
    ``decode`` = continue from the attached ``kv`` segment, packed by
    ``llama_infer.pack_kv_segment`` with an embedded CRC)."""

    req_id: str = ""
    prompt: List[int] = dataclasses.field(default_factory=list)
    max_new_tokens: int = 16
    deadline_s: float = 0.0  # 0 = no per-request deadline
    prefix_len: int = 0  # leading tokens shared with other requests
    prefix_fp: str = ""  # fingerprint of prompt[:prefix_len]
    stage: str = "full"  # full | prefill | decode (grant direction)
    kv: bytes = b""  # packed KV segment (relayed decode grants only)
    # Peer-to-peer KV handoff (ISSUE 9).  On a decode grant, a
    # non-empty ``kv_addr`` is a TICKET: the decode replica pulls the
    # segment bytes directly from the prefill replica's segment server
    # at that address (``KvSegmentFetch``), verifying ``kv_crc32`` /
    # ``kv_nbytes`` / ``kv_fp`` — the gateway never touched the bytes.
    # On a prefill grant, ``kv_relay=True`` orders the old
    # through-the-gateway payload path (the fallback after a failed
    # pull, and the compat mode for non-P2P replicas).
    kv_addr: str = ""
    kv_fp: str = ""
    kv_crc32: int = 0
    kv_nbytes: int = 0
    kv_relay: bool = False
    #: Distributed-trace context (ISSUE 12): ``{"tid": trace_id,
    #: "sid": parent span id}``.  Wire-optional — a trace-less submit
    #: (or an unsampled request's grant) encodes byte-identically to
    #: the pre-trace wire, keeping the msgpack fast path intact.
    trace: dict = dataclasses.field(default_factory=dict)
    #: Cross-cell spillover (ISSUE 17).  A saturated/dying cell's
    #: gateway forwards the submit to a sibling cell UNDER THE SAME
    #: req_id — the hop rides the existing req_id-keyed dedupe/journal
    #: contracts, so killing either side mid-hop still completes the
    #: request exactly once.  ``spill_from`` names the origin cell;
    #: ``spill_hops`` counts forwards so depth stays bounded (a request
    #: never ping-pongs between two saturated cells).  Both are
    #: wire-optional: a local submit encodes byte-identically to the
    #: pre-spillover wire.
    spill_from: str = ""
    spill_hops: int = 0

    _WIRE_OPTIONAL = frozenset({"trace", "spill_from", "spill_hops"})


@dataclasses.dataclass
class ServeAck(Message):
    """Gateway's immediate answer to a submit: ``accepted`` (queued),
    ``rejected`` with an explicit ``retry_after_s`` (bounded-queue
    backpressure: the client backs off instead of the queue growing
    without bound), or a terminal state from the dedupe cache —
    ``done`` (tokens included), ``failed``, or ``timeout`` (the req_id
    is the idempotency key; retry a failure under a fresh id)."""

    req_id: str = ""
    status: str = "accepted"  # accepted | done | rejected
    tokens: List[int] = dataclasses.field(default_factory=list)
    retry_after_s: float = 0.0
    reason: str = ""


@dataclasses.dataclass
class ServeStatusRequest(Message):
    req_id: str = ""


@dataclasses.dataclass
class ServeStatusReply(Message):
    """``state``: queued | running | done | failed | timeout | unknown.
    ``tokens`` carries the streamed-so-far prefix while running and the
    full completion once done."""

    req_id: str = ""
    state: str = "unknown"
    tokens: List[int] = dataclasses.field(default_factory=list)
    replica: str = ""
    reason: str = ""


@dataclasses.dataclass
class ServeReplicaRegister(Message):
    """``role`` (ISSUE 8): ``unified`` replicas run the full
    prefill+decode path; ``prefill`` replicas only score prompts and
    export KV segments; ``decode`` replicas only continue from imported
    segments (missing field on old senders decodes to "" = unified).

    Speculative serving (ISSUE 11): ``spec`` advertises that this
    replica can run speculative decode rounds (a local draft model, or
    a server sized to accept a remote draft handle) — the gateway's
    grant scan prefers spec replicas for long-decode requests.  A
    ``draft``-role replica additionally announces ``draft_addr``, the
    address of its proposal server, which the gateway hands to spec
    targets in every poll reply."""

    replica_id: str = ""
    slots: int = 0
    role: str = "unified"  # unified | prefill | decode | draft
    spec: bool = False
    draft_addr: str = ""


@dataclasses.dataclass
class ServeReplicaDeregister(Message):
    replica_id: str = ""


@dataclasses.dataclass
class ServeReplicaPoll(Message):
    """Replica -> gateway heartbeat + work pull.  ``active`` lists every
    req_id the replica currently owns (pending + in-flight) so the
    gateway can reconcile lost grants; ``stats`` carries slot occupancy
    / queue depth / TTFT / tokens-per-second / speculative acceptance
    for the fleet gauges and the autoscaler."""

    replica_id: str = ""
    free_slots: int = 0
    active: List[str] = dataclasses.field(default_factory=list)
    stats: dict = dataclasses.field(default_factory=dict)
    #: Prefix-template fingerprints this replica holds warm (ISSUE 8):
    #: replaces the gateway's residency entry wholesale every poll, so
    #: the routing map self-corrects (LRU evictions, restarts).
    warm_prefixes: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ServeGrants(Message):
    """Gateway -> replica poll reply: new work, cancellations (deadline
    expiries — the replica drops them from its pending queue, or sheds
    the slot mid-decode via ``DecodeServer.abort``), the drain flag
    (stop admitting, finish in-flight, deregister), and ``known``
    (False = the gateway restarted and lost this replica — re-register)."""

    requests: List[ServeSubmit] = dataclasses.field(default_factory=list)
    cancel: List[str] = dataclasses.field(default_factory=list)
    drain: bool = False
    known: bool = True
    #: Current draft-proposal endpoint (ISSUE 11): the address of a
    #: live draft-role replica's proposal server, refreshed every poll
    #: so spec targets attach/detach their remote draft as draft
    #: replicas come and go ("" = no draft alive).
    draft_addr: str = ""


@dataclasses.dataclass
class ServeTokens(Message):
    """Replica -> gateway: streamed tokens for one in-flight request
    (batched per poll round — the burst size is the dispatch batching
    the decode paths buy throughput with)."""

    replica_id: str = ""
    req_id: str = ""
    tokens: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ServeDone(Message):
    """Replica -> gateway: terminal completion report.  Idempotent: the
    gateway dedupes by req_id, so a journal replay after a replica kill
    (``replayed=True``) or a re-dispatch race can never complete a
    request twice."""

    replica_id: str = ""
    req_id: str = ""
    tokens: List[int] = dataclasses.field(default_factory=list)
    ok: bool = True
    reason: str = ""
    replayed: bool = False
    #: Per-request speculation telemetry (ISSUE 11): the accepted-
    #: tokens-per-round this request earned and the speculative rounds
    #: it rode.  Journaled with the completion, so a replay reports
    #: the SAME numbers the request earned live (0 = never speculated).
    tokens_per_round: float = 0.0
    spec_rounds: int = 0
    #: Trace context of a JOURNAL-REPLAYED completion (ISSUE 12): the
    #: replica ships the trace id the request earned when served live,
    #: so a replay landing at a fresh gateway (failover adoption) joins
    #: the ORIGINAL trace instead of orphaning a new one.  Empty on
    #: live completions (the gateway already holds the context) and
    #: omitted from the wire (byte compat).
    trace: dict = dataclasses.field(default_factory=dict)

    _WIRE_OPTIONAL = frozenset({"trace"})


@dataclasses.dataclass
class ServeKvReady(Message):
    """Prefill replica -> gateway: the prefill-grant's KV segment is
    ready (stage two of the disaggregated path, ISSUE 8).  ``payload``
    is ``llama_infer.pack_kv_segment`` bytes (CRC embedded);
    ``fp32_bytes`` is the segment's un-quantized size so the int8
    transfer saving is measurable at the gateway without unpacking.

    Peer-to-peer mode (ISSUE 9): ``payload`` stays EMPTY and the
    message carries only a ticket — ``addr`` of the prefill replica's
    segment server plus the segment's ``seg_fp``/``crc32``/``nbytes``
    — which the gateway holds and attaches to the decode grant; the
    decode replica pulls the bytes directly from the peer."""

    replica_id: str = ""
    req_id: str = ""
    payload: bytes = b""
    fp32_bytes: int = 0
    addr: str = ""  # non-empty = ticket mode (P2P)
    seg_fp: str = ""
    crc32: int = 0
    nbytes: int = 0
    #: Trace context (ISSUE 12), wire-optional (byte compat).
    trace: dict = dataclasses.field(default_factory=dict)

    _WIRE_OPTIONAL = frozenset({"trace"})


@dataclasses.dataclass
class KvSegmentFetch(Message):
    """Decode replica -> prefill replica's segment server (ISSUE 9):
    pull the published KV segment for ``req_id``.  ``seg_fp`` pins the
    exact segment the ticket promised — a re-prefilled request must
    never decode from a stale publication under the same req_id."""

    req_id: str = ""
    seg_fp: str = ""


@dataclasses.dataclass
class KvSegmentData(Message):
    found: bool = False
    reason: str = ""
    payload: bytes = b""
    crc32: int = 0


@dataclasses.dataclass
class DraftRoll(Message):
    """Spec target replica -> draft replica's proposal server (ISSUE
    11): one speculative round's proposal fetch for every stream the
    target is speculating.  Each entry of ``streams`` is a dict —
    ``{"rid": str, "ctx": [ints emitted since the last roll], "open":
    [prompt tokens]}`` (``open`` only on the first roll of a stream, or
    after the draft evicted it) — the draft catches its per-stream
    cache up from exactly that delta, rolls ``k`` proposals, and ships
    them back CRC-wrapped (the KV-segment envelope idiom).  ``close``
    piggybacks finished/aborted stream ids for cache hygiene."""

    replica_id: str = ""
    k: int = 4
    sample: bool = False
    streams: List[dict] = dataclasses.field(default_factory=list)
    close: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class DraftProposals(Message):
    """Proposal-server reply: ``payload`` is the CRC-wrapped msgpack
    proposal bundle (``serving.draft.pack_proposals``); ``found=False``
    carries the failure reason — the target degrades to plain decode,
    it never waits."""

    found: bool = False
    reason: str = ""
    payload: bytes = b""


@dataclasses.dataclass
class ServeKvReject(Message):
    """Decode replica -> gateway: the decode-grant's KV segment failed
    verification (torn in flight — chaos ``serving.kv_drop``).  The
    gateway drops the payload and re-queues the request for a fresh
    prefill (bounded by ``max_attempts``); a torn segment is NEVER
    decoded from."""

    replica_id: str = ""
    req_id: str = ""
    reason: str = ""


@dataclasses.dataclass
class ServeDrainRequest(Message):
    """Operator/autoscaler -> gateway: drain one replica (scale-down)."""

    replica_id: str = ""


@dataclasses.dataclass
class ServeFleetStatsRequest(Message):
    pass


@dataclasses.dataclass
class ServeFleetStats(Message):
    stats: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ObsScrapeRequest(Message):
    """Live flight-recorder scrape (ISSUE 12): pull the process's
    bounded event ring over the existing RPC idiom.  ``since_seq``
    resumes an incremental scrape (0 = everything still in the ring)."""

    since_seq: int = 0


@dataclasses.dataclass
class ObsScrape(Message):
    """Scrape reply: ``events`` are the recorder's structured dicts
    (spans + journal events), ``dropped`` the ring's lifetime drop
    count (bounded ring — every drop is counted, never silent), and
    ``next_seq`` the cursor for the next incremental scrape."""

    process: str = ""
    events: list = dataclasses.field(default_factory=list)
    dropped: int = 0
    next_seq: int = 0


@dataclasses.dataclass
class FleetStatsRequest(Message):
    """Fleet control-plane view (ISSUE 10): per-role desired/observed
    membership, drains in flight and cross-role policy phases."""

    pass


@dataclasses.dataclass
class FleetStats(Message):
    roles: dict = dataclasses.field(default_factory=dict)
    policies: list = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------------------
# Embedding store service (PS analogue; reference tfplus KvVariable serving)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class EmbeddingOp(Message):
    """One embedding-store RPC: op in {lookup, apply, export,
    export_keys, import, delete, filter, size}.  keys/grads/blob are
    packed numpy bytes."""

    table: str = ""
    op: str = "lookup"
    keys: bytes = b""
    grads: bytes = b""
    blob: bytes = b""
    train: bool = True
    optimizer: dict = dataclasses.field(default_factory=dict)
    rank_filter: int = 0
    world: int = 1
    min_freq: int = 0
    max_version_age: int = 0


@dataclasses.dataclass
class EmbeddingResult(Message):
    success: bool = True
    reason: str = ""
    rows: bytes = b""
    blob: bytes = b""
    count: int = 0


# ---------------------------------------------------------------------------
# Live resharding (ISSUE 6): mesh-to-mesh state moves without restart
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ReshardFetch(Message):
    """Pull one plan segment's bytes from a peer's published shard table.

    ``box`` is the segment's region in global tensor coordinates
    (``[[start, stop], ...]``); the peer slices it out of its local shard
    and answers with CRC-verified bytes."""

    epoch: int = 0
    step: int = -1
    src_rank: int = 0
    key: str = ""  # "<path>|<k>" shard key in the peer's table
    box: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ReshardSegment(Message):
    found: bool = False
    reason: str = ""
    payload: bytes = b""
    crc32: int = 0
    dtype: str = ""
    shape: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ReshardEpochRequest(Message):
    """Worker poll: is a live resize pending? (``epoch`` = last epoch the
    caller observed; the master answers with the current one)."""

    node_id: int = 0
    epoch: int = -1


@dataclasses.dataclass
class ReshardEpochInfo(Message):
    """The master's resize broadcast: at ``epoch`` the job wants
    ``target_num_processes`` processes laid out as ``target_spec``
    (MeshSpec axis sizes).  ``status`` in {idle, preparing, done,
    aborted}."""

    epoch: int = -1
    status: str = "idle"
    target_num_processes: int = 0
    target_spec: dict = dataclasses.field(default_factory=dict)
    deadline_s: float = 0.0


@dataclasses.dataclass
class ReshardReport(Message):
    """A worker's verdict on one resize epoch: live reshard completed
    (``ok``) or failed with ``reason`` (the master then lets the
    checkpoint-restart ladder run)."""

    node_id: int = 0
    epoch: int = 0
    ok: bool = False
    reason: str = ""
    downtime_ms: float = 0.0
    moved_mb: float = 0.0


@dataclasses.dataclass
class ReshardAnnounce(Message):
    """Operator/admin request: announce a live resize epoch (ISSUE 13).
    Until now only the in-process autoscaler could announce; this RPC
    lets an operator (or a test harness) open an epoch from outside.
    The reply is a ``ReshardEpochInfo`` for the announced epoch."""

    node_id: int = 0
    target_num_processes: int = 0
    target_spec: dict = dataclasses.field(default_factory=dict)
    expected_reports: int = 0
    deadline_s: float = 0.0  # 0 = the master's configured default


@dataclasses.dataclass
class JournalFetch(Message):
    """Standby -> primary streaming replication (ISSUE 13): read the
    control-state WAL from byte ``offset``.  ``offset=-1`` asks for the
    current snapshot file instead; the mirror then (re-)reads the WAL
    from byte 0 — frames carry their own seq, so a tail dedupes any
    overlap, and a compaction is detected via the reply's
    ``wal_size``/``wal_ino``."""

    offset: int = 0
    max_bytes: int = 1 << 20


@dataclasses.dataclass
class JournalChunk(Message):
    """A chunk of the primary's WAL (or snapshot, for ``offset=-1``).
    ``eof`` means no bytes past ``offset`` right now (poll again);
    ``found`` is False when the primary runs without a state journal.
    ``wal_size``/``wal_ino`` identify the remote WAL file (size + inode
    of the open fd the bytes were read from): a mirror that sees the
    inode change — or its offset exceed the size — knows the primary
    compacted (atomic-replaced) the file and rebuilds instead of
    appending new-inode bytes at an old-inode offset."""

    data: bytes = b""
    offset: int = 0  # offset of the FIRST byte of ``data``
    eof: bool = True
    found: bool = True
    wal_size: int = -1
    wal_ino: int = 0


# ---------------------------------------------------------------------------
# Multi-cell control plane (ISSUE 15): cell snapshot + placement wire
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CellSnapshotRequest(Message):
    """Federation -> cell master: one snapshot read (identity, ring
    view, placement epoch, node/task/pool counts).  Pure read — safe
    for ``idempotent=True`` retries — and the ONLY recurring RPC the
    federation tier makes, TTL-cached on its side so a cell pays at
    most one per refresh interval."""

    cell_id: str = ""


@dataclasses.dataclass
class CellSnapshot(Message):
    """A cell master's snapshot body (``CellManager.snapshot`` plus
    the hosting master's live stats).  ``found=False`` means the
    answering master carries no cell identity (a plain single-master
    job asked by mistake)."""

    cell_id: str = ""
    snapshot: dict = dataclasses.field(default_factory=dict)
    found: bool = True


@dataclasses.dataclass
class CellPlacementUpdate(Message):
    """Federation -> cell master: adopt this role plan (role -> member
    count for THIS cell).  Idempotent by ``epoch`` — the handler
    journals then applies only strictly-newer epochs, so a
    DEADLINE-retried push (or two federations racing) converges on the
    highest epoch without tokens (nothing is consumed)."""

    cell_id: str = ""
    epoch: int = -1
    placement: dict = dataclasses.field(default_factory=dict)
