"""Node model: the master's view of one training node.

Capability parity with reference ``dlrover/python/common/node.py``
(``NodeResource:38``, ``Node:150``) re-cast for TPU: a "node" is one TPU-VM
host (or one local process in dev mode) owning ``tpu_chips`` chips of a slice,
plus host CPU/memory.  Includes the legal status-transition flow
(reference ``master/node/status_flow.py:136``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from dlrover_tpu.common.constants import (
    NodeExitReason,
    NodeStatus,
    NodeType,
)


@dataclasses.dataclass
class NodeResource:
    """Requested/used resources of a node.

    Reference ``common/node.py:38``.  ``tpu_chips`` replaces ``gpu_num``;
    ``tpu_type`` carries the accelerator flavour (e.g. ``v5e``, ``v5p``).
    """

    cpu: float = 0.0
    memory_mb: int = 0
    tpu_chips: int = 0
    tpu_type: str = ""
    # GKE slice topology (``2x4``, ``4x4x4``): the
    # ``cloud.google.com/gke-tpu-topology`` node selector — which slice
    # SHAPE the pod's host must belong to, not how many chips it uses.
    tpu_topology: str = ""
    disk_mb: int = 0
    priority: str = ""

    @classmethod
    def resource_str_to_node_resource(cls, resource_str: str) -> "NodeResource":
        """Parse ``"cpu=4,memory=8192Mi,tpu=8"`` style strings
        (reference ``NodeResource.resource_str_to_node_resource``)."""
        res = cls()
        if not resource_str:
            return res
        for kv in resource_str.split(","):
            if "=" not in kv:
                continue
            k, v = kv.split("=", 1)
            k, v = k.strip().lower(), v.strip()
            if k == "cpu":
                res.cpu = float(v)
            elif k == "memory":
                res.memory_mb = int(v.lower().replace("mi", "").replace("m", ""))
            elif k in ("tpu", "tpu_chips"):
                res.tpu_chips = int(v)
            elif k == "tpu_type":
                res.tpu_type = v
            elif k == "tpu_topology":
                res.tpu_topology = v
        return res

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class NodeGroupResource:
    """Resource spec for a group of same-typed nodes
    (reference ``common/node.py NodeGroupResource``)."""

    count: int = 0
    node_resource: NodeResource = dataclasses.field(default_factory=NodeResource)


class Node:
    """One training node as tracked by the master's job manager.

    Reference ``common/node.py:150``.  Keeps identity (type, id, rank),
    status, restart accounting, heartbeat, health-check verdicts, and
    resource usage.
    """

    def __init__(
        self,
        node_type: str,
        node_id: int,
        rank_index: Optional[int] = None,
        name: str = "",
        status: str = NodeStatus.INITIAL,
        config_resource: Optional[NodeResource] = None,
        max_relaunch_count: int = 3,
        critical: bool = False,
    ):
        self.type = node_type
        self.id = node_id
        self.rank_index = rank_index if rank_index is not None else node_id
        self.name = name or f"{node_type}-{node_id}"
        self.status = status
        self.config_resource = config_resource or NodeResource()
        self.used_resource = NodeResource()

        self.critical = critical
        self.max_relaunch_count = max_relaunch_count
        self.relaunch_count = 0
        self.relaunchable = True
        self.is_released = False
        self.exit_reason = ""

        self.create_time: Optional[float] = None
        self.start_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.heartbeat_time: float = 0.0
        self.start_hang_time: float = 0.0

        # Pre-flight health check results (reference Node.node_check fields).
        self.node_check_passed: Optional[bool] = None
        self.node_check_elapsed: float = 0.0
        self.is_straggler = False

        # Addressing: host:port of the agent on this node.
        self.host: str = ""
        self.agent_port: int = 0
        # ICI/DCN locality key used by the topology-aware rank sort
        # (reference net_topology.py NodeTopologyMeta asw/psw -> slice/host).
        self.slice_id: str = ""
        self.host_id: str = ""

        self.paral_config: dict = {}

    # -- status ------------------------------------------------------------
    def update_status(self, status: str) -> None:
        if NodeStatusFlow.is_allowed(self.status, status):
            self.status = status
            if status == NodeStatus.RUNNING and self.start_time is None:
                self.start_time = time.time()
            if status in NodeStatus.TERMINAL and self.finish_time is None:
                self.finish_time = time.time()

    def is_unrecoverable_failure(self) -> bool:
        """Whether the master should stop relaunching this node
        (reference ``Node.is_unrecoverable_failure``)."""
        if not self.relaunchable:
            return True
        if self.relaunch_count >= self.max_relaunch_count:
            return True
        if self.exit_reason == NodeExitReason.FATAL_ERROR:
            return True
        return False

    def inc_relaunch_count(self) -> None:
        self.relaunch_count += 1

    def update_heartbeat(self, ts: Optional[float] = None) -> None:
        self.heartbeat_time = ts if ts is not None else time.time()

    def get_relaunch_node(self, new_id: int) -> "Node":
        """Create the successor node when this one is replaced
        (reference ``Node.get_relaunch_node_info``)."""
        new = Node(
            self.type,
            new_id,
            rank_index=self.rank_index,
            status=NodeStatus.INITIAL,
            config_resource=self.config_resource,
            max_relaunch_count=self.max_relaunch_count,
            critical=self.critical,
        )
        new.relaunch_count = self.relaunch_count + 1
        return new

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "id": self.id,
            "rank_index": self.rank_index,
            "name": self.name,
            "status": self.status,
            "relaunch_count": self.relaunch_count,
            "exit_reason": self.exit_reason,
            "host": self.host,
            "slice_id": self.slice_id,
            "is_straggler": self.is_straggler,
        }

    def __repr__(self) -> str:  # pragma: no cover
        return f"Node({self.type}-{self.id} rank={self.rank_index} {self.status})"


class NodeStatusFlow:
    """Legal node status transitions (reference ``status_flow.py:136``
    NODE_STATE_FLOWS).  Transitions not listed are ignored — this makes the
    event loop idempotent under out-of-order platform events."""

    _ALLOWED = {
        NodeStatus.INITIAL: {
            NodeStatus.PENDING,
            NodeStatus.RUNNING,
            NodeStatus.FAILED,
            NodeStatus.DELETED,
        },
        NodeStatus.PENDING: {
            NodeStatus.RUNNING,
            NodeStatus.SUCCEEDED,
            NodeStatus.FAILED,
            NodeStatus.DELETED,
        },
        NodeStatus.RUNNING: {
            NodeStatus.SUCCEEDED,
            NodeStatus.FAILED,
            NodeStatus.DELETED,
            NodeStatus.BREAKDOWN,
        },
        NodeStatus.SUCCEEDED: {NodeStatus.DELETED},
        NodeStatus.FAILED: {NodeStatus.DELETED, NodeStatus.RUNNING},
        NodeStatus.BREAKDOWN: {NodeStatus.DELETED},
        NodeStatus.DELETED: set(),
        NodeStatus.UNKNOWN: set(NodeStatus.TERMINAL)
        | {NodeStatus.PENDING, NodeStatus.RUNNING},
    }

    @classmethod
    def is_allowed(cls, from_status: str, to_status: str) -> bool:
        if from_status == to_status:
            return False
        return to_status in cls._ALLOWED.get(from_status, set())


@dataclasses.dataclass
class NodeEvent:
    """A platform event about one node, consumed by the job manager's event
    loop (reference ``master/watcher/base_watcher.py NodeEvent``)."""

    event_type: str
    node: Node
