"""Environment helpers (reference ``dlrover/python/common/env_utils.py``)."""

from __future__ import annotations

import os
from typing import Optional

from dlrover_tpu.common.constants import NodeEnv


def get_env_int(name: str, default: int = 0) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def get_env_str(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def get_node_id() -> int:
    return get_env_int(NodeEnv.NODE_ID, 0)


def get_node_rank() -> int:
    return get_env_int(NodeEnv.NODE_RANK, get_node_id())

def get_node_num() -> int:
    return get_env_int(NodeEnv.NODE_NUM, 1)


def get_master_addr() -> str:
    return get_env_str(NodeEnv.MASTER_ADDR)


def get_master_standby_addr() -> str:
    """Address of the warm-standby master (ISSUE 13), if one is running.
    Clients fail over to it when the primary stops answering."""
    return get_env_str("DLROVER_TPU_MASTER_STANDBY_ADDR")


def get_master_state_dir() -> str:
    """The master's durable control-plane state dir (ISSUE 13).  When
    set, clients re-resolve the serving master's address from the
    ``addr`` file the current leader publishes there — the chain that
    keeps working across repeated failovers."""
    return get_env_str("DLROVER_TPU_MASTER_STATE_DIR")


def get_job_name() -> str:
    return get_env_str(NodeEnv.JOB_NAME, "local-job")


def get_run_id() -> str:
    """Unique id of one launcher invocation (set by ``tpurun``).  Namespaces
    host-local IPC objects (shm arenas, queues, locks) so a fresh launch
    never warm-restores from a previous job's stale arena, while worker
    restarts *within* a launch still share state."""
    return get_env_str("DLROVER_TPU_RUN_ID", "")


def run_scoped(name: str) -> str:
    """Append the run id (when set) to an IPC object name."""
    rid = get_run_id()
    return f"{name}-{rid}" if rid else name


def get_process_id() -> int:
    return get_env_int(NodeEnv.PROCESS_ID, 0)


def get_num_processes() -> int:
    return get_env_int(NodeEnv.NUM_PROCESSES, 1)


def get_coordinator() -> Optional[str]:
    v = get_env_str(NodeEnv.COORDINATOR_ADDR)
    return v or None


def worker_env(
    *,
    job_name: str,
    master_addr: str,
    node_id: int,
    node_rank: int,
    node_num: int,
    process_id: int,
    num_processes: int,
    coordinator: str,
    restart_count: int = 0,
) -> dict:
    """The env contract the agent passes to each spawned worker process."""
    return {
        NodeEnv.JOB_NAME: job_name,
        NodeEnv.MASTER_ADDR: master_addr,
        NodeEnv.NODE_ID: str(node_id),
        NodeEnv.NODE_RANK: str(node_rank),
        NodeEnv.NODE_NUM: str(node_num),
        NodeEnv.PROCESS_ID: str(process_id),
        NodeEnv.NUM_PROCESSES: str(num_processes),
        NodeEnv.COORDINATOR_ADDR: coordinator,
        NodeEnv.RESTART_COUNT: str(restart_count),
    }
