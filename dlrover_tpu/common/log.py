"""Central logger (reference: ``dlrover/python/common/log.py``).

One process-wide logger with a consistent format; level from
``DLROVER_TPU_LOG_LEVEL``.  Sub-process roles (master/agent/worker) prefix
their records via ``set_role``.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(filename)s:%(lineno)d:%(funcName)s] %(message)s"
)

_ROLE = os.environ.get("DLROVER_TPU_ROLE", "")


def _build_logger() -> logging.Logger:
    logger = logging.getLogger("dlrover_tpu")
    if logger.handlers:
        return logger
    level = os.environ.get("DLROVER_TPU_LOG_LEVEL", "INFO").upper()
    logger.setLevel(getattr(logging, level, logging.INFO))
    handler = logging.StreamHandler(sys.stderr)
    fmt = _FORMAT if not _ROLE else f"[{_ROLE}] {_FORMAT}"
    handler.setFormatter(logging.Formatter(fmt))
    logger.addHandler(handler)
    logger.propagate = False
    return logger


logger = _build_logger()


def set_role(role: str) -> None:
    """Tag this process's log lines with its role (master/agent/worker-N)."""
    global _ROLE
    _ROLE = role
    for h in logger.handlers:
        h.setFormatter(logging.Formatter(f"[{role}] {_FORMAT}"))
