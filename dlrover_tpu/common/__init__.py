"""L0 common substrate: constants, logging, node model, typed RPC messages,
gRPC channel helpers, shared-memory IPC, storage abstraction, global context.

Everything above (master, agent, trainer) sits on this layer; it depends on
nothing internal.  Capability parity with the reference's
``dlrover/python/common/`` (see SURVEY.md §1 L0) but with typed msgpack
messages instead of pickled dataclasses over gRPC (reference wart:
``common/grpc.py:161-512``).
"""
