"""Object-store storage backend + Orbax interop tests (completes the
round-1 partial: checkpoint storage was POSIX-only with no ecosystem
interop)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dlrover_tpu.checkpoint.checkpointer import FlashCheckpointer
from dlrover_tpu.checkpoint.orbax_compat import (
    flash_to_orbax,
    load_from_orbax,
    orbax_to_flash,
    save_as_orbax,
)
from dlrover_tpu.common.storage import (
    ClassMeta,
    ObjectStoreStorage,
    PosixDiskStorage,
)


@pytest.fixture(params=["memory", "file"])
def object_store(request, tmp_path):
    if request.param == "memory":
        spec = {"driver": "memory"}
    else:
        spec = {"driver": "file", "path": str(tmp_path / "objs") + "/"}
    return ObjectStoreStorage(spec)


class TestObjectStoreStorage:
    def test_write_read_exists_remove(self, object_store):
        st = object_store
        st.write(b"abc", "/ck/step_1/shard_0.bin")
        assert st.exists("/ck/step_1/shard_0.bin")
        assert st.read("/ck/step_1/shard_0.bin") == b"abc"
        st.write("text", "/ck/meta.txt")
        assert st.read("/ck/meta.txt", mode="r") == "text"
        st.safe_remove("/ck/meta.txt")
        assert st.read("/ck/meta.txt") is None
        assert st.read("/missing") is None

    def test_listdir_and_prefix_delete(self, object_store):
        st = object_store
        for p in ("a/1.bin", "a/2.bin", "a/sub/3.bin", "b/4.bin"):
            st.write(b"x", f"/root/{p}")
        assert st.listdir("/root") == ["a", "b"]
        assert st.listdir("/root/a") == ["1.bin", "2.bin", "sub"]
        st.safe_rmtree("/root/a")
        assert st.listdir("/root/a") == []
        assert st.exists("/root/b/4.bin")

    def test_flash_checkpoint_over_object_store(self, object_store):
        """The whole flash-checkpoint engine runs against the object
        store backend (the saver only speaks the storage ABC)."""
        ckpt = FlashCheckpointer(
            "/jobs/ck", job_name="obj-store-test", storage=object_store
        )
        state = {"w": jnp.arange(8.0), "step": jnp.asarray(3)}
        ckpt.save(state, meta={"step": 3}, storage=True)
        assert ckpt.wait(timeout=60)
        target = jax.tree_util.tree_map(jnp.zeros_like, state)
        # A fresh checkpointer (cold process analogue) restores from the
        # object store.
        ckpt2 = FlashCheckpointer(
            "/jobs/ck", job_name="obj-store-test2", storage=object_store
        )
        got, meta = ckpt2.load(target=target)
        assert int(meta["step"]) == 3
        np.testing.assert_array_equal(
            np.asarray(got["w"]), np.arange(8.0)
        )

    def test_class_meta_builds_it(self, tmp_path):
        meta = ClassMeta(
            class_name="ObjectStoreStorage",
            kwargs={"spec": {"driver": "memory"}},
        )
        st = meta.build()
        assert isinstance(st, ObjectStoreStorage)
        st.write(b"z", "/k")
        assert st.read("/k") == b"z"


class TestOrbaxInterop:
    def _state(self):
        return {
            "params": {"w": jnp.arange(6.0).reshape(2, 3)},
            "step": jnp.asarray(7),
        }

    def test_round_trip(self, tmp_path):
        state = self._state()
        save_as_orbax(state, str(tmp_path / "obx"))
        target = jax.tree_util.tree_map(jnp.zeros_like, state)
        got = load_from_orbax(str(tmp_path / "obx"), target)
        np.testing.assert_array_equal(
            np.asarray(got["params"]["w"]),
            np.asarray(state["params"]["w"]),
        )
        assert int(got["step"]) == 7

    def test_flash_to_orbax_and_back(self, tmp_path):
        state = self._state()
        flash = FlashCheckpointer(
            str(tmp_path / "flash"), job_name="obx-a"
        )
        flash.save(state, meta={"step": 7}, storage=True)
        assert flash.wait(timeout=60)

        out = flash_to_orbax(
            flash, str(tmp_path / "obx"),
            jax.tree_util.tree_map(jnp.zeros_like, state),
        )
        assert out is not None
        step, path = out
        assert step == 7

        # Seed a brand-new flash run from that orbax dir.
        flash2 = FlashCheckpointer(
            str(tmp_path / "flash2"), job_name="obx-b"
        )
        orbax_to_flash(
            path, flash2,
            jax.tree_util.tree_map(jnp.zeros_like, state), step=step,
        )
        got, meta = flash2.load(
            target=jax.tree_util.tree_map(jnp.zeros_like, state)
        )
        assert int(meta["step"]) == 7
        np.testing.assert_array_equal(
            np.asarray(got["params"]["w"]),
            np.asarray(state["params"]["w"]),
        )
