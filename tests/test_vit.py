"""ViT model-family tests: shapes, learning, accelerate() integration,
and the conf-executor path (the non-LLM generality check)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.models import vit


@pytest.fixture(scope="module")
def cfg():
    return vit.ViTConfig.tiny()


class TestForward:
    def test_patchify_is_exact(self, cfg):
        imgs = np.arange(
            2 * cfg.image_size * cfg.image_size * cfg.channels,
            dtype=np.float32,
        ).reshape(2, cfg.image_size, cfg.image_size, cfg.channels)
        patches = np.asarray(vit.patchify(jnp.asarray(imgs), cfg))
        assert patches.shape == (2, cfg.n_patches, cfg.patch_dim)
        # First patch = the top-left 8x8 block, row-major.
        P = cfg.patch_size
        np.testing.assert_array_equal(
            patches[0, 0].reshape(P, P, cfg.channels),
            imgs[0, :P, :P, :],
        )

    def test_logits_shape_and_finite(self, cfg):
        params = vit.init_params(jax.random.PRNGKey(0), cfg)
        imgs = jnp.asarray(
            np.random.RandomState(0).randn(
                4, cfg.image_size, cfg.image_size, cfg.channels
            ),
            jnp.float32,
        )
        logits = jax.jit(
            lambda p, x: vit.forward(p, x, cfg)
        )(params, imgs)
        assert logits.shape == (4, cfg.num_classes)
        assert np.isfinite(np.asarray(logits)).all()

    def test_attention_is_bidirectional(self, cfg):
        """A change in the LAST patch must affect the CLS logits —
        causal attention would block that information flow."""
        params = vit.init_params(jax.random.PRNGKey(1), cfg)
        rs = np.random.RandomState(1)
        imgs = rs.randn(
            1, cfg.image_size, cfg.image_size, cfg.channels
        ).astype(np.float32)
        base = np.asarray(vit.forward(params, jnp.asarray(imgs), cfg))
        imgs2 = imgs.copy()
        imgs2[0, -cfg.patch_size:, -cfg.patch_size:, :] += 3.0
        got = np.asarray(vit.forward(params, jnp.asarray(imgs2), cfg))
        assert not np.allclose(base, got)


class TestLearning:
    def test_learns_prototype_classification(self, cfg):
        params = vit.init_params(jax.random.PRNGKey(0), cfg)
        tx = optax.adam(1e-3)
        opt = tx.init(params)

        rs = np.random.RandomState(0)
        protos = rs.randn(
            cfg.num_classes, cfg.image_size, cfg.image_size, cfg.channels
        ).astype(np.float32)

        @jax.jit
        def step(params, opt, batch):
            loss, grads = jax.value_and_grad(vit.loss_fn)(
                params, batch, cfg
            )
            updates, opt = tx.update(grads, opt, params)
            return optax.apply_updates(params, updates), opt, loss

        losses = []
        for i in range(30):
            labels = np.arange(8) % cfg.num_classes
            noise = np.random.RandomState(i).randn(*protos[labels].shape)
            batch = {
                "images": jnp.asarray(
                    protos[labels] + 0.3 * noise.astype(np.float32)
                ),
                "labels": jnp.asarray(labels.astype(np.int32)),
            }
            params, opt, loss = step(params, opt, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    def test_accelerate_integration(self, cpu_mesh_devices):
        from dlrover_tpu.parallel.accelerate import Strategy, accelerate
        from dlrover_tpu.parallel.mesh import MeshSpec

        cfg = vit.ViTConfig.tiny()
        rs = np.random.RandomState(0)
        batch = {
            "images": rs.randn(
                8, cfg.image_size, cfg.image_size, cfg.channels
            ).astype(np.float32),
            "labels": (np.arange(8) % cfg.num_classes).astype(np.int32),
        }
        job = accelerate(
            loss_fn=lambda p, b: vit.loss_fn(p, b, cfg),
            init_fn=lambda r: vit.init_params(r, cfg),
            optimizer=optax.adam(1e-3),
            sample_batch=batch,
            strategy=Strategy(mesh=MeshSpec(dp=2, fsdp=2)),
            devices=cpu_mesh_devices[:4],
        )
        state = job.create_state(jax.random.PRNGKey(0))
        b = jax.device_put(batch, job.batch_sharding)
        state, metrics = job.train_step(state, b)
        assert np.isfinite(float(metrics["loss"]))

    def test_conf_executor_family(self):
        from dlrover_tpu.trainer.conf_executor import execute

        state = execute(
            {
                "model": "vit",
                "dataset_size": 128,
                "model_args": {},
                "train": {
                    "global_batch_size": 8,
                    "max_micro_batch_per_proc": 8,
                    "max_steps": 3,
                    "logging_steps": 1,
                },
                "strategy": {"mesh": {"dp": 1}},
            },
            devices=[jax.devices("cpu")[0]],
        )
        assert state.step == 3
