"""Offline tier tests (ISSUE 20): the preemptible priority class.

Tier-1, all sub-second.  Four surfaces:

- the journaled :class:`OfflineWorkQueue` (submit dedupe, chunking,
  torn-tail reopen, exactly-once completion, preempt-youngest);
- the :class:`OfflineRunner` chunk loop over the fake decode server's
  incremental surface, including the ``offline.chunk_kill`` chaos
  site's exactly-once replay;
- the instant-reclaim bound: the loopback fleet unit where the REAL
  :class:`ChipBorrowArbiter` reclaims mid-chunk and the assertion is
  on decode ROUNDS elapsed (<= 1) before the chip is granted online;
  plus the arbiter's cooldown exemption for preemptible lenders;
- the speed-weight economics: ``chip_speed_weight``, the weighted
  ``decide``/``decide_pools`` queue pressure, and weighted
  ``place_roles`` ordering (with the weight-1.0 backward-compat law).
"""

import collections
import os
import threading
import time

import pytest

from dlrover_tpu.fleet.policy import (
    BorrowPolicy,
    ChipBorrowArbiter,
    BORROWED,
    IDLE,
    LENDING,
)
from dlrover_tpu.fleet.role import RoleAdapter, RoleSpec, RoleStatus
from dlrover_tpu.fleet.roles import OfflineRole
from dlrover_tpu.offline import (
    OfflinePolicy,
    OfflineRunner,
    OfflineWorkQueue,
)

pytestmark = pytest.mark.offline


class FakeOfflineServer:
    """The DecodeServer incremental surface with the arithmetic token
    law (token i of prompt p is ``(sum(p) + i) % 97``) — same fake as
    the serving runner tests, trimmed to what the offline loop uses."""

    def __init__(self, slots=4):
        self.slots = slots
        self._pending = collections.deque()
        self._active = {}

    def submit(self, rid, prompt, mnt, prefix_len=0, prefix_fp=""):
        self._pending.append((rid, [int(t) for t in prompt], int(mnt)))

    def cancel(self, rid):
        for i, item in enumerate(self._pending):
            if item[0] == rid:
                del self._pending[i]
                return True
        return False

    def abort(self, rid):
        if self.cancel(rid):
            return True
        return self._active.pop(rid, None) is not None

    def serve_incremental(self, tick=None, on_finish=None,
                          on_token=None, idle_wait=0.0005):
        results = {}
        while True:
            keep = tick() is not False if tick else True
            while self._pending and len(self._active) < self.slots:
                rid, p, mnt = self._pending.popleft()
                self._active[rid] = (p, [], mnt)
            if not self._active:
                if not self._pending:
                    if tick is None or not keep:
                        break
                    time.sleep(idle_wait)
                continue
            for rid in list(self._active):
                p, out, mnt = self._active[rid]
                t = (sum(p) + len(out)) % 97
                out.append(t)
                if on_token:
                    on_token(rid, t)
                if len(out) >= mnt:
                    full = list(p) + out
                    results[rid] = full
                    del self._active[rid]
                    if on_finish:
                        on_finish(rid, full)
        return results


def expected_tokens(prompt, mnt):
    out = list(prompt)
    for i in range(mnt):
        out.append((sum(prompt) + i) % 97)
    return out


# ---------------------------------------------------------------------------
# the work plane
# ---------------------------------------------------------------------------


class TestOfflineWorkQueue:
    def test_submit_chunks_and_is_idempotent(self, tmp_path):
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=2)
        n = q.submit("job-a", [[1, 2], [3], [4, 5], [6], [7]], 8)
        assert n == 3
        assert q.backlog() == 3
        # Same id + same prompts: a no-op (req-id-keyed dedupe).
        assert q.submit("job-a", [[1, 2], [3], [4, 5], [6], [7]], 8) == 3
        assert q.backlog() == 3
        # Same id + DIFFERENT prompts: refused loudly.
        with pytest.raises(ValueError):
            q.submit("job-a", [[9]], 8)
        with pytest.raises(ValueError):
            q.submit("job-b", [], 8)

    def test_complete_is_exactly_once(self, tmp_path):
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=2)
        q.submit("j", [[1], [2], [3]], 4)
        c = q.lease()
        results = {rid: [1, 2, 3] for rid in c.request_ids}
        assert q.complete(c.chunk_id, results) is True
        # The replayed completion dedupes: no double count, no write.
        assert q.complete(c.chunk_id, results) is False
        assert q.result(c.chunk_id) == {
            rid: [1, 2, 3] for rid in c.request_ids
        }
        with pytest.raises(KeyError):
            q.complete("nope/0", {})
        c2 = q.lease()
        with pytest.raises(ValueError):
            q.complete(c2.chunk_id, {})  # missing rids

    def test_reopen_replays_jobs_minus_done(self, tmp_path):
        path = str(tmp_path / "q.jsonl")
        q = OfflineWorkQueue(path, chunk_size=1)
        q.submit("j", [[1], [2], [3]], 4)
        c = q.lease()
        q.complete(c.chunk_id, {rid: [7] for rid in c.request_ids})
        q.lease()  # leased-but-never-completed: scratch state
        q.close()
        q2 = OfflineWorkQueue(path, chunk_size=1)
        st = q2.stats()
        # The done chunk stays done; the dangling lease is pending
        # again — a lease that died with its worker must replay.
        assert st["done"] == 1
        assert st["pending"] == 2
        assert st["leased"] == 0
        assert q2.result(c.chunk_id) == {
            rid: [7] for rid in c.request_ids
        }

    def test_torn_tail_is_truncated_on_reopen(self, tmp_path):
        path = str(tmp_path / "q.jsonl")
        q = OfflineWorkQueue(path, chunk_size=1)
        q.submit("j", [[1], [2]], 4)
        q.close()
        with open(path, "a") as f:
            f.write('{"kind": "chunk", "rid": "j/0", "tok')  # SIGKILL
        q2 = OfflineWorkQueue(path, chunk_size=1)
        assert q2.stats()["pending"] == 2
        # The next append lands on a clean line boundary.
        c = q2.lease()
        q2.complete(c.chunk_id, {rid: [5] for rid in c.request_ids})
        q3 = OfflineWorkQueue(path, chunk_size=1)
        assert q3.stats()["done"] == 1

    def test_compaction_never_resurrects_completed_work(self, tmp_path):
        """The reopen-after-compaction law: a fully-complete job is
        retired to a job_done tombstone — its chunks must NOT come
        back pending (re-leasing acknowledged work is the exactly-once
        violation), resubmit stays a no-op, and a very late replayed
        completion still dedupes instead of raising."""
        path = str(tmp_path / "q.jsonl")
        # max_records=8 -> compaction triggers past 8 + 64 done records.
        q = OfflineWorkQueue(path, chunk_size=1, max_records=8)
        n_jobs = 80
        for j in range(n_jobs):
            q.submit(f"job{j:03d}", [[j]], 2)
        while True:
            c = q.lease()
            if c is None:
                break
            q.complete(c.chunk_id, {
                rid: expected_tokens(c.prompts[0], 2)
                for rid in c.request_ids
            })
        st = q.stats()
        # Compaction fired once at done == 8 + 64, retiring the 64
        # oldest complete jobs down to max_records; the 8 completions
        # after it stay journaled in full.
        assert st["retired_jobs"] == 64
        assert st["done"] == 16
        assert st["jobs"] + st["retired_jobs"] == n_jobs
        q.close()
        q2 = OfflineWorkQueue(path, chunk_size=1, max_records=8)
        st2 = q2.stats()
        assert st2["pending"] == 0, (
            "compacted-away completions came back pending: completed "
            "chunks would re-execute after a restart")
        assert q2.lease() is None
        assert q2.drained()
        # Progress survives the tombstone; resubmit is still a no-op
        # (and a changed payload under a retired id still refuses).
        assert q2.job_progress("job000") == (1, 1)
        assert q2.submit("job000", [[0]], 2) == 1
        assert q2.stats()["pending"] == 0
        with pytest.raises(ValueError):
            q2.submit("job000", [[999]], 2)
        # A replay that raced past compaction dedupes, never KeyErrors.
        assert q2.complete(
            "job000/0", {"job000/0#0": expected_tokens([0], 2)}
        ) is False
        # Only the PAYLOAD ages out past the retention cap.
        assert q2.result("job000/0") is None

    def test_views_race_free_against_submit(self, tmp_path):
        """job_progress()/result() take the lock: polling them while
        another thread submits must never see a mid-mutation dict
        ('dictionary changed size during iteration')."""
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=1)
        q.submit("warm", [[1], [2]], 2)
        errors = []

        def poll():
            try:
                for _ in range(400):
                    q.job_progress("warm")
                    q.result("warm/0")
            except Exception as e:  # noqa: BLE001 - the test's assert
                errors.append(e)

        th = threading.Thread(target=poll)
        th.start()
        for j in range(60):
            q.submit(f"j{j}", [[j], [j + 1], [j + 2]], 2)
        th.join(timeout=10.0)
        assert not th.is_alive()
        assert errors == []

    def test_requeue_goes_to_front_preempt_picks_youngest(
            self, tmp_path):
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=1)
        q.submit("j", [[1], [2], [3]], 4)
        a = q.lease()
        b = q.lease()
        # preempt-youngest: b is the newest lease, the least sunk cost.
        assert q.preempt_youngest() == b.chunk_id
        assert q.lease().chunk_id == b.chunk_id  # requeued to the FRONT
        assert q.requeue(a.chunk_id) is True
        assert q.lease().chunk_id == a.chunk_id
        assert q.requeue("never-leased/0") is False


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------


class TestOfflineRunner:
    def test_runs_queue_to_drained_with_correct_tokens(self, tmp_path):
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=2)
        q.submit("a", [[1, 2], [3], [4]], 5)
        q.submit("b", [[9]], 3)
        srv = FakeOfflineServer(slots=4)
        r = OfflineRunner(srv, q, "ow0")
        row = r.run()
        assert q.drained()
        assert row["chunks_done"] == 3
        assert q.job_progress("a") == (2, 2)
        assert q.job_progress("b") == (1, 1)
        got = q.result("a/0")
        assert got["a/0#0"] == expected_tokens([1, 2], 5)
        assert got["a/0#1"] == expected_tokens([3], 5)

    def test_chunk_kill_replays_exactly_once(self, tmp_path):
        from dlrover_tpu import chaos

        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=2)
        q.submit("a", [[1, 2], [3], [4], [5]], 6)
        chaos.configure("offline.chunk_kill:p=1,times=1,seed=3")
        try:
            srv = FakeOfflineServer(slots=4)
            r = OfflineRunner(srv, q, "ow0")
            row = r.run()
        finally:
            chaos.reset()
        # The killed chunk replayed: every chunk completed exactly
        # once, the kill cost a requeue, never a lost or doubled chunk.
        assert row["chunk_kills"] == 1
        assert row["chunks_done"] == 2
        assert q.drained()
        assert q.stats()["requeues"] == 1
        assert q.result("a/0")["a/0#0"] == expected_tokens([1, 2], 6)
        assert q.result("a/1")["a/1#1"] == expected_tokens([5], 6)

    def test_replayed_completion_dedupes_across_workers(self, tmp_path):
        """A chunk completed by a crashed worker's replay must not
        double-count when a second worker re-executes it (the journal
        record, not the partials, owns exactly-once)."""
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=1)
        q.submit("a", [[1]], 4)
        c = q.lease()
        q.complete(c.chunk_id, {
            rid: expected_tokens([1], 4) for rid in c.request_ids
        })
        # Simulate the raced worker: the same chunk leased elsewhere
        # (pre-crash) finishes late — requeue then re-run.
        q.submit("a", [[1]], 4)  # idempotent; chunk already done
        srv = FakeOfflineServer()
        r = OfflineRunner(srv, q, "ow1")
        row = r.run()
        assert row["chunks_done"] == 0  # dedupe hit, not a fresh chunk
        assert q.drained()

    def test_reclaim_commits_a_fully_decoded_chunk(self, tmp_path):
        """The reclaim tick commits a chunk whose decode finished in
        the previous round (one local fsync, inside the round bound)
        instead of discarding it for another worker to re-decode."""
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=2)
        q.submit("a", [[1, 2], [3]], 4)
        srv = FakeOfflineServer(slots=4)
        r = OfflineRunner(srv, q, "ow0", stop_when_drained=False)
        # Drive the incremental surface by hand so the interleaving is
        # deterministic: the first tick leases the chunk...
        assert r._tick() is True
        assert r.busy
        chunk = r._chunk
        # ...the server finishes every request within the round...
        for rid, prompt in zip(chunk.request_ids, chunk.prompts):
            r._on_finish(rid, expected_tokens(list(prompt), 4))
        # ...and the reclaim lands before the next commit tick.
        r.request_reclaim()
        assert r._tick() is False
        assert r.reclaim_rounds is not None
        assert r.reclaim_rounds <= 1
        assert r.chunks_done == 1
        assert q.backlog() == 0  # committed, not requeued for replay
        assert q.stats()["leased"] == 0
        got = q.result(chunk.chunk_id)
        assert got[chunk.request_ids[0]] == expected_tokens([1, 2], 4)
        assert got[chunk.request_ids[1]] == expected_tokens([3], 4)

    def test_instant_reclaim_within_one_round(self, tmp_path):
        """The hard bound: request_reclaim -> the loop drains at the
        NEXT tick (<= 1 decode round), chunk requeued intact."""
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=2)
        # Effectively-infinite decode: the reclaim MUST land mid-chunk.
        q.submit("a", [[1, 2], [3]], 10**6)
        srv = FakeOfflineServer(slots=4)
        r = OfflineRunner(srv, q, "ow0", stop_when_drained=False)
        th = threading.Thread(target=r.run)
        th.start()
        deadline = time.monotonic() + 5.0
        while not r.busy and time.monotonic() < deadline:
            time.sleep(0.001)
        assert r.busy
        r.request_reclaim()
        th.join(timeout=5.0)
        assert not th.is_alive()
        assert r.reclaim_rounds is not None
        assert r.reclaim_rounds <= 1
        # Zero lost work: the chunk is pending again, nothing done.
        assert q.backlog() == 1
        assert q.stats()["leased"] == 0
        assert r.chunks_done == 0


# ---------------------------------------------------------------------------
# priority classes in the fleet core
# ---------------------------------------------------------------------------


class _StubOnlineRole(RoleAdapter):
    """A borrower that grows instantly; counts grants."""

    def __init__(self, name="online", max_count=8):
        super().__init__(RoleSpec(name=name, desired=2, min_count=1,
                                  max_count=max_count))
        self.count = 2
        self.grants = 0

    def observe(self):
        return RoleStatus(
            members=tuple(f"on{i}" for i in range(self.count)))

    def spawn(self, n):
        self.count += n
        return n

    def grow_one(self):
        if super().grow_one():
            self.grants += 1
            return True
        return False

    def begin_drain(self):
        if self.count <= self.spec.min_count:
            return None
        self.count -= 1
        return f"on{self.count}"


class _StubLenderRole(RoleAdapter):
    """A non-preemptible lender with a one-pass drain (cooldown
    contrast fixture)."""

    def __init__(self):
        super().__init__(RoleSpec(name="idle", desired=4, min_count=0,
                                  max_count=8))
        self.count = 4
        self._draining = 0

    def observe(self):
        return RoleStatus(
            members=tuple(f"i{i}" for i in range(self.count)))

    def spawn(self, n):
        self.count += n
        return n

    def begin_drain(self):
        if self.count <= 0:
            return None
        self.count -= 1
        self._draining = 1
        return "i"

    def drain_pending(self):
        return self._draining > 0

    def pump_drain(self):
        self._draining = max(0, self._draining - 1)


class TestOfflineRoleFleet:
    def _spiky_arbiter(self, lender, borrower):
        sig = {"queue_depth": 1000, "members_alive": borrower.count}
        arb = ChipBorrowArbiter(
            lender=lender, borrower=borrower,
            policy=BorrowPolicy(
                queue_high_per_member=8.0, spike_patience=1,
                queue_low_per_member=1.0, decay_patience=1,
                max_borrow=4, cooldown_passes=3,
            ),
            signal_fn=lambda: dict(sig),
        )
        return arb, sig

    def test_arbiter_reclaims_offline_chip_within_one_round(
            self, tmp_path):
        """The loopback fleet unit: a REAL arbiter, a REAL OfflineRole
        over a REAL runner mid-chunk.  The assertion is on rounds
        elapsed — decode rounds AND arbiter passes — before the chip
        is granted to online work."""
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=2)
        q.submit("a", [[1, 2], [3]], 10**6)
        srv = FakeOfflineServer(slots=4)
        runner = OfflineRunner(srv, q, "ow0", stop_when_drained=False)
        workers = {"ow0": runner}
        role = OfflineRole(
            RoleSpec(name="offline", desired=1, min_count=0,
                     max_count=4),
            workers_fn=lambda: workers,
            spawn_fn=lambda n: n,
            queue=q,
            policy=OfflinePolicy(),
        )
        online = _StubOnlineRole()
        arb, sig = self._spiky_arbiter(role, online)
        th = threading.Thread(target=runner.run)
        th.start()
        try:
            deadline = time.monotonic() + 5.0
            while not runner.busy and time.monotonic() < deadline:
                time.sleep(0.001)
            assert runner.busy
            assert arb.step() == LENDING  # spike: reclaim requested
            # The drain must complete within ONE decode round of the
            # worker loop: wait for the loop to exit, then ONE more
            # arbiter pass grants the chip.
            th.join(timeout=5.0)
            assert not th.is_alive()
            assert runner.reclaim_rounds is not None
            assert runner.reclaim_rounds <= 1
            passes_in_lending = 0
            while arb.phase == LENDING:
                passes_in_lending += 1
                assert passes_in_lending <= 1, (
                    "arbiter stuck LENDING past the one-round bound")
                arb.step()
            assert arb.phase == BORROWED
            assert online.grants == 1
            # The preempted chunk survived intact.
            assert q.backlog() == 1
        finally:
            runner.request_reclaim()
            th.join(timeout=1.0)

    def test_offline_role_bids_zero_whatever_the_backlog(
            self, tmp_path):
        q = OfflineWorkQueue(str(tmp_path / "q.jsonl"), chunk_size=1)
        q.submit("a", [[n] for n in range(50)], 4)
        role = OfflineRole(
            RoleSpec(name="offline", desired=0, min_count=0,
                     max_count=4),
            workers_fn=dict, spawn_fn=lambda n: n,
            queue=q, policy=OfflinePolicy(),
        )
        st = role.observe()
        assert st.signals["queue_depth"] == 0
        assert st.signals["offline_backlog"] == 50
        assert OfflinePolicy().borrow_bid() == 0

    def test_cooldown_exempt_for_preemptible_lender_only(self):
        """The ISSUE 20 small fix: a borrow cycle that reclaims FROM
        the offline tier charges no cooldown — the next spike borrows
        immediately; the same cycle against an SLO lender still
        cools down."""
        for preemptible, expect_relend in ((True, True), (False, False)):
            lender = _StubLenderRole()
            lender.preemptible = preemptible
            online = _StubOnlineRole()
            arb, sig = self._spiky_arbiter(lender, online)
            assert arb.step() == LENDING
            lender.pump_drain()
            assert arb.step() == BORROWED
            # Decay: hand the chip back (borrower drains instantly).
            sig["queue_depth"] = 0
            assert arb.step() == "reclaiming"
            assert arb.step() == IDLE  # borrower drain done; reclaimed
            # Spike again at the very next pass.
            sig["queue_depth"] = 1000
            phase = arb.step()
            if expect_relend:
                assert phase == LENDING, (
                    "preemptible reclaim must not impose a cooldown")
            else:
                assert phase == IDLE, (
                    "SLO-lender reclaim must keep its cooldown")

    def test_offline_role_policy_target_soaks_idle(self):
        role = OfflineRole(
            RoleSpec(name="offline", desired=0, min_count=0,
                     max_count=64),
            workers_fn=dict, spawn_fn=lambda n: n,
            policy=OfflinePolicy(reserve_chips=2),
            idle_chips_fn=lambda: 10,
        )
        status = role.observe()
        # 10 idle - 2 reserve = 8 workers, capped by backlog.
        assert role._policy.target_workers(10, 100) == 8
        assert role._policy.target_workers(10, 3) == 3
        assert role._policy.target_workers(10, 100,
                                           online_pressure=True) == 0
        # Faster chips need fewer workers for the same backlog.
        assert role._policy.target_workers(10, 8, speed_weight=2.0) == 4
        assert role.policy_target(status) == 0  # empty queue: nothing


# ---------------------------------------------------------------------------
# honest economics: speed weights
# ---------------------------------------------------------------------------


class TestSpeedWeights:
    def test_chip_speed_weight_map_and_overrides(self):
        from dlrover_tpu.scheduler.platform import chip_speed_weight

        assert chip_speed_weight("v4") == 1.0
        assert chip_speed_weight("v6e") > chip_speed_weight("v5p") > 1.0
        assert chip_speed_weight("v5e") < 1.0
        assert chip_speed_weight("") == 1.0
        assert chip_speed_weight("tpu-v9-future") == 1.0
        assert chip_speed_weight("v5e", overrides={"v5e": 1.5}) == 1.5

    def test_target_workers_fractional_weight_precision(self):
        pol = OfflinePolicy(chunks_per_worker=1)
        # ceil(8 / 2.7) = 3 — truncating the divisor to int said 4.
        assert pol.target_workers(100, 8, speed_weight=2.7) == 3
        # A weight below 2 must still bite: ceil(10 / 1.9) = 6.
        assert pol.target_workers(100, 10, speed_weight=1.9) == 6
        # Integer weights and weight 1.0 are exactly the old answers.
        assert pol.target_workers(100, 8, speed_weight=2.0) == 4
        assert pol.target_workers(100, 8) == 8

    def test_decide_judges_queue_per_weighted_replica(self):
        from dlrover_tpu.serving.autoscale import (
            ScalePolicy,
            ScaleState,
            decide,
        )

        pol = ScalePolicy(queue_high_per_replica=4.0, up_patience=1,
                          max_replicas=10)
        # 10 queued over 2 unweighted replicas: pressure (5 > 4).
        assert decide({"replicas_alive": 2, "queue_depth": 10},
                      pol, ScaleState()) == 3
        # The same depth over v6e-weighted replicas: no pressure
        # (10 / (2 * 2.7) < 4) — fast chips absorb more queue.
        assert decide({"replicas_alive": 2, "queue_depth": 10,
                       "speed_weight": 2.7}, pol, ScaleState()) == 2
        # Weight 1.0 is EXACTLY the old behavior.
        assert decide({"replicas_alive": 2, "queue_depth": 10,
                       "speed_weight": 1.0}, pol, ScaleState()) == 3

    def test_decide_pools_carries_pool_speed_weight(self):
        from dlrover_tpu.serving.autoscale import (
            ScalePolicy,
            decide_pools,
        )

        pol = {"decode": ScalePolicy(queue_high_per_replica=4.0,
                                     up_patience=1, max_replicas=10)}
        snap = {"pools": {"decode": {
            "alive": 2, "queue_depth": 10, "occupancy": 0.9,
            "speed_weight": 2.7,
        }}}
        assert decide_pools(snap, pol, {}) == {"decode": 2}
        snap["pools"]["decode"].pop("speed_weight")
        assert decide_pools(snap, pol, {}) == {"decode": 3}

    def test_place_roles_weighted_ordering(self):
        from dlrover_tpu.cells.federation import place_roles

        cells = {
            "a": {"capacity": 100},                       # v4
            "b": {"capacity": 64, "speed_weight": 2.7},   # v6e
        }
        out = place_roles(cells, {"serving": 1, "training": 60})
        # Spread visits the fastest cell first; pack ranks by
        # weighted capacity (64 * 2.7 > 100 * 1.0).
        assert out["serving"] == {"b": 1}
        assert out["training"]["b"] == 60

    def test_place_roles_unweighted_is_byte_compatible(self):
        from dlrover_tpu.cells.federation import place_roles

        cells_plain = {"a": {"capacity": 6}, "b": {"capacity": 4}}
        cells_w1 = {
            "a": {"capacity": 6, "speed_weight": 1.0},
            "b": {"capacity": 4, "speed_weight": 1.0},
        }
        demands = {"serving": 3, "training": 5, "master": 2}
        assert place_roles(cells_plain, demands) == \
            place_roles(cells_w1, demands)
