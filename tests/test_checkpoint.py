"""Flash-checkpoint tests: flatten/assemble (resharding), engine save/load,
shard-file commit protocol, agent saver breakpoint save."""

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.checkpoint import shard_file, tree_utils
from dlrover_tpu.checkpoint.checkpointer import FlashCheckpointer
from dlrover_tpu.common.shm import SharedMemoryArena, arena_name
from dlrover_tpu.common.storage import PosixDiskStorage


@pytest.fixture()
def mesh(cpu_mesh_devices):
    return Mesh(np.array(cpu_mesh_devices[:8]).reshape(4, 2), ("dp", "tp"))


class TestTreeUtils:
    def test_flatten_replicated_and_sharded(self, mesh):
        repl = NamedSharding(mesh, P())
        sharded = NamedSharding(mesh, P("dp", "tp"))
        state = {
            "w": jax.device_put(jnp.arange(64.0).reshape(8, 8), sharded),
            "b": jax.device_put(jnp.ones(4), repl),
            "step": np.int64(7),
        }
        tensors, info = tree_utils.flatten_to_shards(state)
        # Replicated leaf -> 1 shard; (4,2)-sharded 8x8 -> 8 unique shards.
        w_keys = [k for k in tensors if "'w'" in k]
        b_keys = [k for k in tensors if "'b'" in k]
        assert len(w_keys) == 8 and len(b_keys) == 1
        assert info[b_keys[0]]["global_shape"] == [4]

    def test_assemble_exact_and_reshard(self, mesh):
        sharded = NamedSharding(mesh, P("dp", None))
        x = jax.device_put(jnp.arange(32.0).reshape(8, 4), sharded)
        tensors, info = tree_utils.flatten_to_shards({"x": x})
        source = tree_utils.ShardSource()
        source.add(tensors, info)
        path = next(iter(source.pieces))
        # Exact shard.
        got = source.assemble(path, ((0, 2), (0, 4)))
        np.testing.assert_array_equal(got, np.arange(8.0).reshape(2, 4))
        # Resharded region spanning two original shards.
        got2 = source.assemble(path, ((1, 3), (0, 4)))
        np.testing.assert_array_equal(
            got2, np.arange(32.0).reshape(8, 4)[1:3]
        )
        # Full array.
        got3 = source.assemble(path, ((0, 8), (0, 4)))
        np.testing.assert_array_equal(got3, np.arange(32.0).reshape(8, 4))
        # Uncovered region -> None.
        assert source.assemble(path, ((0, 9), (0, 4))) is None

    def test_restore_to_new_sharding(self, mesh):
        """Save under (dp)-sharding, restore under (tp)-style sharding —
        the Tenplex-style reshard-on-restore."""
        s1 = NamedSharding(mesh, P("dp", None))
        x = jax.device_put(jnp.arange(64.0).reshape(8, 8), s1)
        tensors, info = tree_utils.flatten_to_shards({"x": x})
        source = tree_utils.ShardSource()
        source.add(tensors, info)
        s2 = NamedSharding(mesh, P("tp", "dp"))
        target = {"x": jax.device_put(jnp.zeros((8, 8)), s2)}
        restored = tree_utils.restore_to_target(target, source)
        np.testing.assert_array_equal(
            np.asarray(restored["x"]), np.arange(64.0).reshape(8, 8)
        )
        assert restored["x"].sharding == s2


class TestShardFile:
    def test_pack_unpack(self):
        tensors = {
            "a|0": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b|0": np.array([True, False]),
        }
        blob = shard_file.pack_shard(tensors, {"step": 3})
        out, extra = shard_file.unpack_shard(blob)
        assert extra["step"] == 3
        np.testing.assert_array_equal(out["a|0"], tensors["a|0"])
        np.testing.assert_array_equal(out["b|0"], tensors["b|0"])

    def test_uncommitted_step_restorable_when_covered(self, tmp_path, monkeypatch):
        """A breakpoint save from a partial world (no commit) must still
        restore when its shards cover the target (replicated layout)."""
        monkeypatch.setenv("DLROVER_TPU_JOB_NAME", "ckpt-unc")
        monkeypatch.setenv("DLROVER_TPU_RUN_ID", "unc1")
        monkeypatch.setenv("DLROVER_TPU_PROCESS_ID", "0")
        monkeypatch.setenv("DLROVER_TPU_NUM_PROCESSES", "1")
        from dlrover_tpu.checkpoint.engine import CheckpointEngine

        # Committed step 10 and an uncommitted (newer) step 20 whose one
        # shard fully covers the replicated tensor.
        for step, val, commit_it in ((10, 1.0, True), (20, 2.0, False)):
            tensors = {"['w']|0": np.full(4, val, np.float32)}
            extra = {
                "step": step,
                "meta": {"step": step},
                "tensors_info": {
                    "['w']|0": {
                        "path": "['w']",
                        "global_shape": [4],
                        "index": [[0, 4]],
                    }
                },
                "num_processes": 1,
                "process_id": 0,
            }
            shard_file.write_shard(
                PosixDiskStorage(), str(tmp_path), step, 0, tensors, extra
            )
            if commit_it:
                shard_file.commit(PosixDiskStorage(), str(tmp_path), step)
        eng = CheckpointEngine(str(tmp_path), job_name="ckpt-unc")
        try:
            got = eng.load(target={"w": np.zeros(4, np.float32)})
            assert got is not None
            state, meta = got
            # Committed step wins (deterministic across ranks) ...
            assert meta["step"] == 10
            np.testing.assert_array_equal(state["w"], np.full(4, 1.0))
            # ... but with no tracker at all, the newest covered step is
            # used.
            import os as _os

            _os.unlink(shard_file.tracker_path(str(tmp_path)))
            got2 = eng.load(target={"w": np.zeros(4, np.float32)})
            assert got2[1]["step"] == 20
            np.testing.assert_array_equal(got2[0]["w"], np.full(4, 2.0))
        finally:
            eng.close()

    def test_truncated_shard_raises_typed_error(self, tmp_path):
        """Regression (ISSUE 3): a truncated on-disk shard used to
        surface as raw struct.error/ValueError from unpack; every damage
        mode is now one typed ShardCorruptionError."""
        storage = PosixDiskStorage()
        d = str(tmp_path)
        shard_file.write_shard(storage, d, 10, 0, {"x|0": np.ones(3)}, {})
        path = shard_file.shard_path(d, 10, 0)
        with open(path, "rb") as f:
            raw = f.read()
        for cut in (0, 7, 18, len(raw) - 2):
            with open(path, "wb") as f:
                f.write(raw[:cut])
            with pytest.raises(shard_file.ShardCorruptionError):
                shard_file.read_shard(storage, d, 10, 0)

    def test_pack_unpack_zero_d(self):
        # Regression: np.ascontiguousarray promotes 0-d to (1,); a restored
        # scalar (e.g. optimizer step count) must stay 0-d or
        # make_array_from_single_device_arrays rejects the shard.
        tensors = {"count|0": np.asarray(np.int32(7))}
        out, _ = shard_file.unpack_shard(shard_file.pack_shard(tensors, {}))
        assert out["count|0"].shape == ()
        assert out["count|0"] == 7

    def test_commit_protocol(self, tmp_path):
        storage = PosixDiskStorage()
        d = str(tmp_path)
        shard_file.write_shard(storage, d, 10, 0, {"x|0": np.ones(3)}, {})
        assert not shard_file.all_shards_done(storage, d, 10, 2)
        assert shard_file.latest_step(storage, d) is None  # not committed
        shard_file.write_shard(storage, d, 10, 1, {"x|1": np.ones(3)}, {})
        assert shard_file.all_shards_done(storage, d, 10, 2)
        shard_file.commit(storage, d, 10)
        assert shard_file.latest_step(storage, d) == 10
        assert shard_file.list_shard_ids(storage, d, 10) == [0, 1]

    def test_gc_keeps_last(self, tmp_path):
        storage = PosixDiskStorage()
        d = str(tmp_path)
        for step in (1, 2, 3, 4, 5):
            shard_file.write_shard(storage, d, step, 0, {"x|0": np.ones(2)}, {})
            shard_file.commit(storage, d, step, keep_last=2)
        remaining = [n for n in os.listdir(d) if n.startswith("step_")]
        assert len(remaining) == 2


class TestEngineStandalone:
    def test_save_load_memory_and_storage(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_JOB_NAME", "ckpt-ut")
        monkeypatch.setenv("DLROVER_TPU_PROCESS_ID", "0")
        monkeypatch.setenv("DLROVER_TPU_NUM_PROCESSES", "1")
        ckpt = FlashCheckpointer(str(tmp_path), job_name="ckpt-ut")
        state = {
            "params": {"w": jnp.arange(16.0).reshape(4, 4)},
            "count": jnp.array(3),
        }
        ckpt.save(state, meta={"step": 5})  # memory only
        restored, meta = ckpt.load(target=state)
        assert meta["step"] == 5
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["w"]), np.arange(16.0).reshape(4, 4)
        )
        # Storage save + wait -> tracker advanced.
        ckpt.save(state, meta={"step": 6}, storage=True)
        assert ckpt.wait(timeout=60)
        assert shard_file.latest_step(PosixDiskStorage(), str(tmp_path)) == 6
        ckpt.close()
        # What the engine writes is fsck-clean (CRCs, done votes,
        # tracker, coverage).
        from dlrover_tpu.checkpoint import fsck

        report = fsck.fsck(str(tmp_path))
        assert not report.damaged, report.findings

    def test_cold_restore_from_storage(self, tmp_path, monkeypatch):
        """Simulates full host restart: shm gone, restore reads shard files."""
        monkeypatch.setenv("DLROVER_TPU_JOB_NAME", "ckpt-cold")
        ckpt = FlashCheckpointer(str(tmp_path), job_name="ckpt-cold")
        state = {"w": jnp.ones((4, 4)) * 2.5}
        ckpt.save(state, meta={"step": 9}, storage=True)
        assert ckpt.wait(timeout=60)
        ckpt.close()
        # Wipe the shm arena (simulate reboot).
        arena = SharedMemoryArena(arena_name("ckpt-cold", 0))
        arena.close(unlink=True)
        ckpt2 = FlashCheckpointer(str(tmp_path), job_name="ckpt-cold")
        restored, meta = ckpt2.load(target={"w": jnp.zeros((4, 4))})
        assert meta["step"] == 9
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.full((4, 4), 2.5)
        )
        ckpt2.close()


class TestAgentSaver:
    def test_event_persist_and_breakpoint_save(self, tmp_path, monkeypatch):
        from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

        job = "ckpt-agent"
        monkeypatch.setenv("DLROVER_TPU_JOB_NAME", job)
        saver = AsyncCheckpointSaver(job, nproc_per_node=1)
        saver.start()
        try:
            # Engine must auto-detect agent mode now.
            ckpt = FlashCheckpointer(str(tmp_path), job_name=job)
            assert ckpt.engine.agent_mode
            state = {"w": jnp.full((8, 8), 1.5)}
            ckpt.save(state, meta={"step": 4}, storage=True)
            assert ckpt.wait(timeout=60)
            assert shard_file.latest_step(
                PosixDiskStorage(), str(tmp_path)
            ) == 4
            # Stage step 8 in shm only, then breakpoint-save persists it.
            ckpt.save(state, meta={"step": 8})
            saver.save_shm_to_storage("test-breakpoint")
            deadline = time.time() + 60
            while time.time() < deadline:
                if shard_file.latest_step(
                    PosixDiskStorage(), str(tmp_path)
                ) == 8:
                    break
                time.sleep(0.5)
            assert shard_file.latest_step(
                PosixDiskStorage(), str(tmp_path)
            ) == 8
            ckpt.close()
        finally:
            saver.stop()
