"""L0 substrate tests: messages, RPC, node model, storage, context."""

import threading

import pytest

from dlrover_tpu.common import messages as msgs
from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.common.global_context import get_context
from dlrover_tpu.common.node import Node, NodeResource, NodeStatusFlow
from dlrover_tpu.common.rpc import RpcClient, RpcServer, addr_connectable


class TestMessages:
    def test_roundtrip_simple(self):
        m = msgs.JoinRendezvous(node_id=3, node_rank=1, local_world_size=4)
        out = msgs.deserialize(msgs.serialize(m))
        assert out == m

    def test_roundtrip_nested(self):
        hb = msgs.HeartbeatResponse(
            actions=[
                msgs.DiagnosisAction(action_type="restart_worker", reason="hang"),
                msgs.DiagnosisAction(action_type="no_action"),
            ]
        )
        out = msgs.deserialize(msgs.serialize(hb))
        assert isinstance(out, msgs.HeartbeatResponse)
        assert out.actions[0].action_type == "restart_worker"
        assert len(out.actions) == 2

    def test_roundtrip_bytes_and_dict(self):
        m = msgs.KVStoreSet(key="store/rank0", value=b"\x00\x01binary")
        out = msgs.deserialize(msgs.serialize(m))
        assert out.value == b"\x00\x01binary"
        w = msgs.CommWorld(round=2, world={0: {"id": 0}, 1: {"id": 1}})
        out2 = msgs.deserialize(msgs.serialize(w))
        assert out2.world[1]["id"] == 1


class TestRpc:
    def test_server_dispatch_and_retry(self):
        calls = []

        def handler(msg):
            calls.append(msg)
            if isinstance(msg, msgs.TaskRequest):
                return msgs.Task(task_id=7, start=0, end=10)
            return None

        server = RpcServer(0, handler)
        server.start()
        try:
            addr = f"127.0.0.1:{server.port}"
            assert addr_connectable(addr)
            client = RpcClient(addr)
            task = client.call(msgs.TaskRequest(dataset_name="d", worker_id=1))
            assert isinstance(task, msgs.Task)
            assert task.task_id == 7
            # Unknown-handled message -> default success response.
            resp = client.call(msgs.Heartbeat(node_id=1))
            assert isinstance(resp, msgs.BaseResponse) and resp.success
            client.close()
        finally:
            server.stop()
        assert len(calls) == 2

    def test_handler_exception_returns_failure(self):
        def handler(msg):
            raise ValueError("boom")

        server = RpcServer(0, handler)
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            resp = client.call(msgs.Heartbeat())
            assert isinstance(resp, msgs.BaseResponse)
            assert not resp.success and "boom" in resp.reason
            client.close()
        finally:
            server.stop()

    def test_concurrent_calls(self):
        lock = threading.Lock()
        count = [0]

        def handler(msg):
            with lock:
                count[0] += 1
            return msgs.KVStoreCount(value=count[0])

        server = RpcServer(0, handler)
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            threads = [
                threading.Thread(target=lambda: client.call(msgs.Empty()))
                for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert count[0] == 8
            client.close()
        finally:
            server.stop()


class TestNode:
    def test_status_flow(self):
        n = Node("worker", 0)
        n.update_status(NodeStatus.PENDING)
        n.update_status(NodeStatus.RUNNING)
        assert n.status == NodeStatus.RUNNING
        # Illegal transition ignored.
        n.update_status(NodeStatus.PENDING)
        assert n.status == NodeStatus.RUNNING
        n.update_status(NodeStatus.SUCCEEDED)
        assert n.status == NodeStatus.SUCCEEDED
        assert n.finish_time is not None

    def test_status_flow_rules(self):
        assert NodeStatusFlow.is_allowed(NodeStatus.FAILED, NodeStatus.RUNNING)
        assert not NodeStatusFlow.is_allowed(NodeStatus.DELETED, NodeStatus.RUNNING)
        assert not NodeStatusFlow.is_allowed(NodeStatus.RUNNING, NodeStatus.RUNNING)

    def test_relaunch_accounting(self):
        n = Node("worker", 0, max_relaunch_count=2)
        assert not n.is_unrecoverable_failure()
        n.inc_relaunch_count()
        n.inc_relaunch_count()
        assert n.is_unrecoverable_failure()
        succ = n.get_relaunch_node(new_id=5)
        assert succ.id == 5 and succ.rank_index == n.rank_index
        assert succ.relaunch_count == 3

    def test_resource_parse(self):
        r = NodeResource.resource_str_to_node_resource("cpu=4,memory=8192Mi,tpu=8")
        assert r.cpu == 4 and r.memory_mb == 8192 and r.tpu_chips == 8


class TestStorageAndContext:
    def test_posix_storage(self, tmp_path):
        from dlrover_tpu.common.storage import ClassMeta, PosixDiskStorage

        s = PosixDiskStorage()
        p = str(tmp_path / "a" / "f.bin")
        s.safe_makedirs(str(tmp_path / "a"))
        s.write(b"hello", p)
        assert s.read(p) == b"hello"
        assert s.exists(p)
        assert "f.bin" in s.listdir(str(tmp_path / "a"))
        s.safe_remove(p)
        assert not s.exists(p)
        # ClassMeta round-trip builds the same backend.
        built = ClassMeta().build()
        assert isinstance(built, PosixDiskStorage)

    def test_context_singleton_and_update(self):
        ctx = get_context()
        assert ctx is get_context()
        old = ctx.rdzv_timeout
        ctx.update(rdzv_timeout=123.0)
        assert get_context().rdzv_timeout == 123.0
        ctx.update(rdzv_timeout=old)


class TestPublicAPI:
    def test_every_lazy_export_resolves(self):
        """dt.<name> must import for every advertised top-level symbol
        (regression: a stale module path made dt.ElasticTrainer raise
        ModuleNotFoundError)."""
        import dlrover_tpu as dt

        for name in dt._LAZY:
            obj = getattr(dt, name)
            assert obj is not None, name

    def test_unknown_attribute_raises(self):
        import pytest

        import dlrover_tpu as dt

        with pytest.raises(AttributeError):
            dt.does_not_exist


class TestCompilationCache:
    def test_enable_compilation_cache_modes(self, tmp_path, monkeypatch):
        import jax

        from dlrover_tpu.common.jax_env import enable_compilation_cache

        prev = jax.config.jax_compilation_cache_dir
        try:
            monkeypatch.setenv("DLROVER_TPU_COMPILE_CACHE", "0")
            assert enable_compilation_cache() is False

            d = str(tmp_path / "xla")
            monkeypatch.setenv("DLROVER_TPU_COMPILE_CACHE", d)
            assert enable_compilation_cache() is True
            assert jax.config.jax_compilation_cache_dir == d
            assert (tmp_path / "xla").is_dir()

            # A compiled program actually lands in the cache dir.
            jax.jit(lambda x: x * 2 + 1)(jax.numpy.ones((32,))
                                         ).block_until_ready()
            assert any((tmp_path / "xla").iterdir())
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
