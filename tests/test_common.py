"""L0 substrate tests: messages, RPC, node model, storage, context."""

import os
import threading
import time

import grpc
import pytest

from dlrover_tpu.common import messages as msgs
from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.common.global_context import get_context
from dlrover_tpu.common.node import Node, NodeResource, NodeStatusFlow
from dlrover_tpu.common.rpc import (
    ChaosRpcError,
    RpcClient,
    RpcServer,
    addr_connectable,
)


class TestMessages:
    def test_roundtrip_simple(self):
        m = msgs.JoinRendezvous(node_id=3, node_rank=1, local_world_size=4)
        out = msgs.deserialize(msgs.serialize(m))
        assert out == m

    def test_roundtrip_nested(self):
        hb = msgs.HeartbeatResponse(
            actions=[
                msgs.DiagnosisAction(action_type="restart_worker", reason="hang"),
                msgs.DiagnosisAction(action_type="no_action"),
            ]
        )
        out = msgs.deserialize(msgs.serialize(hb))
        assert isinstance(out, msgs.HeartbeatResponse)
        assert out.actions[0].action_type == "restart_worker"
        assert len(out.actions) == 2

    def test_roundtrip_bytes_and_dict(self):
        m = msgs.KVStoreSet(key="store/rank0", value=b"\x00\x01binary")
        out = msgs.deserialize(msgs.serialize(m))
        assert out.value == b"\x00\x01binary"
        w = msgs.CommWorld(round=2, world={0: {"id": 0}, 1: {"id": 1}})
        out2 = msgs.deserialize(msgs.serialize(w))
        assert out2.world[1]["id"] == 1


class TestRpc:
    def test_server_dispatch_and_retry(self):
        calls = []

        def handler(msg):
            calls.append(msg)
            if isinstance(msg, msgs.TaskRequest):
                return msgs.Task(task_id=7, start=0, end=10)
            return None

        server = RpcServer(0, handler)
        server.start()
        try:
            addr = f"127.0.0.1:{server.port}"
            assert addr_connectable(addr)
            client = RpcClient(addr)
            task = client.call(msgs.TaskRequest(dataset_name="d", worker_id=1))
            assert isinstance(task, msgs.Task)
            assert task.task_id == 7
            # Unknown-handled message -> default success response.
            resp = client.call(msgs.Heartbeat(node_id=1))
            assert isinstance(resp, msgs.BaseResponse) and resp.success
            client.close()
        finally:
            server.stop()
        assert len(calls) == 2

    def test_handler_exception_returns_failure(self):
        def handler(msg):
            raise ValueError("boom")

        server = RpcServer(0, handler)
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            resp = client.call(msgs.Heartbeat())
            assert isinstance(resp, msgs.BaseResponse)
            assert not resp.success and "boom" in resp.reason
            client.close()
        finally:
            server.stop()

    def test_concurrent_calls(self):
        lock = threading.Lock()
        count = [0]

        def handler(msg):
            with lock:
                count[0] += 1
            return msgs.KVStoreCount(value=count[0])

        server = RpcServer(0, handler)
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            threads = [
                threading.Thread(target=lambda: client.call(msgs.Empty()))
                for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert count[0] == 8
            client.close()
        finally:
            server.stop()


def _fake_client(responses):
    """An RpcClient whose channel is scripted: each entry in ``responses``
    is either an exception to raise or bytes to return.  No real server."""
    client = RpcClient("127.0.0.1:1")
    attempts = []

    def fake_call(data, timeout=None):
        attempts.append(timeout)
        item = responses.pop(0)
        if isinstance(item, Exception):
            raise item
        return item

    client._call = fake_call
    return client, attempts


class TestRpcRetryPolicy:
    """The retry contract itself, against a scripted channel: UNAVAILABLE
    retried under jittered-bounded backoff, DEADLINE_EXCEEDED only for
    idempotent calls, exhausted retries re-raise the LAST error, and the
    total deadline budget caps the loop."""

    def _unavailable(self):
        return ChaosRpcError(grpc.StatusCode.UNAVAILABLE, "test")

    def _deadline(self):
        return ChaosRpcError(grpc.StatusCode.DEADLINE_EXCEEDED, "test")

    def test_unavailable_retried_with_bounded_backoff(self, monkeypatch):
        ok = msgs.serialize(msgs.BaseResponse(success=True))
        client, attempts = _fake_client(
            [self._unavailable(), self._unavailable(),
             self._unavailable(), ok]
        )
        sleeps = []
        monkeypatch.setattr(
            "dlrover_tpu.common.rpc.time.sleep", sleeps.append
        )
        resp = client.call(msgs.Heartbeat(), retries=5, backoff=0.5)
        assert isinstance(resp, msgs.BaseResponse) and resp.success
        assert len(attempts) == 4
        assert len(sleeps) == 3
        for i, s in enumerate(sleeps):
            base = min(0.5 * (2**i), 8.0)
            # Half-jittered exponential: within [base/2, base], capped.
            assert 0.5 * base <= s <= base

    def test_deadline_exceeded_not_retried(self, monkeypatch):
        client, attempts = _fake_client([self._deadline()])
        monkeypatch.setattr(
            "dlrover_tpu.common.rpc.time.sleep", lambda s: None
        )
        with pytest.raises(grpc.RpcError):
            client.call(msgs.KVStoreSet(key="k", value=b"v"), retries=5)
        assert len(attempts) == 1  # the request may have executed: no resend

    def test_deadline_exceeded_retried_when_idempotent(self, monkeypatch):
        ok = msgs.serialize(msgs.BaseResponse(success=True))
        client, attempts = _fake_client([self._deadline(), ok])
        monkeypatch.setattr(
            "dlrover_tpu.common.rpc.time.sleep", lambda s: None
        )
        resp = client.call(
            msgs.KVStoreGet(key="k"), retries=5, idempotent=True
        )
        assert isinstance(resp, msgs.BaseResponse)
        assert len(attempts) == 2

    def test_exhausted_retries_reraise_last_error(self, monkeypatch):
        errs = [self._unavailable() for _ in range(3)]
        client, attempts = _fake_client(list(errs))
        monkeypatch.setattr(
            "dlrover_tpu.common.rpc.time.sleep", lambda s: None
        )
        with pytest.raises(grpc.RpcError) as ei:
            client.call(msgs.Heartbeat(), retries=3, backoff=0.001)
        assert ei.value is errs[-1]
        assert len(attempts) == 3

    def test_other_codes_raise_immediately(self, monkeypatch):
        err = ChaosRpcError(grpc.StatusCode.INTERNAL, "boom")
        client, attempts = _fake_client([err])
        with pytest.raises(grpc.RpcError):
            client.call(msgs.Heartbeat(), retries=5)
        assert len(attempts) == 1

    def test_deadline_budget_caps_retries(self, monkeypatch):
        """With a tiny total budget the loop stops early even though
        ``retries`` remain — and still raises the transport error."""
        client, attempts = _fake_client(
            [self._unavailable() for _ in range(10)]
        )
        with pytest.raises(grpc.RpcError):
            client.call(
                msgs.Heartbeat(), retries=10, backoff=0.05, deadline=0.08
            )
        assert len(attempts) < 10

    def test_per_attempt_timeout_clamped_to_budget(self):
        ok = msgs.serialize(msgs.BaseResponse(success=True))
        client, attempts = _fake_client([ok])
        client.call(msgs.Heartbeat(), timeout=500.0, deadline=2.0)
        assert attempts[0] <= 2.0

    def test_default_budget_never_shortens_explicit_timeout(self):
        """A caller-configured timeout beyond DEFAULT_DEADLINE must get
        its full window (the default budget stretches to cover it)."""
        ok = msgs.serialize(msgs.BaseResponse(success=True))
        client, attempts = _fake_client([ok])
        client.call(msgs.Heartbeat(), timeout=120.0)
        assert attempts[0] > 60.0


class TestRpcReconnect:
    def test_reconnect_survives_server_restart_on_same_port(self):
        from dlrover_tpu.common.rpc import find_free_port

        port = find_free_port()
        s1 = RpcServer(port, lambda m: msgs.BaseResponse(success=True))
        s1.start()
        client = RpcClient(f"127.0.0.1:{port}")
        try:
            assert client.call(msgs.Heartbeat()).success
            s1.stop(grace=0.1)
            s2 = RpcServer(port, lambda m: msgs.BaseResponse(success=True))
            s2.start()
            try:
                # A rebuilt channel must reach the new incarnation even if
                # the old one is sulking in reconnect backoff.
                client.reconnect(force=True)
                resp = client.call(msgs.Heartbeat(), backoff=0.05)
                assert resp.success
            finally:
                s2.stop()
        finally:
            client.close()


class TestDeadlineClamps:
    def test_addr_connectable_respects_deadline(self):
        from dlrover_tpu.common.rpc import find_free_port

        port = find_free_port()  # nothing listens here: instant refusal
        t0 = time.perf_counter()
        assert not addr_connectable(f"127.0.0.1:{port}", timeout=0.6)
        # The old loop slept a fixed 0.5s past the deadline; the clamp
        # keeps total time near the budget.
        assert time.perf_counter() - t0 < 1.5

    def test_barrier_poll_clamped(self, monkeypatch):
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient.__new__(MasterClient)
        monkeypatch.setattr(
            client, "join_sync", lambda *a, **k: None, raising=False
        )
        monkeypatch.setattr(
            client, "sync_finished", lambda *a, **k: False, raising=False
        )
        t0 = time.perf_counter()
        assert client.barrier("b", timeout=0.3) is False
        assert time.perf_counter() - t0 < 0.8

    def test_kv_wait_get_clamped(self, monkeypatch):
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient.__new__(MasterClient)
        monkeypatch.setattr(
            client, "kv_store_get", lambda *a, **k: None, raising=False
        )
        t0 = time.perf_counter()
        assert client.kv_store_wait_get("k", timeout=0.3, poll=0.2) is None
        assert time.perf_counter() - t0 < 0.8


class TestNode:
    def test_status_flow(self):
        n = Node("worker", 0)
        n.update_status(NodeStatus.PENDING)
        n.update_status(NodeStatus.RUNNING)
        assert n.status == NodeStatus.RUNNING
        # Illegal transition ignored.
        n.update_status(NodeStatus.PENDING)
        assert n.status == NodeStatus.RUNNING
        n.update_status(NodeStatus.SUCCEEDED)
        assert n.status == NodeStatus.SUCCEEDED
        assert n.finish_time is not None

    def test_status_flow_rules(self):
        assert NodeStatusFlow.is_allowed(NodeStatus.FAILED, NodeStatus.RUNNING)
        assert not NodeStatusFlow.is_allowed(NodeStatus.DELETED, NodeStatus.RUNNING)
        assert not NodeStatusFlow.is_allowed(NodeStatus.RUNNING, NodeStatus.RUNNING)

    def test_relaunch_accounting(self):
        n = Node("worker", 0, max_relaunch_count=2)
        assert not n.is_unrecoverable_failure()
        n.inc_relaunch_count()
        n.inc_relaunch_count()
        assert n.is_unrecoverable_failure()
        succ = n.get_relaunch_node(new_id=5)
        assert succ.id == 5 and succ.rank_index == n.rank_index
        assert succ.relaunch_count == 3

    def test_resource_parse(self):
        r = NodeResource.resource_str_to_node_resource("cpu=4,memory=8192Mi,tpu=8")
        assert r.cpu == 4 and r.memory_mb == 8192 and r.tpu_chips == 8


class TestStorageAndContext:
    def test_posix_storage(self, tmp_path):
        from dlrover_tpu.common.storage import ClassMeta, PosixDiskStorage

        s = PosixDiskStorage()
        p = str(tmp_path / "a" / "f.bin")
        s.safe_makedirs(str(tmp_path / "a"))
        s.write(b"hello", p)
        assert s.read(p) == b"hello"
        assert s.exists(p)
        assert "f.bin" in s.listdir(str(tmp_path / "a"))
        s.safe_remove(p)
        assert not s.exists(p)
        # ClassMeta round-trip builds the same backend.
        built = ClassMeta().build()
        assert isinstance(built, PosixDiskStorage)

    def test_context_singleton_and_update(self):
        ctx = get_context()
        assert ctx is get_context()
        old = ctx.rdzv_timeout
        ctx.update(rdzv_timeout=123.0)
        assert get_context().rdzv_timeout == 123.0
        ctx.update(rdzv_timeout=old)


class TestPublicAPI:
    def test_every_lazy_export_resolves(self):
        """dt.<name> must import for every advertised top-level symbol
        (regression: a stale module path made dt.ElasticTrainer raise
        ModuleNotFoundError)."""
        import dlrover_tpu as dt

        for name in dt._LAZY:
            obj = getattr(dt, name)
            assert obj is not None, name

    def test_unknown_attribute_raises(self):
        import pytest

        import dlrover_tpu as dt

        with pytest.raises(AttributeError):
            dt.does_not_exist


class TestCompilationCache:
    def test_enable_compilation_cache_modes(self, tmp_path, monkeypatch):
        """Order-independent by design: the cache backend latches its
        directory at the first compile in the process, so this test used
        to pass only when nothing had jitted before it (tier-1 ordering);
        ``enable_compilation_cache`` now drops that latch itself, and the
        teardown drops it again so the NEXT test never inherits a cache
        pointed at this test's deleted tmp dir."""
        import jax

        from dlrover_tpu.common.jax_env import enable_compilation_cache

        prev = jax.config.jax_compilation_cache_dir
        try:
            monkeypatch.setenv("DLROVER_TPU_COMPILE_CACHE", "0")
            assert enable_compilation_cache() is False

            d = str(tmp_path / "xla")
            monkeypatch.setenv("DLROVER_TPU_COMPILE_CACHE", d)
            assert enable_compilation_cache() is True
            assert jax.config.jax_compilation_cache_dir == d
            assert (tmp_path / "xla").is_dir()

            # A compiled program actually lands in the cache dir — a
            # FRESH computation (unique shape) so neither the in-memory
            # executable cache nor an earlier persistent entry can
            # satisfy it without writing here.
            n = 32 + (os.getpid() % 17)
            jax.jit(lambda x: x * 2 + 1)(jax.numpy.ones((n,))
                                         ).block_until_ready()
            assert any((tmp_path / "xla").iterdir())
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:  # noqa: BLE001 - best-effort unlatch
                pass
