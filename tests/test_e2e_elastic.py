"""End-to-end elastic training tests: tpurun -> master -> agent -> workers.

The flagship system test (SURVEY.md §4 "system tests"): a real process tree
on one host, 2 worker processes forming a 4-device JAX world over CPU, with
a mid-run worker SIGKILL exercising failure detection, breakpoint save,
re-rendezvous and flash-checkpoint warm restore.
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(tmp_path, job_name, extra_args, env_extra=None, steps=15):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "PYTHONPATH": REPO,
        }
    )
    if env_extra:
        env.update(env_extra)
    log = open(tmp_path / "run.log", "w")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dlrover_tpu.run",
            "--standalone", "--nproc_per_node=2",
            f"--job_name={job_name}",
            "--monitor_interval=1",
            os.path.join(REPO, "examples", "nanogpt_train.py"),
            "--", f"--steps={steps}", *extra_args,
        ],
        cwd=REPO,
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
    )
    return proc, tmp_path / "run.log"


def _read(path):
    with open(path) as f:
        return f.read()


@pytest.mark.e2e
class TestEndToEnd:
    def test_happy_path(self, tmp_path):
        proc, log = _launch(tmp_path, "e2e-happy", [], steps=8)
        rc = proc.wait(timeout=420)
        content = _read(log)
        assert rc == 0, content[-3000:]
        assert content.count("TRAIN_DONE step=8") == 2, content[-3000:]
        assert "jax.distributed up: process 0/2" in content

    def test_kill_worker_restore(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        proc, log = _launch(
            tmp_path, "e2e-kill",
            [f"--ckpt_dir={ckpt_dir}", "--ckpt_interval=3"],
            steps=2000,  # long enough that the kill lands mid-run
        )
        # Wait for a checkpoint to be staged (step >= 10 reported).
        deadline = time.time() + 300
        killed = False
        while time.time() < deadline:
            content = _read(log) if os.path.exists(log) else ""
            m = re.search(r"started 2 worker\(s\): pids=\[(\d+), (\d+)\]",
                          content)
            if m and re.search(r"step (1[0-9]|[2-9][0-9]) loss", content):
                os.kill(int(m.group(2)), signal.SIGKILL)
                killed = True
                break
            if proc.poll() is not None:
                pytest.fail("launcher exited early:\n" + content[-3000:])
            time.sleep(1.0)
        assert killed, "never reached a running training step"
        # Shorten the wait: once the job restores past the kill point we
        # don't need all 2000 steps — stop it after confirming restore.
        restored = False
        deadline = time.time() + 420
        while time.time() < deadline:
            content = _read(log)
            if re.search(r"restored step=\d+", content):
                restored = True
                break
            if proc.poll() is not None:
                break
            time.sleep(2.0)
        content = _read(log)
        # The kill must have been absorbed via the agent's breakpoint save
        # (staged-but-unpersisted state flushed before restarting workers).
        assert "breakpoint save" in content, content[-3000:]
        assert restored, "no restore observed:\n" + content[-3000:]
        step = int(re.search(r"restored step=(\d+)", content).group(1))
        assert step >= 3
        # And specifically the warm path: same host, staged shm state —
        # restore must come from shm, not a storage round trip.
        assert "warm restore from shm" in content, content[-3000:]
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()


def _free_port() -> int:
    from dlrover_tpu.common.rpc import find_free_port

    return find_free_port()


def _start_master(tmp_path, job_name, port, extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    log = open(tmp_path / "master.log", "w")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dlrover_tpu.master.main",
            f"--port={port}", f"--job_name={job_name}",
            "--min_nodes=2", "--max_nodes=2", *extra,
        ],
        cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
    )
    return proc, tmp_path / "master.log"


def _start_node(tmp_path, job_name, master_port, node_rank, script_args,
                env_extra=None):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": REPO,
        }
    )
    if env_extra:
        env.update(env_extra)
    log = open(tmp_path / f"node{node_rank}.log", "w")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dlrover_tpu.run",
            "--nnodes=2", "--nproc_per_node=1",
            f"--node_rank={node_rank}",
            f"--master_addr=127.0.0.1:{master_port}",
            f"--job_name={job_name}",
            "--monitor_interval=1",
            *script_args,
        ],
        cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
    )
    return proc, tmp_path / f"node{node_rank}.log"


@pytest.mark.e2e
class TestMultiNode:
    def test_agent_kill_node_relaunch(self, tmp_path):
        """Kill a whole NODE (its agent process), not just a worker: the
        master must evict the dead incarnation, the surviving node must
        re-rendezvous with the replacement, and training must resume from
        the flash checkpoint (VERDICT round-1 e2e matrix item)."""
        job = "e2e-agentkill"
        port = _free_port()
        ckpt = str(tmp_path / "ckpt")
        mproc, mlog = _start_master(tmp_path, job, port)
        script = [
            os.path.join(REPO, "examples", "nanogpt_train.py"),
            "--", "--steps=2000", f"--ckpt_dir={ckpt}",
            "--ckpt_interval=3", "--batch_per_proc=2",
        ]
        n0, log0 = _start_node(tmp_path, job, port, 0, script)
        n1, log1 = _start_node(tmp_path, job, port, 1, script)
        procs = [mproc, n0, n1]
        try:
            # Wait until both nodes are training (a double-digit step).
            deadline = time.time() + 420
            while time.time() < deadline:
                c1 = _read(log1) if os.path.exists(log1) else ""
                if re.search(r"step (1[0-9]|[2-9][0-9]) loss", c1):
                    break
                for p, plog, nm in (
                    (mproc, mlog, "master"),
                    (n0, log0, "node0"),
                    (n1, log1, "node1"),
                ):
                    if p.poll() is not None:
                        pytest.fail(
                            f"{nm} exited early:\n" + _read(plog)[-3000:]
                        )
                time.sleep(1.0)
            else:
                pytest.fail("never reached training:\n" + _read(log1)[-3000:])

            n1.kill()  # SIGKILL the agent: the whole node dies
            n1.wait(timeout=30)

            # Platform-relaunch stand-in: a replacement agent process for
            # the same node_rank (what the reconciler/GKE would do).
            time.sleep(3.0)
            n1b, log1b = _start_node(
                tmp_path, job, port, 1, script,
            )
            procs.append(n1b)

            resumed = False
            deadline = time.time() + 420
            while time.time() < deadline:
                c1b = _read(log1b) if os.path.exists(log1b) else ""
                if re.search(r"restored step=(\d+)", c1b) and re.search(
                    r"step \d+ loss", c1b
                ):
                    resumed = True
                    break
                if n1b.poll() is not None:
                    pytest.fail(
                        "replacement node exited:\n" + c1b[-3000:]
                    )
                time.sleep(2.0)
            c1b = _read(log1b)
            assert resumed, (
                "replacement never resumed:\nnode1b:\n" + c1b[-2500:]
                + "\nnode0:\n" + _read(log0)[-1500:]
            )
            step = int(re.search(r"restored step=(\d+)", c1b).group(1))
            assert step >= 3
            # The surviving node went through a fresh rendezvous round.
            assert re.search(r"restored step=\d+", _read(log0))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    def test_network_check_flags_slow_node(self, tmp_path):
        """Pre-flight node check with an injected slow node: the paired
        benchmark must finish on both nodes and the master's straggler
        detection must flag the slow one (VERDICT round-1 item; reference
        NetworkCheckRendezvousManager straggler isolation)."""
        job = "e2e-netcheck"
        port = _free_port()
        mproc, mlog = _start_master(
            tmp_path, job, port, extra=("--network_check",)
        )
        script = [
            "--network_check",
            os.path.join(REPO, "examples", "nanogpt_train.py"),
            "--", "--steps=4", "--batch_per_proc=2",
        ]
        n0, log0 = _start_node(tmp_path, job, port, 0, script)
        n1, log1 = _start_node(
            tmp_path, job, port, 1, script,
            env_extra={"DLROVER_TPU_CHECK_DELAY_S": "3"},
        )
        procs = [mproc, n0, n1]
        try:
            rc0 = n0.wait(timeout=600)
            rc1 = n1.wait(timeout=600)
            c0, c1 = _read(log0), _read(log1)
            assert rc0 == 0, c0[-3000:]
            assert rc1 == 0, c1[-3000:]
            # Both checks ran to completion...
            assert "node check round 1" in c0
            assert "node check round 1" in c1
            # ...and the delayed node (only) was flagged as the straggler.
            assert "flagged as straggler" in c1, c1[-3000:]
            assert "flagged as straggler" not in c0, c0[-3000:]
            # The check is advisory for stragglers: training still ran.
            assert "TRAIN_DONE" in c0 and "TRAIN_DONE" in c1
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()


@pytest.mark.e2e
class TestScaleUp:
    # slow-lane (ISSUE 8 satellite): 25s, and multi-process XLA
    # collectives cannot run on this CI container anyway — the tier-1
    # budget is better spent on tests that can pass here.
    @pytest.mark.slow
    def test_node_join_grows_world(self, tmp_path):
        """Elastic scale-UP: training starts with one node (min_nodes=1),
        a second node joins mid-run, the master's waiting-list triggers a
        membership change, and training resumes as a 2-process world from
        the flash checkpoint (the allreduce auto-scaler's grow path,
        end-to-end)."""
        job = "e2e-scaleup"
        port = _free_port()
        ckpt = str(tmp_path / "ckpt")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        mlog_f = open(tmp_path / "master.log", "w")
        mproc = subprocess.Popen(
            [
                sys.executable, "-m", "dlrover_tpu.master.main",
                f"--port={port}", f"--job_name={job}",
                "--min_nodes=1", "--max_nodes=2",
            ],
            cwd=REPO, env=env, stdout=mlog_f, stderr=subprocess.STDOUT,
        )
        mlog = tmp_path / "master.log"

        def start_node(rank):
            nenv = dict(os.environ)
            nenv.update(
                {
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                    "PYTHONPATH": REPO,
                }
            )
            log = open(tmp_path / f"node{rank}.log", "w")
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "dlrover_tpu.run",
                    "--nnodes=1:2", "--nproc_per_node=1",
                    f"--node_rank={rank}",
                    f"--master_addr=127.0.0.1:{port}",
                    f"--job_name={job}", "--monitor_interval=1",
                    os.path.join(REPO, "examples", "nanogpt_train.py"),
                    # Big enough that the solo phase can't finish before
                    # the join (tiny nanogpt is ~ms/step on CPU).
                    "--", "--steps=100000", f"--ckpt_dir={ckpt}",
                    "--ckpt_interval=3", "--batch_per_proc=8",
                    "--seq_len=64",
                ],
                cwd=REPO, env=nenv, stdout=log, stderr=subprocess.STDOUT,
            )
            return proc, tmp_path / f"node{rank}.log"

        n0, log0 = start_node(0)
        procs = [mproc, n0]
        try:
            # Phase 1: single-node world training.
            deadline = time.time() + 420
            while time.time() < deadline:
                c0 = _read(log0) if os.path.exists(log0) else ""
                # A world of 1 skips jax.distributed init; the agent's
                # rendezvous log carries the world size instead.
                if (
                    "world=1 nodes" in c0
                    and re.search(r"step (1[0-9]|[2-9][0-9]) loss", c0)
                ):
                    break
                if n0.poll() is not None or mproc.poll() is not None:
                    pytest.fail("early exit:\n" + c0[-3000:]
                                + _read(mlog)[-1500:])
                time.sleep(1.0)
            else:
                pytest.fail("node0 never trained solo:\n"
                            + _read(log0)[-3000:])

            # Phase 2: node 1 joins mid-run.
            n1, log1 = start_node(1)
            procs.append(n1)
            grown = False
            deadline = time.time() + 420
            while time.time() < deadline:
                c0 = _read(log0)
                c1 = _read(log1) if os.path.exists(log1) else ""
                if (
                    "jax.distributed up: process 0/2" in c0
                    and "jax.distributed up: process 1/2" in c1
                    and re.search(r"restored step=\d+", c0)
                    and re.search(r"step \d+ loss", c1)
                ):
                    grown = True
                    break
                for p, nm in ((mproc, "master"), (n0, "node0"),
                              (n1, "node1")):
                    if p.poll() is not None:
                        pytest.fail(f"{nm} died during scale-up:\n"
                                    + c0[-2000:] + c1[-2000:])
                time.sleep(1.0)
            assert grown, (
                "world never grew to 2:\nnode0:\n" + _read(log0)[-2500:]
                + "\nnode1:\n" + (_read(log1) if os.path.exists(log1)
                                  else "")[-2500:]
            )
            # The restore carried training state across the resize.
            step = int(re.search(r"restored step=(\d+)",
                                 _read(log0)).group(1))
            assert step >= 3
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()


@pytest.mark.e2e
class TestScaleDown:
    # slow-lane (ISSUE 8 satellite): 21s, multi-process XLA collectives
    # (see TestScaleUp).
    @pytest.mark.slow
    def test_node_loss_shrinks_world(self, tmp_path):
        """Elastic scale-DOWN: two nodes train; one dies and is NOT
        replaced; with min_nodes=1 the survivor must re-rendezvous as a
        1-node world and keep training from the checkpoint."""
        job = "e2e-scaledown"
        port = _free_port()
        ckpt = str(tmp_path / "ckpt")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        # Fast failure detection so the test (and recovery) is snappy:
        # master declares a silent node dead after 20s of missed
        # heartbeats and broadcasts RESTART_WORKER to the survivors.
        env["DLROVER_TPU_NODE_HEARTBEAT_TIMEOUT"] = "20"
        mproc = subprocess.Popen(
            [
                sys.executable, "-m", "dlrover_tpu.master.main",
                f"--port={port}", f"--job_name={job}",
                "--min_nodes=1", "--max_nodes=2",
            ],
            cwd=REPO, env=env,
            stdout=open(tmp_path / "master.log", "w"),
            stderr=subprocess.STDOUT,
        )

        def start_node(rank):
            nenv = dict(os.environ)
            nenv.update(
                {
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                    "PYTHONPATH": REPO,
                }
            )
            log = open(tmp_path / f"node{rank}.log", "w")
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "dlrover_tpu.run",
                    "--nnodes=1:2", "--nproc_per_node=1",
                    f"--node_rank={rank}",
                    f"--master_addr=127.0.0.1:{port}",
                    f"--job_name={job}", "--monitor_interval=1",
                    os.path.join(REPO, "examples", "nanogpt_train.py"),
                    "--", "--steps=100000", f"--ckpt_dir={ckpt}",
                    "--ckpt_interval=3", "--batch_per_proc=8",
                    "--seq_len=64",
                ],
                cwd=REPO, env=nenv, stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True,  # killpg must take the whole node
            )
            return proc, tmp_path / f"node{rank}.log"

        n0, log0 = start_node(0)
        n1, log1 = start_node(1)
        procs = [mproc, n0, n1]
        try:
            deadline = time.time() + 420
            while time.time() < deadline:
                c0 = _read(log0) if os.path.exists(log0) else ""
                if (
                    "jax.distributed up: process 0/2" in c0
                    and re.search(r"step (1[0-9]|[2-9][0-9]) loss", c0)
                ):
                    break
                for p, nm in ((mproc, "master"), (n0, "node0"),
                              (n1, "node1")):
                    if p.poll() is not None:
                        pytest.fail(f"{nm} exited early:\n" + c0[-3000:])
                time.sleep(1.0)
            else:
                pytest.fail("2-node world never trained:\n"
                            + _read(log0)[-3000:])

            # Node 1 is gone for good (spot preemption): kill its WHOLE
            # process group — agent and workers — so nothing lingers.
            os.killpg(os.getpgid(n1.pid), signal.SIGKILL)
            n1.wait(timeout=30)

            shrunk = False
            deadline = time.time() + 420
            while time.time() < deadline:
                c0 = _read(log0)
                # After the failure round the survivor re-forms a world
                # of 1 and keeps stepping (restore from shm/storage).
                tail = c0.split("jax.distributed up: process 0/2")[-1]
                if (
                    "world=1 nodes" in tail
                    and re.search(r"restored step=\d+", tail)
                    and re.search(r"step \d+ loss", tail)
                ):
                    shrunk = True
                    break
                if n0.poll() is not None or mproc.poll() is not None:
                    pytest.fail("survivor/master died:\n" + c0[-3000:])
                time.sleep(1.0)
            assert shrunk, (
                "world never shrank to 1:\n" + _read(log0)[-3000:]
            )
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()


@pytest.mark.e2e
class TestJobFileLaunch:
    # slow-lane (ISSUE 8 satellite): 20s full job-file launch (see
    # TestScaleUp).
    @pytest.mark.slow
    def test_yaml_job_file_launches_nanogpt(self, tmp_path):
        """The declarative ElasticJob YAML drives tpurun end-to-end
        (VERDICT r2 next #10): script, args, nproc and ckpt config all
        come from the file."""
        yaml_text = f"""\
apiVersion: elastic.dlrover-tpu/v1alpha1
kind: ElasticJob
metadata:
  name: e2e-yaml
spec:
  replicaSpecs:
    worker:
      replicas: 1
  template:
    script: examples/nanogpt_train.py
    args: ["--steps=8"]
    nprocPerNode: 2
  checkpoint:
    dir: {tmp_path / 'ckpt'}
    interval: 3
"""
        job_file = tmp_path / "job.yaml"
        job_file.write_text(yaml_text)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "PYTHONPATH": REPO,
        })
        log = open(tmp_path / "run.log", "w")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "dlrover_tpu.run",
                "--standalone", "--monitor_interval=1",
                f"--job_file={job_file}",
            ],
            cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
        )
        rc = proc.wait(timeout=420)
        content = _read(tmp_path / "run.log")
        assert rc == 0, content[-3000:]
        assert content.count("TRAIN_DONE step=8") == 2, content[-3000:]
        # ckpt config came from the YAML
        assert (tmp_path / "ckpt").exists(), content[-1500:]


@pytest.mark.e2e
class TestElasticServing:
    def test_kill_worker_mid_serving_replays_only_inflight(
        self, tmp_path
    ):
        """Serving under elasticity (beyond the reference, whose RL
        stack shells out to an unsupervised vllm): SIGKILL the serving
        worker mid-run; the agent relaunches it, the journal keeps every
        finished request, and the restarted worker replays only the
        in-flight remainder — final results byte-identical to solo
        greedy decode."""
        journal_dir = tmp_path / "journal"
        proc, log = _launch_serving(tmp_path, journal_dir)
        try:
            # Kill the worker once >=2 requests finished but the job is
            # still running (requests=12, throttled).
            deadline = time.time() + 420
            killed = False
            while time.time() < deadline:
                content = _read(log) if os.path.exists(log) else ""
                m = re.search(
                    r"started 1 worker\(s\): pids=\[(\d+)\]", content
                )
                if m and content.count("SERVED rid=") >= 2:
                    os.kill(int(m.group(1)), signal.SIGKILL)
                    killed = True
                    break
                if proc.poll() is not None:
                    pytest.fail(
                        "launcher exited early:\n" + content[-3000:]
                    )
                time.sleep(0.3)
            assert killed, (
                "never reached 2 served requests:\n"
                + _read(log)[-3000:]
            )
            deadline = time.time() + 420
            done = False
            while time.time() < deadline:
                content = _read(log)
                if "SERVE_ELASTIC_DONE" in content:
                    done = True
                    break
                if proc.poll() is not None:
                    break
                time.sleep(1.0)
            content = _read(log)
            assert done, "serving never completed:\n" + content[-3000:]
            # The restarted incarnation must have REPLAYED the journal:
            # from_journal > 0 (finished work survived the kill) and
            # served_now < 12 (not everything was redone).
            m = re.search(
                r"SERVE_ELASTIC_DONE requests=12 served_now=(\d+) "
                r"from_journal=(\d+)", content,
            )
            assert m, content[-2000:]
            served_now, from_journal = int(m.group(1)), int(m.group(2))
            assert from_journal >= 2, content[-2000:]
            assert served_now == 12 - from_journal
            rc = proc.wait(timeout=120)
            assert rc == 0, content[-2000:]
        finally:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        # Journal-complete and byte-exact vs solo greedy decode.
        import json as _json

        import numpy as np

        recs = {}
        with open(journal_dir / "results.jsonl") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = _json.loads(line)
                except ValueError:
                    continue  # torn tail from the SIGKILL
                recs.setdefault(int(rec["rid"]), rec["tokens"])
        assert sorted(recs) == list(range(12)), sorted(recs)
        from dlrover_tpu.models import llama, llama_infer
        import jax
        import jax.numpy as jnp

        cfg = llama.LlamaConfig.tiny(n_layer=2, dtype=jnp.float32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(1)
        prompts = [
            rng.randint(1, cfg.vocab_size, size=(int(n),)).astype(
                np.int32
            )
            for n in rng.randint(4, 12, size=(12,))
        ]
        for rid in (0, 5, 11):  # spot-check across the set
            solo = np.asarray(llama_infer.generate(
                params, cfg, jnp.asarray(prompts[rid])[None],
                max_new_tokens=48,
            ))[0]
            np.testing.assert_array_equal(
                np.asarray(recs[rid], np.int32), solo
            )


def _launch_serving(tmp_path, journal_dir):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": REPO,
        }
    )
    log = open(tmp_path / "serve.log", "w")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dlrover_tpu.run",
            "--standalone", "--nproc_per_node=1",
            "--job_name=e2e-serve",
            "--monitor_interval=1",
            os.path.join(REPO, "examples", "llama_serve_elastic.py"),
            "--", "--requests=12", "--max_new_tokens=48",
            f"--journal_dir={journal_dir}", "--throttle_s=1.0",
        ],
        cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
    )
    return proc, tmp_path / "serve.log"
