"""End-to-end elastic training tests: tpurun -> master -> agent -> workers.

The flagship system test (SURVEY.md §4 "system tests"): a real process tree
on one host, 2 worker processes forming a 4-device JAX world over CPU, with
a mid-run worker SIGKILL exercising failure detection, breakpoint save,
re-rendezvous and flash-checkpoint warm restore.
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(tmp_path, job_name, extra_args, env_extra=None, steps=15):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "PYTHONPATH": REPO,
        }
    )
    if env_extra:
        env.update(env_extra)
    log = open(tmp_path / "run.log", "w")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dlrover_tpu.run",
            "--standalone", "--nproc_per_node=2",
            f"--job_name={job_name}",
            "--monitor_interval=1",
            os.path.join(REPO, "examples", "nanogpt_train.py"),
            "--", f"--steps={steps}", *extra_args,
        ],
        cwd=REPO,
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
    )
    return proc, tmp_path / "run.log"


def _read(path):
    with open(path) as f:
        return f.read()


@pytest.mark.e2e
class TestEndToEnd:
    def test_happy_path(self, tmp_path):
        proc, log = _launch(tmp_path, "e2e-happy", [], steps=8)
        rc = proc.wait(timeout=420)
        content = _read(log)
        assert rc == 0, content[-3000:]
        assert content.count("TRAIN_DONE step=8") == 2, content[-3000:]
        assert "jax.distributed up: process 0/2" in content

    def test_kill_worker_restore(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        proc, log = _launch(
            tmp_path, "e2e-kill",
            [f"--ckpt_dir={ckpt_dir}", "--ckpt_interval=3"],
            steps=2000,  # long enough that the kill lands mid-run
        )
        # Wait for a checkpoint to be staged (step >= 3 reported).
        worker_pids = []
        deadline = time.time() + 300
        killed = False
        while time.time() < deadline:
            content = _read(log) if os.path.exists(log) else ""
            m = re.search(r"started 2 worker\(s\): pids=\[(\d+), (\d+)\]",
                          content)
            if m and "step 10 " in content.replace("step 10\n", "step 10 "):
                pass
            if m and re.search(r"step (1[0-9]|[2-9][0-9]) loss", content):
                worker_pids = [int(m.group(1)), int(m.group(2))]
                os.kill(worker_pids[1], signal.SIGKILL)
                killed = True
                break
            if proc.poll() is not None:
                pytest.fail("launcher exited early:\n" + content[-3000:])
            time.sleep(1.0)
        assert killed, "never reached a running training step"
        # Shorten the wait: once the job restores past the kill point we
        # don't need all 2000 steps — stop it after confirming restore.
        restored = False
        deadline = time.time() + 420
        while time.time() < deadline:
            content = _read(log)
            if re.search(r"restored step=\d+", content):
                restored = True
                break
            if proc.poll() is not None:
                break
            time.sleep(2.0)
        content = _read(log)
        assert "breakpoint save" in content or "persisted" in content, (
            content[-3000:]
        )
        assert restored, "no restore observed:\n" + content[-3000:]
        step = int(re.search(r"restored step=(\d+)", content).group(1))
        assert step >= 3
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
