"""Serving-fleet control-plane units (ISSUE 5) — tier-1, sub-second.

Everything here runs WITHOUT jax or sockets: the gateway core takes an
injectable clock, the replica runner takes a fake decode server with
the real incremental-admission surface, and transports are loopback.
The real-model integration rides the ``serving+slow`` e2e lane
(``test_chaos_e2e.py``) and ``bench.py --serve_bench``.
"""

import collections
import threading
import time

import pytest

from dlrover_tpu.common.messages import (
    ServeDone,
    ServeGrants,
    ServeKvReady,
    ServeKvReject,
    ServeReplicaDeregister,
    ServeReplicaPoll,
    ServeReplicaRegister,
    ServeSubmit,
    ServeTokens,
    deserialize,
    serialize,
)
from dlrover_tpu.serving import (
    GatewayConfig,
    GatewayCore,
    LoopbackTransport,
    PoolAutoScaler,
    ReplicaRunner,
    ScalePolicy,
    ScaleState,
    decide,
    decide_pools,
)

pytestmark = pytest.mark.serving


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_core(**kw):
    clock = FakeClock()
    cfg = GatewayConfig(**kw)
    return GatewayCore(cfg, clock=clock), clock


# ---------------------------------------------------------------------------
# Admission / backpressure / dedupe
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_accept_then_reject_past_cap_with_retry_after(self):
        core, _ = make_core(queue_cap=2, retry_after_s=1.5)
        assert core.submit("a", [1], 4).status == "accepted"
        assert core.submit("b", [2], 4).status == "accepted"
        ack = core.submit("c", [3], 4)
        assert ack.status == "rejected"
        assert ack.retry_after_s == 1.5
        assert "queue full" in ack.reason
        assert core.counters["rejected"] == 1

    def test_cap_counts_assigned_work_not_just_queued(self):
        """Backpressure is on total in-flight: granting work to a
        replica must not open admission back up."""
        core, _ = make_core(queue_cap=2)
        core.register("r0", 2)
        core.submit("a", [1], 4)
        core.submit("b", [2], 4)
        core.poll("r0", 2, [])  # both now assigned, queue empty
        assert core.submit("c", [3], 4).status == "rejected"

    def test_duplicate_submit_while_in_flight_is_single_entry(self):
        core, _ = make_core()
        core.submit("a", [1], 4)
        ack = core.submit("a", [1], 4)
        assert ack.status == "accepted"
        assert ack.reason == "duplicate-submit"
        assert core.stats_snapshot()["queue_depth"] == 1

    def test_resubmit_of_completed_request_answers_from_cache(self):
        """The req-id IS the idempotency token: a client retry after
        the answer was produced never decodes twice."""
        core, _ = make_core()
        core.register("r0", 1)
        core.submit("a", [1], 4)
        core.poll("r0", 1, [])
        core.complete("r0", "a", [7, 8, 9])
        ack = core.submit("a", [1], 4)
        assert ack.status == "done"
        assert ack.tokens == [7, 8, 9]
        assert core.counters["dedupe_hits"] == 1
        assert core.counters["completed"] == 1

    def test_status_lifecycle(self):
        core, _ = make_core()
        assert core.status("a").state == "unknown"
        core.submit("a", [1], 4)
        assert core.status("a").state == "queued"
        core.register("r0", 1)
        core.poll("r0", 1, [])
        assert core.status("a").state == "running"
        core.stream("r0", "a", [5])
        assert core.status("a").tokens == [5]
        core.complete("r0", "a", [5, 6])
        st = core.status("a")
        assert st.state == "done" and st.tokens == [5, 6]
        assert st.replica == "r0"


# ---------------------------------------------------------------------------
# Routing / grants
# ---------------------------------------------------------------------------


class TestRouting:
    def test_grants_capped_by_free_slots(self):
        core, _ = make_core()
        core.register("r0", 4)
        for i in range(5):
            core.submit(f"q{i}", [i], 4)
        g = core.poll("r0", 2, [])
        assert [r.req_id for r in g.requests] == ["q0", "q1"]
        g = core.poll("r0", 0, ["q0", "q1"])
        assert g.requests == []

    def test_work_flows_to_the_replica_with_free_slots(self):
        """Pull routing == least-loaded routing: the saturated replica
        polls with 0 free slots and gets nothing; the idle one drains
        the queue."""
        core, _ = make_core()
        core.register("busy", 2)
        core.register("idle", 2)
        for i in range(4):
            core.submit(f"q{i}", [i], 4)
        g_busy = core.poll("busy", 0, [])
        g_idle = core.poll("idle", 2, [])
        assert g_busy.requests == []
        assert [r.req_id for r in g_idle.requests] == ["q0", "q1"]

    def test_unknown_replica_is_told_to_reregister(self):
        core, _ = make_core()
        g = core.poll("ghost", 2, [])
        assert g.known is False


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------


class TestDeadlines:
    def test_queued_request_times_out(self):
        core, clock = make_core()
        core.submit("a", [1], 4, deadline_s=5.0)
        clock.advance(6.0)
        core.sweep()
        st = core.status("a")
        assert st.state == "timeout"
        assert core.counters["timeout"] == 1

    def test_expired_request_never_granted(self):
        core, clock = make_core()
        core.register("r0", 1)
        core.submit("a", [1], 4, deadline_s=5.0)
        clock.advance(6.0)
        g = core.poll("r0", 1, [])
        assert g.requests == []
        assert core.status("a").state == "timeout"

    def test_in_flight_deadline_cancels_at_replica(self):
        core, clock = make_core()
        core.register("r0", 1)
        core.submit("a", [1], 4, deadline_s=5.0)
        core.poll("r0", 1, [])
        clock.advance(6.0)
        g = core.poll("r0", 0, ["a"])
        assert g.cancel == ["a"]
        assert core.status("a").state == "timeout"

    def test_resubmit_of_timed_out_request_acks_timeout_not_done(self):
        """A terminal timeout must not be masked as a zero-token
        success on resubmit — the ack carries the cached outcome."""
        core, clock = make_core()
        core.submit("a", [1], 4, deadline_s=5.0)
        clock.advance(6.0)
        core.sweep()
        ack = core.submit("a", [1], 4)
        assert ack.status == "timeout"
        assert ack.tokens == []
        assert "deadline" in ack.reason

    def test_late_completion_after_timeout_is_dropped(self):
        core, clock = make_core()
        core.register("r0", 1)
        core.submit("a", [1], 4, deadline_s=5.0)
        core.poll("r0", 1, [])
        clock.advance(6.0)
        core.poll("r0", 0, ["a"])  # timeout recorded here
        assert core.complete("r0", "a", [9]) == "duplicate"
        assert core.status("a").state == "timeout"
        # Work finished after its gateway timeout is a LATE completion,
        # not a dedupe event — the duplicate counter stays meaningful
        # as journal-replay evidence.
        assert core.counters["late_completions"] == 1
        assert core.counters["duplicate_completions"] == 0


# ---------------------------------------------------------------------------
# Replica death / re-dispatch / exactly-once
# ---------------------------------------------------------------------------


class TestRedispatch:
    def test_lease_expiry_requeues_in_flight_at_front(self):
        core, clock = make_core(lease_timeout_s=10.0)
        core.register("r0", 2)
        core.submit("a", [1], 4)
        core.submit("b", [2], 4)
        core.poll("r0", 1, [])  # 'a' assigned
        clock.advance(11.0)
        core.sweep()
        assert core.counters["replicas_lost"] == 1
        assert core.counters["redispatched"] == 1
        core.register("r1", 2)
        g = core.poll("r1", 2, [])
        # The re-dispatched request goes FIRST (it has waited longest).
        assert [r.req_id for r in g.requests] == ["a", "b"]

    def test_duplicate_completion_from_journal_replay_is_dropped(self):
        """The exactly-once law: re-dispatch races journal replay, the
        first terminal report wins, the second is counted and dropped."""
        core, clock = make_core(lease_timeout_s=10.0)
        core.register("r0", 1)
        core.submit("a", [1], 4)
        core.poll("r0", 1, [])
        clock.advance(11.0)
        core.sweep()  # r0 presumed dead; 'a' re-queued
        core.register("r1", 1)
        core.poll("r1", 1, [])
        assert core.complete("r1", "a", [5, 6]) == "recorded"
        # r0 restarts and replays its journal for the same request.
        assert core.complete("r0", "a", [5, 6], replayed=True) == \
            "duplicate"
        assert core.counters["completed"] == 1
        assert core.counters["duplicate_completions"] == 1
        assert core.status("a").tokens == [5, 6]

    def test_reregister_requeues_assigned_work(self):
        """A replica that crashed and re-registered cannot still be
        running its old assignment: it is re-dispatched (its journal
        replay, if any, wins the dedupe race instead)."""
        core, _ = make_core()
        core.register("r0", 1)
        core.submit("a", [1], 4)
        core.poll("r0", 1, [])
        core.register("r0", 1)  # restart, same id
        assert core.stats_snapshot()["queue_depth"] == 1
        assert core.counters["redispatched"] == 1

    def test_lost_grant_reconciled_from_owned_set(self):
        """chaos serving.drop_request's recovery path: a grant the
        replica never admits is absent from its owned set two polls
        later and goes back to the queue."""
        core, _ = make_core()
        core.register("r0", 2)
        core.submit("a", [1], 4)
        g = core.poll("r0", 2, [])
        assert [r.req_id for r in g.requests] == ["a"]
        # Poll without owning it: one poll of grace (the grant may have
        # raced this poll)...
        core.poll("r0", 2, [])
        assert core.status("a").state == "running"
        # ...then the next unowning poll proves it lost.
        g = core.poll("r0", 2, [])
        assert core.counters["redispatched"] == 1
        assert [r.req_id for r in g.requests] == ["a"]

    def test_poison_request_fails_terminally_after_max_attempts(self):
        """A request that keeps getting lost (or keeps killing its
        replica) must not head-of-line-block the fleet forever: after
        max_attempts re-dispatches it fails terminally."""
        core, clock = make_core(lease_timeout_s=5.0, max_attempts=3)
        core.submit("poison", [1], 4)
        core.submit("healthy", [2], 4)
        for round_i in range(3):
            rid = f"r{round_i}"
            core.register(rid, 1)
            g = core.poll(rid, 1, [])
            assert g.requests and g.requests[0].req_id == "poison"
            clock.advance(6.0)
            core.sweep()  # replica "died"; poison re-queued at front
        st = core.status("poison")
        assert st.state == "failed"
        assert "re-dispatched 3 times" in st.reason
        assert core.counters["failed"] == 1
        # The healthy request is now at the head for the next replica.
        core.register("r9", 1)
        g = core.poll("r9", 1, [])
        assert [r.req_id for r in g.requests] == ["healthy"]

    def test_stale_stream_from_superseded_assignment_ignored(self):
        core, clock = make_core(lease_timeout_s=10.0)
        core.register("r0", 1)
        core.submit("a", [1], 4)
        core.poll("r0", 1, [])
        core.stream("r0", "a", [5])
        clock.advance(11.0)
        core.sweep()
        core.register("r1", 1)
        core.poll("r1", 1, [])
        core.stream("r0", "a", [6])  # zombie r0 streams on
        st = core.status("a")
        # Partial buffer reset at re-dispatch; zombie tokens dropped.
        assert st.tokens == [] and st.replica == "r1"


# ---------------------------------------------------------------------------
# Drain (scale-down)
# ---------------------------------------------------------------------------


class TestDrain:
    def test_draining_replica_gets_no_new_grants(self):
        core, _ = make_core()
        core.register("r0", 2)
        core.submit("a", [1], 4)
        core.poll("r0", 2, [])
        core.submit("b", [2], 4)
        assert core.drain("r0")
        g = core.poll("r0", 1, ["a"])
        assert g.requests == [] and g.drain is False
        # In-flight work finishes normally; only then drain=True.
        core.complete("r0", "a", [5])
        g = core.poll("r0", 2, [])
        assert g.drain is True
        # The queued request is still there for the survivors.
        core.register("r1", 2)
        g = core.poll("r1", 2, [])
        assert [r.req_id for r in g.requests] == ["b"]

    def test_pick_drain_victim_is_least_loaded(self):
        core, _ = make_core()
        core.register("r0", 2)
        core.register("r1", 2)
        for i in range(3):
            core.submit(f"q{i}", [i], 4)
        core.poll("r0", 2, [])
        core.poll("r1", 1, [])
        assert core.pick_drain_victim() == "r1"
        core.drain("r1")
        assert core.pick_drain_victim() == "r0"
        core.drain("r0")
        assert core.pick_drain_victim() is None


# ---------------------------------------------------------------------------
# Autoscale policy
# ---------------------------------------------------------------------------


class TestAutoscalePolicy:
    def _snap(self, alive, queue, occ=0.5, ttft=0.0):
        return {"replicas_alive": alive, "queue_depth": queue,
                "occupancy": occ, "ttft_p95_ms": ttft}

    def test_scale_up_needs_sustained_pressure(self):
        pol = ScalePolicy(queue_high_per_replica=4, up_patience=2,
                          max_replicas=4)
        st = ScaleState()
        assert decide(self._snap(1, 10), pol, st) == 1  # pass 1: wait
        assert decide(self._snap(1, 10), pol, st) == 2  # pass 2: grow
        assert st.up_streak == 0  # streak consumed

    def test_pressure_blip_resets_streak(self):
        pol = ScalePolicy(queue_high_per_replica=4, up_patience=2)
        st = ScaleState()
        decide(self._snap(1, 10), pol, st)
        assert decide(self._snap(1, 1), pol, st) == 1
        assert st.up_streak == 0

    def test_ttft_signal_triggers_up(self):
        pol = ScalePolicy(queue_high_per_replica=1e9,
                          ttft_p95_high_ms=500, up_patience=1)
        st = ScaleState()
        assert decide(self._snap(2, 0, ttft=900), pol, st) == 3

    def test_scale_down_needs_idle_and_patience_and_floor(self):
        pol = ScalePolicy(min_replicas=1, down_patience=3,
                          queue_low_per_replica=0.5, occupancy_low=0.3)
        st = ScaleState()
        idle = self._snap(2, 0, occ=0.1)
        assert decide(idle, pol, st) == 2
        assert decide(idle, pol, st) == 2
        assert decide(idle, pol, st) == 1  # third consecutive: shrink
        st2 = ScaleState()
        one = self._snap(1, 0, occ=0.0)
        for _ in range(10):
            assert decide(one, pol, st2) == 1  # never below min

    def test_busy_but_not_pressured_holds_steady(self):
        pol = ScalePolicy()
        st = ScaleState()
        mid = self._snap(2, 2, occ=0.7)
        for _ in range(10):
            assert decide(mid, pol, st) == 2

    def test_up_capped_at_max(self):
        pol = ScalePolicy(max_replicas=2, up_patience=1,
                          queue_high_per_replica=1)
        st = ScaleState()
        assert decide(self._snap(2, 50), pol, st) == 2


# ---------------------------------------------------------------------------
# ServingFleetAutoScaler (master hook)
# ---------------------------------------------------------------------------


class TestServingFleetAutoScaler:
    def _scaler(self, core):
        from dlrover_tpu.master.job_auto_scaler import (
            ServingFleetAutoScaler,
        )

        class Group:
            min_count = 1
            max_count = 4
            count = 1

        class JobArgs:
            workers = Group()
            node_unit = 1

        class JM:
            def __init__(self):
                self.targets = []
                self.live = 0

            def scale_workers_to(self, n):
                self.targets.append(n)
                return n - self.live

            def alive_workers(self):
                return [object()] * self.live

            def pending_workers(self):
                return []

        jm = JM()
        sc = ServingFleetAutoScaler(JobArgs(), jm, core, interval=999)
        sc._policy.up_patience = 1
        sc._policy.down_patience = 1
        return sc, jm

    def test_scale_up_on_queue_pressure(self):
        core, _ = make_core()
        core.register("r0", 2)
        for i in range(20):
            core.submit(f"q{i}", [i], 4)
        sc, jm = self._scaler(core)
        jm.live = 1
        sc.scale_once()
        assert jm.targets == [2]

    def test_scale_up_held_while_workers_warm_up(self):
        """Launched-but-unregistered workers are capacity on its way:
        pressure must not trigger an absolute scale target computed
        from the REGISTERED count (which could even kill the warming
        workers)."""
        core, _ = make_core()
        core.register("r0", 2)
        for i in range(20):
            core.submit(f"q{i}", [i], 4)
        sc, jm = self._scaler(core)
        jm.live = 3  # 2 workers still warming toward registration
        sc.scale_once()
        assert jm.targets == []

    def test_scale_down_is_two_phase_drain_first(self):
        """Scale-down must never kill a live worker: the manager's
        count drops only after the drained victim deregistered AND its
        worker exit was reaped."""
        core, _ = make_core()
        core.register("r0", 2)
        core.register("r1", 2)
        sc, jm = self._scaler(core)
        jm.live = 2
        sc.scale_once()
        # Phase A: drain only — no scale_workers_to yet.
        assert jm.targets == []
        assert core.stats_snapshot()["replicas_draining"] == 1
        victim = sc._pending_drain[0]
        # Still draining (replica present): every pass holds.
        sc.scale_once()
        assert jm.targets == []
        # Victim deregisters but its worker exit is not yet reaped:
        # still held (an absolute shrink now would kill a live one).
        core.deregister(victim)
        sc.scale_once()
        assert jm.targets == []
        # Worker exit reaped -> phase B: pure-bookkeeping target drop.
        jm.live = 1
        sc.scale_once()
        assert jm.targets == [1]
        assert sc._pending_drain is None

    def test_factory_falls_back_without_gateway_instead_of_crashing(self):
        """dist_master never wires a gateway today: a serving-strategy
        job must still boot (training scaler + loud error), not crash
        the master at startup."""
        from dlrover_tpu.master.job_auto_scaler import (
            AllreduceTrainingAutoScaler,
            ServingFleetAutoScaler,
            new_job_auto_scaler,
        )

        class JobArgs:
            distribution_strategy = "serving"
            workers = None

        sc = new_job_auto_scaler(JobArgs(), None, None)
        assert isinstance(sc, AllreduceTrainingAutoScaler)
        # With a gateway wired, the serving scaler is selected.
        class Group:
            min_count = 1
            max_count = 4

        class ServingJobArgs:
            distribution_strategy = "serving"
            workers = Group()

        core, _ = make_core()
        sc2 = new_job_auto_scaler(
            ServingJobArgs(), None, None, serving_gateway=core
        )
        assert isinstance(sc2, ServingFleetAutoScaler)


def test_gateway_wrapper_injects_ttft_p95_into_snapshot():
    """The autoscaler's ttft_p95_high_ms signal reads ttft_p95_ms off
    the production snapshot — the Gateway wrapper must inject it."""
    from dlrover_tpu.serving import Gateway

    gw = Gateway(port=0)
    try:
        gw.core.observe_ttft_ms(700.0)
        snap = gw.core.stats_snapshot()
        assert snap["ttft_p95_ms"] == 1000.0  # bucket upper bound
        assert "latency_p95_ms" in snap
        # And the signal actually drives decide().
        pol = ScalePolicy(queue_high_per_replica=1e9,
                          ttft_p95_high_ms=500, up_patience=1)
        assert decide(snap, pol, ScaleState()) == 2
    finally:
        gw.stop()


def test_replica_register_survives_dead_gateway():
    """A gateway still booting (or flapping right after a known=False
    poll) must not kill the replica: register is best-effort and the
    next poll retries it."""
    class DeadTransport:
        def call(self, msg, **_kw):
            raise ConnectionError("gateway down")

    runner = ReplicaRunner(FakeDecodeServer(1), DeadTransport(), "r0")
    runner.register()  # must not raise


# ---------------------------------------------------------------------------
# Histogram (gateway latency instrument)
# ---------------------------------------------------------------------------

class TestHistogram:
    def test_percentiles_are_bucket_upper_bounds(self):
        from dlrover_tpu.agent.metrics import Histogram

        h = Histogram(buckets=(10, 100, 1000))
        for _ in range(98):
            h.observe(5)
        h.observe(50)
        h.observe(500)
        assert h.count == 100
        assert h.percentile(0.5) == 10
        assert h.percentile(0.99) == 100
        assert h.percentile(1.0) == 1000

    def test_empty_and_overflow(self):
        from dlrover_tpu.agent.metrics import Histogram

        h = Histogram(buckets=(10,))
        assert h.percentile(0.99) == 0.0
        h.observe(99999)  # beyond the last bound: saturates
        assert h.percentile(0.5) == 10
        assert h.snapshot()["count"] == 1.0

    def test_windowed_histogram_decays_instead_of_ratcheting(self):
        """The autoscaler's TTFT signal must forget a bad warmup
        period: with window_s set, observations older than two windows
        fall out of the percentiles."""
        from dlrover_tpu.agent.metrics import Histogram

        clk = FakeClock()
        h = Histogram(buckets=(10, 1000, 10000), window_s=60.0,
                      clock=clk)
        for _ in range(100):
            h.observe(5000.0)  # terrible cold-start TTFTs
        assert h.percentile(0.95) == 10000
        clk.advance(61.0)
        for _ in range(20):
            h.observe(5.0)  # warm steady state
        # Previous window still in view: p95 still reflects the spike.
        assert h.percentile(0.95) == 10000
        clk.advance(61.0)
        for _ in range(20):
            h.observe(5.0)
        # The spike aged out: only steady-state observations remain.
        assert h.percentile(0.95) == 10
        # Fully idle for 2+ windows: empty, not stale.
        clk.advance(200.0)
        assert h.percentile(0.95) == 0.0
        assert h.count == 0

    def test_register_gauges(self):
        from dlrover_tpu.agent.metrics import (
            Histogram,
            MetricsRegistry,
        )

        h = Histogram()
        reg = MetricsRegistry()
        h.register_gauges(reg, "serve_ttft")
        h.observe(42.0)
        text = reg.render()
        assert "serve_ttft_count 1.0" in text
        assert "serve_ttft_p99_ms 50.0" in text


# ---------------------------------------------------------------------------
# Replica runner protocol (fake decode server, loopback fleet)
# ---------------------------------------------------------------------------


class FakeKvError(ValueError):
    """The runner branches on the duck-typed marker, exactly as it
    does for the real ``llama_infer.KvSegmentError``."""

    KV_REJECT = True


def _fake_segment(prompt, first):
    """A checksummed fake KV payload: enough structure to prove the
    verify-before-decode law without the model stack."""
    import json
    import zlib

    data = json.dumps(
        {"prompt": [int(t) for t in prompt], "first": int(first)}
    ).encode()
    return zlib.crc32(data).to_bytes(4, "big") + data


def _parse_segment(payload):
    import json
    import zlib

    if len(payload) < 4 or \
            zlib.crc32(payload[4:]) != int.from_bytes(payload[:4], "big"):
        raise FakeKvError("fake KV segment CRC mismatch")
    return json.loads(payload[4:])


class FakeDecodeServer:
    """The incremental-admission surface of DecodeServer, with a
    deterministic arithmetic 'decode' (token i of prompt p is
    ``(sum(p) + i) % 97``) — the runner protocol under test, not the
    model."""

    def __init__(self, slots=2):
        self.slots = slots
        self._pending = collections.deque()
        self._active = {}
        self.last_stats = {}
        self.imported = 0

    def submit(self, rid, prompt, mnt, prefix_len=0, prefix_fp=""):
        self._pending.append((rid, [int(t) for t in prompt], int(mnt)))

    def import_kv(self, rid, payload, prompt, mnt):
        """Verify-then-admit: a torn payload raises the duck-typed
        reject error; a clean one enqueues — the fake's arithmetic
        token law makes the result identical to a unified decode, so
        disagg exactness is assertable."""
        seg = _parse_segment(payload)
        if seg["prompt"] != [int(t) for t in prompt]:
            raise FakeKvError("fake KV segment prompt mismatch")
        self.imported += 1
        self._pending.append((rid, [int(t) for t in prompt], int(mnt)))

    def cancel(self, rid):
        for i, item in enumerate(self._pending):
            if item[0] == rid:
                del self._pending[i]
                return True
        return False

    def abort(self, rid):
        if self.cancel(rid):
            return True
        return self._active.pop(rid, None) is not None

    def pending_count(self):
        return len(self._pending)

    def pending_rids(self):
        return [r for r, _, _ in self._pending]

    def active_rids(self):
        return list(self._active)

    def free_slots(self):
        return max(
            0, self.slots - len(self._active) - len(self._pending)
        )

    def serve_incremental(self, tick=None, on_finish=None,
                          on_token=None, idle_wait=0.0005):
        results = {}
        while True:
            keep = tick() is not False if tick else True
            while self._pending and len(self._active) < self.slots:
                rid, p, mnt = self._pending.popleft()
                self._active[rid] = (p, [], mnt)
            if not self._active:
                if not self._pending:
                    if tick is None or not keep:
                        break
                    time.sleep(idle_wait)
                continue
            for rid in list(self._active):
                p, out, mnt = self._active[rid]
                t = (sum(p) + len(out)) % 97
                out.append(t)
                if on_token:
                    on_token(rid, t)
                if len(out) >= mnt:
                    full = list(p) + out
                    results[rid] = full
                    del self._active[rid]
                    if on_finish:
                        on_finish(rid, full)
        return results


class FakePrefillServer(FakeDecodeServer):
    """Prefill-role fake: stages checksummed segments for export; its
    first token matches the decode law's token 0, so the handed-off
    decode reproduces the unified result exactly."""

    def __init__(self, slots=2):
        super().__init__(slots)
        self._exports = {}
        self.prefills = 0

    def prefill_request(self, rid, prompt, mnt, prefix_len=0,
                        prefix_fp=""):
        p = [int(t) for t in prompt]
        first = sum(p) % 97
        self._exports[rid] = _fake_segment(p, first)
        self.prefills += 1
        return first

    def export_kv(self, rid):
        payload = self._exports.pop(rid)
        return payload, len(payload) * 4  # fake fp32 equivalent


def core_handle(core):
    """The Gateway.handle dispatch over a bare core (loopback fleets)."""
    def handle(msg):
        if isinstance(msg, ServeReplicaRegister):
            core.register(msg.replica_id, msg.slots, msg.role)
        elif isinstance(msg, ServeReplicaDeregister):
            core.deregister(msg.replica_id)
        elif isinstance(msg, ServeReplicaPoll):
            return core.poll(msg.replica_id, msg.free_slots,
                             msg.active, msg.stats, msg.warm_prefixes)
        elif isinstance(msg, ServeTokens):
            core.stream(msg.replica_id, msg.req_id, msg.tokens)
        elif isinstance(msg, ServeDone):
            core.complete(msg.replica_id, msg.req_id, msg.tokens,
                          msg.ok, msg.reason, msg.replayed)
        elif isinstance(msg, ServeKvReady):
            core.kv_ready(msg.replica_id, msg.req_id, msg.payload,
                          msg.fp32_bytes, msg.addr, msg.seg_fp,
                          msg.crc32, msg.nbytes)
        elif isinstance(msg, ServeKvReject):
            core.kv_reject(msg.replica_id, msg.req_id, msg.reason)
        return None

    return handle


def make_loopback_fleet(core, n=1, slots=2, tmp=None, poll=0.001):
    """Wire N fake-server runners to a GatewayCore over loopback."""
    transport = LoopbackTransport(core_handle(core))
    runners = []
    for i in range(n):
        journal = f"{tmp}/r{i}.jsonl" if tmp else None
        runners.append(ReplicaRunner(
            FakeDecodeServer(slots), transport, f"r{i}",
            journal_path=journal, poll_interval=poll,
        ))
    return runners


def make_disagg_fleet(core, prefill=1, decode=1, slots=2, tmp=None,
                      poll=0.001):
    """A disaggregated loopback fleet: prefill-role + decode-role
    runners over fake servers.  kv_p2p=False keeps these units on the
    relay plane and socket-free; the P2P plane has its own loopback
    fleets in test_serving_tier.py."""
    transport = LoopbackTransport(core_handle(core))
    runners = []
    for i in range(prefill):
        runners.append(ReplicaRunner(
            FakePrefillServer(slots), transport, f"p{i}",
            poll_interval=poll, role="prefill", kv_p2p=False,
        ))
    for i in range(decode):
        journal = f"{tmp}/d{i}.jsonl" if tmp else None
        runners.append(ReplicaRunner(
            FakeDecodeServer(slots), transport, f"d{i}",
            journal_path=journal, poll_interval=poll, role="decode",
            kv_p2p=False,
        ))
    return runners


def expected_tokens(prompt, mnt):
    return [(sum(int(t) for t in prompt) + i) % 97 for i in range(mnt)]


def wait_for(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return False


class TestReplicaRunner:
    def test_end_to_end_loopback_fleet(self, tmp_path):
        core = GatewayCore(GatewayConfig())
        (runner,) = make_loopback_fleet(core, 1, tmp=str(tmp_path))
        th = threading.Thread(target=runner.run, daemon=True)
        th.start()
        for i in range(5):
            core.submit(f"q{i}", [i + 1, i + 2], 4)
        assert wait_for(lambda: core.counters["completed"] == 5)
        for i in range(5):
            st = core.status(f"q{i}")
            assert st.state == "done"
            assert st.tokens == expected_tokens([i + 1, i + 2], 4)
        core.drain("r0")
        th.join(timeout=10)
        assert not th.is_alive()
        assert runner.served == 5
        # Drained replica deregistered itself.
        assert core.stats_snapshot()["replicas_alive"] == 0

    def test_journal_replay_reports_not_redecodes(self, tmp_path):
        core = GatewayCore(GatewayConfig())
        (r1,) = make_loopback_fleet(core, 1, tmp=str(tmp_path))
        th = threading.Thread(target=r1.run, daemon=True)
        th.start()
        core.submit("a", [3, 4], 4)
        assert wait_for(lambda: core.counters["completed"] == 1)
        core.drain("r0")
        th.join(timeout=10)
        # "Restart": a fresh runner over the same journal; the gateway
        # still remembers the request (dedupe) — the replayed report is
        # dropped, and nothing decodes twice.
        (r2,) = make_loopback_fleet(core, 1, tmp=str(tmp_path))
        r2.register()
        assert r2.replayed == 1
        assert core.counters["duplicate_completions"] == 1
        assert core.counters["completed"] == 1

    def test_journal_grant_hit_answers_without_decoding(self, tmp_path):
        """A re-dispatched request landing on the SAME restarted
        replica is answered from its journal at grant time."""
        core = GatewayCore(GatewayConfig())
        (r1,) = make_loopback_fleet(core, 1, tmp=str(tmp_path))
        th = threading.Thread(target=r1.run, daemon=True)
        th.start()
        core.submit("a", [5, 6], 4)
        assert wait_for(lambda: core.counters["completed"] == 1)
        core.drain("r0")
        th.join(timeout=10)
        # Fresh gateway (lost all state) + restarted replica with the
        # old journal: the same request re-submitted must be served
        # from the journal, not re-decoded.
        core2 = GatewayCore(GatewayConfig())
        (r2,) = make_loopback_fleet(core2, 1, tmp=str(tmp_path))
        served_before = r2.served
        th2 = threading.Thread(target=r2.run, daemon=True)
        th2.start()
        core2.submit("a", [5, 6], 4)
        assert wait_for(lambda: core2.counters["completed"] == 1)
        assert core2.status("a").tokens == expected_tokens([5, 6], 4)
        assert r2.served == served_before  # no fresh decode
        assert r2.replayed >= 1
        core2.drain("r0")
        th2.join(timeout=10)

    def test_cancel_sheds_in_flight_slot_via_abort(self):
        """A gateway cancel for a request already decoding frees the
        slot mid-stream instead of letting it run to its budget."""
        class ScriptedTransport:
            def call(self, msg, **_kw):
                if isinstance(msg, ServeReplicaPoll):
                    return ServeGrants(cancel=["a"], known=True)
                return None

        srv = FakeDecodeServer(1)
        runner = ReplicaRunner(srv, ScriptedTransport(), "r0",
                               poll_interval=0.0)
        srv._active["a"] = ([1, 2], [5], 1000000)  # mid-decode
        runner._granted["a"] = {"prompt": [1, 2]}
        assert runner.tick() is True
        assert srv.active_rids() == []  # slot shed
        assert "a" not in runner._granted

    def test_journal_is_bounded_and_compacts(self, tmp_path):
        from dlrover_tpu.serving.replica import CompletionJournal

        path = str(tmp_path / "j.jsonl")
        j = CompletionJournal(path, max_records=8)
        for i in range(8 + 64 + 1):  # crosses the cap+slack threshold
            j.append(f"q{i}", [i], [i, i])
        # Compaction fired at the 72nd append (cap 8 + slack 64),
        # trimming to the newest 8; one more append lands after it.
        assert len(j.replayable()) == 9
        # Oldest dropped, newest kept — on disk too.
        assert j.lookup("q0", [0]) is None
        assert j.lookup("q72", [72]) == [72, 72]
        j.close()
        lines = open(path).read().strip().split("\n")
        assert len(lines) == 9
        # Reload honours the cap (constructor compacts past-cap files)
        # and still replays the survivors.
        j2 = CompletionJournal(path, max_records=8)
        assert len(j2.replayable()) == 8
        assert j2.lookup("q72", [72]) == [72, 72]

    def test_journal_replay_happens_once_per_incarnation(self, tmp_path):
        """A gateway flap (known=False poll -> re-register) must NOT
        re-send the whole journal: replay is once per process start;
        re-dispatched grants hit the journal at grant time instead."""
        core = GatewayCore(GatewayConfig())
        (r1,) = make_loopback_fleet(core, 1, tmp=str(tmp_path))
        th = threading.Thread(target=r1.run, daemon=True)
        th.start()
        core.submit("a", [3, 4], 4)
        assert wait_for(lambda: core.counters["completed"] == 1)
        core.drain("r0")
        th.join(timeout=10)
        (r2,) = make_loopback_fleet(core, 1, tmp=str(tmp_path))
        r2.register()
        assert r2.replayed == 1
        r2.register()  # flap: second register of the same incarnation
        assert r2.replayed == 1  # no bulk re-replay

    def test_torn_journal_tail_is_ignored(self, tmp_path):
        from dlrover_tpu.serving.replica import CompletionJournal

        j = CompletionJournal(str(tmp_path / "j.jsonl"))
        j.append("a", [1, 2], [7, 8])
        j.close()
        with open(tmp_path / "j.jsonl", "a") as f:
            f.write('{"rid": "b", "ph": "x", "tok')  # SIGKILL mid-append
        j2 = CompletionJournal(str(tmp_path / "j.jsonl"))
        assert set(j2.replayable()) == {"a"}
        assert j2.lookup("a", [1, 2]) == [7, 8]
        # Prompt-hash mismatch (journal-path reuse): no stale replay.
        assert j2.lookup("a", [9, 9]) is None

    def test_drop_request_chaos_recovers_via_reconcile(self, tmp_path):
        from dlrover_tpu import chaos

        core = GatewayCore(GatewayConfig())
        (runner,) = make_loopback_fleet(core, 1, tmp=str(tmp_path))
        chaos.configure("serving.drop_request:p=1,times=1,seed=3")
        try:
            th = threading.Thread(target=runner.run, daemon=True)
            th.start()
            core.submit("a", [2, 3], 4)
            # Dropped once, re-dispatched by reconcile, then served.
            assert wait_for(lambda: core.counters["completed"] == 1)
            assert core.counters["redispatched"] >= 1
            assert runner.dropped == 1
            assert core.status("a").tokens == expected_tokens([2, 3], 4)
            core.drain("r0")
            th.join(timeout=10)
        finally:
            chaos.reset()

    def test_cancel_prunes_replica_pending(self):
        """A gateway cancel (deadline expiry) drops a granted request
        still waiting in the replica's pending queue — in-flight work
        is never interrupted, queued work is."""
        class ScriptedTransport:
            def __init__(self):
                self.sent = []

            def call(self, msg, **_kw):
                self.sent.append(msg)
                if isinstance(msg, ServeReplicaPoll):
                    return ServeGrants(cancel=["a"], known=True)
                return None

        srv = FakeDecodeServer(2)
        transport = ScriptedTransport()
        runner = ReplicaRunner(srv, transport, "r0",
                               poll_interval=0.0)
        srv.submit("a", [1, 2], 4)
        runner._granted["a"] = {"prompt": [1, 2]}
        assert runner.tick() is True
        assert srv.pending_count() == 0  # cancelled before admission
        assert "a" not in runner._granted


# ---------------------------------------------------------------------------
# Wire round-trip of the new messages
# ---------------------------------------------------------------------------


def test_serving_messages_roundtrip():
    g = ServeGrants(
        requests=[ServeSubmit(req_id="x", prompt=[1, 2],
                              max_new_tokens=9, deadline_s=1.5)],
        cancel=["y"], drain=True, known=False,
    )
    g2 = deserialize(serialize(g))
    assert isinstance(g2, ServeGrants)
    assert g2.requests[0].prompt == [1, 2]
    assert g2.requests[0].max_new_tokens == 9
    assert g2.cancel == ["y"] and g2.drain and g2.known is False
    d = deserialize(serialize(ServeDone(
        replica_id="r", req_id="x", tokens=[3], replayed=True,
    )))
    assert d.replayed is True and d.tokens == [3]


def test_empty_req_id_is_rejected_terminally():
    """'' is BoundedTokenCache's no-token sentinel: the completion
    would be unrecordable and the client would poll 'unknown' forever."""
    core = GatewayCore(GatewayConfig())
    ack = core.submit("", [1, 2], 4)
    assert ack.status == "failed"
    assert "empty req_id" in ack.reason
    assert core.stats_snapshot()["queue_depth"] == 0


# ---------------------------------------------------------------------------
# Prefix-aware routing (ISSUE 8): the residency map and its guards
# ---------------------------------------------------------------------------


class TestPrefixRouting:
    def test_warm_replica_preferred_cold_defers(self):
        core, _ = make_core()
        core.register("warm", 2)
        core.register("cold", 2)
        core.poll("warm", 0, [], warm_prefixes=["fpA"])
        core.submit("a", [1, 2, 3], 4, prefix_len=2, prefix_fp="fpA")
        # Cold polls first: the request is reserved for the warm
        # holder (which has capacity, inside the reserve window).
        g = core.poll("cold", 2, [])
        assert g.requests == []
        g = core.poll("warm", 1, [], warm_prefixes=["fpA"])
        assert [r.req_id for r in g.requests] == ["a"]
        assert core.counters["prefix_hits"] == 1
        assert core.counters["prefix_steals"] == 0

    def test_deferred_prefix_does_not_starve_queue_behind_it(self):
        core, _ = make_core()
        core.register("warm", 2)
        core.register("cold", 2)
        core.poll("warm", 0, [], warm_prefixes=["fpA"])
        core.submit("hot", [1, 2, 3], 4, prefix_len=2, prefix_fp="fpA")
        core.submit("plain", [5, 6], 4)
        # The cold replica skips the reserved request and takes the
        # plain one behind it.
        g = core.poll("cold", 2, [])
        assert [r.req_id for r in g.requests] == ["plain"]

    def test_saturated_warm_holder_is_stolen_from(self):
        core, _ = make_core()
        core.register("warm", 1)
        core.register("cold", 2)
        core.submit("a", [1, 2, 3], 4, prefix_len=2, prefix_fp="fpA")
        g = core.poll("warm", 1, [], warm_prefixes=["fpA"])
        assert [r.req_id for r in g.requests] == ["a"]  # warm busy now
        core.submit("b", [1, 2, 9], 4, prefix_len=2, prefix_fp="fpA")
        # warm has 1/1 assigned: the overload guard lets cold steal.
        g = core.poll("cold", 2, [])
        assert [r.req_id for r in g.requests] == ["b"]
        assert core.counters["prefix_steals"] == 1

    def test_reserve_window_expiry_steals(self):
        core, clock = make_core(prefix_reserve_s=2.0)
        core.register("warm", 2)
        core.register("cold", 2)
        core.poll("warm", 0, [], warm_prefixes=["fpA"])
        core.submit("a", [1, 2, 3], 4, prefix_len=2, prefix_fp="fpA")
        assert core.poll("cold", 1, []).requests == []
        clock.advance(3.0)
        g = core.poll("cold", 1, [])
        assert [r.req_id for r in g.requests] == ["a"]
        assert core.counters["prefix_steals"] == 1

    def test_no_warm_holder_is_plain_miss(self):
        """Fingerprint nobody holds (or a stale fp after journal-path
        reuse): falls straight back to least-loaded, counted a miss."""
        core, _ = make_core()
        core.register("r0", 2)
        core.submit("a", [1, 2, 3], 4, prefix_len=2, prefix_fp="fpX")
        g = core.poll("r0", 1, [])
        assert [r.req_id for r in g.requests] == ["a"]
        assert core.counters["prefix_misses"] == 1

    def test_residency_evicted_on_deregister(self):
        core, _ = make_core()
        core.register("warm", 2)
        core.register("cold", 2)
        core.poll("warm", 0, [], warm_prefixes=["fpA"])
        core.deregister("warm")
        core.submit("a", [1, 2, 3], 4, prefix_len=2, prefix_fp="fpA")
        # No defer against a dead replica: immediate miss-grant.
        g = core.poll("cold", 1, [])
        assert [r.req_id for r in g.requests] == ["a"]
        assert core.counters["prefix_misses"] == 1

    def test_residency_evicted_on_lease_expiry(self):
        core, clock = make_core(lease_timeout_s=5.0)
        core.register("warm", 2)
        core.register("cold", 2)
        core.poll("warm", 0, [], warm_prefixes=["fpA"])
        clock.advance(3.0)
        core.poll("cold", 0, [])  # cold stays fresh
        clock.advance(3.0)
        core.sweep()  # warm's lease lapsed (6s); cold is 3s fresh
        core.submit("a", [1, 2, 3], 4, prefix_len=2, prefix_fp="fpA")
        g = core.poll("cold", 1, [])
        assert [r.req_id for r in g.requests] == ["a"]
        assert core.counters["prefix_misses"] == 1

    def test_poll_report_replaces_residency_wholesale(self):
        """LRU eviction on the replica must self-correct the map: the
        next poll stops reporting the fp and the reservation ends."""
        core, _ = make_core()
        core.register("warm", 2)
        core.register("cold", 2)
        core.poll("warm", 0, [], warm_prefixes=["fpA"])
        core.poll("warm", 0, [], warm_prefixes=["fpB"])  # fpA evicted
        core.submit("a", [1, 2, 3], 4, prefix_len=2, prefix_fp="fpA")
        g = core.poll("cold", 1, [])
        assert [r.req_id for r in g.requests] == ["a"]
        assert core.counters["prefix_misses"] == 1

    def test_snapshot_carries_prefix_counters_and_warm_sets(self):
        core, _ = make_core()
        core.register("r0", 2)
        core.poll("r0", 0, [], warm_prefixes=["fpZ"])
        snap = core.stats_snapshot()
        assert snap["replicas"]["r0"]["warm_prefixes"] == ["fpZ"]
        for key in ("prefix_hits", "prefix_misses", "prefix_steals"):
            assert key in snap["counters"]

    def test_runner_reports_server_warm_fps(self):
        """The runner's poll carries the decode server's warm set."""
        polls = []

        class T:
            def call(self, msg, **_kw):
                if isinstance(msg, ServeReplicaPoll):
                    polls.append(msg)
                return None

        srv = FakeDecodeServer(1)
        srv.warm_prefix_fps = lambda: ["fpQ"]
        runner = ReplicaRunner(srv, T(), "r0", poll_interval=0.0)
        runner.tick()
        assert polls and polls[-1].warm_prefixes == ["fpQ"]


# ---------------------------------------------------------------------------
# Prefill/decode disaggregation (ISSUE 8): the two-stage grant path
# ---------------------------------------------------------------------------


class TestDisaggregationCore:
    def test_two_stage_flow(self):
        core, _ = make_core()
        core.register("p0", 1, role="prefill")
        core.register("d0", 1, role="decode")
        core.submit("x", [4, 5, 6], 5)
        assert core.poll("d0", 1, []).requests == []  # decode: no prefill
        g = core.poll("p0", 1, [])
        assert g.requests[0].stage == "prefill"
        assert core.kv_ready("p0", "x", b"SEG", fp32_bytes=40) == \
            "recorded"
        assert core.poll("p0", 1, []).requests == []  # prefill: no decode
        g = core.poll("d0", 1, [])
        assert g.requests[0].stage == "decode"
        assert g.requests[0].kv == b"SEG"
        assert core.complete("d0", "x", [1, 2]) == "recorded"
        c = core.counters
        assert c["kv_handoffs"] == 1 and c["kv_bytes"] == 3
        assert c["kv_fp32_bytes"] == 40

    def test_prefill_withheld_without_decode_capacity(self):
        """A prefill-only fleet must not burn prefills into segments
        nobody can decode."""
        core, _ = make_core()
        core.register("p0", 1, role="prefill")
        core.submit("x", [4], 5)
        assert core.poll("p0", 1, []).requests == []
        core.register("u0", 1, role="unified")
        g = core.poll("p0", 1, [])
        assert g.requests and g.requests[0].stage == "prefill"

    def test_unified_replica_serves_both_stages(self):
        core, _ = make_core()
        core.register("u0", 2, role="unified")
        core.register("p0", 1, role="prefill")
        core.submit("x", [4], 5)
        g = core.poll("p0", 1, [])
        assert g.requests[0].stage == "prefill"
        core.kv_ready("p0", "x", b"S")
        g = core.poll("u0", 1, [])
        assert g.requests[0].stage == "decode" and g.requests[0].kv

    def test_kill_between_prefill_grant_and_kv_ready_requeues(self):
        core, clock = make_core(lease_timeout_s=10.0)
        core.register("p0", 1, role="prefill")
        core.register("d0", 1, role="decode")
        core.submit("y", [7, 8], 5)
        core.poll("p0", 1, [])
        clock.advance(6.0)
        core.poll("d0", 1, [])  # decode lease stays fresh
        clock.advance(5.0)
        core.sweep()  # p0 dead between the stages
        core.register("p1", 1, role="prefill")
        g = core.poll("p1", 1, [])
        # Re-dispatched as a FRESH prefill (no segment existed yet).
        assert g.requests[0].req_id == "y"
        assert g.requests[0].stage == "prefill"
        assert core.counters["redispatched"] == 1

    def test_kill_after_kv_ready_reships_same_segment(self):
        core, clock = make_core(lease_timeout_s=10.0)
        core.register("p0", 1, role="prefill")
        core.register("d0", 1, role="decode")
        core.submit("y", [7, 8], 5)
        core.poll("p0", 1, [])
        core.kv_ready("p0", "y", b"SEG2")
        g = core.poll("d0", 1, [])
        assert g.requests[0].kv == b"SEG2"
        clock.advance(6.0)
        core.poll("p0", 0, [])
        clock.advance(5.0)
        core.sweep()  # d0 dead mid-decode; the segment is NOT lost
        core.register("d1", 1, role="decode")
        g = core.poll("d1", 1, [])
        assert g.requests[0].stage == "decode"
        assert g.requests[0].kv == b"SEG2"
        assert core.complete("d1", "y", [3]) == "recorded"

    def test_stale_kv_ready_from_superseded_prefill_dropped(self):
        core, clock = make_core(lease_timeout_s=10.0)
        core.register("p0", 1, role="prefill")
        core.register("d0", 1, role="decode")
        core.submit("y", [7], 5)
        core.poll("p0", 1, [])
        clock.advance(6.0)
        core.poll("d0", 1, [])
        clock.advance(5.0)
        core.sweep()
        core.register("p1", 1, role="prefill")
        core.poll("p1", 1, [])  # y re-granted to p1
        # Zombie p0 finally reports its segment: dropped.
        assert core.kv_ready("p0", "y", b"ZOMBIE") == "stale"
        core.kv_ready("p1", "y", b"LIVE")
        g = core.poll("d0", 1, [])
        assert g.requests[0].kv == b"LIVE"

    def test_stale_kv_reject_from_superseded_decode_dropped(self):
        """A stalled decode replica rejecting AFTER the lease machinery
        re-granted the segment elsewhere must not tear down the live
        assignment (nor burn attempts on a healthy request)."""
        core, clock = make_core(lease_timeout_s=10.0)
        core.register("p0", 1, role="prefill")
        core.register("d0", 1, role="decode")
        core.submit("y", [7], 5)
        core.poll("p0", 1, [])
        core.kv_ready("p0", "y", b"SEG")
        core.poll("d0", 1, [])  # d0 granted, then stalls
        clock.advance(6.0)
        core.poll("p0", 0, [])
        clock.advance(5.0)
        core.sweep()  # d0 presumed dead; segment kept
        core.register("d1", 1, role="decode")
        g = core.poll("d1", 1, [])
        assert g.requests and g.requests[0].kv == b"SEG"
        # Zombie d0 finally rejects: dropped, d1's decode undisturbed.
        assert core.kv_reject("d0", "y", "late") == "stale"
        assert core.counters["kv_rejects"] == 0
        assert core.status("y").state == "running"
        assert core.complete("d1", "y", [3]) == "recorded"

    def test_torn_segments_fail_terminally_after_max_attempts(self):
        """kv_reject re-prefills, bounded: never hangs, never decodes
        a torn segment."""
        core, _ = make_core(max_attempts=3)
        core.register("p0", 1, role="prefill")
        core.register("d0", 1, role="decode")
        core.submit("z", [9], 5)
        for _ in range(3):
            g = core.poll("p0", 1, [])
            assert g.requests and g.requests[0].stage == "prefill"
            core.kv_ready("p0", "z", b"TORN")
            g = core.poll("d0", 1, [])
            assert g.requests and g.requests[0].req_id == "z"
            core.kv_reject("d0", "z", "crc mismatch")
        st = core.status("z")
        assert st.state == "failed" and "re-dispatched" in st.reason
        assert core.counters["kv_rejects"] == 3

    def test_pools_in_snapshot(self):
        core, _ = make_core()
        core.register("p0", 2, role="prefill")
        core.register("d0", 4, role="decode")
        core.submit("a", [1], 4)
        core.submit("b", [2], 4)
        g = core.poll("p0", 1, [])
        assert g.requests[0].req_id == "a"
        core.kv_ready("p0", "a", b"S")
        snap = core.stats_snapshot()
        pools = snap["pools"]
        assert pools["prefill"]["alive"] == 1
        assert pools["decode"]["alive"] == 1
        # 'b' is stage-queued (feeds the prefill pool); 'a' is a held
        # segment awaiting decode capacity (feeds the decode pool).
        assert pools["prefill"]["queue_depth"] == 1
        assert pools["decode"]["queue_depth"] == 1
        assert snap["queue_prefill"] == 1
        assert snap["queue_kv_ready"] == 1


class TestDisaggFleet:
    """Runner-level loopback fleets over the fake servers."""

    def _run(self, core, runners):
        threads = []
        for runner in runners:
            th = threading.Thread(target=runner.run, daemon=True)
            th.start()
            threads.append(th)
        return threads

    def _stop(self, core, runners, threads):
        for runner in runners:
            core.drain(runner.replica_id)
        for th in threads:
            th.join(timeout=10)
            assert not th.is_alive()

    def test_disagg_results_match_unified_law(self, tmp_path):
        core = GatewayCore(GatewayConfig())
        runners = make_disagg_fleet(core, prefill=1, decode=1,
                                    tmp=str(tmp_path))
        threads = self._run(core, runners)
        try:
            for i in range(6):
                core.submit(f"q{i}", [i + 1, i + 2], 4)
            assert wait_for(lambda: core.counters["completed"] == 6)
            for i in range(6):
                st = core.status(f"q{i}")
                assert st.state == "done"
                assert st.tokens == expected_tokens([i + 1, i + 2], 4)
            c = core.counters
            assert c["kv_handoffs"] == 6 and c["kv_rejects"] == 0
            assert c["kv_bytes"] > 0
        finally:
            self._stop(core, runners, threads)

    def test_kv_drop_at_export_recovers_via_reconcile(self, tmp_path):
        from dlrover_tpu import chaos

        core = GatewayCore(GatewayConfig(lease_timeout_s=0.5))
        runners = make_disagg_fleet(core, prefill=1, decode=1,
                                    tmp=str(tmp_path))
        chaos.configure("serving.kv_drop:method=export,times=1,seed=3")
        try:
            threads = self._run(core, runners)
            core.submit("a", [2, 3], 4)
            assert wait_for(lambda: core.counters["completed"] == 1)
            assert core.status("a").tokens == expected_tokens([2, 3], 4)
            assert runners[0].dropped == 1
            assert core.counters["redispatched"] >= 1
            self._stop(core, runners, threads)
        finally:
            chaos.reset()

    def test_kv_drop_at_import_reprefills_then_completes(self,
                                                         tmp_path):
        from dlrover_tpu import chaos

        core = GatewayCore(GatewayConfig())
        runners = make_disagg_fleet(core, prefill=1, decode=1,
                                    tmp=str(tmp_path))
        chaos.configure("serving.kv_drop:method=import,times=1,seed=3")
        try:
            threads = self._run(core, runners)
            core.submit("a", [2, 3], 4)
            assert wait_for(lambda: core.counters["completed"] == 1)
            assert core.status("a").tokens == expected_tokens([2, 3], 4)
            c = core.counters
            assert c["kv_rejects"] == 1
            assert c["kv_handoffs"] == 2  # torn once, re-prefilled
            assert runners[1].kv_rejected == 1
            self._stop(core, runners, threads)
        finally:
            chaos.reset()

    def test_always_torn_fails_terminally_never_hangs(self, tmp_path):
        from dlrover_tpu import chaos

        core = GatewayCore(GatewayConfig(max_attempts=3))
        runners = make_disagg_fleet(core, prefill=1, decode=1,
                                    tmp=str(tmp_path))
        chaos.configure(
            "serving.kv_drop:method=import,times=-1,seed=3"
        )
        try:
            threads = self._run(core, runners)
            core.submit("a", [2, 3], 4)
            assert wait_for(
                lambda: core.status("a").state == "failed"
            )
            assert "re-dispatched" in core.status("a").reason
            assert core.counters["completed"] == 0
            self._stop(core, runners, threads)
        finally:
            chaos.reset()


# ---------------------------------------------------------------------------
# Per-role pool autoscale (ISSUE 8)
# ---------------------------------------------------------------------------


class TestPoolAutoscale:
    def _pools(self, prefill, decode):
        return {"pools": {
            "prefill": prefill, "decode": decode,
        }}

    def test_independent_signals(self):
        policies = {
            "prefill": ScalePolicy(up_patience=1,
                                   queue_high_per_replica=2),
            "decode": ScalePolicy(up_patience=1, down_patience=2,
                                  queue_high_per_replica=2),
        }
        states = {}
        snap = self._pools(
            {"alive": 1, "queue_depth": 10, "occupancy": 1.0},
            {"alive": 2, "queue_depth": 0, "occupancy": 0.1},
        )
        t = decide_pools(snap, policies, states)
        assert t["prefill"] == 2  # pressure
        assert t["decode"] == 2  # down_patience not yet consumed
        t = decide_pools(snap, policies, states)
        assert t["decode"] == 1  # second idle pass shrinks decode

    def test_ttft_signal_reaches_prefill_not_decode(self):
        policies = {
            role: ScalePolicy(up_patience=1, ttft_p95_high_ms=500,
                              queue_high_per_replica=1e9)
            for role in ("prefill", "decode")
        }
        snap = self._pools(
            {"alive": 1, "queue_depth": 0, "occupancy": 0.5},
            {"alive": 1, "queue_depth": 0, "occupancy": 0.5},
        )
        snap["ttft_p95_ms"] = 900.0
        t = decide_pools(snap, policies, {})
        # Admission latency is the prefill pool's signal.
        assert t["prefill"] == 2
        assert t["decode"] == 1

    def test_pool_autoscaler_actuates_per_role(self):
        ups = []
        drains = []
        snap = self._pools(
            {"alive": 1, "queue_depth": 10, "occupancy": 1.0},
            {"alive": 3, "queue_depth": 0, "occupancy": 0.0},
        )
        sc = PoolAutoScaler(
            snapshot_fn=lambda: snap,
            scale_up_fn=lambda role, n: ups.append((role, n)),
            drain_fn=lambda role: drains.append(role),
            policies={
                "prefill": ScalePolicy(up_patience=1,
                                       queue_high_per_replica=2),
                "decode": ScalePolicy(down_patience=1),
            },
        )
        deltas = sc.scale_once()
        assert ups == [("prefill", 1)]
        assert drains == ["decode"]
        assert deltas == {"prefill": 1, "decode": -1}

    def test_gateway_pick_drain_victim_by_role(self):
        core, _ = make_core()
        core.register("p0", 2, role="prefill")
        core.register("d0", 2, role="decode")
        core.register("d1", 2, role="decode")
        assert core.pick_drain_victim(role="prefill") == "p0"
        assert core.pick_drain_victim(role="decode") == "d0"
        core.drain("d0")
        assert core.pick_drain_victim(role="decode") == "d1"


def test_journal_eager_replay_is_capped(tmp_path):
    """Restart replay must not storm the gateway with one RPC per
    journal record (a full journal would stall polls past the lease):
    only the newest replay_limit records replay eagerly."""
    from dlrover_tpu.serving.replica import CompletionJournal

    path = str(tmp_path / "j.jsonl")
    j = CompletionJournal(path)
    for i in range(40):
        j.append(f"q{i}", [i], [i])
    j.close()

    sent = []

    class T:
        def call(self, msg, **_kw):
            sent.append(msg)
            return None

    runner = ReplicaRunner(FakeDecodeServer(1), T(), "r0",
                           journal_path=path, replay_limit=10)
    runner.register()
    dones = [m for m in sent if isinstance(m, ServeDone)]
    assert len(dones) == 10
    # Newest records replay; the older ones answer via grant-time
    # lookup instead.
    assert {m.req_id for m in dones} == {f"q{i}" for i in range(30, 40)}


def test_every_core_counter_is_exported_as_a_gauge():
    """ISSUE 14 (graftcheck MT601): the admission/exactly-once
    counters (submitted/completed/failed/timeout/...) were visible
    only via the stats-snapshot RPC — /metrics showed none of them.
    Every GatewayCore counter now has a ``serve_<name>`` gauge."""
    from dlrover_tpu.agent.metrics import MetricsRegistry
    from dlrover_tpu.serving.gateway import Gateway

    gw = Gateway(port=0)
    try:
        reg = MetricsRegistry()
        gw.register_gauges(reg)
        core = gw.core
        core.register("r0", slots=2)
        core.submit("rq1", [1, 2, 3], 4, 0.0)
        body = reg.render()
        for name in core.counters:
            assert f"serve_{name} " in body, (
                f"counter {name!r} has no serve_{name} gauge"
            )
        # And the fix's headline signals carry real values.
        assert "serve_submitted 1.0" in body
        assert "serve_accepted 1.0" in body
    finally:
        gw.stop(grace=0.1)


# ---------------------------------------------------------------------------
# Paged KV at the serving layer (ISSUE 19): memory gate, snapshot
# gauges, autoscale memory-pressure signal
# ---------------------------------------------------------------------------


class TestPagedKvServing:
    def test_exhausted_block_pool_gates_grants_despite_free_slots(self):
        core, _ = make_core()
        core.register("r0", 4)
        assert core.submit("a", [1, 2], 4).status == "accepted"
        # Free SLOTS but zero free BLOCKS: granting would only queue
        # (or preempt) replica-side, so the poll comes back empty.
        g = core.poll("r0", 4, [],
                      stats={"total_blocks": 8, "free_blocks": 0})
        assert g.requests == []
        # Blocks freed (a finish or abort replica-side): the very same
        # request is granted on the next poll.
        g = core.poll("r0", 4, [],
                      stats={"total_blocks": 8, "free_blocks": 3})
        assert [r.req_id for r in g.requests] == ["a"]

    def test_dense_replica_stats_never_trip_the_gate(self):
        core, _ = make_core()
        core.register("r0", 2)
        core.submit("a", [1], 4)
        # A slotted replica reports no block gauges (total_blocks 0 /
        # absent): the gate must stay out of its way.
        g = core.poll("r0", 2, [], stats={"occupancy": 0.5})
        assert [r.req_id for r in g.requests] == ["a"]

    def test_snapshot_carries_block_gauges_and_kv_occupancy(self):
        core, _ = make_core()
        core.register("d0", 2, role="decode")
        core.register("d1", 2, role="decode")
        core.poll("d0", 2, [], stats={
            "kv_occupancy": 0.75, "total_blocks": 8, "free_blocks": 2,
        })
        core.poll("d1", 2, [], stats={
            "kv_occupancy": 0.25, "total_blocks": 8, "free_blocks": 6,
        })
        snap = core.stats_snapshot()
        pool = snap["pools"]["decode"]
        assert pool["kv_occupancy"] == pytest.approx(0.5)
        assert pool["total_blocks"] == 16
        assert pool["free_blocks"] == 8
        # Fleet roll-up: slot-weighted mean of the reported values.
        assert snap["kv_occupancy"] == pytest.approx(0.5)

    def test_kv_occupancy_falls_back_to_slot_fraction(self):
        core, _ = make_core()
        core.register("r0", 2)
        core.submit("a", [1], 4)
        g = core.poll("r0", 2, [])
        assert len(g.requests) == 1
        snap = core.stats_snapshot()
        # One of two slots assigned, nobody reporting kv_occupancy:
        # the gauge degrades to the slot fraction — continuous across
        # the paged-flag flip, so hysteresis never sees a step.
        assert snap["kv_occupancy"] == pytest.approx(0.5)
        assert snap["pools"]["unified"]["kv_occupancy"] == \
            pytest.approx(0.5)

    def test_mem_high_occupancy_scales_up_on_block_pressure(self):
        # Queue empty, slot occupancy moderate — but the block pool is
        # nearly full.  Only the memory signal sees this pressure.
        snap = {"replicas_alive": 2, "queue_depth": 0,
                "occupancy": 0.5, "kv_occupancy": 0.95}
        pol = ScalePolicy(max_replicas=4, up_patience=1,
                          mem_high_occupancy=0.8)
        assert decide(snap, pol, ScaleState()) == 3
        # Default 0.0 = signal off: identical snapshot holds steady.
        assert decide(snap, ScalePolicy(max_replicas=4, up_patience=1),
                      ScaleState()) == 2

    def test_decide_prefers_kv_occupancy_over_slot_fraction(self):
        # Slot fraction says idle; the block pool says otherwise — the
        # memory gauge wins, suppressing the scale-down.
        pol = ScalePolicy(min_replicas=1, down_patience=1,
                          queue_low_per_replica=0.5, occupancy_low=0.3)
        busy = {"replicas_alive": 2, "queue_depth": 0,
                "occupancy": 0.1, "kv_occupancy": 0.9}
        assert decide(busy, pol, ScaleState()) == 2
        idle = {"replicas_alive": 2, "queue_depth": 0,
                "occupancy": 0.1, "kv_occupancy": 0.1}
        assert decide(idle, pol, ScaleState()) == 1

    def test_decide_pools_carries_kv_occupancy_through(self):
        policies = {"decode": ScalePolicy(max_replicas=4, up_patience=1,
                                          mem_high_occupancy=0.8)}
        states = {}
        snap = {
            "ttft_p95_ms": 0.0,
            "pools": {
                "decode": {"alive": 2, "queue_depth": 0,
                           "occupancy": 0.5, "kv_occupancy": 0.95},
            },
        }
        targets = decide_pools(snap, policies, states)
        assert targets["decode"] == 3
