"""Quantized-gradient collectives (VERDICT r3 missing #1: the
reference ships quant_reduce.cu/swizzled_quantize.cu for 8-bit
compressed gradient reduction; nothing compressed OUR communication)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from dlrover_tpu.models import llama
from dlrover_tpu.ops.quant_collectives import (
    quantized_pmean,
    quantized_psum,
)
from dlrover_tpu.parallel.accelerate import Strategy, accelerate
from dlrover_tpu.parallel.mesh import MeshSpec


class TestQuantizedCollective:
    def test_psum_and_pmean_close_to_exact(self, cpu_mesh_devices):
        mesh = Mesh(np.array(cpu_mesh_devices[:4]), ("dp",))
        rng = np.random.RandomState(0)
        # Odd sizes exercise both padding paths (block pad + N-chunk
        # pad); mixed magnitudes exercise per-block scaling.
        x = (rng.randn(4, 300, 130) * 10 ** rng.uniform(
            -2, 2, (4, 300, 130)
        )).astype(np.float32)

        got = jax.jit(jax.shard_map(
            lambda xl: quantized_psum(xl[0], "dp"), mesh=mesh,
            in_specs=(P("dp"),), out_specs=P(),
        ))(jnp.asarray(x))
        want = x.sum(axis=0)
        rel = np.abs(np.asarray(got) - want).max() / np.abs(want).max()
        assert rel < 0.03, rel

        gm = jax.jit(jax.shard_map(
            lambda xl: quantized_pmean(xl[0], "dp"), mesh=mesh,
            in_specs=(P("dp"),), out_specs=P(),
        ))(jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(gm), np.asarray(got) / 4, rtol=1e-5
        )

    def test_small_leaf_falls_back_exact(self, cpu_mesh_devices):
        mesh = Mesh(np.array(cpu_mesh_devices[:4]), ("dp",))
        y = np.random.RandomState(1).randn(4, 17).astype(np.float32)
        gy = jax.jit(jax.shard_map(
            lambda yl: quantized_pmean(yl[0], "dp"), mesh=mesh,
            in_specs=(P("dp"),), out_specs=P(),
        ))(jnp.asarray(y))
        np.testing.assert_allclose(np.asarray(gy), y.mean(0), rtol=1e-5)

    def test_replicated_result_passes_vma_check(self, cpu_mesh_devices):
        """out_specs=P() compiles with check_vma ON — the result is
        provably identical on every participant (the psum-based
        exchange phase exists for exactly this)."""
        mesh = Mesh(np.array(cpu_mesh_devices[:2]), ("dp",))
        x = np.random.RandomState(2).randn(2, 64, 256).astype(np.float32)
        out = jax.jit(jax.shard_map(
            lambda xl: quantized_psum(xl[0], "dp"), mesh=mesh,
            in_specs=(P("dp"),), out_specs=P(), check_vma=True,
        ))(jnp.asarray(x))
        assert np.isfinite(np.asarray(out)).all()


def _train(quant_grads, devices, steps=20):
    cfg = llama.LlamaConfig.tiny(n_layer=2, max_seq_len=16)
    toks = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (8, 17)
    ).astype("int32")
    job = accelerate(
        loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
        init_fn=lambda r: llama.init_params(r, cfg),
        optimizer=optax.adamw(1e-2),
        sample_batch={"tokens": toks},
        strategy=Strategy(mesh=MeshSpec(dp=4), quant_grads=quant_grads),
        devices=devices[:4],
    )
    state = job.create_state(jax.random.PRNGKey(0))
    batch = {"tokens": jnp.asarray(toks)}
    losses = []
    for _ in range(steps):
        state, m = job.train_step(state, batch)
        losses.append(float(m["loss"]))
    return losses


class TestQuantGradsStrategy:
    def test_trains_to_loss_parity(self, cpu_mesh_devices):
        """VERDICT done-criterion: Strategy(quant_grads=True) trains
        llama_tiny to loss parity (±tolerance) with exact reduction."""
        exact = _train(False, cpu_mesh_devices)
        quant = _train(True, cpu_mesh_devices)
        assert exact[-1] < exact[0] - 0.5
        assert quant[-1] < quant[0] - 0.5
        # Same trajectory within quantization noise.
        assert abs(quant[-1] - exact[-1]) < 0.05, (exact[-1], quant[-1])
        assert abs(quant[0] - exact[0]) < 0.01

    def test_replicated_batch_leaf_preserved(self, cpu_mesh_devices):
        """batch_axes with a REPLICATED leaf must be honored by the
        quant path (review repro: force-sharding every leaf P('dp')
        silently fed each shard 1/N of a replicated weight vector)."""
        cfg = llama.LlamaConfig.tiny(n_layer=1, max_seq_len=16)
        toks = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (8, 17)
        ).astype("int32")
        posw = np.linspace(1.0, 2.0, 8).astype(np.float32)

        def loss_fn(p, b):
            # A replicated aux leaf entering the loss value.
            return llama.loss_fn(
                p, {"tokens": b["tokens"]}, cfg
            ) + 0.001 * jnp.sum(b["posw"])

        def run(qg):
            job = accelerate(
                loss_fn=loss_fn,
                init_fn=lambda r: llama.init_params(r, cfg),
                optimizer=optax.adamw(1e-2),
                sample_batch={"tokens": toks, "posw": posw},
                batch_axes={"tokens": P("dp"), "posw": P()},
                strategy=Strategy(
                    mesh=MeshSpec(dp=4), quant_grads=qg
                ),
                devices=cpu_mesh_devices[:4],
            )
            state = job.create_state(jax.random.PRNGKey(0))
            batch = {
                "tokens": jnp.asarray(toks),
                "posw": jnp.asarray(posw),
            }
            _, m = job.train_step(state, batch)
            return float(m["loss"])

        exact, quant = run(False), run(True)
        assert abs(exact - quant) < 1e-3, (exact, quant)

    def test_grad_accum_single_reduction_parity(self, cpu_mesh_devices):
        """quant_grads x grad_accum: local accumulation + ONE
        compressed reduction per step must track the exact-accum
        trajectory."""
        cfg = llama.LlamaConfig.tiny(n_layer=2, max_seq_len=16)
        toks = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (8, 17)
        ).astype("int32")

        def run(qg):
            job = accelerate(
                loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
                init_fn=lambda r: llama.init_params(r, cfg),
                optimizer=optax.adamw(1e-2),
                sample_batch={"tokens": toks},
                strategy=Strategy(
                    mesh=MeshSpec(dp=2), grad_accum=2,
                    quant_grads=qg,
                ),
                devices=cpu_mesh_devices[:2],
            )
            state = job.create_state(jax.random.PRNGKey(0))
            batch = {"tokens": jnp.asarray(toks)}
            losses = []
            for _ in range(10):
                state, m = job.train_step(state, batch)
                losses.append(float(m["loss"]))
            return losses

        exact = run(False)
        quant = run(True)
        assert quant[-1] < quant[0] - 1.0  # trains
        # Early/mid trajectory parity; by step 10 this tiny problem is
        # deep into overfit where int8 noise legitimately compounds, so
        # the final bound is loose.
        assert abs(quant[5] - exact[5]) < 0.1, (exact[5], quant[5])
        assert abs(quant[-1] - exact[-1]) < 0.5, (exact[-1], quant[-1])

    def test_rejected_with_fp8_or_sharded_mesh(self, cpu_mesh_devices):
        cfg = llama.LlamaConfig.tiny(n_layer=1, max_seq_len=16)
        toks = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (8, 17)
        ).astype("int32")
        kw = dict(
            loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
            init_fn=lambda r: llama.init_params(r, cfg),
            optimizer=optax.adamw(1e-2),
            sample_batch={"tokens": toks},
        )
        with pytest.raises(ValueError, match="pure-dp mesh"):
            # fsdp x quant_grads: fail fast with the real cause.
            accelerate(
                strategy=Strategy(
                    mesh=MeshSpec(dp=2, fsdp=2), quant_grads=True
                ),
                devices=cpu_mesh_devices[:4], **kw,
            )
        with pytest.raises(ValueError, match="dp > 1"):
            # dp=1 x quant_grads: nothing to compress — fail fast, not
            # a silent no-op.
            accelerate(
                strategy=Strategy(quant_grads=True),
                devices=cpu_mesh_devices[:1], **kw,
            )
        with pytest.raises(ValueError, match="incompatible with fp8"):
            accelerate(
                strategy=Strategy(
                    mesh=MeshSpec(dp=4), quant_grads=True, fp8=True
                ),
                devices=cpu_mesh_devices[:4],
                fp8_init=lambda: llama.init_fp8_states(cfg), **kw,
            )

    def test_space_only_offers_pure_dp_points(self):
        from dlrover_tpu.parallel.strategy_search import default_space

        space = default_space(8, quant_grads=(False, True))
        qg = [s for s in space if s.quant_grads]
        assert qg, "space must contain quant_grads points"
        for s in qg:
            assert s.mesh.dp > 1
            assert all(
                getattr(s.mesh, a) <= 1
                for a in ("pp", "fsdp", "ep", "tp")
            )
            assert not s.fp8

    def test_strategy_roundtrips(self):
        from dlrover_tpu.parallel.strategy_search import (
            strategy_from_dict,
            strategy_to_dict,
        )

        s = Strategy(mesh=MeshSpec(dp=4), quant_grads=True)
        s2 = strategy_from_dict(strategy_to_dict(s))
        assert s2.quant_grads is True


class TestLocalSGDQuantSync:
    def test_quant_outer_sync_close_to_exact(self, cpu_mesh_devices):
        """DiLoCo outer sync with int8-compressed drift reduction: the
        synced params stay within quantization noise of the exact sync
        — on the hybrid-mesh layout whose DCN hop this compresses."""
        from dlrover_tpu.parallel.local_sgd import LocalSGDSync

        mesh = Mesh(np.array(cpu_mesh_devices[:4]), ("dp",))
        rng = np.random.RandomState(0)
        params = {
            "w": jnp.asarray(rng.randn(64, 256), jnp.float32),
            "b": jnp.asarray(rng.randn(256), jnp.float32),
        }

        def run(quant):
            sync = LocalSGDSync(
                outer_lr=0.7, outer_momentum=0.9, quant_sync=quant
            )
            anchor, mom = sync.init(params)
            local = sync.scatter(mesh, params)
            # Divergent per-replica drift.
            local = jax.tree_util.tree_map(
                lambda x: x + 0.01 * jnp.arange(
                    4, dtype=jnp.float32
                ).reshape((4,) + (1,) * (x.ndim - 1)),
                local,
            )
            new_p, _, _ = sync.apply(mesh, local, anchor, mom)
            return new_p

        exact = run(False)
        quant = run(True)
        for a, b in zip(
            jax.tree_util.tree_leaves(exact),
            jax.tree_util.tree_leaves(quant),
        ):
            denom = max(float(jnp.abs(a).max()), 1e-6)
            rel = float(jnp.abs(a - b).max()) / denom
            assert rel < 0.03, rel


class TestQuantGradsMultiprocess:
    def test_two_process_train_step(self):
        """2 real OS processes under jax.distributed (2 CPU devices
        each, global dp=4): the quantized-reduction step must trace
        (the vma custom-VJP variance check only fires multiprocess —
        this is the repro that caught it) and both processes must agree
        on the loss."""
        import socket
        import subprocess
        import sys

        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        script = r"""
import os, sys
import numpy as np
pid = int(sys.argv[1]); coord = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.distributed.initialize(coord, num_processes=2, process_id=pid)
import jax.numpy as jnp, optax
from dlrover_tpu.models import llama
from dlrover_tpu.parallel.accelerate import Strategy, accelerate
from dlrover_tpu.parallel.mesh import MeshSpec
cfg = llama.LlamaConfig.tiny(max_seq_len=32)
toks = np.random.RandomState(0).randint(
    0, cfg.vocab_size, (8, 33)).astype('int32')
job = accelerate(
    loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
    init_fn=lambda r: llama.init_params(r, cfg),
    optimizer=optax.adamw(3e-4),
    sample_batch={'tokens': toks},
    strategy=Strategy(mesh=MeshSpec(dp=4), quant_grads=True),
)
state = job.create_state(jax.random.PRNGKey(0))
batch = {'tokens': jax.make_array_from_process_local_data(
    job.batch_sharding['tokens'], toks[4 * pid:4 * pid + 4])}
state, m = job.train_step(state, batch)
print(f"RESULT {pid} {float(m['loss']):.4f}")
"""
        import os

        repo = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        env = {**os.environ, "PYTHONPATH": repo}
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script, str(i),
                 f"127.0.0.1:{port}"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=repo, env=env,
            )
            for i in range(2)
        ]
        outs = [p.communicate(timeout=400)[0] for p in procs]
        results = []
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
            line = [l for l in out.splitlines() if "RESULT" in l][0]
            results.append(line.split()[-1])
        assert results[0] == results[1], results
