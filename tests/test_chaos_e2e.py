"""Flagship chaos e2e scenarios: a real process tree under a seeded
fault plan (``DLROVER_TPU_FAULTS``).

Three scenarios from the chaosd brief, all deterministic via the plan
seed:

1. RPC flap during training — client-side UNAVAILABLE injected on every
   control-plane call; training must still finish.
2. Master restart mid-rendezvous — the master hard-exits (chaos
   ``master.restart``) while node 0 is still waiting for node 1; a
   replacement master on the same port knows nothing, and node 0's
   periodic rendezvous re-join must re-seed it.  (Workers here are
   control-plane-only stubs: multi-process XLA collectives are not
   available on the CPU backend, and the scenario is about the control
   plane anyway.)
3. Crash mid-checkpoint-commit — the agent process hard-exits between
   writing step shards and advancing the tracker; a relaunch (same run
   id) must warm-restore from the surviving shm arena and keep training.

Marked ``slow``: the tier-1 lane runs only the sub-second chaos units in
``test_chaos.py``; these process-tree scenarios ride the e2e lane.
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.chaos, pytest.mark.e2e, pytest.mark.slow]


def _read(path):
    if not os.path.exists(path):
        return ""
    with open(path) as f:
        return f.read()


def _env(extra=None):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": REPO,
        }
    )
    env.pop("DLROVER_TPU_FAULTS", None)
    if extra:
        env.update(extra)
    return env


def _launch_standalone(tmp_path, job_name, script_args, env_extra=None,
                       log_name="run.log"):
    log = open(tmp_path / log_name, "w")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dlrover_tpu.run",
            "--standalone", "--nproc_per_node=1",
            f"--job_name={job_name}",
            "--monitor_interval=1",
            os.path.join(REPO, "examples", "nanogpt_train.py"),
            "--", *script_args,
        ],
        cwd=REPO, env=_env(env_extra), stdout=log,
        stderr=subprocess.STDOUT, start_new_session=True,
    )
    return proc, tmp_path / log_name


def _terminate(procs):
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


class TestRpcFlap:
    def test_training_survives_rpc_flaps(self, tmp_path):
        """Scenario 1: every control-plane RPC drops with p=0.25 (seeded).
        Jittered retry + idempotency tokens + best-effort status reports
        must carry the job to TRAIN_DONE."""
        proc, log = _launch_standalone(
            tmp_path, "chaos-rpcflap", ["--steps=8"],
            env_extra={
                "DLROVER_TPU_FAULTS": "rpc.unavailable:p=0.25,seed=7",
            },
        )
        try:
            rc = proc.wait(timeout=420)
        finally:
            _terminate([proc])
        content = _read(log)
        assert rc == 0, content[-3000:]
        assert "TRAIN_DONE step=8" in content, content[-3000:]
        # The plan actually bit: injected UNAVAILABLEs show up as retries.
        assert "chaos: fault plan active" in content, content[:2000]
        assert "chaos: rpc.unavailable fired" in content, content[-3000:]
        assert re.search(r"RPC \w+ to .* failed .*UNAVAILABLE", content), (
            content[-3000:]
        )


CTRL_WORKER = """\
import sys
import time

print("CTRL_WORKER_START", flush=True)
time.sleep(3.0)
print("CTRL_WORKER_DONE", flush=True)
sys.exit(0)
"""


class TestMasterRestartMidRendezvous:
    def test_rejoin_reseeds_replacement_master(self, tmp_path):
        """Scenario 2: the master dies (chaos master.restart, exit 42)
        while node 0 waits for node 1; a stateless replacement master on
        the same port must learn node 0 again via the agent's periodic
        re-join, then complete the round once node 1 arrives."""
        from dlrover_tpu.common.rpc import find_free_port

        job = "chaos-mrestart"
        port = find_free_port()
        worker_py = tmp_path / "ctrl_worker.py"
        worker_py.write_text(CTRL_WORKER)

        def start_master(faults):
            env = _env({"DLROVER_TPU_FAULTS": faults} if faults else None)
            log = open(tmp_path / "master.log", "a")
            return subprocess.Popen(
                [
                    sys.executable, "-m", "dlrover_tpu.master.main",
                    f"--port={port}", f"--job_name={job}",
                    "--min_nodes=2", "--max_nodes=2",
                ],
                cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
            )

        def start_node(rank):
            env = _env(
                {
                    # Fast re-join so the scenario stays snappy (>
                    # master's 3s lastcall window, well under default 10).
                    "DLROVER_TPU_RDZV_REJOIN_INTERVAL": "4",
                }
            )
            log = open(tmp_path / f"node{rank}.log", "w")
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "dlrover_tpu.run",
                    "--nnodes=2", "--nproc_per_node=1",
                    f"--node_rank={rank}",
                    f"--master_addr=127.0.0.1:{port}",
                    f"--job_name={job}", "--monitor_interval=1",
                    str(worker_py),
                ],
                cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
            )
            return proc, tmp_path / f"node{rank}.log"

        # Master that hard-exits ~6s in — while node0 (min_nodes=2, no
        # peer yet) is still mid-rendezvous.
        m1 = start_master("master.restart:at=6s")
        n0, log0 = start_node(0)
        procs = [m1, n0]
        try:
            rc = m1.wait(timeout=60)
            assert rc == 42, f"master exited {rc}, wanted chaos 42:\n" + (
                _read(tmp_path / "master.log")[-2000:]
            )
            assert n0.poll() is None, (
                "node0 died with the master:\n" + _read(log0)[-3000:]
            )
            # Replacement master, same port, no faults, zero state.
            m2 = start_master(None)
            procs.append(m2)
            # Hold node 1 back past node 0's re-join interval so the log
            # provably shows node 0 re-seeding the blank master itself.
            time.sleep(6.0)
            n1, log1 = start_node(1)
            procs.append(n1)
            rc0 = n0.wait(timeout=300)
            rc1 = n1.wait(timeout=300)
            c0, c1 = _read(log0), _read(log1)
            assert rc0 == 0, c0[-3000:]
            assert rc1 == 0, c1[-3000:]
            assert "CTRL_WORKER_DONE" in c0, c0[-3000:]
            assert "CTRL_WORKER_DONE" in c1, c1[-3000:]
            # Node 0 really did ride through the restart via re-join.
            assert "re-sent join" in c0, c0[-3000:]
        finally:
            _terminate(procs)


class TestCrashMidCommit:
    def test_agent_crash_between_shards_and_tracker(self, tmp_path):
        """Scenario 3: the agent hard-exits mid-commit (after shard+done
        files, before the tracker advance — ``every=2`` crashes the 2nd
        commit so the 1st step is durably committed first).  The tracker
        must still name the previous step, and a relaunch with the same
        run id must warm-restore from the surviving shm arena."""
        job = "chaos-commit"
        ckpt = str(tmp_path / "ckpt")
        run_id = "chaoscommit1"
        proc, log = _launch_standalone(
            tmp_path, job,
            ["--steps=100000", f"--ckpt_dir={ckpt}", "--ckpt_interval=3",
             "--ckpt_storage_interval=3", "--batch_per_proc=2"],
            env_extra={
                "DLROVER_TPU_FAULTS":
                    "ckpt.crash_before_commit:every=2,times=1",
                "DLROVER_TPU_RUN_ID": run_id,
            },
            log_name="run1.log",
        )
        worker_pids = []
        try:
            rc = proc.wait(timeout=420)
            content = _read(log)
            # The commit crash takes down the whole agent process.
            assert rc == 66, f"rc={rc}\n" + content[-3000:]
            m = re.search(
                r"started 1 worker\(s\): pids=\[(\d+)\]", content
            )
            assert m, content[-3000:]
            worker_pids = [int(m.group(1))]
        finally:
            # The agent died hard: reap its orphans (the worker runs in
            # its own session; the master shares the launcher's group).
            for pid in worker_pids:
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        # Commit atomicity: the crash hit a commit before its tracker
        # write, so the tracker either names the prior durable commit (a
        # valid step) or — if the two in-flight commits raced — does not
        # exist at all.  It is never torn.
        tracker = os.path.join(ckpt, "latest_checkpointed_step.txt")
        committed = 3
        if os.path.exists(tracker):
            committed = int(open(tracker).read().strip())
            assert committed >= 3

        # Relaunch with the SAME run id: the shm arena survived the agent
        # crash, so the restore must take the warm path.
        proc2, log2 = _launch_standalone(
            tmp_path, job,
            ["--steps=100000", f"--ckpt_dir={ckpt}", "--ckpt_interval=3",
             "--batch_per_proc=2"],
            env_extra={"DLROVER_TPU_RUN_ID": run_id},
            log_name="run2.log",
        )
        try:
            restored = False
            deadline = time.time() + 420
            while time.time() < deadline:
                c2 = _read(log2)
                if re.search(r"restored step=\d+", c2) and re.search(
                    r"step \d+ loss", c2
                ):
                    restored = True
                    break
                if proc2.poll() is not None:
                    break
                time.sleep(1.0)
            c2 = _read(log2)
            assert restored, "no restore after relaunch:\n" + c2[-3000:]
            assert "warm restore from shm" in c2, c2[-3000:]
            step = int(re.search(r"restored step=(\d+)", c2).group(1))
            assert step >= committed
        finally:
            _terminate([proc2])


class TestCorruptCommittedShard:
    def test_restore_falls_back_and_fsck_flags(self, tmp_path):
        """Scenario 4 (ISSUE 3 flagship): chaos corrupts the committed
        step's shard bytes as the agent persists them — the done file and
        tracker advance normally, exactly silent bit-rot.  A cold
        relaunch (new run id, no warm shm) must detect the damage,
        quarantine the step dir as ``step_N.corrupt``, and restore the
        previous committed step; ``checkpoint.fsck`` must exit nonzero
        naming the corrupt shard."""
        job = "chaos-corrupt"
        ckpt = str(tmp_path / "ckpt")
        proc, log = _launch_standalone(
            tmp_path, job,
            ["--steps=8", f"--ckpt_dir={ckpt}", "--ckpt_interval=3",
             "--ckpt_storage_interval=3", "--batch_per_proc=2"],
            env_extra={
                "DLROVER_TPU_FAULTS": "storage.corrupt_shard:step=8",
                "DLROVER_TPU_RUN_ID": "corrupt1",
            },
            log_name="run1.log",
        )
        try:
            rc = proc.wait(timeout=420)
        finally:
            _terminate([proc])
        content = _read(log)
        assert rc == 0, content[-3000:]
        assert "chaos: storage.corrupt_shard fired" in content, (
            content[-3000:]
        )
        # The commit protocol proceeded: the tracker names the damaged
        # final step (the trainer's end-of-run durable save) — integrity
        # is restore-side verification's job.
        tracker = os.path.join(ckpt, "latest_checkpointed_step.txt")
        assert int(_read(tracker).strip()) == 8

        # fsck flags the damage, naming the corrupt shard.
        fsck = subprocess.run(
            [sys.executable, "-m", "dlrover_tpu.checkpoint.fsck", ckpt],
            cwd=REPO, env=_env(), capture_output=True, text=True,
            timeout=120,
        )
        assert fsck.returncode == 1, fsck.stdout + fsck.stderr
        assert "shard_00000.ckpt" in fsck.stdout, fsck.stdout

        # Cold relaunch (different run id -> fresh shm arena): the ladder
        # must skip the corrupt committed step 8 and restore step 6.
        proc2, log2 = _launch_standalone(
            tmp_path, job,
            ["--steps=8", f"--ckpt_dir={ckpt}", "--ckpt_interval=3",
             "--batch_per_proc=2"],
            env_extra={"DLROVER_TPU_RUN_ID": "corrupt2"},
            log_name="run2.log",
        )
        try:
            rc2 = proc2.wait(timeout=420)
        finally:
            _terminate([proc2])
        c2 = _read(log2)
        assert rc2 == 0, c2[-3000:]
        assert "restored step=6" in c2, c2[-3000:]
        assert "corrupt checkpoint shard (step 8" in c2, c2[-3000:]
        assert os.path.isdir(
            os.path.join(ckpt, "step_0000000008.corrupt")
        ), sorted(os.listdir(ckpt))
        # The quarantined dir still holds the evidence for fsck.
        fsck2 = subprocess.run(
            [sys.executable, "-m", "dlrover_tpu.checkpoint.fsck", ckpt],
            cwd=REPO, env=_env(), capture_output=True, text=True,
            timeout=120,
        )
        assert fsck2.returncode == 1
        assert "quarantined" in fsck2.stdout.lower()


@pytest.mark.serving
class TestServingFleetKillAndDrain:
    """ISSUE 5 flagship: a 2-replica fleet under a real process tree.

    Replica r0 is chaos-killed mid-stream (``serving.replica_kill``
    fires after its 2nd completion, with work in flight); the gateway
    re-dispatches its in-flight requests, the relaunched r0 replays its
    journal, and EVERY admitted request completes exactly once — no
    loss (all results arrive), no duplicate (the gateway's completed
    counter equals the request count; journal-replay dupes are counted
    and dropped).  Then a scale-down drain retires one replica with
    requests in flight and nothing observes the shrink."""

    def _spawn(self, tmp_path, name, argv, env_extra=None):
        log = open(tmp_path / f"{name}.log", "w")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "examples", "llama_serve_fleet.py"),
             *argv],
            cwd=REPO, env=_env(env_extra), stdout=log,
            stderr=subprocess.STDOUT, start_new_session=True,
        )
        return proc, tmp_path / f"{name}.log"

    def test_exactly_once_across_kill_and_drain(self, tmp_path):
        from dlrover_tpu.common.messages import (
            ServeDrainRequest,
            ServeFleetStats,
            ServeFleetStatsRequest,
        )
        from dlrover_tpu.common.rpc import RpcClient, find_free_port
        from dlrover_tpu.serving import ServeClient

        port = find_free_port()
        journal_dir = str(tmp_path / "journals")
        procs = []
        gw_proc, gw_log = self._spawn(
            tmp_path, "gateway",
            ["--role", "gateway", "--port", str(port),
             "--lease_timeout", "3"],
        )
        procs.append(gw_proc)

        def spawn_replica(rid, faults=None):
            extra = {"DLROVER_TPU_FAULTS": faults} if faults else None
            proc, log = self._spawn(
                tmp_path, f"replica-{rid}",
                ["--role", "replica", "--gateway",
                 f"127.0.0.1:{port}", "--replica_id", rid,
                 "--slots", "2", "--max_len", "64",
                 "--journal_dir", journal_dir,
                 "--poll_interval", "0.02",
                 "--round_floor_ms", "40"],
                env_extra=extra,
            )
            procs.append(proc)
            return proc, log

        try:
            # r0 dies the moment its 3rd completion would start
            # (served==2), leaving admitted work in flight.
            r0, r0_log = spawn_replica(
                "r0", faults="serving.replica_kill:step=2",
            )
            r1, _ = spawn_replica("r1")
            rpc = RpcClient(f"127.0.0.1:{port}", timeout=10.0)

            def fleet_stats():
                reply = rpc.call(ServeFleetStatsRequest(),
                                 idempotent=True)
                assert isinstance(reply, ServeFleetStats), reply
                return reply.stats

            deadline = time.time() + 120
            while time.time() < deadline:
                try:
                    if fleet_stats()["replicas_alive"] >= 2:
                        break
                except Exception:
                    pass
                time.sleep(0.5)
            else:
                raise AssertionError(
                    "fleet never formed: " + _read(gw_log)[-2000:]
                )

            client = ServeClient(rpc, poll_interval=0.05)
            n_req = 12
            prompts = [[(7 * i + j) % 50 + 1 for j in range(5)]
                       for i in range(n_req)]
            # STAGGERED budgets: equal budgets finish a replica's two
            # slots in the same emit pass, and the kill (which fires at
            # the tick AFTER the 2nd completion) would then land with
            # nothing in flight.  Desynchronized completions guarantee
            # r0 dies holding admitted work — the re-dispatch path
            # under test.
            budgets = [8 + (i % 7) for i in range(n_req)]
            for i, prompt in enumerate(prompts):
                ack = client.submit(f"req-{i}", prompt, budgets[i])
                assert ack.status in ("accepted", "done"), ack

            # The chaos kill lands mid-stream: r0 exits 78.
            rc0 = r0.wait(timeout=120)
            assert rc0 == 78, _read(r0_log)[-2000:]

            # The supervisor's role: relaunch r0 (spent crash site
            # scrubbed), same journal -> replay + re-register.
            r0b, r0b_log = spawn_replica("r0")

            results = {}
            for i in range(n_req):
                reply = client.result(f"req-{i}", timeout=120)
                assert reply.state == "done", (
                    f"req-{i}: {reply.state} {reply.reason}; gateway: "
                    + _read(gw_log)[-2000:]
                )
                results[i] = list(reply.tokens)
                # Full budget, no EOS cut, whoever served it.
                assert len(results[i]) == budgets[i]

            # r0's relaunch replays its journal when it registers —
            # wait for that report to land (its pre-kill completions
            # were already answered, so the replay MUST dedupe).
            deadline = time.time() + 60
            while time.time() < deadline:
                c = fleet_stats()["counters"]
                if c["duplicate_completions"] >= 1:
                    break
                time.sleep(0.5)
            stats = fleet_stats()
            c = stats["counters"]
            # No loss, no duplicate: every admitted request completed
            # EXACTLY once at the gateway.
            assert c["completed"] == n_req, c
            assert c["failed"] == 0 and c["timeout"] == 0, c
            # The kill actually cost in-flight work that was
            # re-dispatched (lease expiry or r0's re-register).
            assert c["redispatched"] >= 1, c
            # r0's journal replay re-reported its pre-kill completions;
            # dedupe dropped them.
            assert c["duplicate_completions"] >= 1, c

            # Exactly-once is also client-visible: resubmitting every
            # request answers from the dedupe cache with the SAME
            # tokens (no second decode, byte-identical).
            for i in range(n_req):
                ack = client.submit(f"req-{i}", prompts[i], budgets[i])
                assert ack.status == "done", ack
                assert list(ack.tokens) == results[i]
            assert fleet_stats()["counters"]["completed"] == n_req

            # --- scale-down drain with requests in flight ---
            for i in range(6):
                client.submit(f"late-{i}", prompts[i], 12)
            assert rpc.call(
                ServeDrainRequest(replica_id="r1")
            ).success
            for i in range(6):
                reply = client.result(f"late-{i}", timeout=120)
                assert reply.state == "done", (reply.state,
                                               reply.reason)
                assert len(reply.tokens) == 12
            # The drained replica exits cleanly after finishing its
            # in-flight work; the fleet shrinks to r0 only.
            assert r1.wait(timeout=60) == 0, _read(gw_log)[-1000:]
            deadline = time.time() + 30
            while time.time() < deadline:
                if fleet_stats()["replicas_alive"] == 1:
                    break
                time.sleep(0.5)
            stats = fleet_stats()
            assert stats["replicas_alive"] == 1, stats
            c = stats["counters"]
            assert c["completed"] == n_req + 6, c
            assert c["failed"] == 0 and c["timeout"] == 0, c
            content = _read(tmp_path / "replica-r0.log")
            assert "REPLICA_READY id=r0" in content
        finally:
            _terminate(procs)


@pytest.mark.reshard
class TestReshardDropSegmentFallsToLadder:
    """ISSUE 6 acceptance e2e: a plan segment lost mid-move
    (``reshard.drop_segment``) fails the live reshard LOUDLY; the job
    degrades to the checkpoint-restart ladder (flash-ckpt restore onto
    the new mesh), resumes past the resize point, and storage is
    fsck-clean afterwards — no hang, no torn state."""

    DRIVER = r"""
import os
import sys
import tempfile

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from dlrover_tpu.checkpoint import fsck as fsck_mod
from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.checkpoint.tree_utils import flatten_to_shards
from dlrover_tpu.parallel.mesh import MeshSpec, build_mesh
from dlrover_tpu.reshard.coordinator import (
    ReshardError,
    reshard_shards,
    target_placeholders,
)
from dlrover_tpu.reshard.mover import (
    LocalShardSource,
    ReshardPeer,
    SegmentMover,
)

devs = jax.devices()
mesh2 = build_mesh(MeshSpec(fsdp=2), devs[:2])
mesh4 = build_mesh(MeshSpec(fsdp=4), devs[:4])
host = np.arange(256, dtype=np.float32).reshape(32, 8)
state = {"w": jax.device_put(host, NamedSharding(mesh2, P("fsdp")))}
step_fn = jax.jit(lambda s: {k: v + 1.0 for k, v in s.items()})
state = step_fn(state)
jax.block_until_ready(state)  # "step 1" done on the old mesh

ckpt_dir = os.path.join(tempfile.mkdtemp(prefix="rs_e2e_"), "ckpt")
eng = CheckpointEngine(ckpt_dir, job_name="rs-e2e")
eng.save_to_storage(1, state)
assert eng.wait(120), "checkpoint never committed"

# Live reshard attempt with a REAL cross-peer pull: this process holds
# rank 0's half locally; "rank 1"'s half is served over the reshard RPC
# (same wire path a multi-host move takes) — and the chaos plan drops
# exactly one segment on that wire.
tensors, infos = flatten_to_shards(state)
keys = sorted(tensors)
assert len(keys) == 2, keys
(k0, k1) = keys
src_infos = {0: {k0: infos[k0]}, 1: {k1: infos[k1]}}
server = ReshardPeer(rank=1)
server.publish(1, 1, {k1: tensors[k1]}, {k1: infos[k1]})
puller = ReshardPeer(rank=0)
target = target_placeholders(state, mesh4)
try:
    new_state, _stats = reshard_shards(
        {k0: tensors[k0]}, {k0: infos[k0]}, target,
        rank=0, src_infos_by_rank=src_infos,
        fetch=lambda seg: puller.fetch_segment(
            seg, epoch=1, step=1, addr=server.addr
        ),
        epoch=1,
    )
    print("LIVE_RESHARD_OK (chaos did not fire?)")
    sys.exit(3)
except ReshardError as e:
    print(f"LIVE_FAILED: {e}")
finally:
    server.stop()
    puller.stop()

# The ladder: restore the committed checkpoint onto the NEW mesh and
# resume stepping — the correctness backstop the live path fell back to.
got = eng.load(target, target_mesh=mesh4)
assert got is not None, "ladder restore found nothing"
restored, meta = got
np.testing.assert_array_equal(np.asarray(restored["w"]), host + 1.0)
restored = step_fn(restored)
jax.block_until_ready(restored)
np.testing.assert_array_equal(np.asarray(restored["w"]), host + 2.0)
print(f"LADDER_RESTORED step={int(meta.get('step', -1))} resumed_on="
      f"{restored['w'].sharding.mesh.shape['fsdp']}dev")
eng.close()

rc = fsck_mod.main([ckpt_dir])
print(f"fsck_rc={rc}")
print("DONE")
sys.exit(0 if rc == 0 else 4)
"""

    def test_drop_segment_degrades_to_restart_ladder(
        self, cpu_mesh_subprocess
    ):
        proc = cpu_mesh_subprocess(
            self.DRIVER,
            devices=4,
            env_extra={
                "DLROVER_TPU_FAULTS": "reshard.drop_segment:times=1,seed=9",
            },
            timeout=300,
        )
        out = proc.stdout
        assert proc.returncode == 0, (out[-3000:], proc.stderr[-3000:])
        assert "LIVE_FAILED" in out and "dropped" in out, out[-2000:]
        assert "LADDER_RESTORED step=1 resumed_on=4dev" in out
        assert "fsck_rc=0" in out
        assert "DONE" in out


@pytest.mark.serving
class TestDisaggKillMidHandoff:
    """ISSUE 8 acceptance e2e: a prefill replica is chaos-killed in
    the kill-mid-handoff window — AFTER taking a prefill-grant and
    producing the KV segment, BEFORE the kv-ready reaches the gateway
    (``serving.replica_kill:method=prefill_export``).  The gateway's
    lease machinery re-dispatches the prefill to the surviving prefill
    replica, the decode pool imports the re-shipped segment, and every
    request completes EXACTLY once: the journal/dedupe contracts keyed
    by req_id make the replay clean (resubmits answer byte-identically
    from the cache; the completed counter equals the request count)."""

    def _spawn(self, tmp_path, name, argv, env_extra=None):
        log = open(tmp_path / f"{name}.log", "w")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "examples", "llama_serve_fleet.py"),
             *argv],
            cwd=REPO, env=_env(env_extra), stdout=log,
            stderr=subprocess.STDOUT, start_new_session=True,
        )
        return proc, tmp_path / f"{name}.log"

    def test_prefill_kill_replays_and_completes_exactly_once(
            self, tmp_path):
        from dlrover_tpu.common.messages import (
            ServeFleetStats,
            ServeFleetStatsRequest,
        )
        from dlrover_tpu.common.rpc import RpcClient, find_free_port
        from dlrover_tpu.serving import ServeClient

        port = find_free_port()
        journal_dir = str(tmp_path / "journals")
        procs = []
        gw_proc, gw_log = self._spawn(
            tmp_path, "gateway",
            ["--role", "gateway", "--port", str(port),
             "--lease_timeout", "3"],
        )
        procs.append(gw_proc)

        def spawn_replica(rid, role, faults=None):
            extra = {"DLROVER_TPU_FAULTS": faults} if faults else None
            proc, log = self._spawn(
                tmp_path, f"replica-{rid}",
                ["--role", "replica", "--gateway",
                 f"127.0.0.1:{port}", "--replica_id", rid,
                 "--replica_role", role,
                 "--slots", "2", "--max_len", "64",
                 "--journal_dir", journal_dir,
                 "--poll_interval", "0.02",
                 "--round_floor_ms", "20"],
                env_extra=extra,
            )
            procs.append(proc)
            return proc, log

        try:
            # p0 dies exporting its FIRST KV segment (the window
            # between prefill-grant and decode-grant); p1 survives.
            p0, p0_log = spawn_replica(
                "p0", "prefill",
                faults="serving.replica_kill:method=prefill_export",
            )
            p1, _ = spawn_replica("p1", "prefill")
            d0, _ = spawn_replica("d0", "decode")
            rpc = RpcClient(f"127.0.0.1:{port}", timeout=10.0)

            def fleet_stats():
                reply = rpc.call(ServeFleetStatsRequest(),
                                 idempotent=True)
                assert isinstance(reply, ServeFleetStats), reply
                return reply.stats

            deadline = time.time() + 180
            while time.time() < deadline:
                try:
                    if fleet_stats()["replicas_alive"] >= 3:
                        break
                except Exception:
                    pass
                time.sleep(0.5)
            else:
                raise AssertionError(
                    "fleet never formed: " + _read(gw_log)[-2000:]
                )

            client = ServeClient(rpc, poll_interval=0.05)
            n_req = 8
            prompts = [[(5 * i + j) % 50 + 1 for j in range(5)]
                       for i in range(n_req)]
            budgets = [6 + (i % 5) for i in range(n_req)]
            for i, prompt in enumerate(prompts):
                ack = client.submit(f"req-{i}", prompt, budgets[i])
                assert ack.status in ("accepted", "done"), ack

            # The chaos kill lands in the handoff window: p0 exits 78.
            rc0 = p0.wait(timeout=120)
            assert rc0 == 78, _read(p0_log)[-2000:]

            results = {}
            for i in range(n_req):
                reply = client.result(f"req-{i}", timeout=150)
                assert reply.state == "done", (
                    f"req-{i}: {reply.state} {reply.reason}; gateway: "
                    + _read(gw_log)[-2000:]
                )
                results[i] = list(reply.tokens)
                assert len(results[i]) == budgets[i]

            stats = fleet_stats()
            c = stats["counters"]
            # Exactly once at the gateway, despite the mid-handoff
            # kill: no loss, no double-complete, and the killed
            # prefill's work really was re-dispatched.
            assert c["completed"] == n_req, c
            assert c["failed"] == 0 and c["timeout"] == 0, c
            assert c["redispatched"] >= 1, c
            assert c["kv_handoffs"] >= n_req, c
            assert c["duplicate_completions"] == 0, c

            # Client-visible exactly-once: resubmits answer from the
            # dedupe cache, byte-identical, with no second decode.
            for i in range(n_req):
                ack = client.submit(f"req-{i}", prompts[i], budgets[i])
                assert ack.status == "done", ack
                assert list(ack.tokens) == results[i]
            assert fleet_stats()["counters"]["completed"] == n_req

            # The decode journal replays across a decode-replica
            # restart: kill d0, relaunch on the same journal; its
            # replay reports dedupe instead of double-completing.
            d0.send_signal(signal.SIGKILL)
            d0.wait(timeout=30)
            d0b, _ = spawn_replica("d0", "decode")
            deadline = time.time() + 90
            while time.time() < deadline:
                if fleet_stats()["counters"][
                        "duplicate_completions"] >= 1:
                    break
                time.sleep(0.5)
            c = fleet_stats()["counters"]
            assert c["duplicate_completions"] >= 1, c
            assert c["completed"] == n_req, c
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)
            for proc in procs:
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()


@pytest.mark.serving
class TestGatewayKillFailover:
    """ISSUE 9 flagship: a SHARDED gateway tier under a real process
    tree — registry server in-test, two tier gateways and two
    journaled replicas as subprocesses, a consistent-hash TierClient
    driver.

    ``serving.gateway_kill:method=g1,step_ge=2`` hard-kills gateway g1
    (exit 81) at its first registry heartbeat after two requests
    COMPLETED at it — deterministically mid-stream, seeded, no
    wall-clock guess.  The failover law under test: g1's lease ages
    out of the shared registry, the ring re-forms so the surviving
    gateway adopts g1's hash range, the client resubmits every id it
    never saw a result for, the replicas' fan-out link re-registers
    and re-routes reports — and every admitted request completes
    EXACTLY once: results for g1's orphaned ids arrive via the
    adopting gateway (journal replay answering for already-decoded
    work), and a second resubmit round returns byte-identical tokens
    from the dedupe cache."""

    def _spawn(self, tmp_path, name, argv, env_extra=None):
        log = open(tmp_path / f"{name}.log", "w")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "examples", "llama_serve_fleet.py"),
             *argv],
            cwd=REPO, env=_env(env_extra), stdout=log,
            stderr=subprocess.STDOUT, start_new_session=True,
        )
        return proc, tmp_path / f"{name}.log"

    def test_surviving_gateway_adopts_range_exactly_once(
            self, tmp_path):
        from dlrover_tpu import obs
        from dlrover_tpu.chaos.plan import EXIT_GATEWAY_KILL
        from dlrover_tpu.serving import (
            RegistryServer,
            RpcKv,
            ServeRegistry,
            TierClient,
        )

        registry_server = RegistryServer()
        journal_dir = str(tmp_path / "journals")
        # Flight-recorder dumps (ISSUE 12): every role spills here —
        # g1 via the chaos pre-exit hook, g0/replicas at shutdown, the
        # in-test driver explicitly — and the trace-verified
        # assertions after teardown merge them.
        obs_dir = str(tmp_path / "obs")
        obs.configure(out_dir=obs_dir, process="driver")
        procs = []
        try:
            def spawn_gateway(gid, faults=None):
                extra = {"DLROVER_TPU_OBS_DIR": obs_dir}
                if faults:
                    extra["DLROVER_TPU_FAULTS"] = faults
                proc, log = self._spawn(
                    tmp_path, f"gateway-{gid}",
                    ["--role", "gateway", "--registry",
                     registry_server.addr, "--gateway_id", gid,
                     "--lease_timeout", "2"],
                    env_extra=extra,
                )
                procs.append(proc)
                return proc, log

            g0, _g0_log = spawn_gateway("g0")
            g1, _g1_log = spawn_gateway(
                "g1", "serving.gateway_kill:method=g1,step_ge=2,seed=7"
            )

            def spawn_replica(rid):
                proc, log = self._spawn(
                    tmp_path, f"replica-{rid}",
                    ["--role", "replica", "--registry",
                     registry_server.addr, "--lease_timeout", "2",
                     "--replica_id", rid,
                     "--slots", "2", "--max_len", "96",
                     "--journal_dir", journal_dir,
                     "--poll_interval", "0.02",
                     "--round_floor_ms", "30"],
                    env_extra={"DLROVER_TPU_OBS_DIR": obs_dir},
                )
                procs.append(proc)
                return proc, log

            spawn_replica("r0")
            spawn_replica("r1")

            registry = ServeRegistry(
                RpcKv(registry_server.addr), job="fleet", lease_s=2.0,
            )
            cli = TierClient(registry, poll_interval=0.05,
                             refresh_s=0.2)
            deadline = time.time() + 120
            while time.time() < deadline:
                snaps = cli.stats()
                if len(snaps) == 2 and all(
                    s.get("replicas_alive", 0) >= 2 for s in snaps
                ):
                    break
                time.sleep(0.5)
            else:
                pytest.fail("tier never became 2 gateways x 2 "
                            "replicas")

            # Wave 1 primes the kill trigger (g1 needs >= 2
            # completions); wave 2's longer budgets keep work in
            # flight across the death.  Prompts are the seeded
            # deterministic stream, so every decode of one id yields
            # identical tokens wherever it runs.
            import numpy as np

            rng = np.random.RandomState(3)
            prompts = {
                f"req-{i}": rng.randint(
                    1, 64, size=(int(rng.randint(4, 10)),)
                ).astype(int).tolist()
                for i in range(12)
            }
            budgets = {}
            for i, (rid, prompt) in enumerate(prompts.items()):
                budgets[rid] = 6 if i < 4 else 24
                ack = cli.submit(rid, prompt, budgets[rid],
                                 submit_timeout=30)
                assert ack.status in ("accepted", "done"), (rid, ack)
                time.sleep(0.05)

            # The chaos site must fire: g1 exits with the tier's
            # dedicated code while the fleet still holds work.
            try:
                g1.wait(timeout=90)
            except subprocess.TimeoutExpired:
                pytest.fail("gateway g1 never chaos-killed")
            assert g1.returncode == EXIT_GATEWAY_KILL

            # Every admitted request reaches DONE through the
            # survivor; ids orphaned at g1 arrive via failover
            # resubmit + journal replay/dedupe.
            tokens = {}
            for rid in prompts:
                reply = cli.result(rid, timeout=120)
                assert reply.state == "done", (rid, reply)
                assert len(reply.tokens) == budgets[rid], rid
                tokens[rid] = list(reply.tokens)
            assert cli.resubmitted >= 1  # failover actually exercised

            # Exactly-once, proven from the outside: a full resubmit
            # round answers every id from the dedupe cache,
            # byte-identical — nothing re-decodes, nothing is lost.
            snaps = cli.stats()
            assert len(snaps) == 1  # only the survivor remains
            completed_before = snaps[0]["counters"]["completed"]
            for rid, prompt in prompts.items():
                ack = cli.submit(rid, prompt, budgets[rid],
                                 submit_timeout=30)
                assert ack.status == "done", (rid, ack)
                assert list(ack.tokens) == tokens[rid], rid
            after = cli.stats()[0]["counters"]
            assert after["completed"] == completed_before
            assert after["dedupe_hits"] >= len(prompts)
            assert g0.poll() is None  # the survivor is still up
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)
            for proc in procs:
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            registry_server.stop()

        # ---- Trace-verified epilogue (ISSUE 12) -----------------------
        # Every process has now spilled its flight recorder: g1 via the
        # chaos pre-exit hook, g0 via its clean-shutdown atexit, the
        # replicas via the SIGTERM hook — and the in-test driver here.
        from dlrover_tpu.obs import collect
        from dlrover_tpu.obs.postmortem import analyze
        from dlrover_tpu.utils.trace_analysis import TraceAnalysis

        obs.get_recorder().dump(reason="exit")
        dumps = collect.load_dir(obs_dir)
        by_proc = {d["meta"]["process"]: d["meta"] for d in dumps}
        # The kill is VISIBLE: a dump whose header names the injected
        # chaos site, from the dead gateway itself.
        assert by_proc["gw-g1"]["reason"] == "chaos", by_proc
        assert by_proc["gw-g1"]["chaos_site"] == \
            "serving.gateway_kill"
        assert "gw-g0" in by_proc and "driver" in by_proc
        assert any(p.startswith("rep-") for p in by_proc)
        # One merged, Perfetto-loadable fleet trace; the repo's own
        # chrome-trace tooling consumes it.
        merged_path = str(tmp_path / "fleet_trace.json")
        collect.write_chrome_trace(obs_dir, merged_path)
        ta = TraceAnalysis.from_file(merged_path)
        assert ta.events, "merged chrome trace holds no spans"
        # Every admitted request: a complete span tree ending in
        # exactly one EFFECTIVE terminal (a journal replay at the
        # adopting gateway may supersede the dead gateway's terminal —
        # the duplicates must AGREE, which is exactly-once evidence),
        # with the gateway's phase spans summing to the measured
        # TTFT/latency within 5%.
        rep = collect.validate_traces(dumps, tolerance=0.05)
        for rid in prompts:
            tr = rep["traces"].get(obs.trace_id_for(rid))
            assert tr is not None, f"{rid}: no trace in the merge"
            assert tr["ok"], (rid, tr)
            assert tr["state"] == "done", (rid, tr)
        # The failover is visible as resubmit spans in the ORIGINAL
        # traces (the driver's dump), never as duplicate traces.
        driver = next(d for d in dumps
                      if d["meta"]["process"] == "driver")
        resub_tids = {e.get("tid") for e in driver["events"]
                      if e.get("name") == "client.resubmit"}
        assert resub_tids, "no resubmit spans recorded"
        assert resub_tids <= {
            obs.trace_id_for(rid) for rid in prompts
        }
        # The postmortem reconstructs the incident from the dumps.
        pm = analyze(obs_dir)
        assert pm["crashed"] == ["gw-g1"]
        assert pm["chaos_sites"] == ["serving.gateway_kill"]
        assert any(r["terminal_process"] in ("gw-g0", "gw-g1")
                   for r in pm["rerouted"]) or pm["rerouted"] == []


@pytest.mark.serving
@pytest.mark.fleet
class TestFleetGatewayRelaunchMixed:
    """ISSUE 10 acceptance e2e: ONE fleet — training workers (a real
    job manager over the in-memory platform, the control-plane-only
    worker pattern scenario 2 uses) AND a serving role (two subprocess
    tier gateways + two journaled subprocess replicas) — under one
    FleetManager.

    ``serving.gateway_kill:method=g1,step_ge=2`` hard-kills gateway g1
    (exit 81) after two completions with work still in flight.  Where
    the ISSUE-9 e2e proved the tier merely SURVIVES (survivors adopt
    the range), the law here is SUPERVISED REPLACEMENT: the fleet
    reconciler observes the lease lapse, relaunches the gateway under
    the SAME id (so the replacement re-adopts exactly the dead hash
    ranges), desired count is restored — and every in-flight request
    still completes exactly once, with the training role untouched by
    the churn."""

    def _spawn(self, tmp_path, name, argv, env_extra=None):
        log = open(tmp_path / f"{name}.log", "w")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "examples", "llama_serve_fleet.py"),
             *argv],
            cwd=REPO, env=_env(env_extra), stdout=log,
            stderr=subprocess.STDOUT, start_new_session=True,
        )
        return proc, tmp_path / f"{name}.log"

    def test_supervisor_replaces_killed_gateway_exactly_once(
            self, tmp_path):
        import threading

        from dlrover_tpu.chaos.plan import EXIT_GATEWAY_KILL
        from dlrover_tpu.common.constants import NodeType
        from dlrover_tpu.fleet import (
            FleetManager,
            GatewayRole,
            RoleSpec,
            TrainingRole,
        )
        from dlrover_tpu.master.dist_job_manager import (
            DistributedJobManager,
        )
        from dlrover_tpu.master.job_auto_scaler import (
            AllreduceTrainingAutoScaler,
        )
        from dlrover_tpu.master.scaler import PlatformScaler
        from dlrover_tpu.master.speed_monitor import SpeedMonitor
        from dlrover_tpu.scheduler.job import JobArgs, NodeGroupArgs
        from dlrover_tpu.scheduler.platform import InMemoryPlatform
        from dlrover_tpu.serving import (
            HashRing,
            RegistryServer,
            RpcKv,
            ServeRegistry,
            TierClient,
        )

        registry_server = RegistryServer()
        journal_dir = str(tmp_path / "journals")
        procs = []
        gw_launches = {}  # gid -> [proc, ...] in launch order
        mu = threading.Lock()

        def spawn_gateway(gid):
            with mu:
                first = gid not in gw_launches
                n = len(gw_launches.setdefault(gid, [])) + 1
            faults = (
                "serving.gateway_kill:method=g1,step_ge=2,seed=7"
                if gid == "g1" and first else None
            )
            extra = {"DLROVER_TPU_FAULTS": faults} if faults else None
            proc, _log = self._spawn(
                tmp_path, f"gateway-{gid}-{n}",
                ["--role", "gateway", "--registry",
                 registry_server.addr, "--gateway_id", gid,
                 "--lease_timeout", "2"],
                env_extra=extra,
            )
            with mu:
                gw_launches[gid].append(proc)
                procs.append(proc)
            return proc

        # -- the ONE fleet: training role + supervised gateway role.
        job_args = JobArgs(job_name="fleet")
        job_args.node_groups[NodeType.WORKER] = NodeGroupArgs(
            count=2, min_count=1, max_count=4
        )
        platform = InMemoryPlatform()
        jm = DistributedJobManager(
            job_args, platform, PlatformScaler("fleet", platform)
        )
        jm.start()
        scaler = AllreduceTrainingAutoScaler(
            job_args, jm, SpeedMonitor(), None
        )
        fleet = FleetManager(interval=0.5)
        fleet.add_role(TrainingRole(
            RoleSpec("training", desired=2, min_count=1, max_count=4),
            scaler, jm,
        ))
        fleet.add_role(GatewayRole(
            RoleSpec("gateway", desired=2, min_count=1, max_count=3),
            ServeRegistry(RpcKv(registry_server.addr), job="fleet",
                          lease_s=2.0),
            spawn_gateway, id_prefix="g",
        ))

        def spawn_replica(rid):
            proc, log = self._spawn(
                tmp_path, f"replica-{rid}",
                ["--role", "replica", "--registry",
                 registry_server.addr, "--lease_timeout", "2",
                 "--replica_id", rid,
                 "--slots", "2", "--max_len", "96",
                 "--journal_dir", journal_dir,
                 "--poll_interval", "0.02",
                 "--round_floor_ms", "30"],
            )
            procs.append(proc)
            return proc, log

        try:
            fleet.start()  # spawns g0 + g1 on the first pass
            spawn_replica("r0")
            spawn_replica("r1")

            registry = ServeRegistry(
                RpcKv(registry_server.addr), job="fleet", lease_s=2.0,
            )
            cli = TierClient(registry, poll_interval=0.05,
                             refresh_s=0.2)
            deadline = time.time() + 120
            while time.time() < deadline:
                snaps = cli.stats()
                if len(snaps) == 2 and all(
                    s.get("replicas_alive", 0) >= 2 for s in snaps
                ):
                    break
                time.sleep(0.5)
            else:
                pytest.fail("fleet never became 2 gateways x 2 "
                            "replicas")
            assert len(jm.alive_workers()) == 2  # training side is up

            import numpy as np

            rng = np.random.RandomState(3)
            prompts = {
                f"req-{i}": rng.randint(
                    1, 64, size=(int(rng.randint(4, 10)),)
                ).astype(int).tolist()
                for i in range(12)
            }
            budgets = {}
            for i, (rid, prompt) in enumerate(prompts.items()):
                budgets[rid] = 6 if i < 4 else 24
                ack = cli.submit(rid, prompt, budgets[rid],
                                 submit_timeout=30)
                assert ack.status in ("accepted", "done"), (rid, ack)
                time.sleep(0.05)

            # The chaos site fires: g1's FIRST incarnation exits 81.
            g1_first = None
            deadline = time.time() + 90
            while time.time() < deadline:
                with mu:
                    launches = gw_launches.get("g1", [])
                    g1_first = launches[0] if launches else None
                if g1_first is not None and \
                        g1_first.poll() is not None:
                    break
                time.sleep(0.5)
            assert g1_first is not None and \
                g1_first.returncode == EXIT_GATEWAY_KILL, (
                    "gateway g1 never chaos-killed"
                )

            # SUPERVISED REPLACEMENT: the reconciler relaunches g1
            # under its own id; the registry shows the full desired
            # set again (not merely the survivor adopting the range).
            deadline = time.time() + 60
            while time.time() < deadline:
                with mu:
                    relaunched = len(gw_launches.get("g1", [])) >= 2
                if set(registry.gateways()) == {"g0", "g1"} \
                        and relaunched:
                    break
                time.sleep(0.5)
            assert set(registry.gateways()) == {"g0", "g1"}, (
                "gateway count never returned to desired"
            )
            with mu:
                assert len(gw_launches["g1"]) >= 2  # real relaunch

            # Every in-flight request completes EXACTLY once across
            # the death + replacement.
            tokens = {}
            for rid in prompts:
                reply = cli.result(rid, timeout=120)
                assert reply.state == "done", (rid, reply)
                assert len(reply.tokens) == budgets[rid], rid
                tokens[rid] = list(reply.tokens)

            # Exactly-once proven from outside: a full resubmit round
            # answers byte-identical from journals/dedupe caches.
            for rid, prompt in prompts.items():
                ack = cli.submit(rid, prompt, budgets[rid],
                                 submit_timeout=30)
                assert ack.status == "done", (rid, ack)
                assert list(ack.tokens) == tokens[rid], rid

            # The replacement really OWNS the re-adopted ranges: a
            # fresh request consistent-hashed to g1 completes there.
            ring = HashRing(["g0", "g1"])
            extra_rid = next(
                f"extra-{i}" for i in range(1000)
                if ring.owner(f"extra-{i}") == "g1"
            )
            ack = cli.submit(extra_rid, [1, 2, 3, 4], 6,
                             submit_timeout=30)
            assert ack.status in ("accepted", "done")
            reply = cli.result(extra_rid, timeout=60)
            assert reply.state == "done"

            # The training role rode through the serving churn.
            assert len(jm.alive_workers()) == 2
            status = fleet.status()
            assert status["roles"]["gateway"]["desired"] == 2
        finally:
            fleet.stop()
            jm.stop()
            with mu:
                all_procs = list(procs)
            for proc in all_procs:
                if proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)
            for proc in all_procs:
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            registry_server.stop()


class TestMasterKillWarmFailover:
    """Flagship ISSUE 13 scenario: training + serving fleet in flight,
    the PRIMARY master is chaos-SIGKILLed (``master.kill``, exit 83 —
    the unclean death, distinct from the supervised ``master.restart``
    cold path) mid-rendezvous and mid-task.  The warm standby replays
    the control-state journal and takes over; the proof obligations:

    - no data-shard task is lost or double-completed across the
      blackout (held doing tasks complete exactly once, the rest of the
      queue drains with every task id granted exactly once);
    - the half-formed rendezvous (node 0 waiting, node 1 absent)
      completes on the NEW master when node 1 finally joins;
    - the in-flight reshard epoch resolves (DONE after both workers
      report ok post-takeover);
    - the master-backed serving registry never observes a blank master
      (the gateway entry is visible at the first post-takeover read),
      and every serving request submitted across the window finishes
      exactly-once;
    - ``statecheck`` exits 0 on the surviving journal.
    """

    @pytest.mark.ha
    def test_training_and_serving_ride_warm_takeover(self, tmp_path):
        import threading

        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.common.rpc import addr_connectable
        from dlrover_tpu.master.state import read_addr
        from dlrover_tpu.serving import (
            GatewayConfig,
            GatewayCore,
            LoopbackTransport,
            ReplicaRunner,
        )
        from dlrover_tpu.serving.tier import MasterKv, ServeRegistry

        job = "hakill"
        state_dir = tmp_path / "state"
        state_dir.mkdir()

        def start_master_proc(extra_args, faults, log_name, extra_env=None):
            env = _env({"DLROVER_TPU_FAULTS": faults} if faults else None)
            if extra_env:
                env.update(extra_env)
            env.pop("DLROVER_TPU_MASTER_STATE_DIR", None)
            port_file = tmp_path / f"{log_name}.port"
            log = open(tmp_path / f"{log_name}.log", "w")
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "dlrover_tpu.master.main",
                    "--port=0", f"--port_file={port_file}",
                    f"--job_name={job}", "--min_nodes=2", "--max_nodes=2",
                    f"--state_dir={state_dir}", *extra_args,
                ],
                cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT,
            )
            deadline = time.time() + 60
            while time.time() < deadline:
                if port_file.exists() and port_file.read_text().strip():
                    return proc, f"127.0.0.1:{port_file.read_text().strip()}"
                assert proc.poll() is None, (
                    f"{log_name} died rc={proc.returncode}:\n"
                    + _read(tmp_path / f"{log_name}.log")[-3000:]
                )
                time.sleep(0.2)
            raise TimeoutError(f"{log_name} never reported a port")

        # Primary: chaos-killed ~7s after its import (setup below takes
        # ~2-3s, so the kill lands with tasks doing, a reshard epoch
        # PREPARING, node 0 alone in the waiting set, and serving
        # traffic mid-stream).
        primary, paddr = start_master_proc(
            [], "master.kill:at=7s", "primary"
        )
        standby, saddr = start_master_proc(
            ["--standby", f"--primary_addr={paddr}"], None, "standby",
            extra_env={
                "DLROVER_TPU_HA_LEASE_S": "1.5",
                "DLROVER_TPU_HA_TAIL_POLL_S": "0.1",
            },
        )
        procs = [primary, standby]

        class FakeServer:
            """Deterministic arithmetic decode over the real
            ReplicaRunner protocol (token i = (sum(prompt)+i) % 97)."""

            def __init__(self, slots=4):
                self.slots = slots
                self._pending = []
                self._active = {}
                self.last_stats = {}

            def submit(self, rid, prompt, mnt, prefix_len=0, prefix_fp=""):
                self._pending.append((rid, [int(t) for t in prompt],
                                      int(mnt)))

            def cancel(self, rid):
                before = len(self._pending)
                self._pending = [p for p in self._pending if p[0] != rid]
                return len(self._pending) < before

            def abort(self, rid):
                return self.cancel(rid) or \
                    self._active.pop(rid, None) is not None

            def pending_count(self):
                return len(self._pending)

            def pending_rids(self):
                return [r for r, _, _ in self._pending]

            def active_rids(self):
                return list(self._active)

            def free_slots(self):
                return max(
                    0, self.slots - len(self._active) - len(self._pending)
                )

            def serve_incremental(self, tick=None, on_finish=None,
                                  on_token=None, idle_wait=0.0005):
                while True:
                    if tick is not None and tick() is False:
                        return {}
                    while self._pending and len(self._active) < self.slots:
                        rid, p, mnt = self._pending.pop(0)
                        self._active[rid] = (p, mnt)
                    for rid in list(self._active):
                        p, mnt = self._active.pop(rid)
                        new = [(sum(p) + i) % 97 for i in range(mnt)]
                        if on_finish is not None:
                            # Contract: the full sequence (prompt echoed
                            # + new tokens); the runner strips the echo.
                            on_finish(rid, list(p) + new)
                    time.sleep(idle_wait)

        hb_stop = threading.Event()
        clients = []
        try:
            c0 = MasterClient(paddr, 0, state_dir=str(state_dir))
            c1 = MasterClient(paddr, 1, state_dir=str(state_dir))
            clients += [c0, c1]
            for nid, c in ((0, c0), (1, c1)):
                c.register_node(node_rank=nid, host="127.0.0.1",
                                agent_port=9100 + nid, local_world_size=1)
            # Mid-rendezvous: ONLY node 0 joins pre-kill.
            c0.join_rendezvous(node_rank=0, local_world_size=1)
            # Data sharding: 12 shards; 2 completed, 2 HELD doing
            # across the kill.
            c0.report_dataset_shard_params(
                dataset_name="ds", dataset_size=120, shard_size=10
            )
            granted_ids = []
            pre = [c0.get_task("ds") for _ in range(4)]
            granted_ids += [t.task_id for t in pre]
            assert all(t.task_id >= 0 for t in pre)
            c0.report_task_result("ds", pre[0].task_id, True)
            c0.report_task_result("ds", pre[1].task_id, True)
            held = pre[2:]
            # In-flight reshard epoch.
            epoch_info = c0.announce_reshard(
                2, {"dp": 2}, expected_reports=2, deadline_s=120.0
            )
            epoch = epoch_info.epoch
            assert epoch >= 1 and epoch_info.status == "preparing"
            # Serving: master-backed registry + a real loopback fleet.
            reg_client = MasterClient(paddr, 9, state_dir=str(state_dir))
            clients.append(reg_client)
            registry = ServeRegistry(MasterKv(reg_client), job=job,
                                     lease_s=60.0)
            registry.announce_gateway("g0", "127.0.0.1:7777")

            def heartbeat():
                while not hb_stop.wait(0.5):
                    try:
                        registry.announce_gateway("g0", "127.0.0.1:7777")
                    except Exception:  # noqa: BLE001 - blackout window
                        pass

            threading.Thread(target=heartbeat, daemon=True).start()

            core = GatewayCore(GatewayConfig())
            transport = LoopbackTransport(self._core_handle(core))
            runner = ReplicaRunner(
                FakeServer(), transport, "rep0", poll_interval=0.005,
            )
            threading.Thread(target=runner.run, daemon=True).start()
            serve_ids = []
            serve_stop = threading.Event()

            def submit_loop():
                i = 0
                while not serve_stop.wait(0.15):
                    rid = f"s{i}"
                    core.submit(rid, [i + 1, i + 2], 4)
                    serve_ids.append(rid)
                    i += 1

            threading.Thread(target=submit_loop, daemon=True).start()

            # --- the kill -------------------------------------------------
            rc = primary.wait(timeout=90)
            assert rc == 83, (
                f"primary exited {rc}, wanted chaos master.kill 83:\n"
                + _read(tmp_path / "primary.log")[-3000:]
            )
            t_kill = time.monotonic()
            deadline = time.time() + 60
            while time.time() < deadline:
                if read_addr(str(state_dir)) == saddr and \
                        addr_connectable(saddr, timeout=0.5):
                    break
                assert standby.poll() is None, (
                    "standby died:\n"
                    + _read(tmp_path / "standby.log")[-3000:]
                )
                time.sleep(0.2)
            assert read_addr(str(state_dir)) == saddr, (
                "no takeover observed:\n"
                + _read(tmp_path / "standby.log")[-3000:]
            )
            blackout_s = time.monotonic() - t_kill
            # The registry never observes a blank master: the FIRST
            # post-takeover read shows the journaled gateway entry.
            fresh = MasterClient(saddr, 8)
            clients.append(fresh)
            gws = ServeRegistry(MasterKv(fresh), job=job,
                                lease_s=60.0).gateways()
            assert "g0" in gws, f"blank registry after takeover: {gws}"

            # Held doing tasks complete EXACTLY once on the new master.
            for t in held:
                c0.report_task_result("ds", t.task_id, True)
            # Node 1 finally joins: the half-formed round completes on
            # the standby (its waiting set replayed node 0).
            c1.join_rendezvous(node_rank=1, local_world_size=1)
            world = {}
            deadline = time.time() + 60
            while time.time() < deadline and len(world) != 2:
                _, _, world, coord = c0.get_comm_world()
                time.sleep(0.2)
            assert len(world) == 2, "rendezvous never completed"
            node_ids = sorted(w["node_id"] for w in world.values())
            assert node_ids == [0, 1]

            # Drain the queue: every task id granted exactly once
            # fleet-wide, none lost, none double-completed.
            while True:
                t = c1.get_task("ds")
                if t.task_id < 0:
                    break
                granted_ids.append(t.task_id)
                c1.report_task_result("ds", t.task_id, True)
            assert sorted(granted_ids) == list(range(12)), granted_ids
            assert len(set(granted_ids)) == 12  # no double grants

            # The in-flight reshard epoch resolves DONE.
            assert c0.report_reshard(epoch, ok=True)
            assert c1.report_reshard(epoch, ok=True)
            assert c0.get_reshard_epoch().status == "done"

            # Serving: stop admitting, everything submitted across the
            # window finishes exactly-once with correct bytes.
            serve_stop.set()
            time.sleep(0.3)
            deadline = time.time() + 60
            while time.time() < deadline and \
                    core.counters["completed"] < len(serve_ids):
                time.sleep(0.1)
            assert core.counters["completed"] == len(serve_ids)
            assert core.counters["duplicate_completions"] == 0
            for i, rid in enumerate(serve_ids):
                st = core.status(rid)
                assert st.state == "done"
                assert st.tokens == [
                    (2 * i + 3 + k) % 97 for k in range(4)
                ]
            hb_stop.set()
            core.drain("rep0")
            print(f"WARM_FAILOVER_OK blackout_s={blackout_s:.2f} "
                  f"serving={len(serve_ids)} tasks=12")
        finally:
            hb_stop.set()
            for c in clients:
                try:
                    c.close()
                except Exception:  # noqa: BLE001 - teardown
                    pass
            _terminate(procs)
        # The surviving journal passes fsck (after the standby exited).
        proc = subprocess.run(
            [sys.executable, "-m", "dlrover_tpu.master.statecheck",
             str(state_dir)],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    @staticmethod
    def _core_handle(core):
        """Gateway.handle dispatch over a bare core (loopback)."""
        from dlrover_tpu.common import messages as m

        def handle(msg):
            if isinstance(msg, m.ServeReplicaRegister):
                core.register(msg.replica_id, msg.slots, msg.role)
            elif isinstance(msg, m.ServeReplicaDeregister):
                core.deregister(msg.replica_id)
            elif isinstance(msg, m.ServeReplicaPoll):
                return core.poll(msg.replica_id, msg.free_slots,
                                 msg.active, msg.stats, msg.warm_prefixes)
            elif isinstance(msg, m.ServeTokens):
                core.stream(msg.replica_id, msg.req_id, msg.tokens)
            elif isinstance(msg, m.ServeDone):
                core.complete(msg.replica_id, msg.req_id, msg.tokens,
                              msg.ok, msg.reason, msg.replayed)
            return None

        return handle


@pytest.mark.cells
@pytest.mark.ha
class TestCellMasterKillFailover:
    """Flagship ISSUE 15 scenario: TWO cells, each a full master with
    its own PR-13 journal + warm standby, training-shaped (data-shard
    queues) and serving-shaped (master-KV serve registry) control-plane
    load on BOTH.  Cell0's master is chaos-SIGKILLed
    (``cell.master_kill``, exit 85) mid-stream.  Proof obligations:

    - cell0's warm standby adopts the journaled state: the partly
      consumed shard queue continues exactly-once (no task id lost or
      double-granted fleet-wide), and the serving-registry entries
      announced pre-kill are visible post-takeover;
    - cell1 NEVER blacks out: its probe stream of short-budget RPCs
      shows no gap above one probe budget while cell0 fails over (the
      per-cell blackout metric extending HA_BENCH_CPU.json's
      fleet-wide one);
    - the shared cell registry re-learns cell0 from the promoted
      standby, so the ring covers both cells again;
    - ``statecheck`` exits 0 on cell0's surviving journal.
    """

    def test_one_cell_dies_the_other_never_blacks_out(self, tmp_path):
        import json as _json
        import threading

        from dlrover_tpu import chaos as _chaos
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.cells.registry import CellRegistry
        from dlrover_tpu.common import messages as wire
        from dlrover_tpu.common.rpc import RpcClient
        from dlrover_tpu.master.state import read_addr
        from dlrover_tpu.serving.tier import RpcKv, ServeRegistry, MasterKv

        job = "cellkill"

        def start(cmd_args, log_name, extra_env=None):
            env = _env(extra_env)
            env.pop("DLROVER_TPU_MASTER_STATE_DIR", None)
            port_file = tmp_path / f"{log_name}.port"
            log = open(tmp_path / f"{log_name}.log", "w")
            proc = subprocess.Popen(
                [sys.executable, *cmd_args,
                 f"--port_file={port_file}"],
                cwd=REPO, env=env, stdout=log,
                stderr=subprocess.STDOUT,
            )
            deadline = time.time() + 60
            while time.time() < deadline:
                if port_file.exists() and port_file.read_text().strip():
                    return proc, (
                        f"127.0.0.1:{port_file.read_text().strip()}"
                    )
                assert proc.poll() is None, (
                    f"{log_name} died rc={proc.returncode}:\n"
                    + _read(tmp_path / f"{log_name}.log")[-3000:]
                )
                time.sleep(0.1)
            raise TimeoutError(f"{log_name} never reported a port")

        procs = []
        try:
            reg_proc, reg_addr = start(
                ["-m", "dlrover_tpu.cells.main", "--registry",
                 "--port", "0"],
                "registry",
            )
            procs.append(reg_proc)

            cells = {}
            for cid in ("cell0", "cell1"):
                state_dir = tmp_path / f"state_{cid}"
                state_dir.mkdir()
                base = ["-m", "dlrover_tpu.master.main", "--port=0",
                        f"--job_name={job}", "--min_nodes=1",
                        "--max_nodes=4", f"--cell_id={cid}",
                        f"--cell_registry={reg_addr}",
                        f"--state_dir={state_dir}"]
                hb_env = {"DLROVER_TPU_CELL_LEASE_S": "2.0"}
                prim_env = dict(hb_env)
                if cid == "cell0":
                    # The kill site fires in the cell heartbeat after
                    # ~4s — mid-task-queue, mid-serving-announce.
                    prim_env["DLROVER_TPU_FAULTS"] = (
                        "cell.master_kill:method=cell0,at=4s"
                    )
                primary, paddr = start(base, f"{cid}_primary",
                                       extra_env=prim_env)
                standby, saddr = start(
                    base + ["--standby", f"--primary_addr={paddr}"],
                    f"{cid}_standby",
                    extra_env={
                        **hb_env,
                        "DLROVER_TPU_HA_LEASE_S": "1.0",
                        "DLROVER_TPU_HA_TAIL_POLL_S": "0.05",
                    },
                )
                procs += [primary, standby]
                cells[cid] = {
                    "primary": primary, "standby": standby,
                    "addr": paddr, "state": str(state_dir),
                }

            # Training-shaped load: a data-shard queue per cell,
            # partly consumed pre-kill.
            tasks_per_cell = 12
            granted = {"cell0": [], "cell1": []}
            clients = {}
            for cid, ent in cells.items():
                cli = MasterClient(ent["addr"], 0,
                                   state_dir=ent["state"])
                clients[cid] = cli
                cli.report_dataset_shard_params(
                    dataset_name=f"ds-{cid}",
                    dataset_size=tasks_per_cell * 10, shard_size=10,
                )
                for _ in range(4):
                    t = cli.get_task(f"ds-{cid}")
                    granted[cid].append(t.task_id)
                cli.report_task_result(f"ds-{cid}",
                                       granted[cid][0], True)
            # Serving-shaped load: serve-registry announcements riding
            # each cell's master KV.
            for cid in cells:
                sreg = ServeRegistry(MasterKv(clients[cid]), job=job)
                sreg.announce_gateway(f"gw-{cid}", f"10.0.0.1:{cid}")
                sreg.announce_replica(f"rep-{cid}", slots=4)

            # Cell1's never-blacks-out probe: short-budget RPCs on a
            # tight loop; the max success gap IS the per-cell blackout.
            stop_probe = threading.Event()
            gaps = {"max": 0.0, "count": 0}

            def probe_cell1():
                addr = cells["cell1"]["addr"]
                last = time.monotonic()
                while not stop_probe.is_set():
                    cli = RpcClient(addr, timeout=0.5)
                    try:
                        cli.call(
                            wire.KVStoreGet(key="probe"),
                            timeout=0.5, retries=1, deadline=0.5,
                            idempotent=True,
                        )
                        now = time.monotonic()
                        gaps["max"] = max(gaps["max"], now - last)
                        gaps["count"] += 1
                        last = now
                    except Exception:  # noqa: BLE001 - counted as gap
                        pass
                    finally:
                        cli.close()
                    time.sleep(0.05)

            prober = threading.Thread(target=probe_cell1, daemon=True)
            prober.start()

            # Wait for the chaos kill (exit 85).
            rc = cells["cell0"]["primary"].wait(timeout=60)
            assert rc == _chaos.EXIT_CELL_MASTER_KILL, (
                _read(tmp_path / "cell0_primary.log")[-3000:]
            )
            t_kill = time.monotonic()
            # The standby takes over: the addr file flips.
            old = cells["cell0"]["addr"]
            deadline = time.time() + 30
            new_addr = ""
            while time.time() < deadline:
                cur = read_addr(cells["cell0"]["state"])
                if cur and cur != old:
                    new_addr = cur
                    break
                time.sleep(0.1)
            assert new_addr, "cell0 standby never took over"

            # Drain cell0's queue through the failover-aware client:
            # every remaining task id granted exactly once.
            cli0 = clients["cell0"]
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    t = cli0.get_task("ds-cell0")
                except Exception:  # noqa: BLE001 - blackout window
                    time.sleep(0.2)
                    continue
                if t.task_id < 0:
                    break
                granted["cell0"].append(t.task_id)
            ids0 = granted["cell0"]
            assert sorted(ids0) == list(range(tasks_per_cell)), ids0
            assert len(set(ids0)) == len(ids0), "task double-granted"

            # The pre-kill serving registry survived into the new
            # leader (journaled KV writes replayed).
            sreg0 = ServeRegistry(MasterKv(cli0), job=job)
            assert f"gw-cell0" in sreg0.gateways()
            assert f"rep-cell0" in sreg0.replicas()

            # Cell1 never blacked out, and drains its own queue too.
            stop_probe.set()
            prober.join(timeout=5)
            assert gaps["count"] > 10
            assert gaps["max"] < 1.0, (
                f"cell1 observed a {gaps['max']:.2f}s gap"
            )
            cli1 = clients["cell1"]
            while True:
                t = cli1.get_task("ds-cell1")
                if t.task_id < 0:
                    break
                granted["cell1"].append(t.task_id)
            assert sorted(granted["cell1"]) == \
                list(range(tasks_per_cell))

            # The shared registry re-learned cell0 from the promoted
            # standby: the ring covers both cells again.
            creg = CellRegistry(RpcKv(reg_addr), job=job, lease_s=2.0)
            deadline = time.time() + 20
            live = {}
            while time.time() < deadline:
                live = creg.cells()
                if set(live) == {"cell0", "cell1"} and \
                        live["cell0"]["addr"] == new_addr:
                    break
                time.sleep(0.2)
            assert set(live) == {"cell0", "cell1"}, live
            assert live["cell0"]["addr"] == new_addr

            for cli in clients.values():
                cli.close()

            # The surviving journal is statecheck-clean.
            check = subprocess.run(
                [sys.executable, "-m",
                 "dlrover_tpu.master.statecheck",
                 cells["cell0"]["state"], "--json"],
                capture_output=True, text=True, timeout=120,
                cwd=REPO, env=_env(),
            )
            assert check.returncode == 0, check.stdout + check.stderr
            report = _json.loads(check.stdout)
            assert report["damage"] == []
        finally:
            _terminate(procs)
