"""Host-offloaded optimizer state (ZeRO-Offload analogue,
reference ``atorch/atorch/optimizers/adam_offload.py``).

The CPU test backend exposes a pinned_host memory space but cannot
compile steps that stream host operands (no placement custom-call), so
here the API must degrade to plain device placement with identical
numerics; the streaming path itself runs on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.optim.offload import (
    host_memory_kind,
    host_shardings_for,
    offload_opt_state,
    supports_host_offload,
    with_memory_kind,
)


class TestOffload:
    def test_host_memory_kind_reported(self):
        assert host_memory_kind() == "pinned_host"

    def test_capability_probe_is_stable_bool(self):
        got = supports_host_offload()
        assert isinstance(got, bool)
        assert supports_host_offload() == got  # cached, no flapping

    def test_with_memory_kind(self):
        from jax.sharding import SingleDeviceSharding

        s = SingleDeviceSharding(jax.devices()[0])
        assert with_memory_kind(s, None) is s
        assert (
            with_memory_kind(s, "pinned_host").memory_kind == "pinned_host"
        )

    def test_update_math_unchanged(self):
        params = {"w": jnp.arange(8.0)}
        grads = {"w": jnp.ones(8)}
        base = optax.adam(1e-2)
        off = offload_opt_state(base)
        u0, _ = base.update(grads, base.init(params), params)
        if supports_host_offload():
            u1, _ = jax.jit(off.update)(grads, off.init(params), params)
        else:
            # Degraded mode: the wrapper must be the identity.
            assert off is base
            u1, _ = off.update(grads, off.init(params), params)
        np.testing.assert_allclose(
            np.asarray(u0["w"]), np.asarray(u1["w"]), atol=1e-7
        )

    def test_accelerate_offload_strategy(self, cpu_mesh_devices):
        """accelerate(offload_opt=True) must train correctly whether or
        not the backend supports host streaming; when it does, the opt
        state rests in pinned_host between steps."""
        from dlrover_tpu.parallel.accelerate import Strategy, accelerate
        from dlrover_tpu.parallel.mesh import MeshSpec

        def init_fn(rng):
            return {"w": jax.random.normal(rng, (64, 64)) * 0.1}

        def loss_fn(p, b):
            return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

        x = np.random.RandomState(0).randn(8, 64).astype(np.float32)
        y = np.random.RandomState(1).randn(8, 64).astype(np.float32)
        job = accelerate(
            loss_fn=loss_fn,
            init_fn=init_fn,
            optimizer=optax.adam(0.05),
            sample_batch={"x": x, "y": y},
            strategy=Strategy(mesh=MeshSpec(dp=2), offload_opt=True),
            devices=cpu_mesh_devices[:2],
        )
        state = job.create_state(jax.random.PRNGKey(0))
        leaf = jax.tree_util.tree_leaves(state["opt_state"])[0]
        expect_kind = (
            "pinned_host" if supports_host_offload() else "device"
        )
        assert leaf.sharding.memory_kind == expect_kind
        batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
        first = None
        for _ in range(10):
            state, metrics = job.train_step(state, batch)
            first = first or float(metrics["loss"])
        assert float(metrics["loss"]) < first
        leaf = jax.tree_util.tree_leaves(state["opt_state"])[0]
        assert leaf.sharding.memory_kind == expect_kind

    def test_host_shardings_identity_when_unsupported(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
        tree = {"mu": NamedSharding(mesh, P())}
        out = host_shardings_for(tree)
        if supports_host_offload():
            assert out["mu"].memory_kind == "pinned_host"
        else:
            assert out["mu"] is tree["mu"]


class TestOffloadRemat:
    """Strategy(remat='offload'): block residuals parked in host DRAM
    (VERDICT r2 next #9; reference selective_offloading_checkpoint
    .py:252)."""

    def test_offload_remat_matches_none_and_places_on_host(
        self, cpu_mesh_devices
    ):
        import numpy as np
        import optax

        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel.accelerate import Strategy, accelerate
        from dlrover_tpu.parallel.mesh import MeshSpec

        cfg = llama.LlamaConfig.tiny(n_layer=2)
        rng = np.random.RandomState(0)
        sample = {"tokens": rng.randint(0, 250, size=(8, 17)).astype(
            np.int32)}

        def job_for(remat):
            return accelerate(
                loss_fn=lambda p, b: llama.loss_fn(
                    p, b, cfg, moe_aux_weight=0.0
                ),
                init_fn=lambda r: llama.init_params(r, cfg),
                optimizer=optax.adamw(1e-3),
                sample_batch=sample,
                strategy=Strategy(mesh=MeshSpec(dp=2), remat=remat),
                devices=cpu_mesh_devices[:2],
            )

        j_off = job_for("offload")
        j_none = job_for("none")
        batch = {"tokens": jnp.asarray(sample["tokens"])}
        s_off = j_off.create_state(jax.random.PRNGKey(0))
        s_none = j_none.create_state(jax.random.PRNGKey(0))
        for _ in range(2):
            s_off, m_off = j_off.train_step(s_off, batch)
            s_none, m_none = j_none.train_step(s_none, batch)
        # Rematerialization reorders bf16 reductions: tiny drift is
        # expected, equality is not.
        np.testing.assert_allclose(
            float(m_off["loss"]), float(m_none["loss"]), rtol=1e-3
        )
        # The host-placement effect itself is only observable on TPU
        # runtimes (the single-memory CPU backend elides pinned_host
        # transfers entirely — verified: even an explicit in-jit
        # device_put to pinned_host lowers with no memory annotation).
        # What IS checkable everywhere: the policy names the tagged
        # residual and requests offload, not save.
        from dlrover_tpu.parallel.accelerate import REMAT_POLICIES

        from jax._src.ad_checkpoint import name_p
        from jax._src.interpreters.partial_eval import Offloadable

        pol = REMAT_POLICIES["offload"]
        # Policy contract: the tagged residual offloads device->host;
        # everything else rematerializes.
        decision = pol(name_p, name="block_out")
        assert isinstance(decision, Offloadable)
        assert (decision.src, decision.dst) == ("device", "pinned_host")
        assert not isinstance(pol(name_p, name="other"), Offloadable)
        assert not isinstance(pol(None), Offloadable)
