"""Host-offloaded optimizer state (ZeRO-Offload analogue,
reference ``atorch/atorch/optimizers/adam_offload.py``).

The CPU test backend exposes a pinned_host memory space but cannot
compile steps that stream host operands (no placement custom-call), so
here the API must degrade to plain device placement with identical
numerics; the streaming path itself runs on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.optim.offload import (
    host_memory_kind,
    host_shardings_for,
    offload_opt_state,
    supports_host_offload,
    with_memory_kind,
)


class TestOffload:
    def test_host_memory_kind_reported(self):
        assert host_memory_kind() == "pinned_host"

    def test_capability_probe_is_stable_bool(self):
        got = supports_host_offload()
        assert isinstance(got, bool)
        assert supports_host_offload() == got  # cached, no flapping

    def test_with_memory_kind(self):
        from jax.sharding import SingleDeviceSharding

        s = SingleDeviceSharding(jax.devices()[0])
        assert with_memory_kind(s, None) is s
        assert (
            with_memory_kind(s, "pinned_host").memory_kind == "pinned_host"
        )

    def test_update_math_unchanged(self):
        params = {"w": jnp.arange(8.0)}
        grads = {"w": jnp.ones(8)}
        base = optax.adam(1e-2)
        off = offload_opt_state(base)
        u0, _ = base.update(grads, base.init(params), params)
        if supports_host_offload():
            u1, _ = jax.jit(off.update)(grads, off.init(params), params)
        else:
            # Degraded mode: the wrapper must be the identity.
            assert off is base
            u1, _ = off.update(grads, off.init(params), params)
        np.testing.assert_allclose(
            np.asarray(u0["w"]), np.asarray(u1["w"]), atol=1e-7
        )

    def test_accelerate_offload_strategy(self, cpu_mesh_devices):
        """accelerate(offload_opt=True) must train correctly whether or
        not the backend supports host streaming; when it does, the opt
        state rests in pinned_host between steps."""
        from dlrover_tpu.parallel.accelerate import Strategy, accelerate
        from dlrover_tpu.parallel.mesh import MeshSpec

        def init_fn(rng):
            return {"w": jax.random.normal(rng, (64, 64)) * 0.1}

        def loss_fn(p, b):
            return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

        x = np.random.RandomState(0).randn(8, 64).astype(np.float32)
        y = np.random.RandomState(1).randn(8, 64).astype(np.float32)
        job = accelerate(
            loss_fn=loss_fn,
            init_fn=init_fn,
            optimizer=optax.adam(0.05),
            sample_batch={"x": x, "y": y},
            strategy=Strategy(mesh=MeshSpec(dp=2), offload_opt=True),
            devices=cpu_mesh_devices[:2],
        )
        state = job.create_state(jax.random.PRNGKey(0))
        leaf = jax.tree_util.tree_leaves(state["opt_state"])[0]
        expect_kind = (
            "pinned_host" if supports_host_offload() else "device"
        )
        assert leaf.sharding.memory_kind == expect_kind
        batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
        first = None
        for _ in range(10):
            state, metrics = job.train_step(state, batch)
            first = first or float(metrics["loss"])
        assert float(metrics["loss"]) < first
        leaf = jax.tree_util.tree_leaves(state["opt_state"])[0]
        assert leaf.sharding.memory_kind == expect_kind

    def test_host_shardings_identity_when_unsupported(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
        tree = {"mu": NamedSharding(mesh, P())}
        out = host_shardings_for(tree)
        if supports_host_offload():
            assert out["mu"].memory_kind == "pinned_host"
        else:
            assert out["mu"] is tree["mu"]
