"""Layout-planner tests (the MIP-TP-planner analogue,
reference ``atorch/auto/opt_lib/shard_planners/mip_tp_planner.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dlrover_tpu.parallel.layout_planner import (
    plan_layout,
    plan_report,
    validate_layout,
)


class TestPlanLayout:
    def test_big_matrix_gets_both_axes(self):
        params = {"w": jax.ShapeDtypeStruct((1024, 512), jnp.float32)}
        specs = plan_layout(params, {"fsdp": 2, "tp": 2})
        # fsdp rides dim 0 (row), tp rides the features dim (column) —
        # the Megatron alternation the cost model encodes.
        assert specs["w"] == P("fsdp", "tp")

    def test_indivisible_dim_avoided(self):
        params = {"w": jax.ShapeDtypeStruct((1023, 512), jnp.float32)}
        specs = plan_layout(params, {"fsdp": 2, "tp": 2})
        for d, ax in enumerate(specs["w"]):
            if ax is not None:
                for a in (ax if isinstance(ax, tuple) else (ax,)):
                    assert 1023 % 2 != 0  # dim 0 must not be sharded
                    assert d != 0

    def test_small_leaves_replicated(self):
        params = {"bias": jax.ShapeDtypeStruct((512,), jnp.float32)}
        specs = plan_layout(params, {"fsdp": 2, "tp": 2})
        assert specs["bias"] == P()

    def test_memory_reduction_reported(self):
        params = {
            "w1": jax.ShapeDtypeStruct((2048, 2048), jnp.float32),
            "w2": jax.ShapeDtypeStruct((2048, 8192), jnp.float32),
        }
        axis_sizes = {"fsdp": 4, "tp": 2}
        specs = plan_layout(params, axis_sizes)
        report = plan_report(params, specs, axis_sizes)
        for leaf in report:
            # Every big leaf fully sharded: 8x memory reduction.
            assert leaf.bytes_per_device * 8 == leaf.bytes_total

    def test_3d_leaf(self):
        # Stacked-expert weight [E, D, F]: experts dim indivisible by 4.
        params = {"experts": jax.ShapeDtypeStruct((6, 512, 1024),
                                                  jnp.float32)}
        specs = plan_layout(params, {"fsdp": 4, "tp": 2})
        validate_layout(params, specs, {"fsdp": 4, "tp": 2})
        # fsdp=4 cannot use dim 0 (6 % 4 != 0); it lands on another dim.
        assert specs["experts"][0] != "fsdp"

    def test_validate_rejects_indivisible(self):
        params = {"w": jax.ShapeDtypeStruct((6, 512), jnp.float32)}
        with pytest.raises(ValueError, match="not divisible"):
            validate_layout(params, {"w": P("fsdp", None)}, {"fsdp": 4})

    def test_validate_rejects_unknown_axis(self):
        params = {"w": jax.ShapeDtypeStruct((8, 512), jnp.float32)}
        with pytest.raises(ValueError, match="unknown mesh axis"):
            validate_layout(params, {"w": P("nope", None)}, {"fsdp": 4})


class TestAccelerateIntegration:
    def test_planner_specs_compile_and_run(self, cpu_mesh_devices):
        """accelerate(param_specs='planner') trains a small MLP under an
        fsdp x tp mesh with planner-chosen layouts."""
        import optax

        from dlrover_tpu.parallel.accelerate import Strategy, accelerate
        from dlrover_tpu.parallel.mesh import MeshSpec

        def init_fn(rng):
            k1, k2 = jax.random.split(rng)
            return {
                "w1": jax.random.normal(k1, (256, 512)) * 0.05,
                "w2": jax.random.normal(k2, (512, 256)) * 0.05,
            }

        def loss_fn(p, batch):
            h = jnp.tanh(batch["x"] @ p["w1"])
            return jnp.mean((h @ p["w2"] - batch["y"]) ** 2)

        x = np.random.RandomState(0).randn(8, 256).astype(np.float32)
        y = np.random.RandomState(1).randn(8, 256).astype(np.float32)
        job = accelerate(
            loss_fn=loss_fn,
            init_fn=init_fn,
            optimizer=optax.sgd(0.1),
            sample_batch={"x": x, "y": y},
            strategy=Strategy(mesh=MeshSpec(dp=2, fsdp=2, tp=2)),
            param_specs="planner",
            devices=cpu_mesh_devices[:8],
        )
        state = job.create_state(jax.random.PRNGKey(0))
        # Planner actually sharded the weights over fsdp/tp.
        w1_spec = state["params"]["w1"].sharding.spec
        assert any(ax is not None for ax in w1_spec)
        batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
        l0 = None
        for _ in range(5):
            state, metrics = job.train_step(state, batch)
            l0 = l0 or float(metrics["loss"])
        assert float(metrics["loss"]) < l0
