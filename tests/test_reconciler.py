"""L1 control-plane tests: JobReconciler driving GkePlatform against a fake
Kubernetes API server (test model: the reference's mocked ``k8sClient``,
``dlrover/python/tests/test_utils.py:296``, and the Go operator's
envtest-based controller tests)."""

import queue
import threading
import time
from types import SimpleNamespace

import pytest

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.scaler import ElasticJobScaler, ScalePlan
from dlrover_tpu.scheduler.platform import GkePlatform, InMemoryPlatform
from dlrover_tpu.scheduler.reconciler import (
    JobPhase,
    JobReconciler,
    JobSpec,
    ReplicaSpec,
)


# ---------------------------------------------------------------------------
# Fake kubernetes API (the shapes GkePlatform actually touches)
# ---------------------------------------------------------------------------


class _FakeClientMod:
    """Stand-ins for the kubernetes.client model classes."""

    class V1ObjectMeta(SimpleNamespace):
        def __init__(self, name=None, labels=None):
            super().__init__(name=name, labels=labels or {})

    class V1ResourceRequirements(SimpleNamespace):
        def __init__(self, limits=None):
            super().__init__(limits=limits or {})

    class V1Container(SimpleNamespace):
        def __init__(self, name=None, image=None, resources=None):
            super().__init__(name=name, image=image, resources=resources)

    class V1PodSpec(SimpleNamespace):
        def __init__(self, restart_policy=None, containers=None,
                     node_selector=None):
            super().__init__(
                restart_policy=restart_policy,
                containers=containers or [],
                node_selector=node_selector,
            )

    class V1Pod(SimpleNamespace):
        def __init__(self, metadata=None, spec=None):
            super().__init__(
                metadata=metadata,
                spec=spec,
                status=SimpleNamespace(phase="Pending", pod_ip=""),
            )


class FakeKubeApi:
    """In-memory pod store with the CoreV1Api surface GkePlatform uses,
    plus fault-injection (``set_phase``) for tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pods = {}
        self.events = queue.Queue()
        self.create_count = 0

    @staticmethod
    def _snapshot(pod):
        """Real watches deliver object snapshots, not live references."""
        return SimpleNamespace(
            metadata=SimpleNamespace(
                name=pod.metadata.name, labels=dict(pod.metadata.labels)
            ),
            spec=pod.spec,
            status=SimpleNamespace(
                phase=pod.status.phase, pod_ip=pod.status.pod_ip
            ),
        )

    def create_namespaced_pod(self, namespace, pod):
        with self._lock:
            name = pod.metadata.name
            if name in self.pods:
                raise RuntimeError(f"409 pod {name} already exists")
            self.pods[name] = pod
            self.create_count += 1
            self.events.put(("ADDED", self._snapshot(pod)))
        return pod

    def delete_namespaced_pod(self, name, namespace):
        with self._lock:
            pod = self.pods.pop(name, None)
            if pod is None:
                raise RuntimeError(f"404 pod {name} not found")
            self.events.put(("DELETED", self._snapshot(pod)))
        return pod

    def list_namespaced_pod(self, namespace):
        with self._lock:
            return SimpleNamespace(items=list(self.pods.values()))

    # -- fault injection ----------------------------------------------------
    def set_phase(self, name, phase, pod_ip="10.0.0.1"):
        with self._lock:
            pod = self.pods[name]
            pod.status.phase = phase
            pod.status.pod_ip = pod_ip
            self.events.put(("MODIFIED", self._snapshot(pod)))

    def set_all(self, phase, node_type=None):
        with self._lock:
            names = [
                n for n, p in self.pods.items()
                if node_type is None
                or p.metadata.labels.get("node-type") == node_type
            ]
        for n in names:
            self.set_phase(n, phase)


class _FakeWatchMod:
    class Watch:
        def __init__(self):
            self._stopped = False

        def stream(self, list_fn, namespace):
            api = list_fn.__self__
            while not self._stopped:
                try:
                    etype, pod = api.events.get(timeout=0.1)
                except queue.Empty:
                    continue
                yield {"type": etype, "object": pod}

        def stop(self):
            self._stopped = True


def make_gke():
    api = FakeKubeApi()
    platform = GkePlatform(
        namespace="test", image="img",
        api=api, client_mod=_FakeClientMod, watch_mod=_FakeWatchMod,
    )
    return api, platform


# ---------------------------------------------------------------------------
# GkePlatform against the fake API
# ---------------------------------------------------------------------------


class TestGkePlatform:
    def test_create_list_delete(self):
        api, platform = make_gke()
        node = Node(
            NodeType.WORKER, 3, rank_index=1,
            config_resource=NodeResource(tpu_chips=4),
        )
        pn = platform.create_node(node, "jobx")
        assert pn.name == "jobx-worker-3"
        pod = api.pods["jobx-worker-3"]
        assert pod.metadata.labels["rank-index"] == "1"
        limits = pod.spec.containers[0].resources.limits
        assert limits["google.com/tpu"] == "4"

        api.set_phase("jobx-worker-3", "Running")
        nodes = platform.list_nodes()
        assert len(nodes) == 1
        assert nodes[0].status == NodeStatus.RUNNING
        assert nodes[0].node_id == 3 and nodes[0].rank_index == 1

        assert platform.delete_node("jobx-worker-3")
        assert not platform.delete_node("jobx-worker-3")
        assert platform.list_nodes() == []

    def test_tpu_pod_carries_gke_scheduling_contract(self):
        """A TPU pod must select the accelerator flavour + slice
        topology (GKE schedules slices by those node labels; the
        reference pins pod-spec details with envtest, suite_test.go)."""
        api, platform = make_gke()
        node = Node(
            NodeType.WORKER, 0, rank_index=0,
            config_resource=NodeResource(
                tpu_chips=8, tpu_type="v5p", tpu_topology="2x2x2",
                cpu=4, memory_mb=8192,
            ),
        )
        platform.create_node(node, "jobt")
        pod = api.pods["jobt-worker-0"]
        sel = pod.spec.node_selector
        assert sel["cloud.google.com/gke-tpu-accelerator"] == (
            "tpu-v5p-slice"
        )
        assert sel["cloud.google.com/gke-tpu-topology"] == "2x2x2"
        limits = pod.spec.containers[0].resources.limits
        assert limits == {
            "google.com/tpu": "8", "cpu": "4", "memory": "8192Mi",
        }
        # And the submitted pod passes the schema validator.
        from dlrover_tpu.scheduler.platform import validate_gke_tpu_pod

        validate_gke_tpu_pod(pod, expect_tpu=True)

    def test_cpu_only_pod_has_no_tpu_selector(self):
        api, platform = make_gke()
        platform.create_node(
            Node(NodeType.WORKER, 1, rank_index=1,
                 config_resource=NodeResource(cpu=2)),
            "jobt",
        )
        pod = api.pods["jobt-worker-1"]
        assert pod.spec.node_selector is None
        assert "google.com/tpu" not in (
            pod.spec.containers[0].resources.limits
        )

    def test_typeless_tpu_pod_emits_no_selector(self):
        """tpu_chips without tpu_type: the operator declared no flavour,
        so no selector is guessed (pre-r5 behavior preserved — a silent
        v5e default would strand the pod Pending on a v4/v5p cluster)."""
        api, platform = make_gke()
        platform.create_node(
            Node(NodeType.WORKER, 2, rank_index=2,
                 config_resource=NodeResource(tpu_chips=4)),
            "jobt",
        )
        pod = api.pods["jobt-worker-2"]
        assert pod.spec.node_selector is None
        assert pod.spec.containers[0].resources.limits[
            "google.com/tpu"] == "4"

    def test_schema_validator_rejects_contract_violations(self):
        from dlrover_tpu.scheduler.platform import (
            gke_tpu_accelerator,
            validate_gke_tpu_pod,
        )

        c = _FakeClientMod

        def pod(name="jobx-worker-0", labels=None, restart="Never",
                limits=None, selector="default"):
            if selector == "default":
                selector = {
                    "cloud.google.com/gke-tpu-accelerator":
                        "tpu-v5-lite-podslice",
                    "cloud.google.com/gke-tpu-topology": "2x4",
                }
            return c.V1Pod(
                metadata=c.V1ObjectMeta(
                    name=name,
                    labels=labels if labels is not None else {
                        "app": "jobx", "node-type": "worker",
                        "node-id": "0", "rank-index": "0",
                    },
                ),
                spec=c.V1PodSpec(
                    restart_policy=restart,
                    node_selector=selector,
                    containers=[c.V1Container(
                        name="main", image="img",
                        resources=c.V1ResourceRequirements(
                            limits=limits if limits is not None
                            else {"google.com/tpu": "4"},
                        ),
                    )],
                ),
            )

        validate_gke_tpu_pod(pod())  # the good spec passes
        import pytest as _pytest

        with _pytest.raises(ValueError, match="RFC1123"):
            validate_gke_tpu_pod(pod(name="Bad_Name"))
        with _pytest.raises(ValueError, match="missing label"):
            validate_gke_tpu_pod(pod(labels={"app": "jobx"}))
        with _pytest.raises(ValueError, match="restart_policy"):
            validate_gke_tpu_pod(pod(restart="Always"))
        with _pytest.raises(ValueError, match="positive integer"):
            validate_gke_tpu_pod(pod(limits={"google.com/tpu": "-1"}))
        # no selector at all is legal (type-less resource)...
        validate_gke_tpu_pod(pod(selector=None))
        # ...but topology without the accelerator flavour is incoherent
        with _pytest.raises(ValueError, match="gke-tpu-accelerator"):
            validate_gke_tpu_pod(pod(selector={
                "cloud.google.com/gke-tpu-topology": "2x4",
            }))
        with _pytest.raises(ValueError, match="gke-tpu-topology"):
            validate_gke_tpu_pod(pod(selector={
                "cloud.google.com/gke-tpu-accelerator":
                    "tpu-v5-lite-podslice",
                "cloud.google.com/gke-tpu-topology": "8chips",
            }))
        # every violation reported, not just the first
        with _pytest.raises(ValueError) as ei:
            validate_gke_tpu_pod(pod(name="Bad", restart="Always"))
        assert "RFC1123" in str(ei.value)
        assert "restart_policy" in str(ei.value)
        # accelerator mapping: known flavours, pass-through, rejection
        # (incl. the empty type — guessing a flavour would pin the pod
        # to hosts the cluster may not have)
        assert gke_tpu_accelerator("v6e") == "tpu-v6e-slice"
        assert gke_tpu_accelerator("tpu-v7x-slice") == "tpu-v7x-slice"
        with _pytest.raises(ValueError, match="unknown tpu_type"):
            gke_tpu_accelerator("v99")
        with _pytest.raises(ValueError, match="unknown tpu_type"):
            gke_tpu_accelerator("")

    def test_watch_streams_events(self):
        api, platform = make_gke()
        stop = threading.Event()
        got = []

        def consume():
            for ev in platform.watch(stop):
                got.append((ev.event_type, ev.node.name, ev.node.status))
                if len(got) >= 2:
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        platform.create_node(Node(NodeType.WORKER, 0, rank_index=0), "jobx")
        api.set_phase("jobx-worker-0", "Failed")
        t.join(timeout=5.0)
        stop.set()
        assert ("added", "jobx-worker-0", NodeStatus.PENDING) in got
        assert ("modified", "jobx-worker-0", NodeStatus.FAILED) in got


# ---------------------------------------------------------------------------
# JobReconciler
# ---------------------------------------------------------------------------


def make_reconciler(n_workers=2, plan_dir=None, max_relaunch=2):
    api, platform = make_gke()
    spec = JobSpec(
        job_name="jobx",
        replicas={
            NodeType.WORKER: ReplicaSpec(
                count=n_workers, max_relaunch=max_relaunch
            )
        },
    )
    rec = JobReconciler(spec, platform, plan_dir=plan_dir)
    return api, platform, rec


class TestJobReconciler:
    def test_master_first_bootstrap(self):
        api, platform, rec = make_reconciler(n_workers=2)
        # Pass 1: only the master is created; workers wait.
        summary = rec.reconcile_once()
        assert summary["launched"] == 1
        assert list(api.pods) == ["jobx-master-0"]
        assert rec.phase == JobPhase.PENDING
        # Master pending (not yet running): still no workers.
        rec.reconcile_once()
        assert len(api.pods) == 1
        # Master up: workers launch, ranks 0..n-1.
        api.set_phase("jobx-master-0", "Running")
        summary = rec.reconcile_once()
        assert summary["launched"] == 2
        assert rec.phase == JobPhase.RUNNING
        ranks = sorted(
            int(p.metadata.labels["rank-index"])
            for p in api.pods.values()
            if p.metadata.labels["node-type"] == NodeType.WORKER
        )
        assert ranks == [0, 1]
        # Steady state: reconcile is a no-op.
        assert rec.reconcile_once() == {"launched": 0, "removed": 0}

    def test_failed_worker_relaunched_same_rank(self):
        api, platform, rec = make_reconciler(n_workers=2)
        rec.reconcile_once()
        api.set_phase("jobx-master-0", "Running")
        rec.reconcile_once()
        api.set_all("Running", node_type=NodeType.WORKER)

        api.set_phase("jobx-worker-2", "Failed")  # rank 1 (ids 1,2)
        rank = int(api.pods["jobx-worker-2"].metadata.labels["rank-index"])
        summary = rec.reconcile_once()
        assert summary["launched"] == 1
        replacement = [
            p for p in api.pods.values()
            if p.metadata.labels["node-type"] == NodeType.WORKER
            and p.status.phase == "Pending"
        ]
        assert len(replacement) == 1
        assert int(replacement[0].metadata.labels["rank-index"]) == rank
        # New pod, new node id — never reuses the dead pod's name.
        assert replacement[0].metadata.name != "jobx-worker-2"
        # The dead pod's failure is answered exactly once.
        assert rec.reconcile_once()["launched"] == 0

    def test_relaunch_budget_exhaustion_fails_job(self):
        api, platform, rec = make_reconciler(n_workers=1, max_relaunch=1)
        rec.reconcile_once()
        api.set_phase("jobx-master-0", "Running")
        rec.reconcile_once()

        def fail_running_worker():
            for name, p in list(api.pods.items()):
                if (
                    p.metadata.labels["node-type"] == NodeType.WORKER
                    and p.status.phase in ("Pending", "Running")
                ):
                    api.set_phase(name, "Failed")

        fail_running_worker()
        rec.reconcile_once()  # relaunch 1/1
        assert rec.phase == JobPhase.RUNNING
        fail_running_worker()
        rec.reconcile_once()  # budget exhausted
        assert rec.phase == JobPhase.FAILED

    def test_scale_plan_files_applied(self, tmp_path):
        api, platform, rec = make_reconciler(
            n_workers=2, plan_dir=str(tmp_path)
        )
        rec.reconcile_once()
        api.set_phase("jobx-master-0", "Running")
        rec.reconcile_once()
        api.set_all("Running", node_type=NodeType.WORKER)

        # The master's auto-scaler emits a ScalePlan spec (CR analogue).
        scaler = ElasticJobScaler("jobx", str(tmp_path))
        scaler.scale(
            ScalePlan(
                node_group_resources={
                    NodeType.WORKER: NodeGroupResource(
                        count=3, node_resource=NodeResource()
                    )
                }
            )
        )
        summary = rec.reconcile_once()
        assert summary["launched"] == 1
        workers = [
            p for p in api.pods.values()
            if p.metadata.labels["node-type"] == NodeType.WORKER
        ]
        assert len(workers) == 3
        # Scale back down to 1: the two highest ranks are removed.
        scaler.scale(
            ScalePlan(
                node_group_resources={
                    NodeType.WORKER: NodeGroupResource(
                        count=1, node_resource=NodeResource()
                    )
                }
            )
        )
        summary = rec.reconcile_once()
        assert summary["removed"] == 2
        ranks = [
            int(p.metadata.labels["rank-index"])
            for p in api.pods.values()
            if p.metadata.labels["node-type"] == NodeType.WORKER
        ]
        assert ranks == [0]

    def test_job_completion(self):
        api, platform, rec = make_reconciler(n_workers=2)
        rec.reconcile_once()
        api.set_phase("jobx-master-0", "Running")
        rec.reconcile_once()
        api.set_all("Succeeded", node_type=NodeType.WORKER)
        rec.reconcile_once()
        assert rec.phase == JobPhase.COMPLETED
        # Terminal: no further action even if pods vanish.
        api.pods.clear()
        assert rec.reconcile_once() == {"launched": 0, "removed": 0}

    def test_background_loop_relaunches_on_watch_event(self):
        api, platform, rec = make_reconciler(n_workers=1)
        rec._resync = 0.2
        rec.start()
        try:
            deadline = time.time() + 10
            while "jobx-master-0" not in api.pods and time.time() < deadline:
                time.sleep(0.05)
            api.set_phase("jobx-master-0", "Running")
            while (
                len(api.pods) < 2 and time.time() < deadline
            ):
                time.sleep(0.05)
            api.set_all("Running", node_type=NodeType.WORKER)
            # Kill the worker; the watch-triggered loop must replace it.
            worker = [
                n for n, p in api.pods.items()
                if p.metadata.labels["node-type"] == NodeType.WORKER
            ][0]
            api.set_phase(worker, "Failed")
            ok = False
            while time.time() < deadline:
                live = [
                    p for p in api.pods.values()
                    if p.metadata.labels["node-type"] == NodeType.WORKER
                    and p.status.phase in ("Pending", "Running")
                ]
                if live:
                    ok = True
                    break
                time.sleep(0.05)
            assert ok, "reconciler loop did not relaunch the dead worker"
        finally:
            rec.stop()

    def test_reconciler_on_inmemory_platform(self):
        """Same reconciler code path over the InMemory platform (local
        dev / e2e substrate)."""
        platform = InMemoryPlatform()
        spec = JobSpec(
            job_name="jobl",
            replicas={NodeType.WORKER: ReplicaSpec(count=2)},
            with_master=False,
        )
        rec = JobReconciler(spec, platform)
        assert rec.reconcile_once()["launched"] == 2
        assert rec.phase == JobPhase.RUNNING
        name = platform.list_nodes()[0].name
        platform.fail_node(name)
        assert rec.reconcile_once()["launched"] == 1
        live = [
            n for n in platform.list_nodes()
            if n.status in (NodeStatus.PENDING, NodeStatus.RUNNING)
        ]
        assert len(live) == 2


class TestElasticJobFile:
    """Declarative ElasticJob YAML (VERDICT r2 next #10; reference CRD
    elasticjob_types.go:39 + examples/pytorch/nanogpt/elastic_job.yaml)."""

    YAML = """\
apiVersion: elastic.dlrover-tpu/v1alpha1
kind: ElasticJob
metadata:
  name: testjob
spec:
  nodeUnit: 2
  maxRestarts: 5
  replicaSpecs:
    worker:
      replicas: 3
      minReplicas: 2
      maxReplicas: 6
      maxRelaunch: 4
      resources:
        tpuChips: 8
        tpuType: v5p
        tpuTopology: 2x2x2
        cpu: 16
        memoryMB: 4096
  template:
    script: train.py
    args: ["--lr=3e-4"]
    nprocPerNode: 4
  checkpoint:
    dir: /ckpt
    interval: 7
"""

    def test_parse_and_to_job_spec(self, tmp_path):
        from dlrover_tpu.scheduler.jobfile import (
            load_elastic_job,
            nnodes_arg,
            to_job_spec,
        )

        f = tmp_path / "job.yaml"
        f.write_text(self.YAML)
        jf = load_elastic_job(str(f))
        assert jf.name == "testjob"
        assert jf.worker.replicas == 3
        assert jf.worker.resource.tpu_chips == 8
        assert jf.worker.resource.tpu_topology == "2x2x2"
        assert jf.worker.resource.tpu_type == "v5p"
        assert jf.nproc_per_node == 4
        assert jf.script == "train.py"
        assert jf.script_args == ["--lr=3e-4"]
        assert jf.ckpt_dir == "/ckpt" and jf.ckpt_interval == 7
        assert nnodes_arg(jf) == "2:6"

        spec = to_job_spec(jf)
        assert spec.job_name == "testjob"
        w = spec.replicas["worker"]
        assert w.count == 3 and w.max_relaunch == 4
        assert w.resource.memory_mb == 4096

    def test_validation_errors(self, tmp_path):
        from dlrover_tpu.scheduler.jobfile import parse_elastic_job

        with pytest.raises(ValueError, match="missing 'metadata'"):
            parse_elastic_job({"kind": "ElasticJob"})
        with pytest.raises(ValueError, match="kind"):
            parse_elastic_job({"kind": "Job", "metadata": {"name": "x"},
                               "spec": {}})
        with pytest.raises(ValueError, match="replicaSpecs"):
            parse_elastic_job(
                {"metadata": {"name": "x"}, "spec": {"replicaSpecs": {}}}
            )
        with pytest.raises(ValueError, match="missing 'replicas'"):
            parse_elastic_job(
                {"metadata": {"name": "x"},
                 "spec": {"replicaSpecs": {"worker": {}}}}
            )

    def test_reconciler_consumes_job_file(self, tmp_path):
        """The reconcile loop reaches the desired replica count from a
        YAML JobSpec on the in-memory platform."""
        from dlrover_tpu.scheduler.jobfile import (
            load_elastic_job,
            to_job_spec,
        )
        from dlrover_tpu.scheduler.platform import InMemoryPlatform
        from dlrover_tpu.scheduler.reconciler import JobReconciler

        f = tmp_path / "job.yaml"
        f.write_text(self.YAML)
        spec = to_job_spec(load_elastic_job(str(f)))
        platform = InMemoryPlatform()  # auto_run: nodes go RUNNING
        rec = JobReconciler(spec, platform)
        rec.reconcile_once()
        nodes = platform.list_nodes()
        # master-first bootstrap: only the master exists on pass 1
        assert any(n.node_type == "master" for n in nodes)
        assert not any(n.node_type == "worker" for n in nodes)
        rec.reconcile_once()
        workers = [
            n for n in platform.list_nodes() if n.node_type == "worker"
        ]
        assert len(workers) == 3
        assert all(n.resource.tpu_chips == 8 for n in workers)


# ---------------------------------------------------------------------------
# Role node pools (ISSUE 15): CPU pools for control-plane roles, TPU
# pools for chip-holding workers, pinned on top of --node_role.
# ---------------------------------------------------------------------------


class TestRoleNodePools:
    def _gke(self, pools):
        api = FakeKubeApi()
        platform = GkePlatform(
            namespace="test", image="img",
            api=api, client_mod=_FakeClientMod, watch_mod=_FakeWatchMod,
            node_pools=pools,
        )
        return api, platform

    def test_role_node_pools_mapping(self):
        from dlrover_tpu.scheduler.platform import role_node_pools

        pools = role_node_pools("cp-pool", "tpu-pool")
        assert pools["master"] == "cp-pool"
        assert pools["cell-master"] == "cp-pool"
        assert pools["gateway"] == "cp-pool"
        assert pools["worker"] == "tpu-pool"
        # No TPU pool named: TPU roles stay unpinned (the accelerator
        # selectors already constrain them).
        unpinned = role_node_pools("cp-pool")
        assert "worker" not in unpinned
        # Explicit overrides win.
        extra = role_node_pools("cp", "tpu", extra={"worker": "big"})
        assert extra["worker"] == "big"

    def test_gateway_pod_pinned_to_cpu_pool_without_tpu(self):
        from dlrover_tpu.scheduler.platform import role_node_pools

        api, platform = self._gke(role_node_pools("cp-pool", "tpu-pool"))
        node = Node(
            NodeType.GATEWAY, 0, rank_index=0,
            config_resource=NodeResource(cpu=2, memory_mb=2048),
        )
        platform.create_node(node, "jobp")
        pod = api.pods["jobp-gateway-0"]
        sel = pod.spec.node_selector
        assert sel["cloud.google.com/gke-nodepool"] == "cp-pool"
        assert "cloud.google.com/gke-tpu-accelerator" not in sel
        limits = pod.spec.containers[0].resources.limits
        assert "google.com/tpu" not in limits

    def test_worker_pod_pinned_to_tpu_pool_with_selectors(self):
        from dlrover_tpu.scheduler.platform import role_node_pools

        api, platform = self._gke(role_node_pools("cp-pool", "tpu-pool"))
        node = Node(
            NodeType.WORKER, 1, rank_index=1,
            config_resource=NodeResource(
                tpu_chips=4, tpu_type="v5e", tpu_topology="2x4",
            ),
        )
        platform.create_node(node, "jobp")
        sel = api.pods["jobp-worker-1"].spec.node_selector
        assert sel["cloud.google.com/gke-nodepool"] == "tpu-pool"
        assert sel["cloud.google.com/gke-tpu-accelerator"] == (
            "tpu-v5-lite-podslice"
        )

    def test_tpu_pod_pinned_to_cpu_pool_rejected_at_submit(self):
        """A chip-requesting pod pinned to a declared CPU pool would
        sit Pending forever — the validator refuses the submit."""
        import pytest as _pytest

        api, platform = self._gke({"worker": "cp-pool",
                                   "master": "cp-pool"})
        node = Node(
            NodeType.WORKER, 0, rank_index=0,
            config_resource=NodeResource(tpu_chips=4, tpu_type="v5e"),
        )
        with _pytest.raises(ValueError, match="CPU node pool"):
            platform.create_node(node, "jobp")
        assert api.pods == {}

    def test_bad_pool_name_rejected(self):
        import pytest as _pytest

        api, platform = self._gke({"worker": "Bad_Pool!"})
        node = Node(
            NodeType.WORKER, 0, rank_index=0,
            config_resource=NodeResource(tpu_chips=4, tpu_type="v5e"),
        )
        with _pytest.raises(ValueError, match="RFC1123"):
            platform.create_node(node, "jobp")
