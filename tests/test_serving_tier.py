"""Sharded gateway tier + P2P KV handoff units (ISSUE 9) — tier-1,
sub-second, no jax.

Everything runs in-process: gateways are bare ``GatewayCore`` state
machines behind loopback transports, the registry is a ``LocalKv``,
segment servers are stores behind ``kvseg.handle_fetch`` loopbacks.
The real-socket tier (RegistryServer + RpcKv + gateway subprocesses +
``serving.gateway_kill``) rides the ``serving+chaos+slow`` e2e lane in
``test_chaos_e2e.py`` and ``bench.py --load_bench``.
"""

import threading
import time

import pytest

from dlrover_tpu import chaos
from dlrover_tpu.agent.metrics import Histogram
from dlrover_tpu.common import messages as wire
from dlrover_tpu.serving import (
    GatewayConfig,
    GatewayCore,
    HashRing,
    KvPullError,
    KvSegmentStore,
    LocalKv,
    LoopbackTransport,
    ReplicaRunner,
    ServeRegistry,
    TierClient,
    TierReplicaLink,
    TierStats,
    merge_snapshots,
    pull_kv_segment,
)
from dlrover_tpu.serving.kvseg import handle_fetch, segment_fingerprint
from dlrover_tpu.serving.tier import ring_hash

from test_serving import (  # noqa: I100 - shared fleet fixtures
    FakeClock,
    FakeDecodeServer,
    FakePrefillServer,
    core_handle,
    expected_tokens,
    wait_for,
)

pytestmark = pytest.mark.serving


def full_handle(core):
    """client + replica dispatch over a bare core — what
    ``Gateway.handle`` does, loopback."""
    base = core_handle(core)

    def handle(msg):
        if isinstance(msg, wire.ServeSubmit):
            return core.submit(msg.req_id, msg.prompt,
                               msg.max_new_tokens, msg.deadline_s,
                               msg.prefix_len, msg.prefix_fp)
        if isinstance(msg, wire.ServeStatusRequest):
            return core.status(msg.req_id)
        if isinstance(msg, wire.ServeFleetStatsRequest):
            return wire.ServeFleetStats(stats=core.stats_snapshot())
        return base(msg)

    return handle


class _Tier:
    """Two (or N) bare-core gateways on a LocalKv registry, loopback
    transports keyed by fake addresses."""

    def __init__(self, n=2, job="j", lease_s=5.0, **core_kw):
        self.kv = LocalKv()
        self.registry = ServeRegistry(self.kv, job=job,
                                      lease_s=lease_s)
        self.cores = {}
        self.addr_map = {}
        for i in range(n):
            gid = f"g{i}"
            core = GatewayCore(GatewayConfig(**core_kw))
            self.cores[gid] = core
            self.addr_map[f"addr-{gid}"] = LoopbackTransport(
                full_handle(core)
            )
            self.registry.announce_gateway(gid, f"addr-{gid}")
        self.ring = HashRing(list(self.cores))

    def connect(self, addr):
        # A proxy resolving through addr_map at CALL time: kill()
        # swaps the entry, so even transports cached before the death
        # start erroring — like a real closed socket.
        class _Proxy:
            def call(_self, msg, **kw):
                return self.addr_map[addr].call(msg, **kw)

        return _Proxy()

    def kill(self, gid):
        """The gateway process dies: registry entry gone, transport
        errors from now on."""
        self.registry.remove_gateway(gid)

        class _Dead:
            def call(self, msg, **kw):
                raise RuntimeError(f"gateway {gid} is dead")

        self.addr_map[f"addr-{gid}"] = _Dead()

    def client(self, **kw):
        kw.setdefault("poll_interval", 0.002)
        kw.setdefault("refresh_s", 0.0)
        return TierClient(self.registry, connect=self.connect, **kw)

    def link(self, rid, **kw):
        kw.setdefault("refresh_s", 0.0)
        return TierReplicaLink(self.registry, rid,
                               connect=self.connect, **kw)

    def start_replica(self, rid, server=None, journal=None, **runner_kw):
        runner_kw.setdefault("poll_interval", 0.001)
        runner_kw.setdefault("kv_p2p", False)
        runner = ReplicaRunner(
            server or FakeDecodeServer(slots=4), self.link(rid), rid,
            journal_path=journal, **runner_kw,
        )
        th = threading.Thread(target=runner.run, daemon=True)
        th.start()
        return runner, th

    def drain_all(self):
        for core in self.cores.values():
            for rid in list(core.stats_snapshot()["replicas"]):
                core.drain(rid)


# ---------------------------------------------------------------------------
# Consistent hashing
# ---------------------------------------------------------------------------


class TestHashRing:
    def test_owner_is_deterministic_and_total(self):
        ring = HashRing(["g0", "g1", "g2"])
        owners = {f"r{i}": ring.owner(f"r{i}") for i in range(200)}
        ring2 = HashRing(["g2", "g0", "g1"])  # order-insensitive
        assert all(ring2.owner(r) == o for r, o in owners.items())
        assert set(owners.values()) == {"g0", "g1", "g2"}

    def test_death_moves_only_the_dead_range(self):
        """Consistent hashing's contract IS the failover semantics:
        removing g1 re-homes exactly g1's requests (the survivors
        adopt its arcs); every other assignment is untouched."""
        before = HashRing(["g0", "g1", "g2"])
        after = HashRing(["g0", "g2"])
        moved = stayed = 0
        for i in range(500):
            rid = f"q{i}"
            b, a = before.owner(rid), after.owner(rid)
            if b == "g1":
                assert a in ("g0", "g2")
                moved += 1
            else:
                assert a == b
                stayed += 1
        assert moved > 0 and stayed > 0

    def test_balance_is_rough_but_real(self):
        ring = HashRing(["g0", "g1"], vnodes=64)
        counts = {"g0": 0, "g1": 0}
        for i in range(2000):
            counts[ring.owner(f"x{i}")] += 1
        assert 0.25 < counts["g0"] / 2000 < 0.75

    def test_empty_ring_owns_nothing(self):
        assert HashRing([]).owner("x") is None

    def test_ring_hash_is_process_stable(self):
        # Pinned value: sha1 is the cross-process contract (a
        # PYTHONHASHSEED-dependent hash would split ownership between
        # a client and a replica of the same tier).
        assert ring_hash("req-0") == int.from_bytes(
            __import__("hashlib").sha1(b"req-0").digest()[:4], "big"
        )


# ---------------------------------------------------------------------------
# Shared registry (satellite: register/re-register/lease/GC/namespacing)
# ---------------------------------------------------------------------------


class TestServeRegistry:
    def make(self, lease_s=10.0):
        clock = FakeClock()
        kv = LocalKv()
        return ServeRegistry(kv, job="jobA", lease_s=lease_s,
                             clock=clock), kv, clock

    def test_announce_visible_immediately_from_any_reader(self):
        reg, kv, clock = self.make()
        reg.announce_gateway("g0", "h:1")
        reg.announce_replica("r0", slots=4, role="prefill",
                             kv_addr="h:9")
        # A SECOND registry handle over the same kv (another gateway
        # process) sees both within one read — "within one poll".
        reader = ServeRegistry(kv, job="jobA", lease_s=10.0,
                               clock=clock)
        assert reader.gateways() == {"g0": "h:1"}
        rep = reader.replicas()["r0"]
        assert rep["slots"] == 4 and rep["role"] == "prefill"
        assert rep["kv_addr"] == "h:9"

    def test_reregister_updates_in_place(self):
        reg, kv, clock = self.make()
        reg.announce_replica("r0", slots=2)
        reg.announce_replica("r0", slots=8, role="decode")
        reps = reg.replicas()
        assert len(reps) == 1
        assert reps["r0"]["slots"] == 8
        assert reps["r0"]["role"] == "decode"

    def test_lease_expiry_hides_then_gc_deletes(self):
        reg, kv, clock = self.make(lease_s=5.0)
        reg.announce_gateway("g0", "h:1")
        reg.announce_replica("r0", slots=2)
        clock.advance(5.1)
        assert reg.gateways() == {}
        assert reg.replicas() == {}
        # Physically still there until a sweep...
        assert kv.scan("serve/jobA/") != {}
        deleted = reg.gc_stale()
        assert sorted(deleted) == [
            "serve/jobA/gw/g0", "serve/jobA/rep/r0",
        ]
        assert kv.scan("serve/jobA/") == {}

    def test_heartbeat_keeps_the_lease_alive(self):
        reg, kv, clock = self.make(lease_s=5.0)
        reg.announce_gateway("g0", "h:1")
        clock.advance(4.0)
        reg.announce_gateway("g0", "h:1")  # heartbeat
        clock.advance(4.0)
        assert reg.gateways() == {"g0": "h:1"}
        assert reg.gc_stale() == []

    def test_keys_namespaced_per_job(self):
        clock = FakeClock()
        kv = LocalKv()
        a = ServeRegistry(kv, job="jobA", clock=clock)
        b = ServeRegistry(kv, job="jobB", clock=clock)
        a.announce_gateway("g0", "h:1")
        b.announce_gateway("g9", "h:9")
        assert a.gateways() == {"g0": "h:1"}
        assert b.gateways() == {"g9": "h:9"}
        assert a.gw_key("g0").startswith("serve/jobA/")

    def test_lease_is_reader_side_and_skew_immune(self):
        """Liveness never compares writer and reader wall clocks: a
        writer 100s 'in the future' (or past) stays live as long as
        its heartbeat value keeps changing, and a skewed reader's
        gc_stale can never delete fresh peers."""
        clock = FakeClock()
        kv = LocalKv()
        writer_clock = FakeClock()
        writer_clock.t = clock.t + 100.0  # gross skew
        writer = ServeRegistry(kv, job="jobA", lease_s=5.0,
                               clock=writer_clock)
        reader = ServeRegistry(kv, job="jobA", lease_s=5.0,
                               clock=clock)
        writer.announce_gateway("g0", "h:1")
        assert reader.gateways() == {"g0": "h:1"}
        # Heartbeats keep it alive on the reader's clock...
        for _ in range(3):
            clock.advance(4.0)
            writer_clock.advance(4.0)
            writer.announce_gateway("g0", "h:1")
            assert reader.gateways() == {"g0": "h:1"}
            assert reader.gc_stale() == []
        # ... and once the heartbeats STOP, the reader expires it by
        # its own observation window.
        clock.advance(5.1)
        assert reader.gateways() == {}
        assert reader.gc_stale() == ["serve/jobA/gw/g0"]

    def test_undecodable_entry_is_dropped_not_fatal(self):
        reg, kv, clock = self.make()
        kv.set("serve/jobA/gw/bad", b"\xff{not json")
        reg.announce_gateway("g0", "h:1")
        assert reg.gateways() == {"g0": "h:1"}
        assert "serve/jobA/gw/bad" in reg.gc_stale()


def test_registry_over_real_wire_roundtrip():
    """RegistryServer + RpcKv: the subprocess path (gateway/replica/
    driver of an e2e) speaks the same KVStore* messages as the
    master's KV — one real-socket check that scan/set/delete agree."""
    from dlrover_tpu.serving import RegistryServer, RpcKv

    server = RegistryServer()
    try:
        kv = RpcKv(server.addr)
        reg = ServeRegistry(kv, job="wire", lease_s=30.0)
        reg.announce_gateway("g0", "h:1")
        reg.announce_replica("r0", slots=2)
        assert reg.gateways() == {"g0": "h:1"}
        assert list(reg.replicas()) == ["r0"]
        reg.remove_gateway("g0")
        assert reg.gateways() == {}
        kv.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Histogram.merge (satellite: window-aware, bucket-wise)
# ---------------------------------------------------------------------------


class TestHistogramMerge:
    def test_merged_percentile_equals_combined_observations(self):
        h1, h2 = Histogram(), Histogram()
        for v in (5, 5, 50):
            h1.observe(v)
        for v in (500, 5000):
            h2.observe(v)
        agg = Histogram.merged([h1, h2.state()])
        assert agg.count == 5
        assert agg.percentile(0.50) == 50.0
        assert agg.percentile(0.99) == 5000.0
        ref = Histogram()
        for v in (5, 5, 50, 500, 5000):
            ref.observe(v)
        for p in (0.5, 0.9, 0.95, 0.99):
            assert agg.percentile(p) == ref.percentile(p)

    def test_merge_is_window_aware(self):
        """Aged-out observations never reach the merged view: the
        state() of a windowed histogram covers only its live span, so
        one gateway's ancient cold-start latencies can't ratchet the
        tier-wide p95."""
        clock = FakeClock()
        h = Histogram(window_s=60.0, clock=clock)
        h.observe(30000)  # cold start
        clock.advance(130.0)  # two windows later: aged out
        h.observe(10)
        st = h.state()
        assert st["total"] == 1
        agg = Histogram.merged([st])
        assert agg.percentile(0.99) == 10.0

    def test_bounds_mismatch_raises(self):
        h1 = Histogram(buckets=(1, 2, 5))
        h2 = Histogram(buckets=(1, 2, 10))
        with pytest.raises(ValueError, match="bounds mismatch"):
            h1.merge(h2)

    def test_merge_sums_bucket_wise_and_counts(self):
        h1 = Histogram(buckets=(10, 100))
        h2 = Histogram(buckets=(10, 100))
        h1.observe(5)
        h2.observe(5)
        h2.observe(50)
        h1.merge(h2)
        st = h1.state()
        assert st["counts"] == [2, 1, 0]
        assert st["total"] == 3

    def test_merged_empty_input_is_empty_default(self):
        agg = Histogram.merged([])
        assert agg.count == 0
        assert agg.percentile(0.95) == 0.0


# ---------------------------------------------------------------------------
# merge_snapshots: the tier-wide autoscale view
# ---------------------------------------------------------------------------


class TestMergeSnapshots:
    def make_pair(self):
        """Two cores sharing one replica, split queues/assignments."""
        a, _ = GatewayCore(GatewayConfig()), None
        b = GatewayCore(GatewayConfig())
        for core in (a, b):
            core.register("r0", 4)
        a.register("r1", 4)
        for i in range(3):
            a.submit(f"a{i}", [1], 4)
        b.submit("b0", [1], 4)
        # one grant at each gateway
        a.poll("r0", 1, [])
        b.poll("r0", 1, [])
        return a, b

    def test_sums_and_union(self):
        a, b = self.make_pair()
        snap = merge_snapshots([a.stats_snapshot(),
                                b.stats_snapshot()])
        # 4 submitted, 2 granted -> 2 queued; all 4 in flight.
        assert snap["queue_depth"] == 2
        assert snap["in_flight"] == 4
        assert snap["counters"]["accepted"] == 4
        # r0 registered at BOTH gateways: union, slots not doubled.
        assert snap["replicas_alive"] == 2
        assert snap["replicas"]["r0"]["slots"] == 4
        assert snap["replicas"]["r0"]["assigned"] == 2
        pool = snap["pools"]["unified"]
        assert pool["alive"] == 2 and pool["slots"] == 8
        assert snap["gateways"] == 2

    def test_draining_anywhere_is_draining_everywhere(self):
        a, b = self.make_pair()
        a.drain("r0")
        snap = merge_snapshots([a.stats_snapshot(),
                                b.stats_snapshot()])
        assert snap["replicas"]["r0"]["draining"] is True
        assert snap["replicas_alive"] == 1

    def test_histogram_states_merge_into_p95(self):
        a, b = self.make_pair()
        h_a, h_b = Histogram(), Histogram()
        for v in (10, 10, 10, 10):
            h_a.observe(v)
        h_b.observe(5000)
        sa = a.stats_snapshot()
        sb = b.stats_snapshot()
        sa["ttft_hist"] = h_a.state()
        sb["ttft_hist"] = h_b.state()
        snap = merge_snapshots([sa, sb])
        # 4 of 5 at 10ms -> p95 reaches into the 5s observation; a
        # mean/max of per-gateway p95s could not represent this.
        assert snap["ttft_p95_ms"] == 5000.0

    def test_tier_stats_skips_dead_fetchers(self):
        a, b = self.make_pair()

        def dead():
            raise RuntimeError("gateway down")

        stats = TierStats([a.stats_snapshot, dead, b.stats_snapshot])
        snap = stats.snapshot()
        assert snap["gateways"] == 2
        assert snap["counters"]["accepted"] == 4

    def test_empty_input(self):
        snap = merge_snapshots([])
        assert snap["replicas_alive"] == 0
        assert snap["gateways"] == 0


# ---------------------------------------------------------------------------
# Replica fan-out link
# ---------------------------------------------------------------------------


class TestTierReplicaLink:
    def test_free_slots_never_double_granted(self):
        tier = _Tier(2)
        for i in range(8):
            # every id lands somewhere; both gateways hold work
            gid = tier.ring.owner(f"q{i}")
            tier.cores[gid].submit(f"q{i}", [1], 4)
        link = tier.link("r0")
        link.call(wire.ServeReplicaRegister(replica_id="r0", slots=3))
        grants = link.call(wire.ServeReplicaPoll(
            replica_id="r0", free_slots=3, active=[],
        ))
        assert isinstance(grants, wire.ServeGrants)
        # Fan-out offered 3 slots TOTAL across both gateways.
        assert len(grants.requests) == 3

    def test_drain_requires_every_gateway(self):
        tier = _Tier(2)
        link = tier.link("r0")
        link.call(wire.ServeReplicaRegister(replica_id="r0", slots=2))
        tier.cores["g0"].drain("r0")
        reply = link.call(wire.ServeReplicaPoll(
            replica_id="r0", free_slots=2, active=[],
        ))
        assert reply.drain is False  # g1 has not released it
        tier.cores["g1"].drain("r0")
        reply = link.call(wire.ServeReplicaPoll(
            replica_id="r0", free_slots=2, active=[],
        ))
        assert reply.drain is True

    def test_known_false_reregisters_at_that_gateway_only(self):
        tier = _Tier(2)
        link = tier.link("r0")
        link.call(wire.ServeReplicaRegister(replica_id="r0", slots=2))
        # Give g1 assigned work so a spurious re-register would
        # requeue it (redispatched counter).
        g1_rids = [f"w{i}" for i in range(40)
                   if tier.ring.owner(f"w{i}") == "g1"][:1]
        tier.cores["g1"].submit(g1_rids[0], [1], 4)
        link.call(wire.ServeReplicaPoll(replica_id="r0",
                                        free_slots=1, active=[]))
        # g0 "restarts": loses the replica.
        tier.cores["g0"]._replicas.clear()
        reply = link.call(wire.ServeReplicaPoll(
            replica_id="r0", free_slots=0, active=g1_rids,
        ))
        assert isinstance(reply, wire.ServeGrants)
        assert wait_for(
            lambda: "r0" in tier.cores["g0"].stats_snapshot()[
                "replicas"
            ], timeout=2.0,
        )
        # The healthy gateway never saw a re-register requeue.
        assert tier.cores["g1"].counters["redispatched"] == 0

    def test_reports_route_to_granting_gateway(self):
        tier = _Tier(2)
        rid = next(f"q{i}" for i in range(40)
                   if tier.ring.owner(f"q{i}") == "g1")
        tier.cores["g1"].submit(rid, [1, 2], 2)
        link = tier.link("r0")
        link.call(wire.ServeReplicaRegister(replica_id="r0", slots=2))
        grants = link.call(wire.ServeReplicaPoll(
            replica_id="r0", free_slots=2, active=[],
        ))
        assert [g.req_id for g in grants.requests] == [rid]
        link.call(wire.ServeDone(replica_id="r0", req_id=rid,
                                 tokens=[7, 8], ok=True))
        assert tier.cores["g1"].counters["completed"] == 1
        assert tier.cores["g0"].counters["completed"] == 0

    def test_report_falls_back_to_ring_owner_when_granter_died(self):
        tier = _Tier(2)
        rid = next(f"q{i}" for i in range(40)
                   if tier.ring.owner(f"q{i}") == "g0")
        tier.cores["g0"].submit(rid, [1, 2], 2)
        link = tier.link("r0")
        link.call(wire.ServeReplicaRegister(replica_id="r0", slots=2))
        link.call(wire.ServeReplicaPoll(replica_id="r0",
                                        free_slots=2, active=[]))
        # g0 dies; the failover owner (g1 adopted the whole ring)
        # received the client's resubmit.
        tier.kill("g0")
        tier.cores["g1"].submit(rid, [1, 2], 2)
        link.call(wire.ServeDone(replica_id="r0", req_id=rid,
                                 tokens=[7, 8], ok=True))
        assert tier.cores["g1"].counters["completed"] == 1

    def test_granted_routes_pruned_on_every_terminal_report(self):
        """ServeDone, ServeKvReject AND ServeKvReady all end this
        replica's ownership of a rid — and cancels prune too; routes
        must not leak one entry per prefilled/cancelled request on a
        long-lived replica."""
        tier = _Tier(1)
        core = tier.cores["g0"]
        core.register("p0", 4, role="prefill")
        core.register("d0", 4, role="decode")
        core.submit("k0", [1, 2], 2)
        link = tier.link("r0")
        link.call(wire.ServeReplicaRegister(replica_id="r0", slots=4,
                                            role="prefill"))
        grants = link.call(wire.ServeReplicaPoll(
            replica_id="r0", free_slots=4, active=[],
        ))
        assert [g.req_id for g in grants.requests] == ["k0"]
        assert "k0" in link._granted_by
        link.call(wire.ServeKvReady(replica_id="r0", req_id="k0",
                                    payload=b"seg"))
        assert "k0" not in link._granted_by
        # Cancel path: a deadline-expired grant produces no report.
        core.submit("k1", [1], 2, deadline_s=5.0)
        grants = link.call(wire.ServeReplicaPoll(
            replica_id="r0", free_slots=4, active=[],
        ))
        # (k0 went kv_ready -> decode stage; this replica is prefill
        # so only k1 is granted to it.)
        assert "k1" in link._granted_by
        tier.cores["g0"]._clock = None  # unused; cancel via poll
        # Simulate the gateway cancelling k1 on a later poll reply.
        reply = wire.ServeGrants(cancel=["k1"], known=True)

        class _CancelOnce:
            def __init__(self, inner):
                self.inner = inner
                self.sent = False

            def call(self, msg, **kw):
                if isinstance(msg, wire.ServeReplicaPoll) and \
                        not self.sent:
                    self.sent = True
                    return reply
                return self.inner.call(msg, **kw)

        link._set._transports["g0"] = _CancelOnce(
            link._set._transports["g0"]
        )
        link.call(wire.ServeReplicaPoll(replica_id="r0",
                                        free_slots=0, active=[]))
        assert "k1" not in link._granted_by

    def test_no_live_gateway_poll_is_calm(self):
        tier = _Tier(1)
        link = tier.link("r0")
        link.call(wire.ServeReplicaRegister(replica_id="r0", slots=2))
        tier.kill("g0")
        reply = link.call(wire.ServeReplicaPoll(
            replica_id="r0", free_slots=2, active=[],
        ))
        assert isinstance(reply, wire.ServeGrants)
        assert reply.requests == [] and reply.known


# ---------------------------------------------------------------------------
# Tier client + failover (the tentpole's exactly-once law)
# ---------------------------------------------------------------------------


class TestTierClientFailover:
    def test_requests_route_to_owner_and_both_gateways_serve(self):
        tier = _Tier(2)
        runner, th = tier.start_replica("r0")
        cli = tier.client()
        n = 12
        for i in range(n):
            assert cli.submit(f"q{i}", [i + 1], 4).status == "accepted"
        for i in range(n):
            reply = cli.result(f"q{i}", timeout=15)
            assert reply.state == "done"
            assert reply.tokens == expected_tokens([i + 1], 4)
        done = {g: c.counters["completed"]
                for g, c in tier.cores.items()}
        assert sum(done.values()) == n
        assert all(v > 0 for v in done.values()), done
        tier.drain_all()
        th.join(timeout=5)

    def test_gateway_death_resubmit_answers_from_journal(
            self, tmp_path):
        """The flagship failover law, in-process: requests admitted at
        g0 complete at the replica (journaled), g0 dies before the
        client sees the results, the ring re-forms onto g1, the client
        resubmits — and the REPLICA'S JOURNAL answers (replayed, not
        re-decoded), so every request completes exactly once with
        byte-identical tokens."""
        tier = _Tier(2, lease_s=2.0)
        server = FakeDecodeServer(slots=4)
        runner, th = tier.start_replica(
            "r0", server=server, journal=str(tmp_path / "r0.jsonl"),
        )
        cli = tier.client()
        g0_rids = [f"f{i}" for i in range(60)
                   if tier.ring.owner(f"f{i}") == "g0"][:4]
        for rid in g0_rids:
            assert cli.submit(rid, [5, 6], 4).status == "accepted"
        # Wait until the replica decoded + journaled them all.
        assert wait_for(
            lambda: tier.cores["g0"].counters["completed"]
            == len(g0_rids)
        )
        decoded_before = runner.served
        tier.kill("g0")
        for rid in g0_rids:
            reply = cli.result(rid, timeout=15)
            assert reply.state == "done", (rid, reply)
            assert reply.tokens == expected_tokens([5, 6], 4)
        assert cli.resubmitted >= len(g0_rids)
        # Journal replay answered the failover copies: the decode ran
        # ONCE per request.
        assert wait_for(lambda: runner.replayed >= len(g0_rids))
        assert runner.served == decoded_before
        # And the adopting gateway recorded them exactly once each.
        assert tier.cores["g1"].counters["completed"] == len(g0_rids)
        tier.drain_all()
        th.join(timeout=5)

    def test_resubmit_of_terminal_request_answers_from_cache(self):
        tier = _Tier(1)
        runner, th = tier.start_replica("r0")
        cli = tier.client()
        cli.submit("t0", [2], 3)
        reply = cli.result("t0", timeout=15)
        assert reply.state == "done"
        ack = cli.submit("t0", [2], 3)
        assert ack.status == "done"
        assert ack.tokens == expected_tokens([2], 3)
        assert tier.cores["g0"].counters["dedupe_hits"] == 1
        tier.drain_all()
        th.join(timeout=5)


# ---------------------------------------------------------------------------
# P2P KV handoff: store, pulls, ticket path, fallback ladder
# ---------------------------------------------------------------------------


class _FakeKvServer:
    """store + addr, no sockets — what tests inject as the runner's
    kv_server; pulls go through ``handle_fetch`` loopbacks."""

    def __init__(self, addr):
        self.addr = addr
        self.store = KvSegmentStore()
        self.stopped = False

    def stop(self):
        self.stopped = True


class TestKvSegmentStore:
    def test_put_get_roundtrip_with_ticket(self):
        store = KvSegmentStore()
        fp, crc, nb = store.put("r1", b"abcdef")
        assert nb == 6 and fp == segment_fingerprint(b"abcdef")
        payload, crc2 = store.get("r1")
        assert payload == b"abcdef" and crc2 == crc

    def test_fingerprint_pins_the_publication(self):
        store = KvSegmentStore()
        fp_old, _, _ = store.put("r1", b"old-segment")
        store.put("r1", b"new-segment")  # re-prefill under same rid
        assert store.get("r1", fp_old) is None
        assert store.get("r1")[0] == b"new-segment"

    def test_ttl_expiry(self):
        clock = FakeClock()
        store = KvSegmentStore(ttl_s=10.0, clock=clock)
        store.put("r1", b"x")
        clock.advance(11.0)
        assert store.get("r1") is None

    def test_bounded_by_count_and_bytes_oldest_first(self):
        store = KvSegmentStore(max_segments=2, max_bytes=1 << 20)
        store.put("a", b"1")
        store.put("b", b"2")
        store.put("c", b"3")
        assert store.get("a") is None
        assert store.get("b") is not None
        store2 = KvSegmentStore(max_segments=100, max_bytes=10)
        store2.put("a", b"x" * 8)
        store2.put("b", b"y" * 8)
        assert store2.get("a") is None
        assert store2.nbytes == 8

    def test_put_refuses_what_it_cannot_retain(self):
        """A ticket for bytes the server no longer holds guarantees a
        failed pull that burns a bounded attempt — put() must return
        None (caller relays) instead of a dead ticket."""
        store = KvSegmentStore(max_bytes=10)
        assert store.put("big", b"x" * 11) is None
        assert len(store) == 0
        # An insert whose sweep evicts the entry itself also refuses.
        tiny = KvSegmentStore(max_segments=0)
        assert tiny.put("r1", b"ab") is None

    def test_pull_verifies_ticket(self):
        store = KvSegmentStore()
        fp, crc, nb = store.put("r1", b"payload-bytes")
        loop = LoopbackTransport(lambda m: handle_fetch(store, m))
        got = pull_kv_segment("x", "r1", fp, crc, nb, transport=loop)
        assert got == b"payload-bytes"
        with pytest.raises(KvPullError, match="not served"):
            pull_kv_segment("x", "missing", fp, crc, nb,
                            transport=loop)
        with pytest.raises(KvPullError, match="CRC mismatch"):
            pull_kv_segment("x", "r1", fp, crc ^ 1, nb,
                            transport=loop)
        with pytest.raises(KvPullError, match="ticket promised"):
            pull_kv_segment("x", "r1", fp, crc, nb + 1,
                            transport=loop)
        # Stale publication: the stored fp differs from the ticket's.
        with pytest.raises(KvPullError, match="not served"):
            pull_kv_segment("x", "r1", "0" * 16, crc, nb,
                            transport=loop)


class TestGatewayTicketPath:
    def make_core(self):
        clock = FakeClock()
        core = GatewayCore(GatewayConfig(max_attempts=3), clock=clock)
        return core, clock

    def grant_prefill(self, core, rid="d0"):
        core.register("p0", 2, role="prefill")
        core.register("d0r", 2, role="decode")
        core.submit(rid, [1, 2, 3], 4)
        grants = core.poll("p0", 2, [])
        assert [g.req_id for g in grants.requests] == [rid]
        return grants.requests[0]

    def test_ticket_holds_no_bytes_and_rides_the_decode_grant(self):
        core, _ = self.make_core()
        grant = self.grant_prefill(core)
        assert grant.stage == "prefill" and grant.kv_relay is False
        out = core.kv_ready("p0", "d0", b"", fp32_bytes=400,
                            addr="peer:1", seg_fp="ab" * 8,
                            crc32=77, nbytes=100)
        assert out == "recorded"
        c = core.counters
        assert c["kv_handoffs"] == 1
        assert c["kv_bytes"] == 0  # nothing transited the gateway
        # p2p bytes are booked when the ticket is GRANTED for a pull,
        # not at kv_ready (bytes that never moved must not count).
        assert c["kv_p2p_bytes"] == 0
        dec = core.poll("d0r", 2, []).requests[0]
        assert dec.stage == "decode" and dec.kv == b""
        assert dec.kv_addr == "peer:1" and dec.kv_crc32 == 77
        assert dec.kv_nbytes == 100 and dec.kv_fp == "ab" * 8
        assert core.counters["kv_p2p_bytes"] == 100

    def test_relay_mode_ordered_when_p2p_disabled(self):
        clock = FakeClock()
        core = GatewayCore(GatewayConfig(kv_p2p=False), clock=clock)
        grant = self.grant_prefill(core)
        assert grant.kv_relay is True

    def test_decode_death_reships_the_same_ticket(self):
        core, clock = self.make_core()
        self.grant_prefill(core)
        core.kv_ready("p0", "d0", b"", addr="peer:1", seg_fp="f" * 16,
                      crc32=9, nbytes=10)
        core.poll("d0r", 2, [])
        core.deregister("d0r")  # decode replica died
        core.register("d2", 2, role="decode")
        dec = core.poll("d2", 2, []).requests[0]
        assert dec.stage == "decode" and dec.kv_addr == "peer:1"
        assert core.counters["redispatched"] == 1

    def test_failed_pull_falls_back_to_relay_prefill(self):
        core, _ = self.make_core()
        self.grant_prefill(core)
        core.kv_ready("p0", "d0", b"", addr="peer:1", seg_fp="f" * 16,
                      crc32=9, nbytes=10)
        core.poll("d0r", 2, [])
        out = core.kv_reject("d0r", "d0", reason="pull: peer gone")
        assert out == "recorded"
        c = core.counters
        assert c["kv_rejects"] == 1 and c["kv_relay_fallbacks"] == 1
        # Next prefill grant orders the relay path for THIS request.
        regrant = core.poll("p0", 2, []).requests[0]
        assert regrant.stage == "prefill"
        assert regrant.kv_relay is True
        # ... and a relayed kv_ready then ships bytes via the gateway.
        core.kv_ready("p0", "d0", b"relayed-segment", fp32_bytes=60)
        assert core.counters["kv_bytes"] == len(b"relayed-segment")
        dec = core.poll("d0r", 2, []).requests[0]
        assert dec.kv == b"relayed-segment" and dec.kv_addr == ""

    def test_persistently_failing_pull_is_bounded_by_max_attempts(
            self):
        core, _ = self.make_core()
        self.grant_prefill(core)
        for _n in range(3):
            core.kv_ready("p0", "d0", b"", addr="p:1",
                          seg_fp="f" * 16, crc32=9, nbytes=10)
            grants = core.poll("d0r", 2, [])
            if not grants.requests:
                break
            core.kv_reject("d0r", "d0", reason="pull: gone")
            regrants = core.poll("p0", 2, [])
            if not regrants.requests:
                break
        assert core.status("d0").state == "failed"


class TestReplicaP2P:
    def make_fleet(self, core, pull_fails=False):
        """prefill + decode runners on one core; segments move through
        an in-process fake segment server (no sockets)."""
        transport = LoopbackTransport(core_handle(core))
        servers = {}

        def connect(addr):
            if pull_fails:
                class _Gone:
                    def call(self, msg, **kw):
                        raise RuntimeError("peer unreachable")

                return _Gone()
            return LoopbackTransport(
                lambda m: handle_fetch(servers[addr].store, m)
            )

        kv_p = _FakeKvServer("peer-p0")
        servers["peer-p0"] = kv_p
        prefill = ReplicaRunner(
            FakePrefillServer(2), transport, "p0",
            poll_interval=0.001, role="prefill", kv_p2p=True,
            kv_server=kv_p,
        )
        decode = ReplicaRunner(
            FakeDecodeServer(2), transport, "d0",
            poll_interval=0.001, role="decode", kv_p2p=True,
            kv_connect=connect,
        )
        threads = [
            threading.Thread(target=r.run, daemon=True)
            for r in (prefill, decode)
        ]
        for th in threads:
            th.start()
        return prefill, decode, threads

    def drain(self, core, threads):
        for rid in list(core.stats_snapshot()["replicas"]):
            core.drain(rid)
        for th in threads:
            th.join(timeout=5)

    def test_p2p_disagg_exact_and_byteless_at_gateway(self):
        core = GatewayCore(GatewayConfig())
        prefill, decode, threads = self.make_fleet(core)
        n = 6
        for i in range(n):
            core.submit(f"q{i}", [i + 1, i + 2], 4)
        assert wait_for(lambda: core.counters["completed"] == n)
        for i in range(n):
            reply = core.status(f"q{i}")
            # unified-law exactness through the P2P handoff
            assert reply.tokens == expected_tokens([i + 1, i + 2], 4)
        c = core.counters
        assert c["kv_handoffs"] == n
        assert c["kv_bytes"] == 0
        assert c["kv_p2p_bytes"] > 0
        assert prefill.kv_published == n
        assert decode.kv_pulled == n
        self.drain(core, threads)

    def test_pull_failure_falls_back_to_relay_and_completes(self):
        core = GatewayCore(GatewayConfig())
        prefill, decode, threads = self.make_fleet(core,
                                                   pull_fails=True)
        core.submit("q0", [3, 4], 4)
        assert wait_for(lambda: core.counters["completed"] == 1)
        assert core.status("q0").tokens == expected_tokens([3, 4], 4)
        c = core.counters
        assert c["kv_rejects"] >= 1
        assert c["kv_relay_fallbacks"] >= 1
        assert c["kv_bytes"] > 0  # the fallback relayed the bytes
        assert decode.kv_pull_failed >= 1
        self.drain(core, threads)

    def test_chaos_kv_drop_pull_mode_recovers(self):
        chaos.configure("serving.kv_drop:method=pull,times=1")
        try:
            core = GatewayCore(GatewayConfig())
            prefill, decode, threads = self.make_fleet(core)
            core.submit("q0", [2, 5], 4)
            assert wait_for(lambda: core.counters["completed"] == 1)
            assert core.status("q0").tokens == \
                expected_tokens([2, 5], 4)
            assert core.counters["kv_rejects"] == 1
            assert core.counters["kv_relay_fallbacks"] == 1
            self.drain(core, threads)
        finally:
            chaos.reset()

    def test_runner_stops_its_kv_server_on_exit(self):
        core = GatewayCore(GatewayConfig())
        prefill, decode, threads = self.make_fleet(core)
        kv_server = prefill._kv_server
        self.drain(core, threads)
        assert kv_server.stopped is True


# ---------------------------------------------------------------------------
# chaos site + messages fast path
# ---------------------------------------------------------------------------


class TestGatewayKillSite:
    def test_site_registered_with_exit_code(self):
        from dlrover_tpu.chaos.plan import SITES

        site = SITES["serving.gateway_kill"]
        assert site["kind"] == "crash"
        assert site["exit"] == 81 and site["times"] == 1

    def test_method_selects_the_victim_and_step_ge_gates(self):
        plan = chaos.FaultPlan.parse(
            "serving.gateway_kill:method=g1,step_ge=2"
        )
        assert plan.fire("serving.gateway_kill", method="g0",
                         step=5) is None
        assert plan.fire("serving.gateway_kill", method="g1",
                         step=1) is None
        spec = plan.fire("serving.gateway_kill", method="g1", step=3)
        assert spec is not None and spec.exit_code == 81
        # times=1: spent
        assert plan.fire("serving.gateway_kill", method="g1",
                         step=9) is None

    def test_step_ge_requires_a_step_report(self):
        plan = chaos.FaultPlan.parse("worker.kill:step_ge=4")
        assert plan.fire("worker.kill") is None
        assert plan.fire("worker.kill", step=4) is not None


class TestMessagesFastPath:
    CASES = [
        wire.ServeSubmit(req_id="x", prompt=list(range(300)),
                         max_new_tokens=4, kv_addr="h:1",
                         kv_crc32=9, kv_nbytes=3),
        wire.ServeGrants(requests=[
            wire.ServeSubmit(req_id=f"g{i}", prompt=[1, 2])
            for i in range(5)
        ], cancel=["a", "b"], drain=True),
        wire.ServeReplicaPoll(replica_id="r", free_slots=3,
                              active=["a"], stats={"x": 1.5},
                              warm_prefixes=["ff"]),
        wire.ServeKvReady(replica_id="p", req_id="q",
                          payload=b"\x00\xff", addr="h:2",
                          seg_fp="ab", crc32=1, nbytes=2),
        wire.KVStoreScan(prefix="serve/"),
        wire.KVStoreScanResult(kvs={"k": b"v"}),
        wire.KVStoreDelete(key="k"),
        wire.ServeFleetStats(stats={"pools": {"unified": {"alive": 1}},
                                    "ids": [1, 2, 3]}),
        wire.Empty(),
    ]

    def test_fast_path_is_byte_identical_to_baseline(self):
        for msg in self.CASES:
            assert wire.serialize(msg) == wire.serialize_baseline(msg)

    def test_roundtrip(self):
        for msg in self.CASES:
            assert wire.deserialize(wire.serialize(msg)) == msg

    def test_nested_message_in_dict_and_tuple_fields(self):
        msg = wire.ServeFleetStats(stats={
            "nested": wire.ServeAck(req_id="a", tokens=[1, 2]),
            "plain": [1, 2, 3],
        })
        out = wire.deserialize(wire.serialize(msg))
        assert out.stats["nested"] == wire.ServeAck(req_id="a",
                                                    tokens=[1, 2])
        assert out.stats["plain"] == [1, 2, 3]
        assert wire.serialize(msg) == wire.serialize_baseline(msg)


def test_gateway_tier_node_heartbeats_and_gcs(tmp_path):
    """One real GatewayTierNode (socketed Gateway + heartbeat thread):
    it announces itself, keeps the lease fresh, GCs a stale peer, and
    deregisters on stop."""
    clock_now = time.time
    kv = LocalKv()
    registry = ServeRegistry(kv, job="node", lease_s=1.0,
                             clock=clock_now)
    from dlrover_tpu.serving import GatewayTierNode

    # A stale peer entry from a long-dead gateway.
    kv.set("serve/node/gw/dead", b'{"addr": "h:9", "ts": 1.0}')
    node = GatewayTierNode("g0", registry, heartbeat_s=0.05)
    node.start()
    try:
        assert wait_for(
            lambda: registry.gateways().get("g0") == node.addr,
            timeout=5.0,
        )
        assert wait_for(
            lambda: kv.get("serve/node/gw/dead") is None, timeout=5.0,
        )
        # Lease stays fresh across several windows.
        time.sleep(0.3)
        assert "g0" in registry.gateways()
        snap = node.core.stats_snapshot()
        assert snap["gateway_id"] == "g0"
    finally:
        node.stop()
    assert kv.get("serve/node/gw/g0") is None


def test_registry_server_tokened_delete_answers_first_result():
    """ISSUE 14 (graftcheck PC403): RpcKv.delete retries DEADLINE, so
    the standalone registry dedupes delete tokens exactly like the
    master KV — a retried duplicate of a landed delete answers True."""
    from dlrover_tpu.common.messages import (
        KVStoreDelete,
        KVStoreSet,
    )
    from dlrover_tpu.serving.tier import RegistryServer

    srv = RegistryServer(port=0)
    try:
        srv.handle(KVStoreSet(key="k", value=b"v"))
        rm = KVStoreDelete(key="k", token="tok")
        assert srv.handle(rm).success
        assert srv.handle(rm).success  # retried duplicate
        assert not srv.handle(
            KVStoreDelete(key="k", token="tok2")
        ).success
    finally:
        srv.stop()


def test_registry_server_delete_dedupe_is_race_safe():
    """A DEADLINE retry can race its own slow first attempt: both must
    answer the FIRST result (True), and the cache must not latch the
    loser's False (the handle() pool is 64 threads wide)."""
    import threading as _threading

    from dlrover_tpu.common.messages import KVStoreDelete, KVStoreSet
    from dlrover_tpu.serving.tier import RegistryServer

    srv = RegistryServer(port=0)
    try:
        srv.handle(KVStoreSet(key="k", value=b"v"))
        slow = _threading.Event()
        real_delete = srv.kv.delete

        def slow_delete(key):
            got = real_delete(key)
            slow.wait(0.2)  # hold the first attempt mid-sequence
            return got

        srv.kv.delete = slow_delete
        results = {}

        def attempt(tag):
            results[tag] = srv.handle(
                KVStoreDelete(key="k", token="tok")
            ).success

        t1 = _threading.Thread(target=attempt, args=("first",))
        t2 = _threading.Thread(target=attempt, args=("retry",))
        t1.start()
        t2.start()
        slow.set()
        t1.join()
        t2.join()
        assert results == {"first": True, "retry": True}
        # The cached answer stays True for any further retry.
        assert srv.handle(KVStoreDelete(key="k", token="tok")).success
    finally:
        srv.kv.delete = real_delete
        srv.stop()


def test_kv_segment_store_stats_report_block_framing():
    """ISSUE 19: store telemetry distinguishes block-list segments
    (paged prefill handoff) from monolithic ones and totals the KV
    blocks held — the handoff-side view of the fleet's memory."""
    import msgpack

    store = KvSegmentStore()
    paged = msgpack.packb(
        {"meta": {"bs": 8, "nblk": 3}, "data": b"x" * 16},
        use_bin_type=True,
    )
    store.put("p1", paged)
    store.put("d1", b"monolithic-segment-bytes")
    st = store.stats()
    assert st["segments"] == 2
    assert st["bytes"] == len(paged) + len(b"monolithic-segment-bytes")
    assert st["paged_segments"] == 1
    assert st["blocks_held"] == 3
    store.discard("p1")
    st = store.stats()
    assert st["paged_segments"] == 0 and st["blocks_held"] == 0
