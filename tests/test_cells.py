"""Multi-cell control plane units (ISSUE 15): HashRing extraction,
cell ownership/registry, the journaled CellManager, the servicer's
cell surface, federation merge/placement/split detection, chaos sites,
and placement surviving a journal recovery.  All tier-1 (marker
``cells``); the process-tree failover e2e lives in test_chaos_e2e.py.
"""

import os
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from dlrover_tpu import chaos  # noqa: E402
from dlrover_tpu.cells import (  # noqa: E402
    CellHeartbeat,
    CellManager,
    CellMap,
    CellRegistry,
    FederationTier,
    cell_for_node,
    detect_splits,
    merge_cell_snapshots,
    node_key,
    place_roles,
    plan_moves,
)
from dlrover_tpu.common import messages as m  # noqa: E402
from dlrover_tpu.common.hashring import HashRing, ring_hash  # noqa: E402
from dlrover_tpu.serving.tier import LocalKv  # noqa: E402

pytestmark = pytest.mark.cells


@pytest.fixture(autouse=True)
def _no_chaos():
    chaos.reset()
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# HashRing extraction (satellite: no ownership churn across the move)
# ---------------------------------------------------------------------------


class TestHashRingExtraction:
    def test_tier_reexport_is_the_common_class(self):
        from dlrover_tpu.common import hashring
        from dlrover_tpu.serving import tier

        assert tier.HashRing is hashring.HashRing
        assert tier.ring_hash is hashring.ring_hash
        # The package-level serving export follows too.
        from dlrover_tpu import serving

        assert serving.HashRing is hashring.HashRing

    def test_ring_assignments_pinned_across_move(self):
        """Golden owners recorded at extraction time: any change to
        the hash, the vnode naming, or the search would re-own live
        requests/nodes during a rolling upgrade."""
        ring = HashRing(["g0", "g1", "g2"])
        assert {k: ring.owner(k) for k in (
            "req-0", "req-1", "req-2", "req-3", "alpha", "beta",
        )} == {
            "req-0": "g1", "req-1": "g0", "req-2": "g2",
            "req-3": "g2", "alpha": "g0", "beta": "g1",
        }
        assert ring_hash("req-0") == 2987311802

    def test_gateway_ids_alias(self):
        ring = HashRing(["b", "a"])
        assert ring.member_ids == ("a", "b")
        assert ring.gateway_ids == ring.member_ids


# ---------------------------------------------------------------------------
# Cell ownership
# ---------------------------------------------------------------------------


class TestCellOwnership:
    def test_pinned_node_owners(self):
        owners = {
            i: cell_for_node(i, ["c0", "c1", "c2"]) for i in range(8)
        }
        assert owners == {0: "c0", 1: "c0", 2: "c1", 3: "c1",
                          4: "c1", 5: "c1", 6: "c2", 7: "c0"}

    def test_death_moves_only_the_dead_range(self):
        cells = ["c0", "c1", "c2"]
        before = {i: cell_for_node(i, cells) for i in range(256)}
        after = {i: cell_for_node(i, ["c0", "c2"]) for i in range(256)}
        for i in range(256):
            if before[i] != "c1":
                assert after[i] == before[i]
        moved = [i for i in range(256) if before[i] == "c1"]
        assert moved  # the dead range really existed
        assert all(after[i] in ("c0", "c2") for i in moved)

    def test_node_key_is_canonical(self):
        assert node_key(7) == "node:7"
        assert cell_for_node("7", ["c0", "c1"]) == \
            cell_for_node(7, ["c0", "c1"])


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


class TestCellRegistry:
    def test_announce_lease_and_gc(self):
        now = [100.0]
        reg = CellRegistry(LocalKv(), job="j", lease_s=5.0,
                           clock=lambda: now[0])
        reg.announce_cell("c0", "h:1", view=["c0", "c1"], epoch=3)
        reg.announce_cell("c1", "h:2")
        cells = reg.cells()
        assert cells["c0"]["addr"] == "h:1"
        assert cells["c0"]["view"] == ["c0", "c1"]
        assert cells["c0"]["epoch"] == 3
        assert cells["c1"]["view"] == ["c1"]  # self always included
        # c1 stops beating; c0 keeps going.
        now[0] = 104.0
        reg.announce_cell("c0", "h:1")
        now[0] = 106.0
        assert set(reg.cells()) == {"c0"}  # c1's lease expired
        dead = reg.gc_stale()
        assert dead == ["cells/j/cell/c1"]
        assert reg.kv.get("cells/j/cell/c1") is None

    def test_namespace_isolated_from_serving(self):
        from dlrover_tpu.serving.tier import ServeRegistry

        kv = LocalKv()
        serve = ServeRegistry(kv, job="j")
        cellr = CellRegistry(kv, job="j")
        serve.announce_gateway("g0", "h:1")
        cellr.announce_cell("c0", "h:2")
        assert set(cellr.cells()) == {"c0"}
        assert set(serve.gateways()) == {"g0"}

    def test_cell_map_reroutes_on_death(self):
        now = [0.0]
        reg = CellRegistry(LocalKv(), job="j", lease_s=2.0,
                           clock=lambda: now[0])
        reg.announce_cell("c0", "h:1")
        reg.announce_cell("c1", "h:2")
        cmap = CellMap(reg, refresh_s=0.0, clock=lambda: now[0])
        assert cmap.cell_ids() == ["c0", "c1"]
        owners = {i: cmap.owner(i) for i in range(32)}
        assert cmap.addr_for_node(0) in ("h:1", "h:2")
        # c1 dies; its nodes re-home to c0, others never move.
        now[0] = 3.0
        reg.announce_cell("c0", "h:1")
        for i in range(32):
            if owners[i] == "c0":
                assert cmap.owner(i) == "c0"
            else:
                assert cmap.owner(i) == "c0"  # adopted
        assert cmap.addr_for_node(5) == "h:1"


# ---------------------------------------------------------------------------
# CellManager: journaled placement
# ---------------------------------------------------------------------------


class _FakeJournal:
    def __init__(self):
        self.records = []

    def append(self, kind, fields):
        self.records.append((kind, dict(fields)))
        return len(self.records)


class TestCellManager:
    def test_placement_epoch_idempotent(self):
        cm = CellManager("c0")
        assert cm.apply_placement(1, {"training": 2}) is True
        assert cm.apply_placement(1, {"training": 9}) is False
        assert cm.apply_placement(0, {"training": 9}) is False
        assert cm.placement() == {"training": 2}
        assert cm.apply_placement(2, {"training": 3}) is True
        assert cm.placement_epoch == 2

    def test_placement_journaled_before_visible(self):
        cm = CellManager("c0")
        j = _FakeJournal()
        cm.bind_journal(j)
        cm.apply_placement(5, {"serving": 1})
        assert j.records == [
            ("cell.placement",
             {"epoch": 5, "placement": {"serving": 1}}),
        ]
        # A stale epoch never journals (replay must converge).
        cm.apply_placement(5, {"serving": 9})
        assert len(j.records) == 1

    def test_dump_load_roundtrip(self):
        cm = CellManager("c0")
        cm.apply_placement(4, {"training": 2, "gateway": 1})
        fresh = CellManager()
        fresh.load_state(cm.dump_state())
        assert fresh.cell_id == "c0"
        assert fresh.placement() == {"training": 2, "gateway": 1}
        assert fresh.placement_epoch == 4

    def test_snapshot_body(self):
        cm = CellManager("c0")
        cm.set_view(["c1", "c0"])
        cm.apply_placement(1, {"training": 2})
        snap = cm.snapshot({"nodes": 3})
        assert snap["cell_id"] == "c0"
        assert snap["view"] == ["c0", "c1"]
        assert snap["placement"] == {"training": 2}
        assert snap["placement_epoch"] == 1
        assert snap["nodes"] == 3


# ---------------------------------------------------------------------------
# Servicer surface (in-process: the dispatch table, no sockets)
# ---------------------------------------------------------------------------


def _cell_master(cell_id="c0", state_dir=""):
    from dlrover_tpu.master.master import LocalJobMaster

    return LocalJobMaster(0, job_name="t", cell_id=cell_id,
                          state_dir=state_dir)


class TestCellServicer:
    def test_snapshot_request(self):
        master = _cell_master()
        resp = master.servicer(m.CellSnapshotRequest(cell_id="c0"))
        assert isinstance(resp, m.CellSnapshot) and resp.found
        assert resp.cell_id == "c0"
        assert resp.snapshot["cell_id"] == "c0"
        assert "tasks_doing" in resp.snapshot
        assert "nodes" in resp.snapshot

    def test_cell_less_master_answers_not_found(self):
        master = _cell_master(cell_id="")
        resp = master.servicer(m.CellSnapshotRequest(cell_id="c0"))
        assert isinstance(resp, m.CellSnapshot) and not resp.found

    def test_placement_update_and_stale_retry(self):
        master = _cell_master()
        ok = master.servicer(m.CellPlacementUpdate(
            cell_id="c0", epoch=1, placement={"training": 2},
        ))
        assert ok.success
        # A DEADLINE-retried duplicate acks without effect.
        dup = master.servicer(m.CellPlacementUpdate(
            cell_id="c0", epoch=1, placement={"training": 99},
        ))
        assert dup.success
        assert master.cell_manager.placement() == {"training": 2}

    def test_misrouted_placement_rejected(self):
        master = _cell_master()
        resp = master.servicer(m.CellPlacementUpdate(
            cell_id="c9", epoch=1, placement={"training": 2},
        ))
        assert not resp.success and "c9" in resp.reason
        assert master.cell_manager.placement_epoch == -1


# ---------------------------------------------------------------------------
# Federation: merge / placement / split detection
# ---------------------------------------------------------------------------


class TestFederationPure:
    def test_merge_cell_snapshots(self):
        merged = merge_cell_snapshots([
            {"cell_id": "c0", "nodes": 2, "tasks_doing": 1,
             "tasks_pending": 4, "placement_epoch": 3,
             "pools": {"serving": {"alive": 2, "slots": 4,
                                   "assigned": 3, "queue_depth": 5}}},
            {"cell_id": "c1", "nodes": 3, "tasks_doing": 2,
             "tasks_pending": 1, "placement_epoch": 3,
             "pools": {"serving": {"alive": 1, "slots": 2,
                                   "assigned": 1, "queue_depth": 2}}},
            {},
        ])
        assert merged["cells_alive"] == 2
        assert merged["nodes"] == 5
        assert merged["tasks_doing"] == 3
        assert merged["tasks_pending"] == 5
        pool = merged["pools"]["serving"]
        assert pool["alive"] == 3 and pool["slots"] == 6
        assert pool["queue_depth"] == 7
        assert pool["occupancy"] == pytest.approx(4 / 6)
        assert set(merged["cells"]) == {"c0", "c1"}

    def test_detect_splits_healthy_and_forged(self):
        healthy = {
            "c0": {"view": ["c0", "c1"]},
            "c1": {"view": ["c0", "c1"]},
        }
        assert detect_splits(healthy) == []
        forged = {
            "c0": {"view": ["c0"]},  # claims the whole ring
            "c1": {"view": ["c0", "c1"]},
        }
        splits = detect_splits(forged)
        assert splits
        assert all(claim == ["c0", "c1"] for _, claim in splits)

    def test_place_roles_properties(self):
        cells = {"c0": {"capacity": 4}, "c1": {"capacity": 4},
                 "c2": {"capacity": 0}}
        demands = {"training": 6, "serving": 2, "gateway": 3,
                   "cell-master": 3, "draft": 1}
        plan = place_roles(cells, demands)
        assert plan == place_roles(cells, demands)  # deterministic
        # CPU roles spread over ALL cells, no capacity charge.
        assert sum(plan["gateway"].values()) == 3
        assert sum(plan["cell-master"].values()) == 3
        assert set(plan["cell-master"]) == {"c0", "c1", "c2"}
        # Serving spreads over TPU cells; training packs the rest.
        assert set(plan["serving"]) == {"c0", "c1"}
        charged = {
            cid: sum(plan[r].get(cid, 0)
                     for r in ("training", "serving", "draft"))
            for cid in ("c0", "c1")
        }
        assert all(v <= 4 for v in charged.values())
        # 8 chips, 9 TPU-role members demanded -> 1 unplaced, loudly.
        placed = sum(
            sum(v for c, v in plan[r].items() if c != "!unplaced")
            for r in ("training", "serving", "draft")
        )
        assert placed == 8
        assert plan["training"]["!unplaced"] == 1

    def test_place_roles_pinned(self):
        plan = place_roles(
            {"c0": {"capacity": 4}, "c1": {"capacity": 4}},
            {"training": 2},
            pinned={"training": {"c1": 2}},
        )
        assert plan["training"] == {"c1": 2}


class _Loopback:
    """connect() stand-in: routes RPC calls straight to a servicer."""

    def __init__(self, servicer):
        self._servicer = servicer

    def call(self, msg, **_kw):
        return self._servicer(msg)

    def close(self):
        pass


class TestFederationTier:
    def _fleet(self, n=2, lease_s=30.0, refresh_s=0.0):
        kv = LocalKv()
        masters = {}
        addr_to = {}
        for i in range(n):
            cid = f"c{i}"
            master = _cell_master(cell_id=cid)
            reg = CellRegistry(kv, job="j", lease_s=lease_s)
            hb = CellHeartbeat(cid, reg, lambda c=cid: f"addr-{c}",
                               cell_manager=master.cell_manager)
            masters[cid] = (master, hb)
            addr_to[f"addr-{cid}"] = master.servicer
        for _cid, (_master, hb) in masters.items():
            hb.beat_once()
        # Second beat round: every view now includes every peer.
        for _cid, (_master, hb) in masters.items():
            hb.beat_once()
        tier = FederationTier(
            CellRegistry(kv, job="j", lease_s=lease_s),
            connect=lambda addr: _Loopback(addr_to[addr]),
            refresh_s=refresh_s,
            demands={"training": 2, "serving": 2, "gateway": 2},
        )
        return kv, masters, tier

    def test_fleet_view_and_no_false_split(self):
        _kv, masters, tier = self._fleet()
        view = tier.fleet_view(force=True)
        assert set(view["registry"]) == {"c0", "c1"}
        assert view["cells_alive"] == 2
        assert view["splits"] == []
        assert tier.counters.get("cell_split_detected") == 0
        assert tier.counters.get("cell_snapshot_fetches") == 2

    def test_placement_push_adopted_and_epochs_converge(self):
        _kv, masters, tier = self._fleet()
        res = tier.push_placement()
        assert res == {"c0": True, "c1": True}
        epochs = {
            cid: master.cell_manager.placement_epoch
            for cid, (master, _hb) in masters.items()
        }
        assert set(epochs.values()) == {1}
        # Every cell got its CPU-role share AND a chip-role share —
        # each master reports capacity (its worker ceiling, 1 here),
        # so the live snapshot path really places TPU roles.
        for cid, (master, _hb) in masters.items():
            placed = master.cell_manager.placement()
            assert placed.get("gateway") == 1
            assert placed.get("serving") == 1
        # A second push with NOTHING changed is a no-op: epochs hold,
        # no journal-spamming re-adoption (the federation loop runs
        # every interval forever).
        assert tier.push_placement() == {}
        for cid, (master, _hb) in masters.items():
            assert master.cell_manager.placement_epoch == 1
        # A demand change really re-places, bumping the epoch.
        tier.demands["gateway"] = 4
        res2 = tier.push_placement()
        assert res2 == {"c0": True, "c1": True}
        for cid, (master, _hb) in masters.items():
            assert master.cell_manager.placement_epoch == 2
            assert master.cell_manager.placement().get("gateway") == 2

    def test_live_snapshot_carries_capacity(self):
        _kv, _masters, tier = self._fleet()
        view = tier.fleet_view(force=True)
        for cid, snap in view["cells"].items():
            assert snap["capacity"] == 1  # LocalJobMaster max_nodes
        plan = tier.plan_placement(view)
        # TPU demand lands on real cells, not "!unplaced"-only.
        assert set(plan["serving"]) <= {"c0", "c1"}
        assert sum(plan["serving"].values()) == 2

    def test_split_detected_only_when_persistent(self):
        _kv, masters, tier = self._fleet()
        assert tier.fleet_view(force=True)["splits"] == []
        # Forge a split: c0 claims the whole ring via chaos.
        chaos.configure("cell.split:method=c0")
        masters["c0"][1].beat_once()
        v1 = tier.fleet_view(force=True)
        assert v1["splits"]  # seen ...
        assert v1["splits_confirmed"] == []  # ... but not yet confirmed
        assert tier.counters.get("cell_split_detected") == 0
        # Still split on the NEXT read (no healing beat in between):
        # now it is confirmed and counted.
        v2 = tier.fleet_view(force=True)
        assert v2["splits_confirmed"]
        assert tier.counters.get("cell_split_detected") == 1
        # The victim's next beat heals the view (one-shot site spent).
        masters["c0"][1].beat_once()
        v3 = tier.fleet_view(force=True)
        assert v3["splits"] == []

    def test_borrow_signal_is_federated(self):
        _kv, masters, tier = self._fleet()
        # Give each cell a serving pool via a fake fleet status.
        class _FakeFleet:
            def __init__(self, queue):
                self._q = queue

            def status(self):
                return {"roles": {"serving": {
                    "desired": 2, "members": ["r0"],
                    "signals": {"queue_depth": self._q},
                }}, "policies": []}

        masters["c0"][0].servicer.fleet_manager = _FakeFleet(7)
        masters["c1"][0].servicer.fleet_manager = _FakeFleet(5)
        sig = tier.borrow_signal_fn("serving")()
        assert sig["queue_depth"] == 12  # summed across cells
        assert sig["members_alive"] == 2

    def test_dead_cell_skipped_not_fatal(self):
        kv, masters, tier = self._fleet()
        kv.delete("cells/j/cell/c1")
        view = tier.fleet_view(force=True)
        assert set(view["registry"]) == {"c0"}
        assert view["cells_alive"] == 1

    def test_push_placement_noop_on_stale_cached_view(self):
        """ISSUE 17 satellite: an unchanged plan must not re-push just
        because the TTL-cached view has not observed the cells adopt
        the epoch yet — before the fix the federation loop re-pushed
        the identical plan every interval, bumping epochs and writing
        one journal record per cell forever."""
        _kv, masters, tier = self._fleet(refresh_s=3600.0)
        assert tier.push_placement() == {"c0": True, "c1": True}
        # The cached view still carries pre-adoption epochs (its TTL
        # is an hour away) -- the push memory must carry the no-op.
        assert tier.push_placement() == {}
        assert tier.push_placement() == {}
        for _cid, (master, _hb) in masters.items():
            assert master.cell_manager.placement_epoch == 1
        # A real demand change still pushes, bumping the epoch once.
        tier.demands["gateway"] = 4
        assert tier.push_placement() == {"c0": True, "c1": True}
        for _cid, (master, _hb) in masters.items():
            assert master.cell_manager.placement_epoch == 2

    def test_plan_cell_moves_diffs_running_against_target(self):
        _kv, masters, tier = self._fleet()
        tier.push_placement()
        for _cid, (_master, hb) in masters.items():
            hb.beat_once()
        view = tier.fleet_view(force=True)
        # Settled fleet: what the cells run IS the target -> no orders.
        assert tier.plan_cell_moves(view) == []
        # Drift: c0 runs a serving unit the target places at c1.
        view["cells"]["c0"]["placement"]["serving"] = 2
        view["cells"]["c1"]["placement"]["serving"] = 0
        orders = tier.plan_cell_moves(view)
        assert ("serving", "c0", "c1", 1) in orders


class TestPlanMoves:
    def test_surplus_feeds_deficit_deterministically(self):
        cur = {"training": {"a": 4, "b": 2}}
        tgt = {"training": {"a": 3, "b": 3}}
        assert plan_moves(cur, tgt) == [("training", "a", "b", 1)]
        assert plan_moves(cur, tgt) == plan_moves(cur, tgt)

    def test_settled_and_unplaced_produce_no_orders(self):
        cur = {"t": {"a": 2, "b": 1}}
        assert plan_moves(cur, cur) == []
        # Capacity that does not exist cannot move.
        assert plan_moves({"t": {"a": 2}},
                          {"t": {"a": 1, "!unplaced": 1}}) == []

    def test_global_shrink_is_in_place_not_a_hop(self):
        # The cell's own reconciler shrinks in place; no hop needed.
        assert plan_moves({"t": {"a": 2}}, {"t": {"a": 1}}) == []

    def test_multi_cell_greedy_match_in_sorted_order(self):
        cur = {"t": {"a": 3, "b": 0, "c": 0}}
        tgt = {"t": {"a": 0, "b": 2, "c": 1}}
        assert plan_moves(cur, tgt) == [("t", "a", "b", 2),
                                        ("t", "a", "c", 1)]


# ---------------------------------------------------------------------------
# Chaos sites
# ---------------------------------------------------------------------------


class TestCellChaos:
    def test_master_kill_fires_in_heartbeat(self, monkeypatch):
        exits = []
        monkeypatch.setattr(os, "_exit",
                            lambda code: exits.append(code))
        chaos.configure("cell.master_kill:method=c1,step_ge=2")
        reg = CellRegistry(LocalKv(), job="j")
        cm = CellManager("c1")
        hb = CellHeartbeat("c1", reg, lambda: "h:1", cell_manager=cm)
        hb.beat_once()  # step 0
        hb.beat_once()  # step 1
        assert exits == []
        hb.beat_once()  # step 2 -> fires
        assert exits == [chaos.EXIT_CELL_MASTER_KILL]

    def test_master_kill_method_filter(self, monkeypatch):
        exits = []
        monkeypatch.setattr(os, "_exit",
                            lambda code: exits.append(code))
        chaos.configure("cell.master_kill:method=c1")
        reg = CellRegistry(LocalKv(), job="j")
        hb = CellHeartbeat("c0", reg, lambda: "h:1")
        hb.beat_once()
        assert exits == []  # wrong cell: never fires

    def test_blackout_fires_in_master_heartbeat_with_exit_86(
            self, monkeypatch):
        exits = []
        monkeypatch.setattr(os, "_exit",
                            lambda code: exits.append(code))
        chaos.configure("cell.blackout:method=c1")
        reg = CellRegistry(LocalKv(), job="j")
        hb0 = CellHeartbeat("c0", reg, lambda: "h:0")
        hb0.beat_once()
        assert exits == []  # method selects the CELL: c0 untouched
        hb1 = CellHeartbeat("c1", reg, lambda: "h:1")
        hb1.beat_once()
        assert exits == [chaos.EXIT_CELL_BLACKOUT]

    def test_split_site_is_one_shot(self):
        chaos.configure("cell.split:method=c0")
        kv = LocalKv()
        reg = CellRegistry(kv, job="j")
        reg.announce_cell("c1", "h:2")
        cm = CellManager("c0")
        hb = CellHeartbeat("c0", reg, lambda: "h:1", cell_manager=cm)
        hb.beat_once()
        assert reg.cells()["c0"]["view"] == ["c0"]  # forged
        hb.beat_once()
        assert reg.cells()["c0"]["view"] == ["c0", "c1"]  # healed


# ---------------------------------------------------------------------------
# HA composition: placement survives journal recovery + statecheck
# ---------------------------------------------------------------------------


class TestCellHA:
    def test_placement_survives_recovery(self, tmp_path):
        state_dir = str(tmp_path / "state")
        master = _cell_master(cell_id="c0", state_dir=state_dir)
        ok = master.servicer(m.CellPlacementUpdate(
            cell_id="c0", epoch=7,
            placement={"training": 3, "gateway": 1},
        ))
        assert ok.success
        master._ha_journal.close()
        reborn = _cell_master(cell_id="c0", state_dir=state_dir)
        assert reborn.cell_manager.placement() == \
            {"training": 3, "gateway": 1}
        assert reborn.cell_manager.placement_epoch == 7
        reborn._ha_journal.close()

    def test_statecheck_clean_over_cell_journal(self, tmp_path):
        from dlrover_tpu.master.statecheck import check_state_dir

        state_dir = str(tmp_path / "state")
        master = _cell_master(cell_id="c0", state_dir=state_dir)
        master.servicer(m.CellPlacementUpdate(
            cell_id="c0", epoch=1, placement={"serving": 2},
        ))
        master.kv_store.set("k", b"v")
        master._ha_journal.close()
        report = check_state_dir(state_dir)
        assert report["damage"] == []
        assert report["divergences"] == []
