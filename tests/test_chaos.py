"""chaosd unit tests: plan grammar, deterministic decisions, injection
points, idempotency-token dedup, and crash-site commit atomicity.

Everything here is deterministic and sub-second (tier-1); the full
process-tree chaos scenarios live in ``test_chaos_e2e.py``.
"""

import os
import subprocess
import sys
import time

import pytest

from dlrover_tpu import chaos
from dlrover_tpu.chaos import FaultPlan, FaultSpec
from dlrover_tpu.common import messages as msgs
from dlrover_tpu.common.rpc import RpcClient, RpcServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plan():
    chaos.reset()
    yield
    chaos.reset()


@pytest.mark.chaos
class TestPlanGrammar:
    def test_full_example_parses(self):
        plan = FaultPlan.parse(
            "rpc.unavailable:p=0.2,seed=7;master.restart:at=10s;"
            "ckpt.crash_before_commit:step=5;worker.kill:rank=1,step=6"
        )
        assert plan.seed == 7
        sites = [s.site for s in plan.specs]
        assert sites == [
            "rpc.unavailable", "master.restart",
            "ckpt.crash_before_commit", "worker.kill",
        ]
        kill = plan.specs[3]
        assert kill.rank == 1 and kill.step == 6
        assert kill.kind == "crash" and kill.times == 1

    def test_durations_and_defaults(self):
        spec = FaultSpec.parse("rpc.latency:delay=250ms")
        assert spec.delay == pytest.approx(0.25)
        assert FaultSpec.parse("master.restart:at=3s").at == 3.0
        # Crash sites default to one-shot; error sites to unlimited.
        assert FaultSpec.parse("ckpt.crash_after_commit").times == 1
        assert FaultSpec.parse("rpc.unavailable").times == -1

    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError, match="unknown fault site"):
            FaultPlan.parse("rpc.unavaliable:p=1")  # typo

    def test_unknown_param_rejected(self):
        with pytest.raises(ValueError, match="unknown fault param"):
            FaultSpec.parse("rpc.unavailable:prob=0.2")

    def test_malformed_param_rejected(self):
        with pytest.raises(ValueError, match="key=value"):
            FaultSpec.parse("rpc.unavailable:p")

    def test_empty_plan_rejected(self):
        with pytest.raises(ValueError):
            FaultPlan.parse("  ;  ")

    def test_without_sites_strips_spent_crash_faults(self):
        """Relaunchers scrub the crash site that just fired so the
        replacement process does not re-arm it and die identically."""
        plan = (
            "rpc.unavailable:p=0.2,seed=7;master.restart:at=10s;"
            "worker.kill:rank=1,step=6"
        )
        out = chaos.without_sites(plan, ("master.restart",))
        assert out == "rpc.unavailable:p=0.2,seed=7;worker.kill:rank=1,step=6"
        out = chaos.without_sites(out, ("worker.kill",))
        assert out == "rpc.unavailable:p=0.2,seed=7"
        assert chaos.without_sites(out, ("rpc.unavailable",)) == ""
        # The stripped string still parses (round-trip safety).
        assert chaos.FaultPlan.parse(out).specs[0].site == "rpc.unavailable"

    def test_without_sites_preserves_plan_seed(self):
        """Stripping the spec that carried seed= must re-pin the seed on a
        survivor so deterministic replay crosses the relaunch."""
        out = chaos.without_sites(
            "master.restart:at=1s,seed=7;rpc.drop:p=0.5",
            ("master.restart",),
        )
        assert chaos.FaultPlan.parse(out).seed == 7
        # No-op when the seed survives on its own spec.
        out = chaos.without_sites(
            "rpc.drop:p=0.5,seed=9;master.restart:at=1s",
            ("master.restart",),
        )
        assert out == "rpc.drop:p=0.5,seed=9"
        # A paramless survivor gets ':seed=N', not an unparseable ',...'.
        out = chaos.without_sites(
            "master.restart:at=1s,seed=7;rpc.drop", ("master.restart",)
        )
        assert chaos.FaultPlan.parse(out).seed == 7

    def test_scrub_env_strips_or_removes(self):
        env = {chaos.ENV_VAR: "worker.kill:rank=0,step=3;rpc.drop:p=0.1"}
        chaos.scrub_env(env, ("worker.kill",))
        assert env[chaos.ENV_VAR] == "rpc.drop:p=0.1"
        chaos.scrub_env(env, ("rpc.drop",))
        assert chaos.ENV_VAR not in env
        chaos.scrub_env(env, ("rpc.drop",))  # absent var: no-op
        assert chaos.ENV_VAR not in env

    def test_env_load_in_subprocess(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        env["DLROVER_TPU_FAULTS"] = "rpc.unavailable:times=1"
        out = subprocess.run(
            [sys.executable, "-c",
             "from dlrover_tpu import chaos; "
             "print(chaos.active_plan() is not None)"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0 and "True" in out.stdout
        # A malformed env plan is ignored loudly, never fatal.
        env["DLROVER_TPU_FAULTS"] = "not-a-site:oops"
        out = subprocess.run(
            [sys.executable, "-c",
             "from dlrover_tpu import chaos; "
             "print(chaos.active_plan() is None)"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0 and "True" in out.stdout


@pytest.mark.chaos
class TestDecisions:
    def test_seeded_sequence_is_reproducible(self):
        seq = []
        for _ in range(2):
            plan = FaultPlan.parse("rpc.unavailable:p=0.3,seed=11")
            seq.append(
                [plan.fire("rpc.unavailable") is not None
                 for _ in range(200)]
            )
        assert seq[0] == seq[1]
        assert 20 < sum(seq[0]) < 100  # p=0.3 actually bites

    def test_different_seeds_differ(self):
        a = FaultPlan.parse("rpc.unavailable:p=0.5,seed=1")
        b = FaultPlan.parse("rpc.unavailable:p=0.5,seed=2")
        sa = [a.fire("rpc.unavailable") is not None for _ in range(100)]
        sb = [b.fire("rpc.unavailable") is not None for _ in range(100)]
        assert sa != sb

    def test_sites_do_not_share_a_stream(self):
        """Interleaving evaluations of another site must not perturb a
        site's decision sequence (index-keyed draws, not a shared RNG)."""
        lone = FaultPlan.parse("rpc.unavailable:p=0.3,seed=5")
        solo = [lone.fire("rpc.unavailable") is not None for _ in range(50)]
        mixed_plan = FaultPlan.parse(
            "rpc.unavailable:p=0.3,seed=5;rpc.drop:p=0.3"
        )
        mixed = []
        for _ in range(50):
            mixed_plan.fire("rpc.drop")
            mixed.append(mixed_plan.fire("rpc.unavailable") is not None)
        assert solo == mixed

    def test_times_and_every(self):
        plan = FaultPlan.parse("rpc.unavailable:every=3,times=2")
        fired = [
            i for i in range(1, 13)
            if plan.fire("rpc.unavailable") is not None
        ]
        assert fired == [3, 6]

    def test_rank_step_method_filters(self):
        plan = FaultPlan.parse("worker.kill:rank=1,step=6")
        assert plan.fire("worker.kill", rank=0, step=6) is None
        assert plan.fire("worker.kill", rank=1, step=5) is None
        assert plan.fire("worker.kill", rank=1, step=6) is not None
        plan2 = FaultPlan.parse("rpc.unavailable:method=JoinRendezvous")
        assert plan2.fire("rpc.unavailable", method="Heartbeat") is None
        assert plan2.fire(
            "rpc.unavailable", method="JoinRendezvous"
        ) is not None

    def test_at_gate(self):
        plan = FaultPlan.parse("rpc.unavailable:at=50ms,times=1")
        assert plan.fire("rpc.unavailable") is None
        time.sleep(0.07)
        assert plan.fire("rpc.unavailable") is not None
        assert plan.fire("rpc.unavailable") is None  # one-shot spent

    def test_inject_noop_without_plan(self):
        assert chaos.active_plan() is None
        assert chaos.inject("rpc.unavailable") is None
        assert chaos.inject("worker.kill", rank=0, step=0) is None

    def test_crash_kind_calls_exit(self, monkeypatch):
        exits = []
        monkeypatch.setattr(os, "_exit", lambda code: exits.append(code))
        chaos.configure("worker.kill:rank=1,step=6")
        chaos.inject("worker.kill", rank=1, step=5)
        assert exits == []
        chaos.inject("worker.kill", rank=1, step=6)
        assert exits == [chaos.EXIT_WORKER_KILL]

    def test_latency_kind_sleeps(self):
        chaos.configure("rdzv.late_join:delay=50ms,times=1")
        t0 = time.perf_counter()
        assert chaos.inject("rdzv.late_join") is not None
        assert time.perf_counter() - t0 >= 0.05
        t0 = time.perf_counter()
        assert chaos.inject("rdzv.late_join") is None  # spent
        assert time.perf_counter() - t0 < 0.04


@pytest.mark.chaos
class TestRpcInjection:
    def _serve(self):
        seen = []

        def handler(msg):
            seen.append(type(msg).__name__)
            return msgs.BaseResponse(success=True)

        server = RpcServer(0, handler)
        server.start()
        return server, seen

    def test_client_unavailable_retried_to_success(self):
        server, seen = self._serve()
        try:
            chaos.configure("rpc.unavailable:times=2")
            client = RpcClient(f"127.0.0.1:{server.port}")
            resp = client.call(msgs.Heartbeat(), backoff=0.01)
            assert isinstance(resp, msgs.BaseResponse) and resp.success
            assert chaos.active_plan().stats()["rpc.unavailable"] == 2
            # The first two attempts never reached the server.
            assert len(seen) == 1
            client.close()
        finally:
            server.stop()

    def test_server_drop_retried_to_success(self):
        server, seen = self._serve()
        try:
            chaos.configure("rpc.drop:times=1")
            client = RpcClient(f"127.0.0.1:{server.port}")
            resp = client.call(msgs.Heartbeat(), backoff=0.01)
            assert isinstance(resp, msgs.BaseResponse) and resp.success
            assert chaos.active_plan().stats()["rpc.drop"] == 1
            client.close()
        finally:
            server.stop()

    def test_client_latency_injected(self):
        server, _ = self._serve()
        try:
            chaos.configure("rpc.latency:delay=80ms,times=1")
            client = RpcClient(f"127.0.0.1:{server.port}")
            t0 = time.perf_counter()
            client.call(msgs.Heartbeat())
            assert time.perf_counter() - t0 >= 0.08
            client.close()
        finally:
            server.stop()

    def test_method_filter_spares_other_calls(self):
        server, seen = self._serve()
        try:
            chaos.configure("rpc.unavailable:method=JoinRendezvous,times=99")
            client = RpcClient(f"127.0.0.1:{server.port}")
            client.call(msgs.Heartbeat(), retries=1)
            assert seen == ["Heartbeat"]
            with pytest.raises(Exception):
                client.call(
                    msgs.JoinRendezvous(), retries=2, backoff=0.01
                )
            client.close()
        finally:
            server.stop()


@pytest.mark.chaos
class TestRendezvousInjection:
    def test_lost_node_then_rejoin_recovers(self):
        from dlrover_tpu.master.rendezvous import (
            ElasticTrainingRendezvousManager,
        )

        chaos.configure("rdzv.lost_node:times=1")
        mgr = ElasticTrainingRendezvousManager()
        mgr.update_rdzv_params(1, 1, waiting_timeout=0.1)
        mgr.join(0, 0, 1, host="h0", attempt_id="a1")
        # The join evaporated: no world forms for this node.
        _, _, world, _ = mgr.get_comm_world(0)
        assert world == {}
        # The agent's periodic re-join (same attempt id) heals it.
        mgr.join(0, 0, 1, host="h0", attempt_id="a1")
        _, _, world, _ = mgr.get_comm_world(0)
        assert 0 in world and world[0]["node_id"] == 0

    def test_rejoin_heartbeat_does_not_rearm_lastcall(self):
        """An already-waiting node's periodic re-join (same attempt id)
        must not reset the lastcall quiescence window, or enough agents
        re-joining on uncorrelated timers would stall the round forever."""
        from dlrover_tpu.master.rendezvous import (
            ElasticTrainingRendezvousManager,
        )

        mgr = ElasticTrainingRendezvousManager()
        mgr.update_rdzv_params(2, 4, waiting_timeout=0.2)
        mgr.join(1, 0, 1, host="h1", attempt_id="a1")
        mgr.join(2, 1, 1, host="h2", attempt_id="a2")
        time.sleep(0.25)  # quiescence window elapses
        mgr.join(1, 0, 1, host="h1", attempt_id="a1")  # heartbeat re-join
        # Completion must fire NOW: the re-join did not re-arm lastcall.
        _, _, world, _ = mgr.get_comm_world(1)
        assert len(world) == 2

    def test_late_join_delays_outside_lock(self):
        from dlrover_tpu.master.rendezvous import (
            ElasticTrainingRendezvousManager,
        )

        chaos.configure("rdzv.late_join:delay=60ms,times=1")
        mgr = ElasticTrainingRendezvousManager()
        mgr.update_rdzv_params(1, 1, waiting_timeout=0.1)
        t0 = time.perf_counter()
        mgr.join(0, 0, 1, host="h0", attempt_id="a1")
        assert time.perf_counter() - t0 >= 0.06
        _, _, world, _ = mgr.get_comm_world(0)
        assert 0 in world


@pytest.mark.chaos
class TestShmTornRead:
    def test_torn_read_once_then_recovers(self, tmp_path):
        import numpy as np

        from dlrover_tpu.common.shm import SharedMemoryArena

        name = f"dlrtpu_test_torn_{os.getpid()}"
        arena = SharedMemoryArena(name)
        try:
            arena.write_state(
                {"w": np.arange(8, dtype=np.float32)}, extra={"step": 3}
            )
            chaos.configure("shm.torn_read")  # one-shot by default
            assert arena.metadata() is None  # torn
            meta = arena.metadata()  # healthy again
            assert meta is not None and meta["extra"]["step"] == 3
        finally:
            arena.close(unlink=True)


@pytest.mark.chaos
class TestIdempotencyTokens:
    def test_kv_add_token_dedups(self):
        from dlrover_tpu.master.kv_store import KVStoreService

        kv = KVStoreService()
        assert kv.add("c", 1, token="t1") == 1
        assert kv.add("c", 1, token="t1") == 1  # retried duplicate
        assert kv.add("c", 1, token="t2") == 2
        assert kv.add("c", 1) == 3  # tokenless keeps old semantics

    def test_task_fetch_token_returns_same_task(self):
        from dlrover_tpu.master.dataset_splitter import new_dataset_splitter
        from dlrover_tpu.master.task_manager import TaskManager

        tm = TaskManager()
        tm.new_dataset(
            new_dataset_splitter(
                dataset_name="d", dataset_size=100, shard_size=10,
            )
        )
        first = tm.get_task("d", worker_id=0, token="tok")
        again = tm.get_task("d", worker_id=0, token="tok")
        assert first is not None and again == first
        other = tm.get_task("d", worker_id=0, token="tok2")
        assert other[0] != first[0]

    def test_kv_delete_token_dedups(self):
        """ISSUE 14 (graftcheck PC403): KVStoreDelete is DEADLINE-
        retried, so its found/not-found answer must come from the
        FIRST attempt — a retried duplicate of a delete that landed
        must not report found=False."""
        from dlrover_tpu.master.kv_store import KVStoreService

        kv = KVStoreService()
        kv.set("k", b"v")
        assert kv.delete("k", token="t1") is True
        assert kv.delete("k", token="t1") is True  # retried duplicate
        assert kv.delete("k", token="t2") is False  # genuinely gone
        kv.set("k2", b"v")
        assert kv.delete("k2") is True  # tokenless keeps old semantics

    def test_tokened_delete_over_the_wire(self):
        from dlrover_tpu.master.kv_store import KVStoreService
        from dlrover_tpu.master.servicer import MasterServicer

        servicer = MasterServicer(kv_store=KVStoreService())
        server = RpcServer(0, servicer)
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            client.call(msgs.KVStoreSet(key="k", value=b"v"))
            rm = msgs.KVStoreDelete(key="k", token="wire-tok")
            r1 = client.call(rm)
            r2 = client.call(rm)  # simulated retry of the same request
            assert r1.success and r2.success
            r3 = client.call(msgs.KVStoreDelete(key="k", token="t2"))
            assert not r3.success
            client.close()
        finally:
            server.stop()

    def test_tokened_add_over_the_wire(self):
        from dlrover_tpu.master.kv_store import KVStoreService
        from dlrover_tpu.master.servicer import MasterServicer

        servicer = MasterServicer(kv_store=KVStoreService())
        server = RpcServer(0, servicer)
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            add = msgs.KVStoreAdd(key="k", delta=2, token="wire-tok")
            r1 = client.call(add)
            r2 = client.call(add)  # simulated retry of the same request
            assert r1.value == 2 and r2.value == 2
            client.close()
        finally:
            server.stop()


@pytest.mark.chaos
class TestCommitCrashSites:
    """Crash-before/after-commit injection proves the tracker write is the
    atomic commit point (a real subprocess takes the os._exit)."""

    SCRIPT = (
        "import sys\n"
        "import numpy as np\n"
        "from dlrover_tpu import chaos\n"
        "from dlrover_tpu.checkpoint import shard_file\n"
        "from dlrover_tpu.common.storage import PosixDiskStorage\n"
        "ckpt_dir, plan = sys.argv[1], sys.argv[2]\n"
        "if plan != '-':\n"
        "    chaos.configure(plan)\n"
        "storage = PosixDiskStorage()\n"
        "for step in (3, 5):\n"
        "    shard_file.write_shard(\n"
        "        storage, ckpt_dir, step, 0,\n"
        "        {'w': np.arange(4.0) + step}, {'step': step})\n"
        "    shard_file.commit(storage, ckpt_dir, step)\n"
        "print('ALL_COMMITS_DONE')\n"
    )

    def _run(self, ckpt_dir, plan):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        env.pop(chaos.ENV_VAR, None)
        return subprocess.run(
            [sys.executable, "-c", self.SCRIPT, str(ckpt_dir), plan],
            env=env, capture_output=True, text=True, timeout=120,
        )

    def test_no_plan_commits_all(self, tmp_path):
        from dlrover_tpu.checkpoint import shard_file
        from dlrover_tpu.common.storage import PosixDiskStorage

        out = self._run(tmp_path, "-")
        assert out.returncode == 0, out.stderr[-2000:]
        assert shard_file.latest_step(PosixDiskStorage(), str(tmp_path)) == 5

    def test_crash_before_commit_keeps_previous_step(self, tmp_path):
        from dlrover_tpu.checkpoint import shard_file
        from dlrover_tpu.common.storage import PosixDiskStorage

        out = self._run(tmp_path, "ckpt.crash_before_commit:step=5")
        assert out.returncode == chaos.EXIT_CKPT_BEFORE_COMMIT, (
            out.stderr[-2000:]
        )
        storage = PosixDiskStorage()
        # Step 3 committed; step 5's shards exist but the tracker still
        # names 3 — the crash cost progress, never consistency.
        assert shard_file.latest_step(storage, str(tmp_path)) == 3
        assert storage.exists(shard_file.shard_path(str(tmp_path), 5, 0))
        got = shard_file.read_shard(storage, str(tmp_path), 3, 0)
        assert got is not None and got[1]["step"] == 3

    def test_crash_after_commit_is_durable(self, tmp_path):
        from dlrover_tpu.checkpoint import shard_file
        from dlrover_tpu.common.storage import PosixDiskStorage

        out = self._run(tmp_path, "ckpt.crash_after_commit:step=5")
        assert out.returncode == chaos.EXIT_CKPT_AFTER_COMMIT, (
            out.stderr[-2000:]
        )
        assert shard_file.latest_step(PosixDiskStorage(), str(tmp_path)) == 5


class TestLatencySites:
    """The two latency sites graftcheck CH503 found untested (ISSUE
    14): both are armed here against their documented contracts, so
    the chaos table's claims about them are properties, not prose."""

    def test_ckpt_slow_storage_delays_matching_step_only(self):
        chaos.configure("ckpt.slow_storage:delay=60ms,step=3,times=1")
        t0 = time.monotonic()
        # Step filter: the persist loops report (step, rank) exactly
        # like engine._stream_shard / the agent saver do.
        assert chaos.inject("ckpt.slow_storage", step=2, rank=0) is None
        assert time.monotonic() - t0 < 0.05
        t1 = time.monotonic()
        spec = chaos.inject("ckpt.slow_storage", step=3, rank=0)
        assert spec is not None and spec.kind == "latency"
        assert time.monotonic() - t1 >= 0.055
        # One-shot: the next matching persist is fast again.
        t2 = time.monotonic()
        assert chaos.inject("ckpt.slow_storage", step=3, rank=0) is None
        assert time.monotonic() - t2 < 0.05

    def test_serving_slow_replica_stalls_the_real_tick(self):
        """Arm ``serving.slow_replica`` and drive the REAL injection
        point — ``ReplicaRunner.tick`` — with a gateway-less
        transport: the tick slows by the configured delay and the
        runner keeps working (degradation, never breakage)."""
        from dlrover_tpu.serving.replica import ReplicaRunner

        class _Srv:
            # The minimal incremental-admission surface tick touches
            # on an idle, grant-less round.
            last_stats = {}
            slots = 1

            def free_slots(self):
                return 1

            def pending_rids(self):
                return []

            def active_rids(self):
                return []

            def pending_count(self):
                return 0

        class _DeadTransport:
            def call(self, msg, **_kw):
                raise ConnectionError("gateway down")

        runner = ReplicaRunner(_Srv(), _DeadTransport(), "r-slow")
        chaos.configure("serving.slow_replica:delay=80ms,times=1")
        t0 = time.monotonic()
        runner.tick()  # slow round: the site fires here
        slow = time.monotonic() - t0
        t1 = time.monotonic()
        runner.tick()  # budget spent: fast again
        fast = time.monotonic() - t1
        assert slow >= 0.075
        assert fast < 0.05
        assert chaos.active_plan().stats()["serving.slow_replica"] == 1


@pytest.mark.chaos
class TestGrayNetwork:
    """``net.gray`` (ISSUE 18): the RPC *succeeds* — the failure modes
    are time and multiplicity.  Armed against the real injection point
    (``RpcClient.call`` after a successful send) and against the
    documented contract: the receiver's dedupe, not the retry
    machinery, absorbs the wire duplicate."""

    def test_delays_and_duplicates_over_the_wire(self):
        seen = []

        def handler(msg):
            seen.append(type(msg).__name__)
            return msgs.BaseResponse(success=True)

        server = RpcServer(0, handler)
        server.start()
        try:
            chaos.configure("net.gray:times=1,delay=60ms")
            client = RpcClient(f"127.0.0.1:{server.port}")
            t0 = time.monotonic()
            resp = client.call(msgs.Heartbeat())
            gray = time.monotonic() - t0
            # The call SUCCEEDED (nothing dropped) ...
            assert isinstance(resp, msgs.BaseResponse) and resp.success
            # ... but the reply came back late and the server executed
            # the request TWICE (the wire duplicate).
            assert gray >= 0.055
            assert seen == ["Heartbeat", "Heartbeat"]
            # Budget spent: the next call is fast and single.
            t1 = time.monotonic()
            client.call(msgs.Heartbeat())
            assert time.monotonic() - t1 < 0.05
            assert seen == ["Heartbeat", "Heartbeat", "Heartbeat"]
            assert chaos.active_plan().stats()["net.gray"] == 1
            client.close()
        finally:
            server.stop()

    def test_duplicate_absorbed_by_receiver_dedupe(self):
        """The site's contract end to end: a gray-duplicated tokened
        mutation executes twice on the wire but mutates ONCE — the
        idempotency token, not luck, is what holds."""
        from dlrover_tpu.master.kv_store import KVStoreService
        from dlrover_tpu.master.servicer import MasterServicer

        servicer = MasterServicer(kv_store=KVStoreService())
        server = RpcServer(0, servicer)
        server.start()
        try:
            chaos.configure("net.gray:times=1,delay=0ms")
            client = RpcClient(f"127.0.0.1:{server.port}")
            add = msgs.KVStoreAdd(key="g", delta=2, token="gray-tok")
            r = client.call(add)  # duplicated on the wire by the site
            assert r.value == 2
            assert chaos.active_plan().stats()["net.gray"] == 1
            # A fresh token proves the counter itself still moves.
            r2 = client.call(
                msgs.KVStoreAdd(key="g", delta=2, token="tok-2")
            )
            assert r2.value == 4
            client.close()
        finally:
            server.stop()

    def test_seeded_decisions_are_deterministic(self):
        """The n-th evaluation's fire/skip decision is a pure function
        of (seed, site, n): two plans with the same seed produce the
        identical firing pattern, a different seed a different one."""
        def pattern(seed):
            plan = FaultPlan.parse(f"net.gray:p=0.5,seed={seed}")
            return [
                plan.fire("net.gray", method="Heartbeat") is not None
                for _ in range(64)
            ]

        a, b, c = pattern(11), pattern(11), pattern(12)
        assert a == b
        assert 0 < sum(a) < 64  # p=0.5 actually flips both ways
        assert a != c
